"""Where does the dense PNA step's time go? Times the fused-algebra
aggregation op (gather + 4 masked K-axis statistics, fwd+grad) alone at
OC20 scale vs a matmul floor — each as ONE dispatch of a chained
lax.fori_loop (the tunneled link's ~0.3 ms/dispatch otherwise swamps the
measurement; see segment_bench). Sizes the Pallas fusion opportunity
(round-3 verdict item 1)."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
from benchmarks.model_bench import _arg

def fence(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))

def timeloop(make_body, z0, iters=50):
    @jax.jit
    def run(z):
        return jax.lax.fori_loop(0, iters, make_body, z)
    out = run(z0); fence(out)
    t0 = time.perf_counter()
    out = run(z0); fence(out)
    return (time.perf_counter() - t0) / iters * 1e3

N, D, K = 5760, int(_arg("hidden", 256)), int(_arg("k", 16))
deg = 12
dtype = jnp.bfloat16 if _arg("bf16", True) else jnp.float32
rng = np.random.default_rng(0)
z0 = jnp.asarray(rng.standard_normal((N, D)), dtype)
base = (np.arange(N) // 90) * 90
idx = (base[:, None] + rng.integers(0, 90, (N, K))).astype(np.int32)
mask = np.zeros((N, K), bool); mask[:, :deg] = True
nbr_idx = jnp.asarray(idx); nbr_mask = jnp.asarray(mask)
from hydragnn_tpu.ops.dense_agg import (
    build_neighbor_lists, gather_neighbors, dense_moments, dense_minmax,
)
send = idx.ravel(); recv = np.repeat(np.arange(N), K)
ex = build_neighbor_lists(jnp.asarray(send), jnp.asarray(recv),
                          jnp.asarray(mask.ravel()), N, K, 2 * K)
rev_idx, rev_mask = ex["rev_idx"], ex["rev_mask"]
wmix = jnp.asarray(rng.standard_normal((4 * D, D)) / 32, dtype)

def agg(z):
    h = gather_neighbors(z, nbr_idx, rev_idx, rev_mask)
    h = jnp.where(nbr_mask[..., None], h, 0.0)
    mean, std, degv, has = dense_moments(h, nbr_mask)
    mn, mx = dense_minmax(h, nbr_mask, has)
    return jnp.concatenate([mean, std, mn, mx], axis=-1).astype(dtype)

def body_fwd(i, z):
    return 0.5 * z + 0.5 * (agg(z) @ wmix)  # carry keeps shape [N, D]

def body_bwd(i, z):
    g = jax.grad(lambda zz: (agg(zz).astype(jnp.float32) ** 2).sum())(z)
    return 0.5 * z + 0.5 * g.astype(dtype)

w1 = jnp.asarray(rng.standard_normal((D, 4 * D)) / 16, dtype)
def body_mm(i, z):
    return 0.5 * z + 0.5 * ((z @ w1) @ wmix)

print("agg fwd (+[4D,D] mix matmul) ms/iter:", round(timeloop(body_fwd, z0), 3))
print("agg fwd+bwd ms/iter:", round(timeloop(body_bwd, z0), 3))
print("matmul pair [N,D]@[D,4D]@[4D,D] ms/iter:", round(timeloop(body_mm, z0), 3))

# ---- windowed-gather prototype: neighbors of node block b live within
# +/-2 blocks (contiguous packed graphs <= 250 rows), so the gather is an
# overlapping-window one-hot batched matmul -- MXU work, no random access.
B = 128
NB = N // B
W = 5 * B
zpad_rows = 2 * B

def windowed_agg(z):
    zp = jnp.pad(z, ((zpad_rows, zpad_rows), (0, 0)))
    # [NB, W, D] overlapping windows (5x z bytes, streamed)
    win = jnp.stack([
        jax.lax.dynamic_slice_in_dim(zp, b * B, W, 0) for b in range(NB)
    ])
    idx_b = nbr_idx.reshape(NB, B * K)
    local = idx_b - (jnp.arange(NB) * B - zpad_rows)[:, None]
    onehot = (local[:, :, None] ==
              jnp.arange(W)[None, None, :]).astype(dtype)
    gathered = jnp.einsum("bkw,bwd->bkd", onehot, win,
                          preferred_element_type=jnp.float32)
    h = gathered.reshape(N, K, D)
    h = jnp.where(nbr_mask[..., None], h, 0.0)
    mean, std, degv, has = dense_moments(h, nbr_mask)
    mn, mx = dense_minmax(h, nbr_mask, has)
    return jnp.concatenate([mean, std, mn, mx], axis=-1).astype(dtype)

def body_wfwd(i, z):
    return 0.5 * z + 0.5 * (windowed_agg(z) @ wmix)

def body_wbwd(i, z):
    g = jax.grad(lambda zz: (windowed_agg(zz).astype(jnp.float32) ** 2).sum())(z)
    return 0.5 * z + 0.5 * g.astype(dtype)

windowed_jit = jax.jit(windowed_agg)
agg_jit = jax.jit(agg)
ok = np.allclose(np.asarray(windowed_jit(z0), np.float32),
                 np.asarray(agg_jit(z0), np.float32), atol=2e-2)
print("windowed == gather parity:", ok)
print("windowed fwd (+mix) ms/iter:", round(timeloop(body_wfwd, z0), 3))
print("windowed fwd+bwd ms/iter:", round(timeloop(body_wbwd, z0), 3))

