"""Serving A/B: micro-batched bucket-compiled server vs naive
per-request predict (ISSUE 2 acceptance artifact).

Drives the in-process :class:`~hydragnn_tpu.serve.InferenceServer` with
concurrent mixed-size requests (OC20-shaped log-normal sizes, the
distribution the bucketed-layout work measured) and reports p50/p99
request latency and sustained throughput against the naive baseline —
one padded single-graph batch per request, dispatched synchronously,
which is what calling the offline predict path per request would cost.

Usage: ``python benchmarks/serve_bench.py [--num=512] [--clients=8]
[--buckets=3] [--batch=8] [--hidden=64] [--wait-ms=5]``

Output: one JSON object per configuration (the BENCH_* line style).
"""

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.model_bench import _arg, _arch  # noqa: E402


def _oc20_requests(num, seed=0, degree=8):
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    sizes = np.clip(
        np.round(np.exp(rng.normal(np.log(60.0), 0.55, num))), 20, 250
    ).astype(int)
    out = []
    for n in sizes:
        d = GraphData(
            x=rng.random((int(n), 1)).astype(np.float32),
            pos=(rng.random((int(n), 3)) * n ** (1 / 3)).astype(np.float32),
        )
        src = np.repeat(np.arange(n), degree // 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        d.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        out.append(d)
    return out


def _build(requests, hidden, batch, buckets):
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.serve import ModelRegistry, plan_from_samples
    from hydragnn_tpu.train.trainer import Trainer

    plan = plan_from_samples(
        requests, max_batch_graphs=batch, num_buckets=buckets
    )
    model = create_model_config(_arch("SAGE", hidden, 3, 250))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    init_batch, _ = plan.pack([requests[0]], 0)
    state = trainer.init_state(init_batch)
    registry = ModelRegistry()
    registry.register("bench", model, state.params, state.batch_stats)
    return registry, plan


def _pcts(lat):
    lat = np.sort(np.asarray(lat))
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
    }


def run_server(registry, plan, requests, clients, wait_ms):
    from hydragnn_tpu.serve import InferenceServer

    server = InferenceServer(
        registry,
        plan,
        max_wait_s=wait_ms / 1e3,
        queue_capacity=max(4 * len(requests), 256),
    )
    latencies = []

    def one(g):
        t0 = time.perf_counter()
        server.predict(g, timeout=120)
        latencies.append(time.perf_counter() - t0)

    with server:
        # warm measurement pass
        with ThreadPoolExecutor(max_workers=clients) as pool:
            list(pool.map(one, requests[: len(requests) // 4]))
        latencies.clear()
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=clients) as pool:
            list(pool.map(one, requests))
        wall = time.perf_counter() - t0
        snap = server.metrics.snapshot()
    return {
        "mode": "server",
        "clients": clients,
        "max_wait_ms": wait_ms,
        "buckets": plan.num_buckets,
        **_pcts(latencies),
        "throughput_rps": round(len(requests) / wall, 1),
        "batches": snap["batches_total"],
        "compiles": snap["compiles_total"],
        "padding_waste_ratio": snap["padding_waste_ratio"],
    }


def run_naive(registry, plan, requests):
    """One synchronous single-graph dispatch per request — the offline
    per-request cost floor (no micro-batching, same bucket shapes)."""
    from hydragnn_tpu.serve import InferenceServer

    server = InferenceServer(registry, plan)
    server.warmup()  # compile parity with the served case
    entry = registry.get("bench")
    latencies = []
    t0 = time.perf_counter()
    for g in requests:
        t1 = time.perf_counter()
        b = plan.select(g)
        batch, _ = plan.pack([g], b)
        outs = server._dispatch_compiled(entry, b, batch)
        np.asarray(outs[0])  # completion fence
        latencies.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    return {
        "mode": "naive_per_request",
        "buckets": plan.num_buckets,
        **_pcts(latencies),
        "throughput_rps": round(len(requests) / wall, 1),
    }


def main():
    num = int(_arg("num", 512))
    clients = int(_arg("clients", 8))
    buckets = int(_arg("buckets", 3))
    batch = int(_arg("batch", 8))
    hidden = int(_arg("hidden", 64))
    wait_ms = float(_arg("wait-ms", 5))
    requests = _oc20_requests(num)
    registry, plan = _build(requests, hidden, batch, buckets)
    print(json.dumps(run_naive(registry, plan, requests)))
    print(json.dumps(run_server(registry, plan, requests, clients, wait_ms)))


if __name__ == "__main__":
    main()
