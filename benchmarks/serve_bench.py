"""Serving A/B: micro-batched bucket-compiled server vs naive
per-request predict (ISSUE 2 acceptance artifact), plus the fleet
fault-schedule bench (ISSUE 15) and its canary phases (ISSUE 16).

Default mode drives the in-process
:class:`~hydragnn_tpu.serve.InferenceServer` with concurrent mixed-size
requests (OC20-shaped log-normal sizes, the distribution the
bucketed-layout work measured) and reports p50/p99 request latency and
sustained throughput against the naive baseline — one padded
single-graph batch per request, dispatched synchronously, which is what
calling the offline predict path per request would cost.

``--fleet`` instead boots a real :class:`~hydragnn_tpu.serve.fleet.
ServingFleet` (N replica processes + :class:`~hydragnn_tpu.serve.
router.FleetRouter`) and replays a two-lane closed-loop traffic mix
through a scripted fault schedule — steady state, SIGKILL a replica
mid-load (kill->heal), zero-downtime hot-swap promote, promote of a
CRC-corrupt candidate (loud rollback), then the canary flywheel: a
published candidate shadow-evaluated off mirrored live traffic and
promoted through the gates (canary_promote — prices the shadow-path
overhead against the steady row, plus samples/shed and gate latency)
and a CRC-corrupt candidate whose canary crash-loops at boot and is
rejected without the fleet ever swapping (canary_reject) — reporting
per-phase p50/p99 latency, SLO-miss rate, and measured availability.

``--fleet --diurnal`` (ISSUE 17) is the multi-tenant capacity model:
N tenants (odd tenants pinned to a SECOND model — two checkpoints
HBM-packed per replica) x M priority lanes x a repeating diurnal load
curve (trough/ramp/peak/evening), with the response cache in front and
the predictive autoscaler closing the loop via ``ServingFleet.resize``.
Each phase row reports per-tenant p99/SLO-miss and prices
cost-per-million-requests from integrated replica-seconds; run two
periods and the second peak shows the forecast pre-scaling.

Usage: ``python benchmarks/serve_bench.py [--num=512] [--clients=8]
[--buckets=3] [--batch=8] [--hidden=64] [--wait-ms=5]`` or
``python benchmarks/serve_bench.py --fleet [--replicas=2] [--clients=4]
[--phase-s=4] [--deadline-ms=2000] [--batch-frac=0.25] [--hidden=16]``
or ``python benchmarks/serve_bench.py --fleet --diurnal [--tenants=2]
[--lanes=2] [--periods=2] [--base-rps=24] [--capacity-rps=20]
[--unique-frac=0.7] [--cost-per-replica-hour=1.0]``

Output: one JSON object per configuration / fault-schedule phase (the
BENCH_* line style, appendable).
"""

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.model_bench import _arg, _arch  # noqa: E402


def _oc20_requests(num, seed=0, degree=8):
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    sizes = np.clip(
        np.round(np.exp(rng.normal(np.log(60.0), 0.55, num))), 20, 250
    ).astype(int)
    out = []
    for n in sizes:
        d = GraphData(
            x=rng.random((int(n), 1)).astype(np.float32),
            pos=(rng.random((int(n), 3)) * n ** (1 / 3)).astype(np.float32),
        )
        src = np.repeat(np.arange(n), degree // 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        d.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        out.append(d)
    return out


def _build(requests, hidden, batch, buckets):
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.serve import ModelRegistry, plan_from_samples
    from hydragnn_tpu.train.trainer import Trainer

    plan = plan_from_samples(
        requests, max_batch_graphs=batch, num_buckets=buckets
    )
    model = create_model_config(_arch("SAGE", hidden, 3, 250))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    init_batch, _ = plan.pack([requests[0]], 0)
    state = trainer.init_state(init_batch)
    registry = ModelRegistry()
    registry.register("bench", model, state.params, state.batch_stats)
    return registry, plan


def _pcts(lat):
    lat = np.sort(np.asarray(lat))
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
    }


def run_server(registry, plan, requests, clients, wait_ms):
    from hydragnn_tpu.serve import InferenceServer

    server = InferenceServer(
        registry,
        plan,
        max_wait_s=wait_ms / 1e3,
        queue_capacity=max(4 * len(requests), 256),
    )
    latencies = []

    def one(g):
        t0 = time.perf_counter()
        server.predict(g, timeout=120)
        latencies.append(time.perf_counter() - t0)

    with server:
        # warm measurement pass
        with ThreadPoolExecutor(max_workers=clients) as pool:
            list(pool.map(one, requests[: len(requests) // 4]))
        latencies.clear()
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=clients) as pool:
            list(pool.map(one, requests))
        wall = time.perf_counter() - t0
        snap = server.metrics.snapshot()
    return {
        "mode": "server",
        "clients": clients,
        "max_wait_ms": wait_ms,
        "buckets": plan.num_buckets,
        **_pcts(latencies),
        "throughput_rps": round(len(requests) / wall, 1),
        "batches": snap["batches_total"],
        "compiles": snap["compiles_total"],
        "padding_waste_ratio": snap["padding_waste_ratio"],
    }


def run_naive(registry, plan, requests):
    """One synchronous single-graph dispatch per request — the offline
    per-request cost floor (no micro-batching, same bucket shapes)."""
    from hydragnn_tpu.serve import InferenceServer

    server = InferenceServer(registry, plan)
    server.warmup()  # compile parity with the served case
    entry = registry.get("bench")
    latencies = []
    t0 = time.perf_counter()
    for g in requests:
        t1 = time.perf_counter()
        b = plan.select(g)
        batch, _ = plan.pack([g], b)
        outs = server._dispatch_compiled(entry, b, batch)
        np.asarray(outs[0])  # completion fence
        latencies.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    return {
        "mode": "naive_per_request",
        "buckets": plan.num_buckets,
        **_pcts(latencies),
        "throughput_rps": round(len(requests) / wall, 1),
    }


# ---- fleet fault-schedule bench (ISSUE 15) ---------------------------------


def _fleet_artifacts(workdir, hidden, batch, buckets, seed=0):
    """Bench-shaped inputs for the shared fleet artifact recipe
    (tests/_fleet_smoke.py's build_artifacts): a small log-normal
    graph-size mix and a GIN arch sized so per-bucket warmup stays
    cheap on CPU."""
    from tests._fleet_smoke import build_artifacts

    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    sizes = np.clip(
        np.round(np.exp(rng.normal(np.log(12.0), 0.45, 48))), 5, 40
    ).astype(int)
    samples = []
    for n in sizes:
        g = GraphData(
            x=rng.random((int(n), 1)).astype(np.float32),
            pos=rng.random((int(n), 3)).astype(np.float32),
        )
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        samples.append(g)

    arch = _arch("GIN", hidden, 2, int(sizes.max()))
    spec_path, ckdir, samples = build_artifacts(
        workdir, arch, samples, batch=batch, buckets=buckets,
        model_name="bench",
    )
    return spec_path, ckdir, arch, samples


def _phase_row(phase, recs, deadline_s, **extra):
    """One BENCH row from this phase's (latency, outcome, lane) recs."""
    n = len(recs)
    ok = [l for l, o, _ in recs if o == "ok"]
    n_shed = sum(1 for _, o, _ in recs if o == "shed")
    n_deadline = sum(1 for _, o, _ in recs if o == "deadline")
    n_failed = sum(1 for _, o, _ in recs if o == "failed")
    shed_by_lane = {}
    for _, o, lane in recs:
        if o == "shed":
            shed_by_lane[lane] = shed_by_lane.get(lane, 0) + 1
    row = {
        "mode": "fleet",
        "phase": phase,
        "deadline_ms": round(deadline_s * 1e3, 1),
        "submitted": n,
        "ok": len(ok),
        "shed": n_shed,
        "deadline_missed": n_deadline,
        "failed": n_failed,
        "availability": round(len(ok) / max(n, 1), 4),
        "slo_miss_rate": round(
            n_deadline / max(len(ok) + n_deadline, 1), 4
        ),
        "shed_by_lane": shed_by_lane,
    }
    if ok:
        row.update(_pcts(ok))
    row.update(extra)
    return row


def run_fleet(replicas, clients, phase_s, deadline_s, batch_frac,
              hidden, batch, buckets):
    """Closed-loop load through a scripted fault schedule; one BENCH row
    per phase: steady -> kill->heal -> promote -> corrupt-rollback."""
    import shutil
    import signal
    import tempfile
    import threading

    from hydragnn_tpu.serve import (
        CanaryController,
        CanaryGates,
        CandidateChannel,
        FleetRouter,
        ServerOverloaded,
    )
    from hydragnn_tpu.serve.fleet import ServingFleet
    from hydragnn_tpu.serve.server import DeadlineExceeded

    workdir = tempfile.mkdtemp(prefix="hydragnn-fleet-bench-")
    rows = []
    try:
        spec_path, ckdir, arch, samples = _fleet_artifacts(
            workdir, hidden, batch, buckets
        )
        fleet = ServingFleet(
            os.path.join(workdir, "coord"),
            replicas,
            spec_path=spec_path,
            heartbeat_s=0.1,
            lease_s=0.75,
            poll_s=0.05,
            log_dir=os.path.join(workdir, "log"),
        )
        t0 = time.perf_counter()
        fleet.start(wait_serving=True, timeout=300)
        boot_s = time.perf_counter() - t0
        router = FleetRouter(
            fleet.coord_dir,
            lease_s=0.75,
            scan_interval_s=0.1,
            max_attempts=6,
            retry_base_delay_s=0.05,
        )

        stop = threading.Event()
        lock = threading.Lock()
        phase = ["steady"]
        recs = {}  # phase -> [(latency_s, outcome, lane)]

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                g = samples[int(rng.integers(0, len(samples)))]
                lane = (
                    "batch" if rng.random() < batch_frac else "default"
                )
                t1 = time.perf_counter()
                try:
                    router.route(g, lane=lane, deadline_s=deadline_s)
                    outcome = "ok"
                except ServerOverloaded:
                    outcome = "shed"
                except DeadlineExceeded:
                    outcome = "deadline"
                except Exception:
                    outcome = "failed"
                with lock:
                    recs.setdefault(phase[0], []).append(
                        (time.perf_counter() - t1, outcome, lane)
                    )

        threads = [
            threading.Thread(target=client, args=(1000 + i,), daemon=True)
            for i in range(clients)
        ]
        for t in threads:
            t.start()

        controller = None
        dec_promote = dec_reject = None
        canary_promote_s = canary_reject_s = float("nan")
        try:
            # phase 1: steady state
            time.sleep(phase_s)

            # phase 2: SIGKILL one replica mid-load -> detect + respawn
            with lock:
                phase[0] = "kill_heal"
            victim = replicas - 1
            os.kill(fleet.replica_pid(victim), signal.SIGKILL)
            t_kill = time.perf_counter()
            deadline = t_kill + 300
            while time.perf_counter() < deadline:
                if fleet.metrics.snapshot()["replica_respawns_total"] >= 1:
                    break
                time.sleep(0.05)
            heal_s = time.perf_counter() - t_kill
            time.sleep(phase_s)  # measure the healed fleet under load

            # phase 3: zero-downtime hot-swap promote
            with lock:
                phase[0] = "promote"
            t1 = time.perf_counter()
            res = fleet.promote(
                "cand", path=ckdir, arch_config=arch, name="bench",
                timeout=300,
            )
            promote_s = time.perf_counter() - t1
            time.sleep(phase_s)

            # phase 4: corrupt candidate -> loud rollback, v2 keeps serving
            with lock:
                phase[0] = "rollback"
            t1 = time.perf_counter()
            res2 = fleet.promote(
                "broken", path=ckdir, arch_config=arch, name="bench",
                timeout=300,
            )
            rollback_s = time.perf_counter() - t1
            time.sleep(phase_s)

            # phase 5: canary shadow-promotion — publish a candidate,
            # mirror half the live 200s into a subprocess canary, pass
            # the gates, all-acked hot-swap. Tolerances are wide open
            # (the bumped candidate legitimately disagrees with the
            # active version); the row prices the SHADOW PATH — live
            # latency vs the steady row, samples/shed, gate latency.
            with lock:
                phase[0] = "canary_promote"
            channel = CandidateChannel(os.path.join(workdir, "chan"))
            controller = CanaryController(
                fleet,
                channel,
                spec_path,
                fraction=0.5,
                gates=CanaryGates(
                    min_samples=8,
                    min_bucket_samples=1,
                    head_mae_tol=100.0,
                    head_mae_rel_tol=100.0,
                    latency_ratio_tol=100.0,
                    latency_slack_s=5.0,
                    max_crashes=1,
                    decide_timeout_s=300.0,
                ),
                poll_s=0.05,
                boot_timeout_s=240.0,
                heartbeat_s=0.1,
            )
            controller.attach(router)
            controller.start()
            t1 = time.perf_counter()
            channel.publish("cand", ckdir, note="bench")
            dec_promote = controller.wait_decision(1, timeout=300.0)
            canary_promote_s = time.perf_counter() - t1
            time.sleep(phase_s)

            # phase 6: CRC-corrupt candidate — the canary replica's
            # strict load refuses it at boot, the controller burns the
            # respawn budget and rejects with crash_loop; the fleet
            # never swaps and live traffic never notices
            with lock:
                phase[0] = "canary_reject"
            t1 = time.perf_counter()
            channel.publish("broken", ckdir, note="bench-corrupt")
            dec_reject = controller.wait_decision(2, timeout=300.0)
            canary_reject_s = time.perf_counter() - t1
            time.sleep(phase_s)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
            if controller is not None:
                controller.stop()
            fleet.stop()

        with lock:
            per_phase = {p: list(v) for p, v in recs.items()}
        snap = router.metrics.snapshot()
        rows.append(_phase_row(
            "steady", per_phase.get("steady", []), deadline_s,
            replicas=replicas, clients=clients, boot_s=round(boot_s, 2),
        ))
        rows.append(_phase_row(
            "kill_heal", per_phase.get("kill_heal", []), deadline_s,
            heal_s=round(heal_s, 2),
        ))
        rows.append(_phase_row(
            "promote", per_phase.get("promote", []), deadline_s,
            promote_s=round(promote_s, 2),
            promote_status=res["status"],
        ))
        rows.append(_phase_row(
            "rollback", per_phase.get("rollback", []), deadline_s,
            rollback_s=round(rollback_s, 2),
            rollback_status=res2["status"],
        ))
        if dec_promote is not None:
            snapc = controller.metrics.snapshot()
            rows.append(_phase_row(
                "canary_promote", per_phase.get("canary_promote", []),
                deadline_s,
                canary_decision_s=round(canary_promote_s, 2),
                canary_verdict=dec_promote["verdict"],
                gate_latency_s=dec_promote.get("gate_latency_s"),
                shadow_samples=int(snapc.get("shadow_samples_total", 0)),
                shadow_shed=int(snapc.get("shadow_shed_total", 0)),
            ))
        if dec_reject is not None:
            rows.append(_phase_row(
                "canary_reject", per_phase.get("canary_reject", []),
                deadline_s,
                canary_decision_s=round(canary_reject_s, 2),
                canary_verdict=dec_reject["verdict"],
                canary_reason=dec_reject.get("reason"),
            ))
        everything = [r for v in per_phase.values() for r in v]
        rows.append(_phase_row(
            "overall", everything, deadline_s,
            slo_miss_ratio_router=snap["slo_miss_ratio"],
        ))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return rows


def run_fleet_drift(replicas, clients, phase_s, deadline_s, hidden,
                    batch, buckets, window=128, shift_scale=4.0):
    """Model-quality observatory phases: a fresh fleet with drift
    detection armed serves steady traffic (the pre-shift row must show
    ZERO alerts), then every client switches to input-scaled graphs
    mid-run and the row prices detection latency — wall seconds from the
    shift to the first raised ``drift_alert``. ``HYDRAGNN_DRIFT_RAISE=1``
    here so one scored window over threshold raises: "detected within
    one reporting window" is the acceptance bar, not hysteresis depth."""
    import shutil
    import tempfile
    import threading

    from hydragnn_tpu.obs.drift import load_quality_events
    from hydragnn_tpu.serve import FleetRouter, ServerOverloaded
    from hydragnn_tpu.serve.fleet import ServingFleet
    from hydragnn_tpu.serve.server import DeadlineExceeded

    workdir = tempfile.mkdtemp(prefix="hydragnn-drift-bench-")
    knobs = {
        "HYDRAGNN_DRIFT_WINDOW": str(window),
        "HYDRAGNN_DRIFT_RAISE": "1",
        "HYDRAGNN_DRIFT_CLEAR": "2",
        # thresholds sit well above the finite-window noise floor of the
        # fixed sample pool (measured worst-case same-distribution PSI
        # ~0.21 / KS ~0.18 at window 128) so the pre-shift row cannot
        # flap, while the injected scale shift scores PSI > 2 / KS > 0.7
        "HYDRAGNN_DRIFT_PSI": "0.8",
        "HYDRAGNN_DRIFT_KS": "0.45",
    }
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    rows = []
    t_shift_wall = None
    detect_s = None
    try:
        spec_path, ckdir, arch, samples = _fleet_artifacts(
            workdir, hidden, batch, buckets
        )
        fleet = ServingFleet(
            os.path.join(workdir, "coord"),
            replicas,
            spec_path=spec_path,
            heartbeat_s=0.1,
            lease_s=0.75,
            poll_s=0.05,
            log_dir=os.path.join(workdir, "log"),
        )
        t0 = time.perf_counter()
        fleet.start(wait_serving=True, timeout=300)
        boot_s = time.perf_counter() - t0
        router = FleetRouter(
            fleet.coord_dir,
            lease_s=0.75,
            scan_interval_s=0.1,
            max_attempts=6,
            retry_base_delay_s=0.05,
        )

        stop = threading.Event()
        shifted = threading.Event()
        lock = threading.Lock()
        phase = ["drift_steady"]
        recs = {}

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                g = samples[int(rng.integers(0, len(samples)))]
                if shifted.is_set():
                    # the injected input-distribution shift: scaled
                    # features/positions on a CLONE, the originals keep
                    # defining the reference distribution
                    g = g.clone()
                    g.x = np.asarray(g.x) * shift_scale
                    if g.pos is not None:
                        g.pos = np.asarray(g.pos) * shift_scale
                t1 = time.perf_counter()
                try:
                    router.route(g, deadline_s=deadline_s)
                    outcome = "ok"
                except ServerOverloaded:
                    outcome = "shed"
                except DeadlineExceeded:
                    outcome = "deadline"
                except Exception:
                    outcome = "failed"
                with lock:
                    recs.setdefault(phase[0], []).append(
                        (time.perf_counter() - t1, outcome, "default")
                    )

        threads = [
            threading.Thread(target=client, args=(2000 + i,), daemon=True)
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        try:
            # pre-shift: long enough to close the bootstrap-reference
            # window plus at least one scored (alert-eligible) window
            time.sleep(phase_s)
            t_shift_wall = time.time()
            with lock:
                phase[0] = "drift_shift"
            shifted.set()
            t1 = time.perf_counter()
            poll_deadline = t1 + 120.0
            while time.perf_counter() < poll_deadline:
                raised = [
                    r
                    for r in load_quality_events(fleet.coord_dir)
                    if r.get("event") == "drift_alert"
                    and r.get("status") == "raised"
                    and float(r.get("ts") or 0.0) >= t_shift_wall
                ]
                if raised:
                    detect_s = time.perf_counter() - t1
                    break
                time.sleep(0.2)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
            fleet.stop()

        with lock:
            per_phase = {p: list(v) for p, v in recs.items()}
        records = load_quality_events(fleet.coord_dir)
        pre_alerts = sum(
            1
            for r in records
            if r.get("event") == "drift_alert"
            and r.get("status") == "raised"
            and t_shift_wall is not None
            and float(r.get("ts") or 0.0) < t_shift_wall
        )
        windows = sum(
            1 for r in records if r.get("event") == "drift_window"
        )
        rows.append(_phase_row(
            "drift_steady", per_phase.get("drift_steady", []), deadline_s,
            replicas=replicas, clients=clients, boot_s=round(boot_s, 2),
            drift_window=window, pre_shift_alerts=pre_alerts,
        ))
        rows.append(_phase_row(
            "drift_shift", per_phase.get("drift_shift", []), deadline_s,
            shift_scale=shift_scale,
            detected=detect_s is not None,
            detect_s=round(detect_s, 2) if detect_s is not None else None,
            windows_evaluated=windows,
        ))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(workdir, ignore_errors=True)
    return rows


# ---- multi-tenant diurnal capacity bench (ISSUE 17) ------------------------

# one synthetic "day": phase name -> load multiplier on --base-rps. The
# peak is sized to overrun the configured per-replica capacity so the
# autoscaler has something to do; the trough is where it walks back.
DIURNAL_CURVE = [
    ("trough", 0.3),
    ("ramp", 1.0),
    ("peak", 3.0),
    ("evening", 0.5),
]


def _tenantize_spec(spec_path, ckdir, arch, tenants):
    """Rewrite the fleet spec for N tenants: odd tenants pin the bumped
    'cand' checkpoint (two DISTINCT models HBM-packed per replica), even
    tenants share the base model; response cache on."""
    with open(spec_path) as f:
        spec = json.load(f)
    names = []
    for i in range(tenants):
        t = {"name": f"t{i}", "quota": 32}
        if i % 2 == 1:
            t["model"] = "cand"
            t["checkpoint"] = {
                "name": "cand", "path": ckdir, "arch": arch,
            }
        else:
            t["model"] = spec["model_name"]
        names.append(t["name"])
        spec.setdefault("tenants", []).append(t)
    spec["cache"] = {"enabled": True}
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    return names


def _fleet_bills(router):
    """Merged per-replica cost bills scraped off the live ``/healthz``
    endpoints (serve/costs.py: every replica's ledger rides its health
    body) — the fleet-global statement per-phase pricing diffs."""
    import urllib.request

    from hydragnn_tpu.serve import costs as costs_mod

    bills = []
    for _rid, port in router.live_replicas():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as resp:
                body = json.loads(resp.read())
        except Exception:
            continue
        if body.get("costs"):
            bills.append(body["costs"])
    return costs_mod.merge_bills(bills)


def _diurnal_row(label, recs, deadline_s, tenant_names,
                 tenant_costs=None, **extra):
    """One BENCH row per diurnal phase: fleet-wide aggregates plus the
    per-tenant p99/SLO-miss split the capacity model prices."""
    ok = [l for l, o, _, _ in recs if o == "ok"]
    n_deadline = sum(1 for _, o, _, _ in recs if o == "deadline")
    row = {
        "mode": "fleet_diurnal",
        "phase": label,
        "deadline_ms": round(deadline_s * 1e3, 1),
        "submitted": len(recs),
        "ok": len(ok),
        "shed": sum(1 for _, o, _, _ in recs if o == "shed"),
        "deadline_missed": n_deadline,
        "failed": sum(1 for _, o, _, _ in recs if o == "failed"),
        "availability": round(len(ok) / max(len(recs), 1), 4),
        "slo_miss_rate": round(
            n_deadline / max(len(ok) + n_deadline, 1), 4
        ),
    }
    if ok:
        row.update(_pcts(ok))
    per_tenant = {}
    for name in tenant_names:
        t_ok = [l for l, o, _, t in recs if t == name and o == "ok"]
        t_dl = sum(
            1 for _, o, _, t in recs if t == name and o == "deadline"
        )
        sub = {
            "ok": len(t_ok),
            "shed": sum(
                1 for _, o, _, t in recs if t == name and o == "shed"
            ),
            "slo_miss_rate": round(
                t_dl / max(len(t_ok) + t_dl, 1), 4
            ),
        }
        if t_ok:
            sub["p99_ms"] = _pcts(t_ok)["p99_ms"]
        if tenant_costs and name in tenant_costs:
            sub.update(tenant_costs[name])
        per_tenant[name] = sub
    row["per_tenant"] = per_tenant
    row.update(extra)
    return row


def run_fleet_diurnal(tenants, lanes, replicas, clients, phase_s, periods,
                      deadline_s, base_rps, capacity_rps,
                      cost_per_replica_hour, unique_frac, hidden, batch,
                      buckets):
    """N tenants x M lanes x a repeating diurnal curve against an
    autoscaled fleet — the ROADMAP capacity model. Each phase row prices
    cost-per-million-requests from integrated replica-seconds; the
    second period is where the forecast starts anticipating the peak."""
    import copy
    import shutil
    import tempfile
    import threading

    from hydragnn_tpu.serve import (
        AutoscalePolicy,
        FleetAutoscaler,
        FleetRouter,
        ResponseCache,
        ServerOverloaded,
    )
    from hydragnn_tpu.serve.fleet import ServingFleet
    from hydragnn_tpu.serve.server import DeadlineExceeded

    workdir = tempfile.mkdtemp(prefix="hydragnn-mt-bench-")
    rows = []
    try:
        spec_path, ckdir, arch, samples = _fleet_artifacts(
            workdir, hidden, batch, buckets
        )
        tenant_names = _tenantize_spec(spec_path, ckdir, arch, tenants)
        fleet = ServingFleet(
            os.path.join(workdir, "coord"),
            replicas,
            spec_path=spec_path,
            heartbeat_s=0.1,
            lease_s=0.75,
            poll_s=0.05,
            log_dir=os.path.join(workdir, "log"),
        )
        t0 = time.perf_counter()
        fleet.start(wait_serving=True, timeout=300)
        boot_s = time.perf_counter() - t0
        lane_names = [f"l{p}" for p in range(lanes)]
        from hydragnn_tpu.obs.trace import Tracer

        router = FleetRouter(
            fleet.coord_dir,
            lease_s=0.75,
            scan_interval_s=0.1,
            max_attempts=6,
            retry_base_delay_s=0.05,
            lanes={name: p for p, name in enumerate(lane_names)},
            cache=ResponseCache(capacity=2048, max_bytes=64 << 20),
            # off unless HYDRAGNN_TRACE_SAMPLE is set: spans land in the
            # fleet's event stream for the obs trace CLI
            tracer=Tracer.from_env(fleet.emit),
        )
        scaler = FleetAutoscaler(
            fleet,
            signals=router.autoscale_signals,
            policy=AutoscalePolicy(
                min_replicas=replicas,
                max_replicas=replicas + 2,
                capacity_rps=capacity_rps,
                slo_budget=0.05,
                up_cooldown_s=phase_s / 2,
                down_cooldown_s=phase_s,
                period_s=phase_s * len(DIURNAL_CURVE),
                n_phases=len(DIURNAL_CURVE),
            ),
            interval_s=max(phase_s / 8, 0.5),
        ).start()

        stop = threading.Event()
        lock = threading.Lock()
        phase = [f"p0.{DIURNAL_CURVE[0][0]}"]
        mult = [DIURNAL_CURVE[0][1]]
        recs = {}  # phase -> [(latency_s, outcome, lane, tenant)]

        def client(idx):
            rng = np.random.default_rng(4000 + idx)
            while not stop.is_set():
                target = base_rps * mult[0]
                interval = clients / max(target, 1e-6)
                g = samples[int(rng.integers(len(samples)))]
                if rng.random() < unique_frac:
                    # a never-seen structure: must MISS the response
                    # cache and land on a replica (the repeat fraction
                    # is what the cache absorbs for free)
                    g = copy.deepcopy(g)
                    g.pos = (
                        g.pos
                        + rng.normal(scale=1e-3, size=g.pos.shape)
                    ).astype(np.float32)
                tenant = tenant_names[int(rng.integers(tenants))]
                lane = lane_names[int(rng.integers(lanes))]
                t1 = time.perf_counter()
                try:
                    router.route(
                        g, lane=lane, tenant=tenant, deadline_s=deadline_s
                    )
                    outcome = "ok"
                except ServerOverloaded:
                    outcome = "shed"
                except DeadlineExceeded:
                    outcome = "deadline"
                except Exception:
                    outcome = "failed"
                elapsed = time.perf_counter() - t1
                with lock:
                    recs.setdefault(phase[0], []).append(
                        (elapsed, outcome, lane, tenant)
                    )
                pause = interval - elapsed
                if pause > 0:
                    stop.wait(min(pause, 0.5))

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(clients)
        ]
        for t in threads:
            t.start()

        phase_meta = {}
        try:
            for period in range(periods):
                for name, m in DIURNAL_CURVE:
                    label = f"p{period}.{name}"
                    with lock:
                        phase[0] = label
                        mult[0] = m
                    target0 = fleet.target
                    replica_s = 0.0
                    t_phase = time.perf_counter()
                    while time.perf_counter() - t_phase < phase_s:
                        time.sleep(0.1)
                        replica_s += 0.1 * fleet.target
                    phase_meta[label] = {
                        "load_multiplier": m,
                        "target_rps": round(base_rps * m, 1),
                        "fleet_target_start": target0,
                        "fleet_target_end": fleet.target,
                        "replica_s": replica_s,
                        # cumulative fleet ledger at phase end: per-phase
                        # tenant attribution diffs consecutive snapshots
                        "bill": _fleet_bills(router),
                    }
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
            scaler.stop()
            final_bill = _fleet_bills(router)
            for name, trow in sorted(final_bill.get("tenants", {}).items()):
                fleet.emit(
                    "tenant_cost", tenant=name,
                    device_s=trow["device_s"], flops=trow["flops"],
                    requests=trow["requests"],
                    replica_s=final_bill["replica_s"],
                )
            cs = router.cache.stats()
            fleet.emit(
                "cache_stats", hits=cs["hits"], misses=cs["misses"],
                evictions=cs["evictions"], bytes=cs["bytes"],
            )
            fleet.stop()

        with lock:
            per_phase = {p: list(v) for p, v in recs.items()}
        total_replica_s = total_ok = 0
        prev_device: dict = {}
        for label, meta in phase_meta.items():
            phase_recs = per_phase.get(label, [])
            n_ok = sum(1 for _, o, _, _ in phase_recs if o == "ok")
            cost = (
                meta["replica_s"] / 3600.0 * cost_per_replica_hour
            )
            # per-tenant cost attribution: this phase's device-second
            # deltas apportion the phase's replica cost (CostLedger
            # bills per dispatched batch, so the shares price real
            # device time, not request counts)
            bill = meta.get("bill") or {}
            deltas = {}
            for name, trow in (bill.get("tenants") or {}).items():
                d = trow["device_s"] - prev_device.get(name, 0.0)
                deltas[name] = max(d, 0.0)
            if bill.get("tenants"):
                prev_device = {
                    n: r["device_s"] for n, r in bill["tenants"].items()
                }
            busy_delta = sum(deltas.values())
            tenant_costs = {
                name: {
                    "device_s": round(d, 6),
                    "cost_share": round(
                        d / busy_delta if busy_delta > 0 else 0.0, 4
                    ),
                    "cost": round(
                        cost * (d / busy_delta) if busy_delta > 0
                        else 0.0, 6
                    ),
                }
                for name, d in deltas.items()
            }
            rows.append(_diurnal_row(
                label, phase_recs, deadline_s, tenant_names,
                tenant_costs=tenant_costs,
                **{k: v for k, v in meta.items()
                   if k not in ("replica_s", "bill")},
                cost_per_m_req=round(cost / max(n_ok, 1) * 1e6, 4),
            ))
            total_replica_s += meta["replica_s"]
            total_ok += n_ok
        everything = [r for v in per_phase.values() for r in v]
        total_cost = total_replica_s / 3600.0 * cost_per_replica_hour
        from hydragnn_tpu.serve import costs as costs_mod

        os.environ["HYDRAGNN_COST_PER_REPLICA_HOUR"] = str(
            cost_per_replica_hour
        )
        cum_costs = {
            name: {"device_s": trow["device_s"],
                   "cost_share": trow.get("cost_share", 0.0)}
            for name, trow in final_bill.get("tenants", {}).items()
        }
        rows.append(_diurnal_row(
            "overall", everything, deadline_s, tenant_names,
            tenant_costs=cum_costs,
            tenants=tenants, lanes=lanes, periods=periods,
            clients=clients, boot_s=round(boot_s, 2),
            cache_hit_ratio=cs["hit_ratio"],
            replica_s=round(total_replica_s, 1),
            cost_per_m_req=round(
                total_cost / max(total_ok, 1) * 1e6, 4
            ),
            ledger=costs_mod.price_per_million(final_bill, total_ok),
        ))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return rows


def main():
    if _arg("fleet") and (_arg("tenants") or _arg("diurnal")):
        tenants = _arg("tenants", 2)
        for row in run_fleet_diurnal(
            tenants=2 if tenants is True else int(tenants),
            lanes=int(_arg("lanes", 2)),
            replicas=int(_arg("replicas", 2)),
            clients=int(_arg("clients", 6)),
            phase_s=float(_arg("phase-s", 5)),
            periods=int(_arg("periods", 2)),
            deadline_s=float(_arg("deadline-ms", 2000)) / 1e3,
            base_rps=float(_arg("base-rps", 24)),
            capacity_rps=float(_arg("capacity-rps", 20)),
            cost_per_replica_hour=float(
                _arg("cost-per-replica-hour", 1.0)
            ),
            unique_frac=float(_arg("unique-frac", 0.7)),
            hidden=int(_arg("hidden", 16)),
            batch=int(_arg("batch", 4)),
            buckets=int(_arg("buckets", 2)),
        ):
            print(json.dumps(row), flush=True)
        return
    if _arg("fleet"):
        for row in run_fleet(
            replicas=int(_arg("replicas", 2)),
            clients=int(_arg("clients", 4)),
            phase_s=float(_arg("phase-s", 4)),
            deadline_s=float(_arg("deadline-ms", 2000)) / 1e3,
            batch_frac=float(_arg("batch-frac", 0.25)),
            hidden=int(_arg("hidden", 16)),
            batch=int(_arg("batch", 4)),
            buckets=int(_arg("buckets", 2)),
        ):
            print(json.dumps(row), flush=True)
        # model-quality phases run on their OWN fleet (drift knobs are
        # process-spawn env; the fault schedule above must stay
        # detector-free so its promote/rollback rows price serving, not
        # alert bookkeeping)
        for row in run_fleet_drift(
            replicas=int(_arg("replicas", 2)),
            clients=int(_arg("clients", 4)),
            phase_s=float(_arg("phase-s", 4)),
            deadline_s=float(_arg("deadline-ms", 2000)) / 1e3,
            hidden=int(_arg("hidden", 16)),
            batch=int(_arg("batch", 4)),
            buckets=int(_arg("buckets", 2)),
        ):
            print(json.dumps(row), flush=True)
        return
    num = int(_arg("num", 512))
    clients = int(_arg("clients", 8))
    buckets = int(_arg("buckets", 3))
    batch = int(_arg("batch", 8))
    hidden = int(_arg("hidden", 64))
    wait_ms = float(_arg("wait-ms", 5))
    requests = _oc20_requests(num)
    registry, plan = _build(requests, hidden, batch, buckets)
    print(json.dumps(run_naive(registry, plan, requests)))
    print(json.dumps(run_server(registry, plan, requests, clients, wait_ms)))


if __name__ == "__main__":
    main()
