#!/bin/bash
# Round-5 north-star re-runs: the two rows whose round-4 validation was
# flat (OC20+DimeNet, MPtrj+EGNN — now with learnable continuous targets)
# plus a fresh GFM row on the composed path (spd from gfm.json).
# Sequential — they share the one chip. Logs under /tmp/northstar_r5/.
set -u
OUT=${1:-/tmp/northstar_r5}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "=== OC20 extxyz + DimeNet (20k frames, shard store) ===" > "$OUT/status"
( cd examples/open_catalyst_2020 && rm -rf dataset/OC20R5* && time python train.py \
    --preonly --num_samples 20000 --modelname OC20R5 ) \
  > "$OUT/oc20_preonly.log" 2>&1
echo "oc20 preonly rc=$?" >> "$OUT/status"
( cd examples/open_catalyst_2020 && time python train.py \
    --modelname OC20R5 --model_type DimeNet --hidden_dim 128 \
    --num_epoch 10 ) \
  > "$OUT/oc20.log" 2>&1
echo "oc20 rc=$?" >> "$OUT/status"

echo "=== MPtrj + EGNN (20k trajectories = 120k frames) ===" >> "$OUT/status"
( cd examples/mptrj && rm -rf dataset/mptrj && time python train.py \
    --num_samples 20000 --max_frames all --num_epoch 10 \
    --log_name_suffix scale ) \
  > "$OUT/mptrj.log" 2>&1
echo "mptrj rc=$?" >> "$OUT/status"

echo "=== Multidataset GFM (3 x 40k, steps_per_dispatch from gfm.json) ===" >> "$OUT/status"
( cd examples/multidataset && time python train.py --preonly \
    --num_samples 40000 ) \
  > "$OUT/gfm_preonly.log" 2>&1
echo "gfm preonly rc=$?" >> "$OUT/status"
( cd examples/multidataset && time python train.py --num_samples 40000 \
    --hidden_dim 128 --num_epoch 10 ) \
  > "$OUT/gfm.log" 2>&1
echo "gfm rc=$?" >> "$OUT/status"
echo "ALL DONE" >> "$OUT/status"
