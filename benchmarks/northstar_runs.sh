#!/bin/bash
# North-star dataset-scale runs on the composed production path
# (buckets + contiguous_buckets + steps_per_dispatch + streaming).
# Sequential — they share the one chip. Logs under /tmp/northstar/.
set -u
OUT=${1:-/tmp/northstar}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "=== QM9 composed (133,885 molecules) ===" > "$OUT/status"
( cd examples/qm9 && time python qm9.py --num_samples 133885 ) \
  > "$OUT/qm9.log" 2>&1
echo "qm9 rc=$?" >> "$OUT/status"

echo "=== MD17 + SchNet energy+forces (100k conformations) ===" >> "$OUT/status"
( cd examples/md17 && time python md17.py --model_type SchNet \
    --num_samples 100000 --num_epoch 10 --log_name_suffix scale ) \
  > "$OUT/md17.log" 2>&1
echo "md17 rc=$?" >> "$OUT/status"

echo "=== OC20 extxyz + DimeNet (20k frames, shard store) ===" >> "$OUT/status"
( cd examples/open_catalyst_2020 && time python train.py --preonly \
    --num_samples 20000 --modelname OC20R4 ) \
  > "$OUT/oc20_preonly.log" 2>&1
echo "oc20 preonly rc=$?" >> "$OUT/status"
( cd examples/open_catalyst_2020 && time python train.py \
    --modelname OC20R4 --model_type DimeNet --hidden_dim 128 \
    --num_epoch 10 ) \
  > "$OUT/oc20.log" 2>&1
echo "oc20 rc=$?" >> "$OUT/status"

echo "=== MPtrj + EGNN (20k trajectories = 120k frames) ===" >> "$OUT/status"
( cd examples/mptrj && time python train.py --num_samples 20000 \
    --max_frames all --num_epoch 10 --log_name_suffix scale ) \
  > "$OUT/mptrj.log" 2>&1
echo "mptrj rc=$?" >> "$OUT/status"
echo "ALL DONE" >> "$OUT/status"
