"""Streaming-path H2D/compute overlap A/B (round-3 verdict item 5).

Trains PNA fed by the streaming ``GraphLoader`` (host->device transfer
per batch — the production path for datasets too big for HBM residency)
with the double-buffered device prefetch ON vs OFF, all else equal.
Fence-true: the epoch's accumulated-metric readback materializes host
bytes, so wall-clock includes every transfer and step.

Usage: ``python benchmarks/streaming_bench.py [--num=2048] [--batch=64]
[--hidden=128] [--epochs=3] [--depth=2] [--host_prefetch=2]``
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.bucket_bench import _oc20_samples  # noqa: E402
from benchmarks.model_bench import _arg, _arch  # noqa: E402


def run(samples, batch_size, hidden, epochs, depth, host_prefetch):
    import jax

    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    layout = compute_layout([samples], batch_size)
    loader = GraphLoader(
        samples, batch_size, layout, shuffle=True, prefetch=host_prefetch
    )
    model = create_model_config(_arch("PNA", hidden, 3, 250))
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            "device_prefetch": depth,
        },
    )
    state = trainer.init_state(next(iter(loader)))
    rng = jax.random.PRNGKey(0)
    # warmup epoch: compile + first-touch
    state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    t0 = time.perf_counter()
    for ep in range(epochs):
        loader.set_epoch(ep + 1)
        state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    assert np.isfinite(loss)
    dt = (time.perf_counter() - t0) / epochs
    return {
        "device_prefetch": depth,
        "host_prefetch": host_prefetch,
        "epoch_sec": round(dt, 3),
        "graphs_per_sec": round(len(samples) / dt, 1),
        "loss": round(float(loss), 5),
    }


def main():
    num = int(_arg("num", 2048))
    batch = int(_arg("batch", 64))
    hidden = int(_arg("hidden", 128))
    epochs = int(_arg("epochs", 3))
    depth = int(_arg("depth", 2))
    host_prefetch = int(_arg("host_prefetch", 2))
    samples = _oc20_samples(num)
    rows = []
    # interleaved ABAB so the tunneled chip's ±30% tenant-contention
    # drift cancels instead of landing on one arm
    for d in (0, depth, 0, depth):
        rows.append(run(samples, batch, hidden, epochs, d, host_prefetch))
        print(json.dumps(rows[-1]), flush=True)
    off = np.mean([r["graphs_per_sec"] for r in rows if not r["device_prefetch"]])
    on = np.mean([r["graphs_per_sec"] for r in rows if r["device_prefetch"]])
    print(json.dumps({"overlap_speedup": round(float(on / off), 3)}))


if __name__ == "__main__":
    main()
