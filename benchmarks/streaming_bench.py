"""Streaming-path benchmarks.

Default mode — H2D/compute overlap A/B (round-3 verdict item 5): trains
PNA fed by the streaming ``GraphLoader`` (host->device transfer per
batch — the production path for datasets too big for HBM residency) with
the double-buffered device prefetch ON vs OFF, all else equal.
Fence-true: the epoch's accumulated-metric readback materializes host
bytes, so wall-clock includes every transfer and step.

``--mix`` mode — the shard-native streaming pipeline end to end
(``hydragnn_tpu/data/stream/``): a two-source weighted mix (QM9-shaped +
OC20-shaped) through WeightedMix -> auto-tuned BucketPlanner ->
StreamLoader, reporting ingestion-side numbers (graphs/sec, sample
bytes/sec, pipeline stall share, measured padding waste, peak window
residency) as a ``BENCH_*``-style JSON row so the perf trajectory covers
ingestion, not just steps.

Usage: ``python benchmarks/streaming_bench.py [--num=2048] [--batch=64]
[--hidden=128] [--epochs=3] [--depth=2] [--host_prefetch=2] [--mix]
[--out=FILE]``
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.bucket_bench import _oc20_samples  # noqa: E402
from benchmarks.model_bench import _arg, _arch  # noqa: E402


def run(samples, batch_size, hidden, epochs, depth, host_prefetch):
    import jax

    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    layout = compute_layout([samples], batch_size)
    loader = GraphLoader(
        samples, batch_size, layout, shuffle=True, prefetch=host_prefetch
    )
    model = create_model_config(_arch("PNA", hidden, 3, 250))
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            "device_prefetch": depth,
        },
    )
    state = trainer.init_state(next(iter(loader)))
    rng = jax.random.PRNGKey(0)
    # warmup epoch: compile + first-touch
    state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    t0 = time.perf_counter()
    for ep in range(epochs):
        loader.set_epoch(ep + 1)
        state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    assert np.isfinite(loss)
    dt = (time.perf_counter() - t0) / epochs
    return {
        "device_prefetch": depth,
        "host_prefetch": host_prefetch,
        "epoch_sec": round(dt, 3),
        "graphs_per_sec": round(len(samples) / dt, 1),
        "loss": round(float(loss), 5),
    }


def _qm9_shaped(num, seed=3):
    """Small molecules (the QM9 end of a GFM mix) with the same head
    schema as the OC20-shaped generator so the two sources mix."""
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = int(rng.integers(4, 30))
        d = GraphData(
            x=rng.random((n, 1)).astype(np.float32),
            pos=rng.random((n, 3)).astype(np.float32),
        )
        src = np.arange(n)
        dst = (src + 1) % n
        d.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        d.targets = [np.asarray([d.x.sum()], np.float32), d.x.copy()]
        d.target_types = ["graph", "node"]
        out.append(d)
    return out


def run_mix(num, batch_size, hidden, epochs, host_prefetch):
    """The shard-native streaming pipeline end to end: weighted
    two-source mix -> auto bucket plan -> StreamLoader -> train. Returns
    one BENCH-style row of ingestion-side numbers."""
    import jax

    from hydragnn_tpu.data.stream import (
        BucketPlanner,
        ListSource,
        StreamLoader,
        WeightedMix,
    )
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    src_small = ListSource(
        _qm9_shaped(num // 2), shard_size=64, name="qm9_shaped"
    )
    src_large = ListSource(
        _oc20_samples(num // 2), shard_size=64, name="oc20_shaped"
    )
    mix = WeightedMix(
        [src_small, src_large], [1.0, 1.0], seed=11, num_shards=1,
        shard_id=0, window=2,
    )
    planner = BucketPlanner(
        mix.sources, batch_size, num_buckets=4
    )
    layout = planner.plan(emit=False)
    loader = StreamLoader(
        mix, batch_size, layout, prefetch=host_prefetch
    )
    model = create_model_config(_arch("PNA", hidden, 3, 250))
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
        },
    )
    state = trainer.init_state(loader.example_batch())
    rng = jax.random.PRNGKey(0)
    loader.set_epoch(0)
    state, rng, loss, _ = trainer.train_epoch(state, loader, rng)  # warmup
    t0 = time.perf_counter()
    graphs = 0
    stall_s = 0.0
    for ep in range(epochs):
        loader.set_epoch(ep + 1)
        state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
        # _epoch_stats is replaced per epoch — accumulate, don't
        # extrapolate the last epoch across the run
        graphs += loader._epoch_stats["samples"]
        stall_s += loader._epoch_stats["stall_s"]
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)
    real, padded = loader.epoch_padding_stats()
    res = mix.residency_stats()
    return {
        "mode": "mix",
        "sources": 2,
        "num_buckets": len(layout.layouts),
        "host_prefetch": host_prefetch,
        "epoch_sec": round(dt / epochs, 3),
        "graphs_per_sec": round(graphs / dt, 1),
        "stall_share": round(stall_s / dt, 4),
        "padding_waste": round(1.0 - real / padded, 4),
        "est_waste": round(planner.estimate_waste(layout), 4),
        "resident_bytes_peak": int(res["resident_bytes_peak"]),
        "open_shards_peak": int(res["open_shards_peak"]),
        "loss": round(float(loss), 5),
    }


def main():
    num = int(_arg("num", 2048))
    batch = int(_arg("batch", 64))
    hidden = int(_arg("hidden", 128))
    epochs = int(_arg("epochs", 3))
    depth = int(_arg("depth", 2))
    host_prefetch = int(_arg("host_prefetch", 2))
    if _arg("mix", False):
        row = run_mix(num, batch, hidden, epochs, host_prefetch)
        print(json.dumps(row), flush=True)
        out = _arg("out")
        if out and out is not True:
            # BENCH_*-style record: append-merge so rounds accumulate
            rows = []
            if os.path.exists(out):
                with open(out) as f:
                    rows = json.load(f)
            rows.append(row)
            with open(out, "w") as f:
                json.dump(rows, f, indent=1)
        return
    samples = _oc20_samples(num)
    rows = []
    # interleaved ABAB so the tunneled chip's ±30% tenant-contention
    # drift cancels instead of landing on one arm
    for d in (0, depth, 0, depth):
        rows.append(run(samples, batch, hidden, epochs, d, host_prefetch))
        print(json.dumps(rows[-1]), flush=True)
    off = np.mean([r["graphs_per_sec"] for r in rows if not r["device_prefetch"]])
    on = np.mean([r["graphs_per_sec"] for r in rows if r["device_prefetch"]])
    print(json.dumps({"overlap_speedup": round(float(on / off), 3)}))


if __name__ == "__main__":
    main()
