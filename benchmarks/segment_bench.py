"""Microbenchmark: pallas one-hot aggregation vs XLA scatter segment ops.

Run on a real TPU to decide the ``HYDRAGNN_PALLAS`` default:

    python benchmarks/segment_bench.py [--edges=100000] [--nodes=5000] [--dim=64]

Prints per-path step times for (a) plain segment_sum and (b) the PNA
statistic set (mean+std+count), forward and forward+grad.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _arg(flag, default):
    for a in sys.argv[1:]:
        if a.startswith(f"--{flag}="):
            return int(a.split("=", 1)[1])
    return default


def _fence(out):
    """True completion fence: materialize a result byte on the host.
    (``block_until_ready`` does not actually block on the tunneled axon
    backend — any timing relying on it measures dispatch rate, not compute.)"""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.ravel()[0])


def timeit(fn, *args, iters=50):
    out = fn(*args)
    _fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _fence(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def main():
    e, n, d = _arg("edges", 100_000), _arg("nodes", 5_000), _arg("dim", 64)
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.standard_normal((e, d)), jnp.float32)
    ids = jnp.asarray(np.sort(rng.integers(0, n, e)), jnp.int32)

    from hydragnn_tpu.ops import segment_moments, segment_sum_onehot

    @jax.jit
    def xla_sum(x):
        return jax.ops.segment_sum(x, ids, num_segments=n)

    @jax.jit
    def pls_sum(x):
        return segment_sum_onehot(x, ids, n)

    @jax.jit
    def xla_stats(x):
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(e), ids, num_segments=n).reshape(-1, 1)
        sq = jax.ops.segment_sum(x * x, ids, num_segments=n)
        mean = s / jnp.maximum(c, 1.0)
        return mean, jnp.sqrt(jnp.maximum(sq / jnp.maximum(c, 1.0) - mean**2, 0) + 1e-5)

    @jax.jit
    def pls_stats(x):
        s, c, sq = segment_moments(x, ids, n)
        mean = s / jnp.maximum(c, 1.0)
        return mean, jnp.sqrt(jnp.maximum(sq / jnp.maximum(c, 1.0) - mean**2, 0) + 1e-5)

    grad_xla = jax.jit(jax.grad(lambda x: sum(jnp.sum(o**2) for o in xla_stats(x))))
    grad_pls = jax.jit(jax.grad(lambda x: sum(jnp.sum(o**2) for o in pls_stats(x))))

    print(f"E={e} N={n} D={d} backend={jax.default_backend()}")
    print(f"segment_sum      xla {timeit(xla_sum, data):8.3f} ms   "
          f"pallas {timeit(pls_sum, data):8.3f} ms")
    print(f"pna stats (fwd)  xla {timeit(xla_stats, data):8.3f} ms   "
          f"pallas {timeit(pls_stats, data):8.3f} ms")
    print(f"pna stats (grad) xla {timeit(grad_xla, data):8.3f} ms   "
          f"pallas {timeit(grad_pls, data):8.3f} ms")


if __name__ == "__main__":
    main()
