"""Bucketed-layout A/B on the streaming path (round-3 verdict item 3).

Trains PNA over an OC20-shaped synthetic size distribution (log-normal
20-250 atoms) fed by the streaming ``GraphLoader``, single max-sized
layout vs N size buckets. Reports fence-true epoch wall-clock,
graphs/sec, and the padding efficiency of each configuration.

Usage: ``python benchmarks/bucket_bench.py [--buckets=4] [--num=2048]
[--batch=32] [--hidden=128] [--epochs=3]``
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.model_bench import _arg, _arch  # noqa: E402


def _oc20_samples(num, seed=0, degree=12):
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    sizes = np.clip(
        np.round(np.exp(rng.normal(np.log(60.0), 0.55, num))), 20, 250
    ).astype(int)
    out = []
    for n in sizes:
        d = GraphData(
            x=rng.random((int(n), 1)).astype(np.float32),
            pos=(rng.random((int(n), 3)) * n ** (1 / 3)).astype(np.float32),
        )
        src = np.repeat(np.arange(n), degree // 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        d.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        d.targets = [np.asarray([d.x.sum()], np.float32), d.x.copy()]
        d.target_types = ["graph", "node"]
        out.append(d)
    return out


def run(samples, batch_size, num_buckets, hidden, epochs, k_dispatch=1,
        contiguous=False):
    import jax

    from hydragnn_tpu.data.loaders import (
        GraphLoader,
        compute_layout,
        padding_efficiency,
    )
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    layout = compute_layout([samples], batch_size, num_buckets=num_buckets)
    eff = padding_efficiency([samples], layout, batch_size)
    loader = GraphLoader(
        samples, batch_size, layout, shuffle=True,
        contiguous_buckets=contiguous,
    )
    model = create_model_config(_arch("PNA", hidden, 3, 250))
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            "steps_per_dispatch": k_dispatch,
        },
    )
    state = trainer.init_state(next(iter(loader)))
    rng = jax.random.PRNGKey(0)
    # warm every bucket's compiled program before timing
    state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    t0 = time.perf_counter()
    for ep in range(epochs):
        loader.set_epoch(ep + 1)
        state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    assert np.isfinite(loss)
    dt = (time.perf_counter() - t0) / epochs
    return {
        "buckets": num_buckets,
        "steps_per_dispatch": k_dispatch,
        "contiguous": contiguous,
        "padding_efficiency": round(eff, 4),
        "epoch_sec": round(dt, 3),
        "graphs_per_sec": round(len(samples) / dt, 1),
        "loss": round(float(loss), 5),
    }


def run_device(samples, batch_size, num_buckets, hidden, iters=20):
    """Fence-true DEVICE time per epoch: per distinct batch shape, enqueue
    ``iters`` dispatches of the compiled step and fence once (the
    segment_bench methodology), then sum step-time x batch-count. Isolates
    compute from the tunneled link's host/dispatch overheads — the number
    a production TPU-VM host (microsecond dispatch, overlapped H2D) sees."""
    import jax

    from hydragnn_tpu.data.loaders import (
        GraphLoader,
        compute_layout,
        padding_efficiency,
    )
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    layout = compute_layout([samples], batch_size, num_buckets=num_buckets)
    eff = padding_efficiency([samples], layout, batch_size)
    loader = GraphLoader(samples, batch_size, layout, shuffle=False)
    by_shape = {}
    for b in loader:
        by_shape.setdefault(b.x.shape, [0, b])
        by_shape[b.x.shape][0] += 1
    model = create_model_config(_arch("PNA", hidden, 3, 250))
    trainer = Trainer(
        model,
        training_config={"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}},
    )
    first = next(iter(by_shape.values()))[1]
    state = trainer.init_state(first)
    rng = jax.random.PRNGKey(0)
    total = 0.0
    for shape, (count, batch) in by_shape.items():
        db = trainer.put_batch(batch)
        # deliberate fixed key: the bench times one fixed program per
        # shape; training statistics are irrelevant here
        state, m = trainer._train_step(state, db, rng)  # jaxlint: disable=prng-key-reuse
        np.asarray(m["loss"])  # fence
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = trainer._train_step(state, db, rng)  # jaxlint: disable=prng-key-reuse
        np.asarray(m["loss"])  # single true-completion fence
        total += (time.perf_counter() - t0) / iters * count
    return {
        "mode": "device_epoch",
        "buckets": num_buckets,
        "padding_efficiency": round(eff, 4),
        "device_epoch_sec": round(total, 3),
        "graphs_per_sec_device": round(len(samples) / total, 1),
    }


def main():
    import json

    num = int(_arg("num", 2048))
    batch = int(_arg("batch", 32))
    hidden = int(_arg("hidden", 128))
    epochs = int(_arg("epochs", 3))
    buckets = int(_arg("buckets", 4))
    kd = int(_arg("k", 8))
    samples = _oc20_samples(num)
    if _arg("device", False):
        print(json.dumps(run_device(samples, batch, 1, hidden)))
        print(json.dumps(run_device(samples, batch, buckets, hidden)))
        return
    print(json.dumps(run(samples, batch, 1, hidden, epochs)))
    print(json.dumps(run(samples, batch, buckets, hidden, epochs)))
    print(json.dumps(run(samples, batch, 1, hidden, epochs, k_dispatch=kd)))
    print(json.dumps(run(samples, batch, buckets, hidden, epochs,
                         k_dispatch=kd, contiguous=True)))


if __name__ == "__main__":
    main()
