"""DimeNet post-bmm stage profile (round-4 verdict item 5).

Times the composed stages of the bmm-path DimeNet step separately at the
BASELINE.md row scale (OC20 shape, hidden 128) so the 46 ms step's top
consumers are measured, not guessed:

  geometry   _dimenet_geometry_dense (rad/cbf transcendental chains)
  bmm        _bmm_triplet_aggregate (the round-4 rewrite)
  forward    full model.apply
  step       full jitted train step (fwd + loss + grad + AdamW)

Fence discipline: chained dispatches of the same program, one host
materialization at the end (block_until_ready does not block on the
tunneled axon backend — see benchmarks/model_bench.py).

Usage: python benchmarks/dimenet_profile.py [--hidden=128] [--iters=30]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.model_bench import _arch, _arg, _collate, make_graphs


def _time(fn, args, iters):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]  # warm fence
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]  # true fence
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def main():
    global jax
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.models.dimenet import (
        _bmm_triplet_aggregate,
        _dimenet_geometry_dense,
    )
    from hydragnn_tpu.models.common import TorchLinear
    from hydragnn_tpu.ops.dense_agg import attach_neighbor_lists
    from hydragnn_tpu.train.trainer import Trainer
    from hydragnn_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    hidden = int(_arg("hidden", 128))
    iters = int(_arg("iters", 30))
    bf16 = bool(_arg("bf16", False))
    num_graphs, nodes, degree = 64, 90, 12

    samples = make_graphs(num_graphs, nodes, degree, seed=0)
    batch = _collate(samples, num_graphs, nodes, degree, with_triplets=True)
    batch = attach_neighbor_lists(batch)
    arch = _arch("DimeNet", hidden, 3, nodes)
    model = create_model_config(arch)
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            "mixed_precision": bf16,
        },
    )
    state = trainer.init_state(batch)
    dbatch = trainer.put_batch(batch)
    rng = jax.random.PRNGKey(0)

    S, R = arch["num_spherical"], arch["num_radial"]
    cutoff, env = arch["radius"], arch["envelope_exponent"]

    geo = jax.jit(
        lambda pos: _dimenet_geometry_dense(dbatch, pos, S, R, cutoff, env)
    )
    t_geo = _time(geo, (dbatch.pos,), iters)

    dist, rad, cbf = geo(dbatch.pos)
    int_emb, basis_emb = arch["int_emb_size"], arch["basis_emb_size"]

    class BmmOnly(__import__("flax").linen.Module):
        @__import__("flax").linen.compact
        def __call__(self, x_down, rad, cbf):
            l1 = TorchLinear(basis_emb, use_bias=False, name="sbf1")
            l2 = TorchLinear(int_emb, use_bias=False, name="sbf2")
            return _bmm_triplet_aggregate(
                x_down, rad, cbf, l1, l2, dbatch, S, R
            )

    x_down = jnp.zeros((dbatch.senders.shape[0], int_emb), jnp.float32)
    bmm = BmmOnly()
    bmm_vars = bmm.init(rng, x_down, rad, cbf)
    bmm_fn = jax.jit(lambda v, xd: bmm.apply(v, xd, rad, cbf))
    t_bmm = _time(bmm_fn, (bmm_vars, x_down), iters)

    fwd = jax.jit(lambda p, b: model.apply({"params": p}, b, train=False))
    t_fwd = _time(fwd, (state.params, dbatch), iters)

    # ``state`` is DONATED by the compiled step: thread the returned state,
    # never reuse the pre-warm one (its buffers are gone after the warm call).
    # Fixed key on purpose: the profile times one fixed program.
    s, m = trainer._train_step(state, dbatch, rng)  # jaxlint: disable=prng-key-reuse
    np.asarray(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        s, m = trainer._train_step(s, dbatch, rng)  # jaxlint: disable=prng-key-reuse
    float(np.asarray(m["loss"]))
    t_step = (time.perf_counter() - t0) / iters * 1e3

    print(
        json.dumps(
            {
                "hidden": hidden,
                "precision": "bf16" if bf16 else "f32",
                "geometry_ms": round(t_geo, 2),
                "bmm_aggregate_ms": round(t_bmm, 2),
                "forward_ms": round(t_fwd, 2),
                "train_step_ms": round(t_step, 2),
                "graphs_per_sec": round(num_graphs / (t_step / 1e3), 1),
            }
        )
    )


if __name__ == "__main__":
    main()
