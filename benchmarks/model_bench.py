"""Per-model / MXU-scale train-step benchmark with MFU accounting.

Addresses the round-1 verdict's two measurement gaps: (a) only PNA was
benchmarked, (b) only a tiny op-latency-bound config (~18-node graphs,
hidden 64) was measured, so nothing showed what the TPU design achieves
when the MXU actually has work. This driver measures fence-true train-step
time for any model at any scale and reports achieved TFLOP/s and MFU next
to graphs/sec. FLOPs come from XLA's own cost model for the exact compiled
step (``.lower(...).compile().cost_analysis()``), not a hand count.

Fence discipline: ``block_until_ready`` does not block on the tunneled
axon backend — timings enqueue ``iters`` dispatches of the SAME program
(the device executes them back-to-back) and fence once by materializing a
result byte on the host, so elapsed/iters is true device step time
(same methodology as ``benchmarks/segment_bench.py``).

Usage: ``python benchmarks/model_bench.py --model=PNA --hidden=256
--graphs=64 --nodes=90 [--bf16] [--iters=20]`` or import
:func:`bench_model` (bench.py uses it for the extra BENCH rows).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# peak dense-matmul TFLOP/s per chip by device kind; used for the MFU
# denominator. Derived from the ONE peak table the goodput/MFU ledger
# owns (obs/ledger.PEAK_FLOPS) so the bench MFU and the live
# hydragnn_train_mfu gauge cannot drift. bf16 column on purpose: fp32
# rows report against the same denominator — conservative, since fp32
# peak is lower (the live gauge is precision-aware instead).
from hydragnn_tpu.obs.ledger import PEAK_FLOPS as _LEDGER_PEAK_FLOPS

_PEAK_TFLOPS = {
    kind: row["bf16"] / 1e12 for kind, row in _LEDGER_PEAK_FLOPS.items()
}
_DEFAULT_PEAK = 197.0


_FALSY = ("0", "false", "no", "off")
_BOOL_FLAGS = ("bf16", "dense", "remat")


def _arg(flag, default=None):
    for a in sys.argv[1:]:
        if a == f"--{flag}":
            return True
        if a.startswith(f"--{flag}="):
            v = a.split("=", 1)[1]
            # boolean spellings (--dense=0 / --bf16=false mean OFF) apply
            # only to the boolean flags; numeric flags pass through so
            # int() can validate them (--iters=0 must not become False)
            if flag in _BOOL_FLAGS:
                return v.lower() not in _FALSY
            return v
    return default


def make_graphs(num_graphs, nodes, degree, seed=0, node_jitter=True,
                input_dim=1):
    """Synthetic molecule-scale graphs: ~`nodes` atoms, `degree` incident
    edges per node (ring-offset structure — same construction as bench.py,
    scaled), positions random so distance-based models get real geometry.
    ``input_dim`` widens the node features — the effective conv width for
    constant-width stacks like CGCNN."""
    rng = np.random.default_rng(seed)

    class _S:
        pass

    out = []
    for _ in range(num_graphs):
        lo = max(2, nodes - 10)  # graphs need >= 2 nodes for ring edges
        n = int(rng.integers(lo, nodes + 1)) if node_jitter else max(2, nodes)
        s = _S()
        s.x = rng.random((n, input_dim)).astype(np.float32)
        s.pos = (rng.random((n, 3)) * n ** (1 / 3)).astype(np.float32)
        src = np.repeat(np.arange(n), degree // 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        d = np.linalg.norm(s.pos[s.edge_index[0]] - s.pos[s.edge_index[1]], axis=1)
        s.edge_attr = d[:, None].astype(np.float32)
        # node-head target stays 1-wide whatever the input width
        s.targets = [
            np.array([s.x.sum()], np.float32),
            s.x[:, :1].astype(np.float32),
        ]
        out.append(s)
    return out


def _arch(model_type, hidden, layers, nodes, input_dim=1):
    shared = max(32, hidden // 4)
    return {
        "model_type": model_type,
        "input_dim": input_dim,
        "hidden_dim": hidden,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": shared,
                "num_headlayers": 2,
                "dim_headlayers": [shared, shared],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [shared, shared],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": layers,
        "num_nodes": nodes,
        "edge_dim": None,
        "pna_deg": [0, 0, 16, 32, 64, 32],
        "equivariance": model_type == "EGNN",
        "max_neighbours": 50,
        "num_gaussians": 50,
        "num_filters": hidden,
        "radius": 5.0,
        "basis_emb_size": 8,
        "envelope_exponent": 5,
        "int_emb_size": 64,
        "out_emb_size": 128,
        "num_after_skip": 2,
        "num_before_skip": 1,
        "num_radial": 6,
        "num_spherical": 7,
    }


def _collate(samples, num_graphs, nodes, degree, with_triplets,
             device_multiple=1):
    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.graph.batch import pack_triplets
    from hydragnn_tpu.models import compute_triplets

    d = max(int(device_multiple), 1)
    n_pad, e_pad, g_pad = pad_sizes_for(
        nodes, nodes * degree, num_graphs,
        node_multiple=8 * d, edge_multiple=8 * d, graph_multiple=d,
    )
    batch = collate_graphs(
        samples, n_pad, e_pad, g_pad,
        head_types=("graph", "node"), head_dims=(1, 1),
    )
    if with_triplets:
        trips = [
            compute_triplets(s.edge_index, s.x.shape[0])
            + (s.x.shape[0], s.edge_index.shape[1])
            for s in samples
        ]
        batch = batch.replace(extras=pack_triplets(trips, n_pad))
    return batch


# the row-identity fields of every BENCH_EXTRA row, in order — bench.py's
# merge/age machinery imports these so the two representations cannot drift
KEY_FIELDS = ("model", "hidden", "graphs_per_batch", "nodes_per_graph",
              "avg_degree", "layers", "precision", "aggregation", "remat",
              "input_dim")


def config_identity(model_type="PNA", hidden=64, num_graphs=64, nodes=90,
                    degree=12, layers=3, bf16=False, dense=False,
                    remat=False, input_dim=1, **_ignored):
    """The BENCH row identity a ``bench_model(**kw)`` call produces —
    SINGLE source of truth used both to build the measured row dict and by
    bench.py to key its age/merge lookups. Non-default knobs appear only
    when active so pre-existing row identities stay stable."""
    ident = {
        "model": model_type,
        "hidden": hidden,
        "graphs_per_batch": num_graphs,
        "nodes_per_graph": nodes,
        "avg_degree": degree,
        "layers": layers,
        "precision": "bf16" if bf16 else "f32",
        "aggregation": "dense" if dense else "segment",
    }
    if remat:
        ident["remat"] = True
    if input_dim != 1:
        ident["input_dim"] = input_dim
    return ident


def bench_model(
    model_type="PNA",
    hidden=64,
    num_graphs=64,
    nodes=90,
    degree=12,
    layers=3,
    bf16=False,
    dense=False,
    iters=20,
    seed=0,
    remat=False,
    input_dim=1,
    mesh=None,
):
    """Measure one jitted train step. Returns a dict with fence-true
    ms/step, graphs/sec, XLA-counted TFLOP/s, and MFU vs the chip's peak.
    ``remat`` enables conv checkpointing (recompute conv activations in the
    backward pass — the memory lever for OOM-prone widths); ``input_dim``
    widens node features (CGCNN's effective conv width). ``mesh=(d, m)``
    runs the step on a 2-D ("data", "model") mesh (bench.py ``--mesh``):
    the row gains per-axis collective result bytes from the compiled HLO
    so 1-D vs 2-D A/B runs compare communication, not just wall."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    import jax

    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer
    from hydragnn_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    device_mesh = None
    if mesh is not None:
        from hydragnn_tpu.parallel.mesh import make_mesh2d

        # deliberately NOT registered as the ambient mesh: padding comes
        # from the explicit device_multiple below and the row's collective
        # bytes from the explicit HLO parse — no process-global state to
        # leak into the next bench_model call
        device_mesh = make_mesh2d(int(mesh[0]), int(mesh[1]))
    samples = make_graphs(num_graphs, nodes, degree, seed, input_dim=input_dim)
    batch = _collate(
        samples, num_graphs, nodes, degree,
        with_triplets=model_type == "DimeNet",
        device_multiple=1 if mesh is None else int(mesh[0]),
    )
    if dense:
        from hydragnn_tpu.ops.dense_agg import attach_neighbor_lists

        batch = attach_neighbor_lists(batch)
    arch = _arch(model_type, hidden, layers, nodes, input_dim=input_dim)
    if remat:
        arch["conv_checkpointing"] = True
    model = create_model_config(arch)
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            "mixed_precision": bool(bf16),
        },
        mesh=device_mesh,
    )
    state = trainer.init_state(batch)
    dbatch = trainer.put_batch(batch)
    rng = jax.random.PRNGKey(0)

    # XLA's own FLOP count for the exact compiled program, through the
    # obs layer's normalizer (list-vs-dict spellings vary by jax version)
    from hydragnn_tpu.obs.introspect import normalize_cost_analysis

    flops = None
    collectives = None
    try:
        compiled = trainer._train_step.lower(state, dbatch, rng).compile()
        cost = normalize_cost_analysis(compiled.cost_analysis())
        flops = cost.get("flops") or None
        if device_mesh is not None:
            from hydragnn_tpu.parallel.collectives import (
                collective_bytes_by_axis,
            )

            collectives = collective_bytes_by_axis(
                compiled.as_text(),
                tuple(device_mesh.axis_names),
                tuple(device_mesh.devices.shape),
            )
    except Exception as e:  # cost model availability varies by backend
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)

    # fixed key on purpose: the bench times one fixed program per config
    state, metrics = trainer._train_step(state, dbatch, rng)  # jaxlint: disable=prng-key-reuse
    np.asarray(metrics["loss"])  # fence
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = trainer._train_step(state, dbatch, rng)  # jaxlint: disable=prng-key-reuse
    loss = float(np.asarray(metrics["loss"]))  # single true-completion fence
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(loss)

    kind = jax.devices()[0].device_kind
    peak = _PEAK_TFLOPS.get(kind, _DEFAULT_PEAK)
    tflops = (flops / dt) / 1e12 if flops else None
    return {
        **config_identity(
            model_type=model_type, hidden=hidden, num_graphs=num_graphs,
            nodes=nodes, degree=degree, layers=layers, bf16=bf16,
            dense=dense, remat=remat, input_dim=input_dim,
        ),
        "ms_per_step": round(dt * 1e3, 3),
        "graphs_per_sec": round(num_graphs / dt, 1),
        "flops_per_step": flops,
        "achieved_tflops": round(tflops, 2) if tflops else None,
        "mfu_pct": round(100 * tflops / peak, 2) if tflops else None,
        "device_kind": kind,
        "peak_tflops_assumed": peak,
        **(
            {}
            if mesh is None
            else {
                "mesh": f"{int(mesh[0])}x{int(mesh[1])}",
                "collective_bytes": collectives or {},
            }
        ),
    }


def main():
    row = bench_model(
        model_type=str(_arg("model", "PNA")),
        hidden=int(_arg("hidden", 64)),
        num_graphs=int(_arg("graphs", 64)),
        nodes=int(_arg("nodes", 90)),
        degree=int(_arg("degree", 12)),
        layers=int(_arg("layers", 3)),
        bf16=bool(_arg("bf16", False)),
        dense=bool(_arg("dense", False)),
        iters=int(_arg("iters", 20)),
        remat=bool(_arg("remat", False)),
        input_dim=int(_arg("input_dim", 1)),
    )
    import json

    print(json.dumps(row))


if __name__ == "__main__":
    main()
