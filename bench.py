"""Headline benchmark (round 5: TWO metrics on one JSON line).

Primary headline: OC20-shaped PNA hidden-256 dense-bf16 train step (64
graphs x ~90 atoms, degree 12, multi-head) — an MXU-scale configuration
that moves when kernels/aggregation actually improve (the round-4 verdict:
the old headline config saturated at the dispatch/VPU floor and stopped
discriminating). Legacy headline (kept for cross-round continuity):
QM9-scale PNA hidden-64 whole-training `fit_staged` throughput.

Ours: ONE jitted XLA program per step (fwd + loss + grad + AdamW + BN stats)
on the default JAX device. Baselines: eager PyTorch implementations of the
same PNA stack/step at the same shapes, in the reference's execution style
(per-op dispatch, index_add_ scatter aggregation —
`hydragnn/models/PNAStack.py`, `train/train_validate_test.py:437-540`) on
this host's CPU, since the reference cannot run on TPU. Prints ONE JSON
line: primary metric + `legacy_*` keys.
"""

import json
import os
import sys
import time

import numpy as np

BATCH_GRAPHS = 256
MAX_NODES = 18
HIDDEN = 64
NUM_LAYERS = 3
EPOCH_BATCHES = 32
EPOCHS = 100
BASELINE_STEPS = 5


def _samples(num_graphs, seed=0):
    rng = np.random.default_rng(seed)

    class _S:
        pass

    out = []
    for _ in range(num_graphs):
        n = int(rng.integers(12, MAX_NODES + 1))
        s = _S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = rng.random((n, 3)).astype(np.float32)
        src = np.repeat(np.arange(n), 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [np.array([s.x.sum()], np.float32), s.x.astype(np.float32)]
        out.append(s)
    return out


def _arch():
    return {
        "model_type": "PNA",
        "input_dim": 1,
        "hidden_dim": HIDDEN,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": 32,
                "num_headlayers": 2,
                "dim_headlayers": [32, 32],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [32, 32],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": NUM_LAYERS,
        "num_nodes": MAX_NODES,
        "edge_dim": None,
        "pna_deg": [0, 0, 16, 32, 64, 32],
        "equivariance": False,
    }


def bench_ours():
    """Device-resident dataset mode (the framework's intended configuration
    for HBM-sized datasets like QM9): the collated training set is staged in
    HBM once, then `fit_staged` runs the ENTIRE 100-epoch training —
    per-batch optimizer steps, epoch shuffling, plateau-LR scheduling, early
    stopping, best-state tracking — as one XLA dispatch with a single
    metric readback. Zero host round-trips inside training."""
    import jax

    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer
    from hydragnn_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    n_pad, e_pad, g_pad = pad_sizes_for(MAX_NODES, 4 * MAX_NODES, BATCH_GRAPHS)
    batches = [
        collate_graphs(
            _samples(BATCH_GRAPHS, seed=k),
            n_pad,
            e_pad,
            g_pad,
            head_types=("graph", "node"),
            head_dims=(1, 1),
        )
        for k in range(EPOCH_BATCHES)
    ]
    model = create_model_config(_arch())
    trainer = Trainer(
        model,
        training_config={"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}},
    )
    state = trainer.init_state(batches[0])
    staged = trainer.stage_batches(batches)
    rng = jax.random.PRNGKey(0)
    # compile + warm the whole-training program at the measured epoch count
    state, _best, _sched, rng, series = trainer.fit_staged(
        state, staged, EPOCHS, rng
    )
    # best of two timed runs: the dev chip is shared and run-to-run
    # contention varies by tens of percent
    best_dt = None
    for _ in range(2):
        t0 = time.perf_counter()
        state, _best, _sched, rng, series = trainer.fit_staged(
            state, staged, EPOCHS, rng
        )
        dt = time.perf_counter() - t0
        assert np.isfinite(series["train_loss"]).all()
        best_dt = dt if best_dt is None else min(best_dt, dt)
    steps = EPOCH_BATCHES * EPOCHS
    return BATCH_GRAPHS * steps / best_dt


def bench_torch_baseline(samples=None, hidden=HIDDEN, steps=BASELINE_STEPS):
    """Eager torch PNA of identical shape, reference execution style.
    Defaults measure the legacy QM9-scale config; pass OC20-shaped samples
    + hidden for the primary-headline baseline."""
    import torch
    import torch.nn as nn

    torch.set_num_threads(max(1, __import__("os").cpu_count() or 1))
    if samples is None:
        samples = _samples(BATCH_GRAPHS)
    # concatenate into one batch (PyG-style ragged collation, no padding)
    xs, eis, gids, y_g, y_n = [], [], [], [], []
    off = 0
    for g, s in enumerate(samples):
        xs.append(s.x)
        eis.append(s.edge_index + off)
        gids.append(np.full(s.x.shape[0], g))
        y_g.append(s.targets[0])
        y_n.append(s.targets[1])
        off += s.x.shape[0]
    x = torch.tensor(np.concatenate(xs))
    ei = torch.tensor(np.concatenate(eis, axis=1))
    gid = torch.tensor(np.concatenate(gids), dtype=torch.long)
    yg = torch.tensor(np.stack(y_g))
    yn = torch.tensor(np.concatenate(y_n))
    N = x.shape[0]
    G = len(samples)
    deg = torch.zeros(N).index_add_(0, ei[1], torch.ones(ei.shape[1]))
    mean_log_deg = float(torch.log(deg + 1).mean())

    class PNALayer(nn.Module):
        def __init__(self, din, dout):
            super().__init__()
            self.pre = nn.Linear(2 * din, din)
            # 4 aggregators x 4 scalers
            self.post = nn.Linear(din + 16 * din, dout)

        def forward(self, h, senders, receivers):
            m = self.pre(torch.cat([h[senders], h[receivers]], dim=1))
            E, D = m.shape
            s = torch.zeros(N, D).index_add_(0, receivers, m)
            mean = s / deg.clamp(min=1).unsqueeze(1)
            # scatter_reduce_ (stable since torch 2.x) instead of the
            # index_reduce_ beta API: identical amax/amin semantics,
            # warning-clean bench output
            ridx = receivers.unsqueeze(1).expand(E, D)
            mx = torch.full((N, D), -1e30).scatter_reduce_(
                0, ridx, m, reduce="amax", include_self=True
            )
            mn = torch.full((N, D), 1e30).scatter_reduce_(
                0, ridx, m, reduce="amin", include_self=True
            )
            sq = torch.zeros(N, D).index_add_(0, receivers, m * m)
            std = (sq / deg.clamp(min=1).unsqueeze(1) - mean**2).clamp(min=0).sqrt()
            aggs = torch.cat([mean, mn, mx, std], dim=1)
            ld = torch.log(deg + 1).unsqueeze(1)
            scaled = torch.cat(
                [
                    aggs,
                    aggs * (ld / mean_log_deg),
                    aggs * (mean_log_deg / ld.clamp(min=1e-6)),
                    aggs,
                ],
                dim=1,
            )
            return self.post(torch.cat([h, scaled], dim=1))

    shared_dim = max(32, hidden // 4)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.embed = nn.Linear(x.shape[1], hidden)
            self.convs = nn.ModuleList(
                [PNALayer(hidden, hidden) for _ in range(NUM_LAYERS)]
            )
            self.bns = nn.ModuleList(
                [nn.BatchNorm1d(hidden) for _ in range(NUM_LAYERS)]
            )
            self.shared = nn.Sequential(
                nn.Linear(hidden, shared_dim), nn.ReLU(),
                nn.Linear(shared_dim, shared_dim), nn.ReLU()
            )
            self.head_g = nn.Sequential(
                nn.Linear(shared_dim, shared_dim), nn.ReLU(),
                nn.Linear(shared_dim, 1)
            )
            self.head_n = nn.Sequential(
                nn.Linear(hidden, shared_dim), nn.ReLU(),
                nn.Linear(shared_dim, 1)
            )

        def forward(self, x, senders, receivers):
            h = self.embed(x)
            for conv, bn in zip(self.convs, self.bns):
                h = torch.relu(bn(conv(h, senders, receivers)))
            cnt = torch.zeros(G).index_add_(0, gid, torch.ones(N))
            pooled = torch.zeros(G, hidden).index_add_(0, gid, h) / cnt.unsqueeze(1)
            return self.head_g(self.shared(pooled)), self.head_n(h)

    net = Net()
    opt = torch.optim.AdamW(net.parameters(), lr=1e-3)
    mse = nn.MSELoss()

    def step():
        opt.zero_grad()
        pg, pn = net(x, ei[0], ei[1])
        loss = 0.5 * mse(pg, yg) + 0.5 * mse(pn, yn)
        loss.backward()
        opt.step()

    step()  # warmup
    # best of two, matching the measured framework's methodology — an
    # asymmetric min() would inflate vs_baseline by the host's contention
    best_dt = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            step()
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    return len(samples) * steps / best_dt


def _extra_configs():
    oc20 = dict(num_graphs=64, nodes=90, degree=12, layers=3)
    configs = [
        dict(model_type="PNA", hidden=256, **oc20),
        dict(model_type="PNA", hidden=256, dense=True, bf16=True, **oc20),
        dict(model_type="PNA", hidden=512, dense=True, bf16=True, **oc20),
        # MFU trend at MXU widths (round-3 verdict item 6)
        dict(model_type="PNA", hidden=1024, dense=True, bf16=True, **oc20),
        dict(model_type="PNA", hidden=2048, dense=True, bf16=True, **oc20),
        # GAT tops out at 512 (the 6-head concat widths OOM at 1024)
        dict(model_type="GAT", hidden=512, dense=True, bf16=True, **oc20),
        # ... unless convs are rematerialized (round-4 verdict item 4):
        # checkpointing keeps the [N, K, heads*C] attention messages out of
        # the fwd residency so hidden 1024 fits
        dict(model_type="GAT", hidden=1024, dense=True, bf16=True,
             remat=True, **oc20),
        # GAT dense precision A/B (bf16 counterpart in the matrix below)
        dict(model_type="GAT", hidden=256, dense=True, **oc20),
        # CGCNN crossover vs INPUT width (its convs run at input_dim —
        # round-4 verdict item 8): segment/dense pairs at the two anchor
        # widths of the measured INVERSE crossover (dense wins narrow,
        # loses wide; ops/autotune.py DENSE_AUTO_MAX_INPUT_DIM)
        dict(model_type="CGCNN", hidden=64, input_dim=4, **oc20),
        dict(model_type="CGCNN", hidden=64, input_dim=4, dense=True,
             bf16=True, **oc20),
        dict(model_type="CGCNN", hidden=64, input_dim=256, **oc20),
        dict(model_type="CGCNN", hidden=64, input_dim=256, dense=True,
             bf16=True, **oc20),
        # headline-scale per-model rows
        dict(model_type="SchNet", hidden=64, num_graphs=256, nodes=18,
             degree=4, layers=3),
        dict(model_type="EGNN", hidden=64, num_graphs=256, nodes=18,
             degree=4, layers=3),
        dict(model_type="DimeNet", hidden=64, num_graphs=64, nodes=18,
             degree=4, layers=3),
    ]
    # MXU-scale matrix: all 9 stacks, segment-f32 vs dense-bf16
    for m in ("GIN", "GAT", "SAGE", "MFC", "CGCNN", "SchNet", "EGNN"):
        configs.append(dict(model_type=m, hidden=256, **oc20))
        configs.append(dict(model_type=m, hidden=256, dense=True, bf16=True,
                            **oc20))
    # DimeNet at the BASELINE.md row scale (hidden 128; 256 is OOM-prone
    # on a shared chip)
    configs.append(dict(model_type="DimeNet", hidden=128, **oc20))
    configs.append(dict(model_type="DimeNet", hidden=128, dense=True,
                        bf16=True, **oc20))
    return configs


def _row_key(row):
    from benchmarks.model_bench import KEY_FIELDS

    return tuple(row.get(f) for f in KEY_FIELDS)


def _config_key(kw):
    """The BENCH_EXTRA row identity a bench_model(**kw) call will produce
    — built by the same ``config_identity`` bench_model itself uses, so
    the two representations cannot drift."""
    from benchmarks.model_bench import config_identity

    return _row_key(config_identity(**kw))


def read_row_ages(path) -> dict:
    """row identity -> runs since last ATTEMPT (attempt_age falls back to
    age for pre-round-5 files) from BENCH_EXTRA.json; empty on a missing/
    unreadable file (every config then counts as never-measured = oldest).
    Attempt age (not data age) drives the refresh order so a permanently
    failing config cannot pin itself at the front of every run."""
    try:
        with open(path) as f:
            return {
                _row_key(r): int(r.get("attempt_age", r.get("age", 0)))
                for r in json.load(f).get("rows", [])
            }
    except Exception:
        return {}


def bench_extra_rows(start: int = 0, ages: dict = None):
    """Per-model and MXU-scale rows (round-2 verdict items 2-3): every one
    of the 9 model stacks measured at OC20 scale (hidden 256, ~90 atoms,
    degree 12) on the segment AND dense paths, plus the headline-scale
    per-model rows and the MFU-trend widths, each with XLA-counted TFLOP/s
    and MFU. Written to BENCH_EXTRA.json (NOT the headline stdout line —
    round-2's headline was lost to driver tail-truncation of one oversized
    line). Refresh order is OLDEST ROW FIRST (never-measured configs lead;
    ``start`` cursor-rotates ties) so maximum staleness is bounded by
    ceil(len(configs)/measured-per-run) runs — the round-4 verdict's
    <=2-round staleness ask — instead of the front rows hogging every
    refresh. Skippable via HYDRAGNN_BENCH_EXTRAS=0.
    Returns (rows, measured_count)."""
    if os.getenv("HYDRAGNN_BENCH_EXTRAS", "1") == "0":
        return [], 0, []
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.model_bench import bench_model
    from hydragnn_tpu.ops.autotune import (
        bucket_signature,
        cached_choice,
        static_aggregation_choice,
    )

    configs = _extra_configs()
    start = start % len(configs)
    rotated = configs[start:] + configs[:start]
    ages = ages or {}
    # stable sort: never-measured first, then oldest; cursor order breaks ties
    rotated.sort(key=lambda kw: -ages.get(_config_key(kw), 1 << 30))
    # soft deadline: the headline JSON prints LAST, so a driver-side kill
    # mid-extras would lose the round's recorded number (exactly round 2's
    # failure). Unmeasured configs keep their previous BENCH_EXTRA.json
    # rows via the merge in main().
    budget_s = float(os.getenv("HYDRAGNN_BENCH_BUDGET", "300"))
    t0 = time.monotonic()
    rows = []
    failures = []
    measured = 0
    skipped = 0
    for kw in rotated:
        if time.monotonic() - t0 > budget_s:
            skipped += 1
            continue
        measured += 1
        try:
            # 8 iters/row (was 12): the per-row cost cut that, with the
            # oldest-first refresh, holds max staleness at <=2 runs
            row = bench_model(**kw, iters=8)
            # what the autotuner would pick for this (model, width) —
            # a cached measured decision for the row's bucket when one
            # exists (ops/autotune.py), else the static policy tier —
            # so the table shows the auto choice against the measured
            # per-path winners
            from hydragnn_tpu.graph import pad_sizes_for

            n_pad, e_pad, _ = pad_sizes_for(
                kw["nodes"], kw["nodes"] * kw["degree"], kw["num_graphs"]
            )
            sig = bucket_signature(
                kw["model_type"], n_pad, e_pad, kw["hidden"]
            )
            cached = cached_choice(sig)
            row["auto_choice"] = (
                cached["choice"]
                if cached is not None
                else static_aggregation_choice(
                    {
                        "model_type": kw["model_type"],
                        "hidden_dim": kw["hidden"],
                        "input_dim": kw.get("input_dim", 1),
                    }
                )
            )
            rows.append(row)
        except Exception as e:
            print(f"extra row {kw} failed: {e}", file=sys.stderr)
            failures.append((kw, str(e)[:200]))
    if skipped:
        print(
            f"extras budget ({budget_s:.0f}s) exhausted: {skipped} configs "
            "kept their previous rows",
            file=sys.stderr,
        )
    return rows, measured, failures


def read_refresh_cursor(path) -> int:
    """Persisted rotation cursor (0 when absent/unreadable)."""
    try:
        with open(path) as f:
            return int(json.load(f).get("refresh_cursor", 0))
    except Exception:
        return 0


def merge_extra_rows(path, extra, cursor=0, failures=()):
    """Merge freshly measured rows into ``path`` by config identity:
    configs not re-measured this run keep their previous rows, explicitly
    marked ``carried_over`` with an ``age`` (number of runs since last
    measured); an unreadable existing file is backed up to ``.bak`` and
    reported instead of silently eating history. ``failures`` (kw, msg)
    pairs annotate the EXISTING row — last good metrics are preserved, the
    failure is recorded, and ``attempt_age`` resets so the refresh order
    moves on. Persists the rotation ``cursor``. Returns the merged row
    list (also written to ``path``, atomically)."""
    _key = _row_key
    merged = {}
    try:
        with open(path) as f:
            for row in json.load(f).get("rows", []):
                merged[_key(row)] = row
    except FileNotFoundError:
        pass
    except Exception as e:
        # a truncated/corrupt file must not silently eat history; report
        # what actually happened to it, not what we hoped would
        try:
            os.replace(path, path + ".bak")
            kept = f"original kept at {path}.bak"
        except OSError as be:
            kept = f"backup to .bak ALSO failed ({be})"
        print(
            f"existing {path} unreadable ({e}); previous rows lost, {kept}",
            file=sys.stderr,
        )
    for key in list(merged):
        r = merged[key]
        r["carried_over"] = True  # stale unless re-measured
        r["age"] = int(r.get("age", 0)) + 1
        r["attempt_age"] = int(r.get("attempt_age", r["age"] - 1)) + 1
    for row in extra:
        row.pop("carried_over", None)
        row.pop("failed", None)
        row["age"] = 0
        row["attempt_age"] = 0
        merged[_key(row)] = row
    for kw, msg in failures:
        key = _config_key(kw)
        if key in merged:
            # annotate, never replace: the last good metrics stay
            merged[key]["failed"] = msg
            merged[key]["attempt_age"] = 0
        else:
            from benchmarks.model_bench import config_identity

            merged[key] = {
                **config_identity(**kw),
                "failed": msg,
                "age": 0,
                "attempt_age": 0,
            }
    rows = list(merged.values())
    carried = [r for r in rows if r.get("carried_over")]
    print(
        f"{len(carried)} of {len(rows)} rows carried over"
        + (
            f" (max age {max(r['age'] for r in carried)} runs)"
            if carried
            else ""
        ),
        file=sys.stderr,
    )
    # atomic replace: a driver-side kill mid-write must not leave the
    # history file truncated (the failure mode this merge exists to survive)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"rows": rows, "refresh_cursor": int(cursor)}, f, indent=1)
    os.replace(tmp, path)
    return rows


MXU_HEADLINE = dict(model_type="PNA", hidden=256, num_graphs=64, nodes=90,
                    degree=12, layers=3, dense=True, bf16=True)


def bench_headline_mxu():
    """Primary headline (round-4 verdict item 6): fence-true train-step
    throughput of the OC20-shaped PNA hidden-256 dense-bf16 config — an
    MXU-scale surface that actually moves when kernels improve. Returns
    the full bench row (the headline line also reports its MFU — the
    number the ROADMAP's <1% -> double-digits campaign is judged by)."""
    from benchmarks.model_bench import bench_model

    return bench_model(**MXU_HEADLINE, iters=20)


def bench_mesh(mesh_arg: str):
    """``bench.py --mesh d,m``: the OC20 headline config on a 2-D
    ("data", "model") mesh — ONE JSON row with graphs/sec and per-axis
    collective result bytes, so the first real-TPU run can A/B the 1-D
    and 2-D layouts on communication as well as wall. ``--mesh 8,1`` is
    the 1-D baseline at identical padding."""
    from benchmarks.model_bench import bench_model

    d, m = (int(v) for v in mesh_arg.split(","))
    row = bench_model(**MXU_HEADLINE, iters=8, mesh=(d, m))
    print(json.dumps(row, separators=(",", ":")))


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if "--mesh" in sys.argv:
        bench_mesh(sys.argv[sys.argv.index("--mesh") + 1])
        return
    # primary headline FIRST: a failure in the (much longer) legacy
    # measurement must not cost the round its recorded number
    headline_row = bench_headline_mxu()
    ours = float(headline_row["graphs_per_sec"])
    # the headline's MFU only rides the driver-parsed line when the
    # device kind has a REAL peak entry — model_bench's 197-TFLOP/s
    # fallback is fine for the annotated BENCH_EXTRA rows (they carry
    # peak_tflops_assumed) but would record a fabricated campaign metric
    # here, where no disclaimer travels with the number
    from hydragnn_tpu.obs.ledger import PEAK_FLOPS

    mfu_pct = (
        headline_row.get("mfu_pct")
        if headline_row.get("device_kind") in PEAK_FLOPS
        else None
    )
    try:
        legacy = bench_ours()
    except Exception as e:
        print(f"legacy headline failed: {e}", file=sys.stderr)
        legacy = None
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_EXTRA.json")
    cursor = read_refresh_cursor(out)
    extra, measured, failures = bench_extra_rows(
        start=cursor, ages=read_row_ages(out)
    )
    # persist the expensive TPU rows BEFORE the torch baselines: a non-
    # exception death there (OOM kill) must not discard them. Merge runs
    # whenever configs were ATTEMPTED (measured > 0) even if every attempt
    # failed — failed attempts reset the config's attempt_age so the
    # oldest-first order moves on instead of re-burning its budget.
    if extra or measured:
        rows = merge_extra_rows(
            out, extra, cursor=cursor + measured, failures=failures
        )
        print(
            f"wrote {len(extra)} fresh / {len(rows)} total extra rows "
            f"to {out}",
            file=sys.stderr,
        )
    from benchmarks.model_bench import make_graphs

    try:
        base = bench_torch_baseline(
            samples=make_graphs(
                MXU_HEADLINE["num_graphs"],
                MXU_HEADLINE["nodes"],
                MXU_HEADLINE["degree"],
            ),
            hidden=MXU_HEADLINE["hidden"],
            steps=2,  # eager-CPU steps at this scale are seconds each
        )
    except Exception as e:
        print(f"mxu baseline failed: {e}", file=sys.stderr)
        base = None
    legacy_base = None
    if legacy is not None:
        try:
            legacy_base = bench_torch_baseline()
        except Exception as e:
            print(f"legacy baseline failed: {e}", file=sys.stderr)
    # the machine-readable headline MUST be the last stdout line and small:
    # the driver tail-captures stdout and json-parses the final line
    sys.stdout.flush()
    print(headline_line(ours, base, legacy, legacy_base, mfu_pct=mfu_pct))


def headline_line(ours, base, legacy, legacy_base, mfu_pct=None):
    """The one driver-parsed stdout line. Compact separators and no
    legacy_metric key (it is the constant
    ``pna_multihead_train_graphs_per_sec``, documented in BASELINE.md) keep
    the line tail-capture safe (<200 chars) with both headlines aboard.
    ``mfu_pct`` is the headline config's measured MFU (XLA-counted FLOPs
    vs the device-kind peak, obs/ledger.PEAK_FLOPS) — the ROADMAP's MFU
    campaign reads its progress off this line."""
    return json.dumps(
        {
            "metric": "oc20_pna_h256_dense_bf16_graphs_per_sec",
            "value": round(ours, 2),
            "unit": "graphs/sec",
            "mfu_pct": mfu_pct,
            "vs_baseline": round(ours / base, 3) if base else None,
            "legacy_value": round(legacy, 2) if legacy else None,
            "legacy_vs_baseline": (
                round(legacy / legacy_base, 3)
                if legacy and legacy_base
                else None
            ),
        },
        separators=(",", ":"),
    )


if __name__ == "__main__":
    main()
