"""Headline benchmark: PNA multi-head training-step throughput (graphs/sec).

Workload: QM9-scale synthetic graphs (~18 nodes / ~36 edges each), batch of
256 graphs, 3 PNA conv layers (4 aggregators x 4 scalers), hidden 64,
graph + node heads with weighted multi-task MSE — the reference's canonical
configuration (`tests/test_graphs.py`, `examples/qm9`).

Ours: ONE jitted XLA program per step (fwd + loss + grad + AdamW + BN stats)
on the default JAX device. Baseline: an eager PyTorch implementation of the
same PNA stack/step in the reference's execution style (per-op dispatch,
index_add_ scatter aggregation — `hydragnn/models/PNAStack.py`,
`train/train_validate_test.py:437-540`) on this host's CPU, since the
reference cannot run on TPU. Prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np

BATCH_GRAPHS = 256
MAX_NODES = 18
HIDDEN = 64
NUM_LAYERS = 3
EPOCH_BATCHES = 32
EPOCHS = 100
BASELINE_STEPS = 5


def _samples(num_graphs, seed=0):
    rng = np.random.default_rng(seed)

    class _S:
        pass

    out = []
    for _ in range(num_graphs):
        n = int(rng.integers(12, MAX_NODES + 1))
        s = _S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = rng.random((n, 3)).astype(np.float32)
        src = np.repeat(np.arange(n), 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [np.array([s.x.sum()], np.float32), s.x.astype(np.float32)]
        out.append(s)
    return out


def _arch():
    return {
        "model_type": "PNA",
        "input_dim": 1,
        "hidden_dim": HIDDEN,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": 32,
                "num_headlayers": 2,
                "dim_headlayers": [32, 32],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [32, 32],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": NUM_LAYERS,
        "num_nodes": MAX_NODES,
        "edge_dim": None,
        "pna_deg": [0, 0, 16, 32, 64, 32],
        "equivariance": False,
    }


def bench_ours():
    """Device-resident dataset mode (the framework's intended configuration
    for HBM-sized datasets like QM9): the collated training set is staged in
    HBM once, then `fit_staged` runs the ENTIRE 100-epoch training —
    per-batch optimizer steps, epoch shuffling, plateau-LR scheduling, early
    stopping, best-state tracking — as one XLA dispatch with a single
    metric readback. Zero host round-trips inside training."""
    import jax

    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer
    from hydragnn_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    n_pad, e_pad, g_pad = pad_sizes_for(MAX_NODES, 4 * MAX_NODES, BATCH_GRAPHS)
    batches = [
        collate_graphs(
            _samples(BATCH_GRAPHS, seed=k),
            n_pad,
            e_pad,
            g_pad,
            head_types=("graph", "node"),
            head_dims=(1, 1),
        )
        for k in range(EPOCH_BATCHES)
    ]
    model = create_model_config(_arch())
    trainer = Trainer(
        model,
        training_config={"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}},
    )
    state = trainer.init_state(batches[0])
    staged = trainer.stage_batches(batches)
    rng = jax.random.PRNGKey(0)
    # compile + warm the whole-training program at the measured epoch count
    state, _best, _sched, rng, series = trainer.fit_staged(
        state, staged, EPOCHS, rng
    )
    # best of two timed runs: the dev chip is shared and run-to-run
    # contention varies by tens of percent
    best_dt = None
    for _ in range(2):
        t0 = time.perf_counter()
        state, _best, _sched, rng, series = trainer.fit_staged(
            state, staged, EPOCHS, rng
        )
        dt = time.perf_counter() - t0
        assert np.isfinite(series["train_loss"]).all()
        best_dt = dt if best_dt is None else min(best_dt, dt)
    steps = EPOCH_BATCHES * EPOCHS
    return BATCH_GRAPHS * steps / best_dt


def bench_torch_baseline():
    """Eager torch PNA of identical shape, reference execution style."""
    import torch
    import torch.nn as nn

    torch.set_num_threads(max(1, __import__("os").cpu_count() or 1))
    samples = _samples(BATCH_GRAPHS)
    # concatenate into one batch (PyG-style ragged collation, no padding)
    xs, eis, gids, y_g, y_n = [], [], [], [], []
    off = 0
    for g, s in enumerate(samples):
        xs.append(s.x)
        eis.append(s.edge_index + off)
        gids.append(np.full(s.x.shape[0], g))
        y_g.append(s.targets[0])
        y_n.append(s.targets[1])
        off += s.x.shape[0]
    x = torch.tensor(np.concatenate(xs))
    ei = torch.tensor(np.concatenate(eis, axis=1))
    gid = torch.tensor(np.concatenate(gids), dtype=torch.long)
    yg = torch.tensor(np.stack(y_g))
    yn = torch.tensor(np.concatenate(y_n))
    N = x.shape[0]
    G = len(samples)
    deg = torch.zeros(N).index_add_(0, ei[1], torch.ones(ei.shape[1]))
    mean_log_deg = float(torch.log(deg + 1).mean())

    class PNALayer(nn.Module):
        def __init__(self, din, dout):
            super().__init__()
            self.pre = nn.Linear(2 * din, din)
            # 4 aggregators x 4 scalers
            self.post = nn.Linear(din + 16 * din, dout)

        def forward(self, h, senders, receivers):
            m = self.pre(torch.cat([h[senders], h[receivers]], dim=1))
            E, D = m.shape
            s = torch.zeros(N, D).index_add_(0, receivers, m)
            mean = s / deg.clamp(min=1).unsqueeze(1)
            mx = torch.full((N, D), -1e30).index_reduce_(
                0, receivers, m, "amax", include_self=True
            )
            mn = torch.full((N, D), 1e30).index_reduce_(
                0, receivers, m, "amin", include_self=True
            )
            sq = torch.zeros(N, D).index_add_(0, receivers, m * m)
            std = (sq / deg.clamp(min=1).unsqueeze(1) - mean**2).clamp(min=0).sqrt()
            aggs = torch.cat([mean, mn, mx, std], dim=1)
            ld = torch.log(deg + 1).unsqueeze(1)
            scaled = torch.cat(
                [
                    aggs,
                    aggs * (ld / mean_log_deg),
                    aggs * (mean_log_deg / ld.clamp(min=1e-6)),
                    aggs,
                ],
                dim=1,
            )
            return self.post(torch.cat([h, scaled], dim=1))

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.embed = nn.Linear(1, HIDDEN)
            self.convs = nn.ModuleList(
                [PNALayer(HIDDEN, HIDDEN) for _ in range(NUM_LAYERS)]
            )
            self.bns = nn.ModuleList(
                [nn.BatchNorm1d(HIDDEN) for _ in range(NUM_LAYERS)]
            )
            self.shared = nn.Sequential(
                nn.Linear(HIDDEN, 32), nn.ReLU(), nn.Linear(32, 32), nn.ReLU()
            )
            self.head_g = nn.Sequential(
                nn.Linear(32, 32), nn.ReLU(), nn.Linear(32, 1)
            )
            self.head_n = nn.Sequential(
                nn.Linear(HIDDEN, 32), nn.ReLU(), nn.Linear(32, 1)
            )

        def forward(self, x, senders, receivers):
            h = self.embed(x)
            for conv, bn in zip(self.convs, self.bns):
                h = torch.relu(bn(conv(h, senders, receivers)))
            cnt = torch.zeros(G).index_add_(0, gid, torch.ones(N))
            pooled = torch.zeros(G, HIDDEN).index_add_(0, gid, h) / cnt.unsqueeze(1)
            return self.head_g(self.shared(pooled)), self.head_n(h)

    net = Net()
    opt = torch.optim.AdamW(net.parameters(), lr=1e-3)
    mse = nn.MSELoss()

    def step():
        opt.zero_grad()
        pg, pn = net(x, ei[0], ei[1])
        loss = 0.5 * mse(pg, yg) + 0.5 * mse(pn, yn)
        loss.backward()
        opt.step()

    step()  # warmup
    # best of two, matching the measured framework's methodology — an
    # asymmetric min() would inflate vs_baseline by the host's contention
    best_dt = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(BASELINE_STEPS):
            step()
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    return BATCH_GRAPHS * BASELINE_STEPS / best_dt


def _extra_configs():
    oc20 = dict(num_graphs=64, nodes=90, degree=12, layers=3)
    configs = [
        dict(model_type="PNA", hidden=256, **oc20),
        dict(model_type="PNA", hidden=256, dense=True, bf16=True, **oc20),
        dict(model_type="PNA", hidden=512, dense=True, bf16=True, **oc20),
        # MFU trend at MXU widths (round-3 verdict item 6)
        dict(model_type="PNA", hidden=1024, dense=True, bf16=True, **oc20),
        dict(model_type="PNA", hidden=2048, dense=True, bf16=True, **oc20),
        # GAT tops out at 512 (the 6-head concat widths OOM at 1024)
        dict(model_type="GAT", hidden=512, dense=True, bf16=True, **oc20),
        # GAT dense precision A/B (bf16 counterpart in the matrix below)
        dict(model_type="GAT", hidden=256, dense=True, **oc20),
        # headline-scale per-model rows
        dict(model_type="SchNet", hidden=64, num_graphs=256, nodes=18,
             degree=4, layers=3),
        dict(model_type="EGNN", hidden=64, num_graphs=256, nodes=18,
             degree=4, layers=3),
        dict(model_type="DimeNet", hidden=64, num_graphs=64, nodes=18,
             degree=4, layers=3),
    ]
    # MXU-scale matrix: all 9 stacks, segment-f32 vs dense-bf16
    for m in ("GIN", "GAT", "SAGE", "MFC", "CGCNN", "SchNet", "EGNN"):
        configs.append(dict(model_type=m, hidden=256, **oc20))
        configs.append(dict(model_type=m, hidden=256, dense=True, bf16=True,
                            **oc20))
    # DimeNet at the BASELINE.md row scale (hidden 128; 256 is OOM-prone
    # on a shared chip)
    configs.append(dict(model_type="DimeNet", hidden=128, **oc20))
    configs.append(dict(model_type="DimeNet", hidden=128, dense=True,
                        bf16=True, **oc20))
    return configs


def bench_extra_rows(start: int = 0):
    """Per-model and MXU-scale rows (round-2 verdict items 2-3): every one
    of the 9 model stacks measured at OC20 scale (hidden 256, ~90 atoms,
    degree 12) on the segment AND dense paths, plus the headline-scale
    per-model rows and the MFU-trend widths, each with XLA-counted TFLOP/s
    and MFU. Written to BENCH_EXTRA.json (NOT the headline stdout line —
    round-2's headline was lost to driver tail-truncation of one oversized
    line). ``start`` rotates the refresh window (persisted cursor in
    BENCH_EXTRA.json) so every config is re-measured within ~2 runs of the
    300 s budget instead of the front rows hogging every refresh.
    Skippable via HYDRAGNN_BENCH_EXTRAS=0. Returns (rows, measured_count).
    """
    if os.getenv("HYDRAGNN_BENCH_EXTRAS", "1") == "0":
        return [], 0
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.model_bench import bench_model
    from hydragnn_tpu.data.loaders import auto_dense_aggregation

    configs = _extra_configs()
    start = start % len(configs)
    rotated = configs[start:] + configs[:start]
    # soft deadline: the headline JSON prints LAST, so a driver-side kill
    # mid-extras would lose the round's recorded number (exactly round 2's
    # failure). Unmeasured configs keep their previous BENCH_EXTRA.json
    # rows via the merge in main().
    budget_s = float(os.getenv("HYDRAGNN_BENCH_BUDGET", "300"))
    t0 = time.monotonic()
    rows = []
    measured = 0
    skipped = 0
    for kw in rotated:
        if time.monotonic() - t0 > budget_s:
            skipped += 1
            continue
        measured += 1
        try:
            row = bench_model(**kw, iters=12)
            # what the AUTO policy would pick for this (model, width) —
            # lets the table show the auto choice against the measured
            # per-path winners
            row["auto_choice"] = (
                "dense"
                if auto_dense_aggregation(
                    {"model_type": kw["model_type"], "hidden_dim": kw["hidden"]}
                )
                else "segment"
            )
            rows.append(row)
        except Exception as e:
            print(f"extra row {kw} failed: {e}", file=sys.stderr)
    if skipped:
        print(
            f"extras budget ({budget_s:.0f}s) exhausted: {skipped} configs "
            "kept their previous rows",
            file=sys.stderr,
        )
    return rows, measured


def read_refresh_cursor(path) -> int:
    """Persisted rotation cursor (0 when absent/unreadable)."""
    try:
        with open(path) as f:
            return int(json.load(f).get("refresh_cursor", 0))
    except Exception:
        return 0


def merge_extra_rows(path, extra, cursor=0):
    """Merge freshly measured rows into ``path`` by config identity:
    configs not re-measured this run keep their previous rows, explicitly
    marked ``carried_over`` with an ``age`` (number of runs since last
    measured); an unreadable existing file is backed up to ``.bak`` and
    reported instead of silently eating history. Persists the rotation
    ``cursor``. Returns the merged row list (also written to ``path``,
    atomically)."""
    key_fields = ("model", "hidden", "graphs_per_batch", "nodes_per_graph",
                  "avg_degree", "layers", "precision", "aggregation")

    def _key(row):
        return tuple(row.get(f) for f in key_fields)

    merged = {}
    try:
        with open(path) as f:
            for row in json.load(f).get("rows", []):
                merged[_key(row)] = row
    except FileNotFoundError:
        pass
    except Exception as e:
        # a truncated/corrupt file must not silently eat history; report
        # what actually happened to it, not what we hoped would
        try:
            os.replace(path, path + ".bak")
            kept = f"original kept at {path}.bak"
        except OSError as be:
            kept = f"backup to .bak ALSO failed ({be})"
        print(
            f"existing {path} unreadable ({e}); previous rows lost, {kept}",
            file=sys.stderr,
        )
    for key in list(merged):
        merged[key]["carried_over"] = True  # stale unless re-measured
        merged[key]["age"] = int(merged[key].get("age", 0)) + 1
    for row in extra:
        row.pop("carried_over", None)
        row["age"] = 0
        merged[_key(row)] = row
    rows = list(merged.values())
    carried = [r for r in rows if r.get("carried_over")]
    print(
        f"{len(carried)} of {len(rows)} rows carried over"
        + (
            f" (max age {max(r['age'] for r in carried)} runs)"
            if carried
            else ""
        ),
        file=sys.stderr,
    )
    # atomic replace: a driver-side kill mid-write must not leave the
    # history file truncated (the failure mode this merge exists to survive)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"rows": rows, "refresh_cursor": int(cursor)}, f, indent=1)
    os.replace(tmp, path)
    return rows


def main():
    ours = bench_ours()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_EXTRA.json")
    cursor = read_refresh_cursor(out)
    extra, measured = bench_extra_rows(start=cursor)
    # persist the expensive TPU rows BEFORE the torch baseline: a non-
    # exception death there (OOM kill) must not discard them. Merge runs
    # whenever configs were ATTEMPTED (measured > 0) even if every attempt
    # failed — the cursor must advance past a failing window or the
    # rotation would re-burn its whole budget on the same config forever.
    if extra or measured:
        rows = merge_extra_rows(out, extra, cursor=cursor + measured)
        print(
            f"wrote {len(extra)} fresh / {len(rows)} total extra rows "
            f"to {out}",
            file=sys.stderr,
        )
    try:
        base = bench_torch_baseline()
    except Exception as e:
        print(f"baseline failed: {e}", file=sys.stderr)
        base = None
    # the machine-readable headline MUST be the last stdout line and small:
    # the driver tail-captures stdout and json-parses the final line
    sys.stdout.flush()
    print(
        json.dumps(
            {
                "metric": "pna_multihead_train_graphs_per_sec",
                "value": round(ours, 2),
                "unit": "graphs/sec",
                "vs_baseline": round(ours / base, 3) if base else None,
            }
        )
    )


if __name__ == "__main__":
    main()
