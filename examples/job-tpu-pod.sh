#!/bin/bash
# Multi-host TPU-pod launch — the analog of the reference's Frontier job
# script (job-frontier-preonly-nvme.sh): stage data to host-local disk,
# export the cluster geometry, launch one Python process per TPU-VM host.
#
# Two launch styles:
#
# (A) GCP TPU pod (one worker per host; JAX auto-detects the pod topology,
#     so no HYDRAGNN_TPU_* env vars are needed):
#
#   gcloud compute tpus tpu-vm ssh "$TPU_NAME" --worker=all --command "
#     cd ~/hydragnn_tpu &&
#     mkdir -p /tmp/oc20run/dataset && gsutil -m rsync -r \
#         gs://my-bucket/oc20-shards /tmp/oc20run/dataset &&  # NVMe-staging analog
#     cd /tmp/oc20run && HYDRAGNN_PREFETCH=2 PYTHONPATH=~/hydragnn_tpu \
#     python -u ~/hydragnn_tpu/examples/open_catalyst_2020/train.py --preload
#   "
#   (first produce the shard store once with
#    `python examples/open_catalyst_2020/train.py --preonly` — the
#    reference's preonly ADIOS-write pass, SURVEY.md §3.4)
#
# (B) SLURM-managed hosts (DCN-connected; setup_distributed() reads the
#     SLURM_* variables, parses the nodelist for the coordinator, and calls
#     jax.distributed.initialize — parity with the reference's setup_ddp
#     env sniffing, hydragnn/utils/distributed.py:87-191):
#
#   #SBATCH -N 8
#   #SBATCH -t 02:00:00
#   export HYDRAGNN_TPU_PORT=12355
#   export HYDRAGNN_PREFETCH=2
#   # stage the shard store to node-local storage on every host first
#   srun -N "$SLURM_JOB_NUM_NODES" --ntasks-per-node=1 \
#       rsync -a "$SHARED_FS/oc20-shards/" /tmp/oc20run/dataset/
#   cd /tmp/oc20run && PYTHONPATH="$REPO" \
#   srun -N "$SLURM_JOB_NUM_NODES" --ntasks-per-node=1 \
#       python -u "$REPO"/examples/open_catalyst_2020/train.py --preload
#
# Each process loads ONLY its shard of every batch (DistributedSampler
# split in hydragnn_tpu/data/loaders.py); the global sharded batch is
# assembled with make_array_from_process_local_data and the gradient
# all-reduce rides ICI within a slice / DCN across slices. No NCCL, no MPI.

echo "This is a template — copy the block matching your launcher." >&2
exit 1
