"""CSCE workload: molecular band gap from SMILES strings.

Mirrors ``examples/csce/train_gap.py`` in the reference: a CSV of
(id, SMILES, gap) rows is featurized through the SMILES graph builder
(``hydragnn/utils/smiles_utils.py``) and a single graph head regresses the
gap. Node features are the standard SMILES layout: one-hot atom type +
[atomic number, aromaticity, SP, SP2, SP3, bonded-H count].

Offline data: a generated CSV of random small organic molecules whose "gap"
is a deterministic structure function (aromatic rings narrow it,
heteroatoms shift it) — same CSV schema as the real CSCE dataset.
"""

import csv
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config, random_smiles, train_example

from hydragnn_tpu.utils.smiles import generate_graphdata_from_smilestr

TYPES = {"C": 0, "H": 1, "O": 2, "N": 3, "F": 4, "S": 5, "Cl": 6, "Br": 7}


def synthetic_gap(data) -> float:
    """Deterministic 'band gap' from the featurized graph: aromatic content
    narrows the gap, heteroatoms shift it."""
    off = len(TYPES)
    z = data.x[:, off]
    aromatic_frac = float(data.x[:, off + 1].mean())
    n_heavy = float((z > 1).sum())
    hetero = float(((z > 1) & (z != 6)).sum())
    return 8.0 - 3.0 * aromatic_frac - 0.15 * n_heavy + 0.3 * hetero


def write_csv(path, num_samples, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["id", "smiles", "gap"])
        for i in range(num_samples):
            w.writerow([i, random_smiles(rng), ""])  # gap filled after parse


def load_csv(path):
    data = []
    with open(path) as f:
        for row in csv.DictReader(f):
            d = generate_graphdata_from_smilestr(row["smiles"], [0.0], TYPES)
            gap = float(row["gap"]) if row["gap"] else synthetic_gap(d)
            d.targets = [np.asarray([gap], np.float32)]
            d.target_types = ["graph"]
            data.append(d)
    return data


def main():
    config = load_config(__file__, "csce_gap.json")
    csv_path = str(example_arg("csv", "./dataset/csce_gap.csv"))
    num_samples = int(example_arg("num_samples", 1000))
    if not os.path.exists(csv_path):
        os.makedirs(os.path.dirname(csv_path) or ".", exist_ok=True)
        write_csv(csv_path, num_samples)
    dataset = load_csv(csv_path)
    train_example(config, dataset, log_name="csce_gap")


if __name__ == "__main__":
    main()
