"""Multi-node HPO over the multi-dataset GFM workload — CONCURRENT
training subprocesses, one per trial.

Mirrors ``examples/multidataset_hpo/gfm_deephyper_multi.py:22-70``: trial
geometry is env-driven (``HPO_NNODES_PER_TRIAL`` / ``HPO_NRANKS_PER_TRIAL``,
srun auto-detected via ``SLURM_JOB_ID``), hyperparameters travel as CLI
flags, and the trial metric is the last ``Val Loss:`` the training script
prints. Like the reference's DeepHyper scheduler, up to
``HPO_MAX_CONCURRENT`` trials run simultaneously, each pinned to its own
node block from ``HPO_NODELIST`` (comma-separated; or derived slots), the
TPE sampler updating as each lands. ``HPO_SERIAL=1`` falls back to the
sequential loop. Run ``examples/multidataset/train.py --preonly`` once
first.
"""

import os
import sys

_EXAMPLES = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _EXAMPLES)
sys.path.insert(0, os.path.dirname(_EXAMPLES))  # repo root

from hydragnn_tpu.hpo import TrialLauncher, create_study, optimize_concurrent

TRAIN_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "multidataset", "train.py",
)


def main():
    n_trials = int(os.environ.get("HPO_NUM_TRIALS", "6"))
    launcher = TrialLauncher(
        TRAIN_SCRIPT,
        log_dir=os.environ.get("HPO_LOG_DIR", "./logs/gfm_hpo"),
    )
    study = create_study(direction="minimize", sampler="tpe", n_startup=3)

    def suggest(trial):
        trial.suggest_categorical("model_type", ["PNA", "GIN", "SAGE"])
        trial.suggest_int("hidden_dim", 32, 128)
        trial.suggest_int("num_conv_layers", 2, 5)
        trial.suggest_int("num_headlayers", 1, 3)
        trial.suggest_int("dim_headlayers", 32, 128)
        trial.params["num_epoch"] = int(os.environ.get("HPO_TRIAL_EPOCHS", "3"))
        trial.params["num_samples"] = int(
            os.environ.get("HPO_NUM_SAMPLES", "600")
        )

    if os.environ.get("HPO_SERIAL") == "1":
        def objective(trial):
            suggest(trial)
            return launcher.run(trial)

        study.optimize(objective, n_trials=n_trials)
    else:
        optimize_concurrent(study, launcher, suggest, n_trials=n_trials)
    print(f"best params: {study.best_params}")
    print(f"best value: {study.best_value}")


if __name__ == "__main__":
    main()
