"""QM9 workload: small-molecule graphs, graph-level free energy.

Mirrors ``examples/qm9/qm9.py`` in the reference: node feature is the atomic
number (``qm9_pre_transform`` sets ``x = z``), the single graph head predicts
per-atom free energy (``y[:, 10] / len(x)``,
``/root/reference/examples/qm9/qm9.py:15-22``).

Ingestion goes through the REAL QM9 format: ``--data_dir`` (default
``dataset/qm9/raw``) is parsed with :class:`QM9RawDataset`, which reads the
actual distribution layout (``gdb9.sdf`` + ``gdb9.sdf.csv`` +
``uncharacterized.txt``, or ``dsgdb9nsd_*.xyz``). Drop the real files there
and they are used as-is. Offline (no network egress in this environment)
the example first materializes deterministic synthetic molecules of the QM9
element set *in that same gdb9 layout*, so the real parser is the single
code path either way.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    load_config,
    example_arg,
    pairwise_energy,
    random_molecule,
    train_example,
)

from hydragnn_tpu.data.elements import symbol
from hydragnn_tpu.data.qm9_raw import HAR2EV, QM9RawDataset, write_qm9_sdf

ELEMENTS = [1, 6, 7, 8, 9]  # H C N O F — the QM9 element set


def generate_qm9_format(root, num_samples, seed=0):
    """Synthetic molecules written in the real gdb9 layout. The free-energy
    CSV column (g298) is set so the parsed per-atom target equals the
    deterministic pairwise potential — same label the example always
    trained on, now round-tripped through the real format. A marker file
    records the generation params so a rerun with a different
    ``--num_samples`` regenerates instead of silently reusing the cache
    (real datasets never carry the marker and are never touched)."""
    rng = np.random.default_rng(seed)
    molecules, targets = [], []
    for _ in range(num_samples):
        z, pos = random_molecule(rng, ELEMENTS, int(rng.integers(4, 19)))
        energy = pairwise_energy(z, pos)  # per-atom
        row = np.zeros(19)
        # CSV order: A,B,C,mu..cv,atomization; g298 is column 13
        row[13] = energy * len(z) / HAR2EV  # parser: *HAR2EV, /natoms
        molecules.append(([symbol(int(zz)) for zz in z], pos))
        targets.append(row)
    write_qm9_sdf(root, molecules, np.asarray(targets))
    with open(os.path.join(root, ".synthetic"), "w") as f:
        f.write(f"{num_samples} {seed} {_sdf_hash(root)}\n")


def _sdf_hash(root):
    import hashlib

    with open(os.path.join(root, "gdb9.sdf"), "rb") as f:
        return hashlib.md5(f.read()).hexdigest()


def _synthetic_state(data_dir, num_samples):
    """(is_synthetic, is_stale). The marker records the generated sdf's
    hash — if the on-disk sdf doesn't match (user dropped the REAL dataset
    in over it), the files are treated as real and NEVER regenerated."""
    marker = os.path.join(data_dir, ".synthetic")
    if not os.path.exists(marker) or not os.path.exists(
        os.path.join(data_dir, "gdb9.sdf")
    ):
        return False, False
    fields = open(marker).read().split()
    if len(fields) < 3:
        # legacy marker (pre-hash format): only the generator ever wrote
        # it, so trust it — old behavior, regenerate on count change
        return True, int(fields[0]) != num_samples
    if fields[2] != _sdf_hash(data_dir):
        return False, False  # files are not the ones we generated
    return True, int(fields[0]) != num_samples


def qm9_dataset(num_samples, radius, max_neighbours, seed=0,
                root="dataset/qm9/raw"):
    """Synthetic QM9 round-tripped through the real gdb9 format (the
    single ingestion path) — used by the HPO example and tests."""
    is_syn, stale = _synthetic_state(root, num_samples)
    if not os.path.exists(os.path.join(root, "gdb9.sdf")) or (is_syn and stale):
        generate_qm9_format(root, num_samples, seed)
    return list(
        QM9RawDataset(
            root,
            radius=radius,
            max_neighbours=max_neighbours,
            num_samples=num_samples,
        )
    )


def main():
    config = load_config(__file__, "qm9.json")
    arch = config["NeuralNetwork"]["Architecture"]
    raw_flag = example_arg("num_samples")
    num_samples = int(raw_flag) if raw_flag not in (None, "all", "0") else 1000
    data_dir = str(example_arg("data_dir", "dataset/qm9/raw"))
    have_data = os.path.exists(os.path.join(data_dir, "gdb9.sdf")) or any(
        f.startswith("dsgdb9nsd_")
        for f in (os.listdir(data_dir) if os.path.isdir(data_dir) else [])
    )
    is_synthetic, is_stale = _synthetic_state(data_dir, num_samples)
    if not have_data or (is_synthetic and is_stale):
        generate_qm9_format(data_dir, num_samples)
        is_synthetic = True
    # --num_samples caps REAL data only when given explicitly
    # (--num_samples all / 0 = the whole dataset); synthetic data is
    # exactly num_samples molecules by construction
    cap = None
    if is_synthetic or raw_flag not in (None, "all", "0"):
        cap = num_samples
    dataset = QM9RawDataset(
        data_dir,
        target_index=10,  # free energy, the reference example's property
        per_atom=True,
        radius=arch["radius"],
        max_neighbours=arch["max_neighbours"],
        num_samples=cap,
    )
    train_example(config, list(dataset), log_name="qm9")


if __name__ == "__main__":
    main()
