"""QM9-style workload: small-molecule graphs, graph-level free energy.

Mirrors ``examples/qm9/qm9.py`` in the reference: node feature is the atomic
number (``qm9_pre_transform`` sets ``x = z``), the single graph head predicts
per-atom free energy, GIN backbone, radius-7 graphs capped at 5 neighbours.

The real QM9 download needs network access; offline we generate molecules of
the QM9 element set (H,C,N,O,F) with a deterministic smooth potential as the
label. Drop a directory of real samples in and the generator is skipped.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    load_config,
    example_arg,
    molecule_graph,
    pairwise_energy,
    random_molecule,
    train_example,
)

ELEMENTS = [1, 6, 7, 8, 9]  # H C N O F — the QM9 element set


def qm9_dataset(num_samples, radius, max_neighbours, seed=0):
    rng = np.random.default_rng(seed)
    data = []
    for _ in range(num_samples):
        z, pos = random_molecule(rng, ELEMENTS, int(rng.integers(4, 19)))
        energy = pairwise_energy(z, pos)  # per-atom, like y/len(x)
        data.append(
            molecule_graph(
                z, pos, radius, max_neighbours,
                targets=[np.array([energy])], target_types=["graph"],
            )
        )
    return data


def main():
    config = load_config(__file__, "qm9.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_samples = int(example_arg("num_samples", 1000))
    dataset = qm9_dataset(num_samples, arch["radius"], arch["max_neighbours"])
    train_example(config, dataset, log_name="qm9")


if __name__ == "__main__":
    main()
