"""Shared glue for the example workloads.

Every reference example follows one shape (``examples/md17/md17.py:36-105``):
load the JSON config next to the script, build/load a dataset, split it,
make loaders, derive config fields from the data, build the model, train,
save. This module is that shape for the TPU framework so each example stays
focused on its dataset.

All examples run OFFLINE: this environment has no network egress, so each
example ships a deterministic synthetic generator producing data in the same
schema as the real workload (drop real data in the same directory layout to
use it instead). Generators are seeded — reruns are reproducible.
"""

import json
import os
import sys

import numpy as np

# examples run from a checkout without installation: repo root on the path
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import hydragnn_tpu
from hydragnn_tpu.data import create_dataloaders, split_dataset
from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.parallel.distributed import setup_distributed
from hydragnn_tpu.parallel.mesh import default_mesh
from hydragnn_tpu.train import Trainer, save_model, train_validate_test
from hydragnn_tpu.utils import print_utils
from hydragnn_tpu.utils.config import save_config, update_config


def load_config(example_file: str, name: str) -> dict:
    with open(os.path.join(os.path.dirname(os.path.abspath(example_file)), name)) as f:
        return apply_cli_overrides(json.load(f))


def apply_cli_overrides(config: dict) -> dict:
    """Map hyperparameter CLI flags into the config — the flag set the
    reference's HPO trial launcher passes to its training scripts
    (``gfm_deephyper_multi.py:70-80``), so ``TrialLauncher`` works against
    any example unchanged."""
    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"]["Training"]
    v = example_arg("model_type")
    if v:
        arch["model_type"] = v
    for key in ("hidden_dim", "num_conv_layers"):
        v = example_arg(key)
        if v is not None:
            arch[key] = int(v)
    num_headlayers = example_arg("num_headlayers")
    dim_headlayers = example_arg("dim_headlayers")
    if num_headlayers is not None or dim_headlayers is not None:
        for head in arch["output_heads"].values():
            if num_headlayers is not None:
                head["num_headlayers"] = int(num_headlayers)
            n = int(num_headlayers or head["num_headlayers"])
            if dim_headlayers is not None:
                head["dim_headlayers"] = [int(dim_headlayers)] * n
            elif len(head["dim_headlayers"]) != n:
                head["dim_headlayers"] = [head["dim_headlayers"][0]] * n
    v = example_arg("learning_rate")
    if v is not None:
        training["Optimizer"]["learning_rate"] = float(v)
    for key in ("num_epoch", "batch_size"):
        v = example_arg(key)
        if v is not None:
            training[key] = int(v)
    v = example_arg("steps_per_dispatch")
    if v is True:
        raise SystemExit(
            "--steps_per_dispatch needs a value (steps per XLA dispatch; "
            "0/off disables stacking), e.g. --steps_per_dispatch 8"
        )
    if v is not None:
        # falsy spellings disable stacking (trainer treats 1 as the plain
        # per-batch path), matching the other boolean-ish flags
        if str(v).lower() in ("0", "off", "false", "no"):
            training["steps_per_dispatch"] = 1
        else:
            try:
                training["steps_per_dispatch"] = int(v)
            except ValueError:
                raise SystemExit(
                    f"--steps_per_dispatch: expected an integer or "
                    f"0/off, got {v!r}"
                )
    # execution-mode flags (every example gets them for free):
    # --device-resident stages the training set in HBM; --fit-chunk N
    # additionally runs whole-training chunks as single XLA dispatches
    if example_arg("device-resident"):
        training["device_resident_dataset"] = True
    v = example_arg("fit-chunk")
    if v is True:
        raise SystemExit(
            "--fit-chunk needs a value (epochs per whole-training "
            "dispatch), e.g. --fit-chunk 10"
        )
    if v is not None:
        training["device_resident_dataset"] = True
        training["fit_chunk_epochs"] = int(v)
    return config


def example_flag(flag: str) -> bool:
    """Boolean flag reader: bare ``--foo`` or truthy value is True;
    ``--foo=0`` / ``--foo=false`` is explicitly False."""
    v = example_arg(flag)
    if v is None:
        return False
    if v is True:
        return True
    return str(v).lower() not in ("0", "false", "no", "off")


def example_arg(flag: str, default=None):
    """Tiny argv reader: ``--key=value``, ``--key value``, or bare ``--key``
    (boolean). Examples use a handful of flags; both spellings work."""
    prefix = f"--{flag}="
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a.startswith(prefix):
            return a[len(prefix):]
        if a == f"--{flag}":
            nxt = argv[i + 1] if i + 1 < len(argv) else None
            if nxt is not None and not nxt.startswith("--"):
                return nxt
            return True
    return default


def train_example(config: dict, dataset, log_name: str, seed: int = 0):
    """Split -> loaders -> train. See :func:`train_with_loaders`."""
    training = config["NeuralNetwork"]["Training"]
    trainset, valset, testset = split_dataset(
        dataset, training["perc_train"], False
    )
    return train_with_loaders(
        config, trainset, valset, testset, log_name, seed=seed
    )


def train_with_loaders(config, trainset, valset, testset, log_name, seed=0):
    """Loaders -> derived config -> model -> train -> save.

    Accepts pre-split datasets (lists or shard/dist datasets). Returns
    (state, trainer, val_loss). Prints ``Val Loss: <x>`` at the end — the
    HPO launcher greps exactly that (the reference's DeepHyper trial
    parser, ``gfm_deephyper_multi.py:34-40``).
    """
    setup_distributed()
    verbosity = config.get("Verbosity", {}).get("level", 0)
    suffix = example_arg("log_name_suffix")
    if suffix:
        log_name = f"{log_name}_{suffix}"
    print_utils.setup_log(log_name)

    training = config["NeuralNetwork"]["Training"]
    from hydragnn_tpu.data.loaders import (
        arch_for_auto_policy,
        needs_dense_neighbors,
    )

    arch_cfg = config["NeuralNetwork"]["Architecture"]
    need_triplets = arch_cfg.get("model_type") == "DimeNet"
    train_loader, val_loader, test_loader = create_dataloaders(
        trainset, valset, testset, training["batch_size"], need_triplets,
        need_neighbors=needs_dense_neighbors(
            arch_for_auto_policy(config["NeuralNetwork"])
        ),
        num_buckets=training.get("batch_buckets"),
        contiguous_buckets=training.get("contiguous_buckets"),
        bucket_graph_cap=training.get("bucket_graph_cap", "batch"),
    )
    config = update_config(config, train_loader, val_loader, test_loader)
    save_config(config, log_name)

    arch = dict(config["NeuralNetwork"]["Architecture"])
    arch["loss_function_type"] = training.get("loss_function_type", "mse")
    arch["conv_checkpointing"] = training.get("conv_checkpointing", False)
    model = create_model_config(arch, verbosity)
    trainer = Trainer(model, training, mesh=default_mesh(), verbosity=verbosity)
    state = trainer.init_state(next(iter(train_loader)), seed=seed)

    state = train_validate_test(
        trainer,
        state,
        train_loader,
        val_loader,
        test_loader,
        config["NeuralNetwork"],
        log_name,
        verbosity,
    )
    save_model(state, log_name)
    val_loss, _ = trainer.evaluate(state, val_loader)
    print(f"Val Loss: {val_loss}")
    return state, trainer, float(val_loss)


def train_with_stream(config, sources, valset, testset, log_name,
                      weights=None, seed=0):
    """:func:`train_with_loaders`'s streaming twin: the TRAIN split never
    materializes — ``sources`` are :class:`~hydragnn_tpu.data.stream.
    StreamSource`\\ s fed through the weighted mix, the auto-tuned bucket
    planner replaces the hand ``batch_buckets`` table, and config
    derivation runs over a cursor-neutral probe window (docs/data.md)."""
    from hydragnn_tpu.data.stream import assemble_stream_loaders
    from hydragnn_tpu.obs import runtime as obs

    setup_distributed()
    verbosity = config.get("Verbosity", {}).get("level", 0)
    suffix = example_arg("log_name_suffix")
    if suffix:
        log_name = f"{log_name}_{suffix}"
    print_utils.setup_log(log_name)

    training = config["NeuralNetwork"]["Training"]
    scfg = config.get("Dataset", {}).get("streaming", {})
    train_loader, val_loader, test_loader, probe_loader = (
        assemble_stream_loaders(
            sources, weights, training["batch_size"], scfg, valset,
            testset, num_buckets=training.get("batch_buckets"),
        )
    )
    if train_loader.plan_event:
        obs.emit("bucket_plan", **train_loader.plan_event)
    config = update_config(config, probe_loader, val_loader, test_loader)
    save_config(config, log_name)

    arch = dict(config["NeuralNetwork"]["Architecture"])
    arch["loss_function_type"] = training.get("loss_function_type", "mse")
    arch["conv_checkpointing"] = training.get("conv_checkpointing", False)
    model = create_model_config(arch, verbosity)
    trainer = Trainer(model, training, mesh=default_mesh(),
                      verbosity=verbosity)
    state = trainer.init_state(train_loader.example_batch(), seed=seed)

    state = train_validate_test(
        trainer,
        state,
        train_loader,
        val_loader,
        test_loader,
        config["NeuralNetwork"],
        log_name,
        verbosity,
    )
    save_model(state, log_name)
    val_loss, _ = trainer.evaluate(state, val_loader)
    print(f"Val Loss: {val_loss}")
    return state, trainer, float(val_loss)


# ---------------------------------------------------------------------------
# Synthetic molecule/crystal builders shared by several examples.
# ---------------------------------------------------------------------------

def random_molecule(rng, elements, n_atoms, spread=1.5):
    """Random cloud molecule: atomic numbers z and jittered positions with a
    minimum-distance relaxation so radius graphs are well conditioned."""
    z = rng.choice(elements, size=n_atoms)
    pos = rng.normal(0.0, spread, (n_atoms, 3))
    for _ in range(10):  # push overlapping atoms apart
        d = pos[:, None, :] - pos[None, :, :]
        dist = np.linalg.norm(d, axis=-1) + np.eye(n_atoms)
        push = (dist < 0.8) & ~np.eye(n_atoms, dtype=bool)
        if not push.any():
            break
        pos += 0.25 * (d / dist[..., None] * push[..., None]).sum(axis=1)
    return z.astype(np.float32), pos.astype(np.float32)


def molecule_graph(z, pos, radius, max_neighbours=None, targets=(),
                   target_types=()):
    """GraphData with radius-graph edges and per-head targets."""
    from hydragnn_tpu.data import GraphData, radius_graph

    d = GraphData(
        x=np.asarray(z, np.float32).reshape(-1, 1),
        pos=np.asarray(pos, np.float32),
    )
    d.edge_index = radius_graph(
        d.pos, radius, max_neighbours if max_neighbours else 32
    )
    d.targets = [np.asarray(t, np.float32) for t in targets]
    d.target_types = list(target_types)
    return d


_SMILES_CORES = ["C", "CC", "CCC", "CCCC", "c1ccccc1", "C1CCCCC1",
                 "c1ccncc1", "C1CCOC1"]
_SMILES_SUBS = ["", "O", "N", "F", "C#N", "C(=O)O", "CO", "C=C", "S"]


def random_smiles(rng, max_subs=2):
    """Small random organic molecule as a SMILES string (offline stand-in
    for a real SMILES CSV; parseable by the built-in parser)."""
    core = _SMILES_CORES[int(rng.integers(len(_SMILES_CORES)))]
    subs = [
        _SMILES_SUBS[int(rng.integers(len(_SMILES_SUBS)))]
        for _ in range(int(rng.integers(0, max_subs + 1)))
    ]
    out = core
    for s in subs:
        if s:
            out += f"({s})" if out[-1].isalnum() else s
    return out


def pair_potential_forces(z, pos, cutoff=3.0, r0=1.5, w_scale=0.05):
    """Smooth species-weighted pair potential of the OBSERVED configuration
    and its exact analytic forces.

    phi(r) = w_ij (r - r0)^2 s(r) with the cosine cutoff
    s(r) = 0.5 (1 + cos(pi r / rc)); w_ij = w_scale * sqrt(z_i z_j).
    Returns (total energy, per-atom forces = -grad E). Both are closed-form
    functions of (z, pos) alone — no latent state — so a GNN can learn them
    from single frames (the property the reference's deterministic targets
    have, ``/root/reference/tests/deterministic_graph_data.py:160-193``).
    """
    pos = np.asarray(pos, np.float64)
    dvec = pos[:, None, :] - pos[None, :, :]
    r = np.linalg.norm(dvec, axis=-1)
    np.fill_diagonal(r, np.inf)
    phi, dphi, inside = _pair_terms(z, r, cutoff, r0, w_scale)
    energy = float(phi.sum() / 2.0)  # each pair counted twice
    with np.errstate(invalid="ignore"):
        unit = np.where(inside[..., None], dvec / r[..., None], 0.0)
    forces = -(dphi[..., None] * unit).sum(axis=1)
    return energy, forces


def _pair_terms(z, r, cutoff, r0, w_scale):
    """Shared pair-potential core: phi(r), dphi/dr, and the inside-cutoff
    mask from a pairwise distance matrix (diagonal pre-set to inf). The
    single place the functional form lives — both the free-space and the
    minimum-image labels call through here."""
    zz = np.asarray(z, np.float64)
    w = w_scale * np.sqrt(zz[:, None] * zz[None, :])
    inside = r < cutoff
    rc = float(cutoff)
    rs = np.where(inside, r, rc)  # finite stand-in outside the cutoff
    s = np.where(inside, 0.5 * (1.0 + np.cos(np.pi * rs / rc)), 0.0)
    ds = np.where(inside, -0.5 * np.pi / rc * np.sin(np.pi * rs / rc), 0.0)
    dr = rs - r0
    phi = w * dr**2 * s
    dphi = w * (2.0 * dr * s + dr**2 * ds)  # dphi/dr
    return phi, dphi, inside


def pbc_pair_energy(z, pos, cell, cutoff=3.0, r0=2.0, w_scale=0.05):
    """Minimum-image (diagonal-cell) variant of the pair potential in
    :func:`pair_potential_forces` — energy only.

    Same smooth functional form (shared :func:`_pair_terms` core),
    distances taken through the periodic cell so slab workloads get a
    label that is a continuous function of the observed geometry. Valid
    while ``cutoff < min(diag(cell)) / 2`` (the minimum-image criterion),
    which the OC20 slab satisfies (cutoff 3.5, in-plane period 7.2)."""
    pos = np.asarray(pos, np.float64)
    period = np.diag(np.asarray(cell, np.float64))
    dvec = pos[:, None, :] - pos[None, :, :]
    dvec -= np.round(dvec / period) * period
    r = np.linalg.norm(dvec, axis=-1)
    np.fill_diagonal(r, np.inf)
    phi, _, _ = _pair_terms(z, r, cutoff, r0, w_scale)
    return float(phi.sum() / 2.0)


def pairwise_energy(z, pos, cutoff=3.0):
    """Deterministic smooth 'potential': element-weighted pair interaction
    within a cutoff. Learnable from (z, pos); plays the role of a real label."""
    zz = np.asarray(z, np.float64)
    d = np.linalg.norm(pos[:, None, :] - pos[None, :, :], axis=-1)
    n = len(zz)
    mask = (d < cutoff) & ~np.eye(n, dtype=bool)
    with np.errstate(divide="ignore", invalid="ignore"):
        contrib = np.where(mask, np.sqrt(zz[:, None] * zz[None, :]) / (d + 1.0), 0.0)
    return float(contrib.sum() / (2 * n))
