"""ANI-1x workload: large CHNO conformer sweep through the shard pipeline.

Mirrors ``examples/ani1_x`` in the reference (ANI-1x DFT energies over ~5M
conformations, streamed through the ADIOS/pickle writers). The offline
example keeps the two-phase shape: ``--preonly`` writes GraphPack shards of
generated CHNO conformers in parallel, training mmaps them back.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    example_arg,
    load_config,
    molecule_graph,
    pairwise_energy,
    random_molecule,
    train_with_loaders,
)

from hydragnn_tpu.data import split_dataset
from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter
from hydragnn_tpu.parallel.distributed import (
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)

ELEMENTS = [1, 6, 7, 8]  # the ANI-1x element set


def preonly(config, modelname, num_samples):
    world, rank = get_comm_size_and_rank()
    arch = config["NeuralNetwork"]["Architecture"]
    my_ids = list(nsplit(range(num_samples), world))[rank]
    rng = np.random.default_rng(7 + rank)
    samples = []
    for _ in my_ids:
        z, pos = random_molecule(rng, ELEMENTS, int(rng.integers(4, 14)))
        energy = pairwise_energy(z, pos)
        samples.append(
            molecule_graph(
                z, pos, arch["radius"], arch["max_neighbours"],
                targets=[np.array([energy])], target_types=["graph"],
            )
        )
    trainset, valset, testset = split_dataset(samples, 0.9, False)
    for name, ds in [("trainset", trainset), ("valset", valset),
                     ("testset", testset)]:
        w = ShardWriter(f"dataset/{modelname}_{name}", rank=rank)
        w.add(ds)
        w.save()
    print(f"rank {rank}: wrote {len(trainset)}/{len(valset)}/{len(testset)}")


def main():
    config = load_config(__file__, "ani1x.json")
    modelname = str(example_arg("modelname", "ANI1x"))
    num_samples = int(example_arg("num_samples", 1500))
    setup_distributed()
    if example_arg("preonly"):
        preonly(config, modelname, num_samples)
        return
    splits = [
        ShardDataset(f"dataset/{modelname}_{name}",
                     preload=bool(example_arg("preload")))
        for name in ("trainset", "valset", "testset")
    ]
    train_with_loaders(config, *splits, log_name=modelname.lower())


if __name__ == "__main__":
    main()
