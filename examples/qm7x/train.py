"""QM7-X workload: perturbed small-molecule conformations, SchNet backbone,
energy + forces multihead.

Mirrors ``examples/qm7x`` in the reference (QM7-X ships ~100 non-equilibrium
conformations per molecule with EPBE0+MBD energies/forces). Offline: random
CHONS molecules, each with several displaced conformations; the energy is a
pair potential around the sampled geometry and the forces are a consistent
harmonic restoring field.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    example_arg,
    load_config,
    molecule_graph,
    pairwise_energy,
    random_molecule,
    train_example,
)

ELEMENTS = [1, 6, 7, 8, 16]


def qm7x_dataset(num_molecules, confs_per_mol, radius, max_neighbours, seed=0):
    rng = np.random.default_rng(seed)
    data = []
    for _ in range(num_molecules):
        z, eq = random_molecule(rng, ELEMENTS, int(rng.integers(4, 8)))
        for _ in range(confs_per_mol):
            disp = rng.normal(0, 0.12, eq.shape).astype(np.float32)
            pos = eq + disp
            energy = pairwise_energy(z, pos) + 0.5 * float((disp**2).sum())
            forces = -disp  # restoring field toward the sampled equilibrium
            data.append(
                molecule_graph(
                    z, pos, radius, max_neighbours,
                    targets=[np.array([energy]), forces],
                    target_types=["graph", "node"],
                )
            )
    return data


def main():
    config = load_config(__file__, "qm7x.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_molecules = int(example_arg("num_samples", 100))
    dataset = qm7x_dataset(
        num_molecules, 8, arch["radius"], arch["max_neighbours"]
    )
    train_example(config, dataset, log_name="qm7x")


if __name__ == "__main__":
    main()
