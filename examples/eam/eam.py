"""EAM workload: NiNb alloy supercells in extended-CFG format, formation
energy prediction.

Mirrors ``examples/eam/eam.py``: AtomEye ``.cfg`` files (H0 supercell,
scaled coordinates, mass/symbol lines) with graph features in the sibling
``.bulk`` file, driven through ``run_training`` with format "CFG".

Offline data: FCC NiNb solid solutions; formation energy is an
EAM-flavoured embedding function of local coordination.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config

import hydragnn_tpu

NI, NB = 28, 41
ALAT = 3.52


def _fcc_positions(cells):
    basis = np.array(
        [[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]], np.float64
    )
    pos = []
    for x in range(cells):
        for y in range(cells):
            for z in range(cells):
                for b in basis:
                    pos.append((np.array([x, y, z]) + b))
    return np.asarray(pos) / cells  # scaled coordinates in [0,1)


def _eam_energy(z, scaled, cell):
    """Embedded-atom flavour: E = sum_i F(rho_i), rho from neighbor density."""
    pos = scaled @ cell
    d = np.linalg.norm(pos[:, None] - pos[None, :], axis=-1)
    np.fill_diagonal(d, np.inf)
    w = np.where(z == NI, 1.0, 1.6)  # Nb contributes more electron density
    rho = (np.exp(-d / 2.5) * w[None, :]).sum(1)
    return float((-np.sqrt(rho) + 0.05 * rho).sum() / len(z))


def write_cfg_dataset(path, num_configs, cells=2, seed=0):
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    scaled = _fcc_positions(cells)
    n = len(scaled)
    cell = np.eye(3) * ALAT * cells
    for c in range(num_configs):
        z = np.where(rng.random(n) < rng.uniform(0.3, 0.9), NI, NB)
        jitter = scaled + rng.normal(0, 0.004, scaled.shape)
        energy = _eam_energy(z, jitter, cell)
        lines = [f"Number of particles = {n}", "A = 1.0 Angstrom"]
        for i in range(3):
            for j in range(3):
                lines.append(f"H0({i+1},{j+1}) = {cell[i, j]:.6f} A")
        lines += [".NO_VELOCITY.", "entry_count = 3"]
        for i in np.argsort(z):  # group by species for mass/symbol blocks
            sym = "Ni" if z[i] == NI else "Nb"
            mass = "58.693" if z[i] == NI else "92.906"
            lines.append(mass)
            lines.append(sym)
            lines.append(
                f"{jitter[i,0]:.6f} {jitter[i,1]:.6f} {jitter[i,2]:.6f}"
            )
        base = os.path.join(path, f"config{c}")
        with open(base + ".cfg", "w") as f:
            f.write("\n".join(lines))
        with open(base + ".bulk", "w") as f:
            f.write(f"{energy:.8f}\n")


def main():
    config = load_config(
        __file__, str(example_arg("config", "NiNb_EAM_energy.json"))
    )
    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())
    raw_path = config["Dataset"]["path"]["total"]
    num_configs = int(example_arg("num_samples", 300))
    if not os.path.exists(raw_path) or not os.listdir(raw_path):
        write_cfg_dataset(raw_path, num_configs)
    hydragnn_tpu.run_training(config)


if __name__ == "__main__":
    main()
