"""OGB-style workload: HOMO-LUMO gap regression over a large SMILES set.

Mirrors ``examples/ogb/train_gap.py`` in the reference (PCQM4Mv2-style CSV
of SMILES + gap, same featurization as the CSCE example but a GIN backbone
and a bigger sample budget). The reference streams this dataset through
pickle/ADIOS writers; at example scale the in-memory path is used — see
``examples/open_catalyst_2020`` for the shard-store pipeline.
"""

import csv
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config, random_smiles, train_example

from hydragnn_tpu.utils.smiles import generate_graphdata_from_smilestr

TYPES = {"C": 0, "H": 1, "O": 2, "N": 3, "F": 4, "S": 5, "Cl": 6, "Br": 7}


def synthetic_gap(data) -> float:
    """Deterministic HOMO-LUMO stand-in: conjugation (aromatic + double
    bonds) closes the gap, saturated carbons open it."""
    off = len(TYPES)
    aromatic = float(data.x[:, off + 1].sum())
    sp2 = float(data.x[:, off + 3].sum())
    sp3 = float(data.x[:, off + 4].sum())
    return 10.0 - 0.5 * aromatic - 0.3 * sp2 + 0.1 * sp3


def write_csv(path, num_samples, seed=1):
    rng = np.random.default_rng(seed)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["smiles", "gap"])
        for _ in range(num_samples):
            w.writerow([random_smiles(rng, max_subs=3), ""])


def load_csv(path):
    data = []
    with open(path) as f:
        for row in csv.DictReader(f):
            d = generate_graphdata_from_smilestr(row["smiles"], [0.0], TYPES)
            gap = float(row["gap"]) if row["gap"] else synthetic_gap(d)
            d.targets = [np.asarray([gap], np.float32)]
            d.target_types = ["graph"]
            data.append(d)
    return data


def main():
    config = load_config(__file__, "ogb_gap.json")
    csv_path = str(example_arg("csv", "./dataset/ogb_gap.csv"))
    num_samples = int(example_arg("num_samples", 2000))
    if not os.path.exists(csv_path):
        os.makedirs(os.path.dirname(csv_path) or ".", exist_ok=True)
        write_csv(csv_path, num_samples)
    dataset = load_csv(csv_path)
    train_example(config, dataset, log_name="ogb_gap")


if __name__ == "__main__":
    main()
