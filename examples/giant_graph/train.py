"""Giant-graph workload: ONE large atomistic system partitioned across the
device mesh (graph-partition parallelism).

No reference counterpart — HydraGNN's scaling axis is data parallelism over
many small graphs; a single system larger than one accelerator's memory is
out of its reach. Here a large FCC supercell (default ~16k atoms; set
--num_atoms) is sharded node-wise over all available devices
(``hydragnn_tpu/parallel/graph_partition.py``): Morton-ordered partitions,
halo all_to_all exchanges per conv layer, psum'd BatchNorm/pool/loss, and a
shard_map training step whose gradients are psum'd across shards.

Run on CPU for a quick look:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/giant_graph/train.py --num_atoms 4096 --steps 10
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg  # noqa: E402

sys.path.insert(
    0,
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
)


class _Sample:
    pass


def fcc_supercell(num_atoms: int, seed: int = 0):
    """FCC lattice with thermal displacement; energy/force labels from a
    smooth pair potential (deterministic, offline)."""
    rng = np.random.default_rng(seed)
    cells = max(1, round((num_atoms / 4) ** (1.0 / 3.0)))
    base = np.array(
        [[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]], np.float32
    )
    pos = []
    for i in range(cells):
        for j in range(cells):
            for k in range(cells):
                pos.append(base + np.array([i, j, k], np.float32))
    pos = np.concatenate(pos, 0) * 3.6  # Cu-like lattice constant (A)
    pos = pos + 0.05 * rng.standard_normal(pos.shape).astype(np.float32)
    n = pos.shape[0]

    # radius graph via the framework's cell-list builder
    from hydragnn_tpu.data.radius_graph import radius_graph

    edge_index = radius_graph(pos, radius=3.0, max_neighbors=12)

    s = _Sample()
    s.pos = pos
    s.x = rng.random((n, 1)).astype(np.float32)
    s.edge_index = edge_index
    s.edge_attr = None
    # smooth per-node target + global energy (same flavor as tests/synthetic)
    send, recv = edge_index
    d = np.linalg.norm(pos[send] - pos[recv], axis=1)
    per_edge = np.exp(-d / 2.0)
    node_e = np.zeros(n, np.float32)
    np.add.at(node_e, recv, per_edge.astype(np.float32))
    s.targets = [
        np.array([node_e.mean()], np.float32),
        node_e[:, None] / max(node_e.max(), 1e-6),
    ]
    return s


def main():
    # --cpu_devices N: demo on a virtual CPU mesh (must pin the platform
    # BEFORE the first backend touch — same trick as tests/conftest.py)
    cpu_devices = example_arg("cpu_devices")
    if cpu_devices:
        try:
            cpu_devices = int(cpu_devices)
        except (TypeError, ValueError):
            raise SystemExit("--cpu_devices needs a device count, e.g. --cpu_devices 8")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={cpu_devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    num_atoms = int(example_arg("num_atoms") or 16384)
    steps = max(int(example_arg("steps") or 20), 5)  # compile + 2 warmup + timed

    import optax

    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.models import create_model_config, init_model_params
    from hydragnn_tpu.parallel.graph_partition import (
        make_partitioned_train_step,
        partition_graph,
        put_partitioned_batch,
        put_partitioned_state,
    )
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.trainer import TrainState

    n_dev = len(jax.devices())
    print(f"devices: {n_dev}, atoms: {num_atoms}")
    sample = fcc_supercell(num_atoms)
    print(f"built graph: {sample.pos.shape[0]} nodes, "
          f"{sample.edge_index.shape[1]} edges")

    arch = {
        "model_type": "PNA",
        "input_dim": 1,
        "hidden_dim": 64,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 32,
                "num_headlayers": 2,
                "dim_headlayers": [32, 32],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [32, 32],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 3,
        "pna_deg": list(np.bincount(
            np.bincount(sample.edge_index[1], minlength=sample.pos.shape[0])
        )),
        "equivariance": False,
    }

    # --dense: scatter-free neighbor-list aggregation inside each shard
    # (ops/dense_agg.py; 1.7-3.3x faster at this scale on v5e)
    from common import example_flag

    dense = example_flag("dense")

    t0 = time.time()
    pbatch, info = partition_graph(
        sample, n_dev, ("graph", "node"), (1, 1), order="morton",
        need_neighbors=dense,
    )
    print(f"partitioned in {time.time() - t0:.2f}s: "
          f"{info.nl} nodes/shard, {info.el} edges/shard, halo {info.halo}"
          + (f", dense k_in {info.k_in}" if dense else ""))

    mesh = make_mesh(n_dev, "graph")
    pbatch = put_partitioned_batch(pbatch, mesh, "graph")

    # init params on a single-shard-sized throwaway batch (params depend
    # only on feature dims)
    ref_model = create_model_config(dict(arch))
    small = fcc_supercell(256, seed=1)
    n_pad, e_pad, g_pad = pad_sizes_for(
        small.pos.shape[0], small.edge_index.shape[1], 1
    )
    init_batch = collate_graphs(
        [small], n_pad, e_pad, g_pad, ("graph", "node"), (1, 1), to_device=True
    )
    variables = init_model_params(ref_model, init_batch)

    arch["partition_axis"] = "graph"
    model = create_model_config(arch)
    tx = optax.adamw(1e-3)
    state = TrainState(
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(variables["params"]),
        step=np.zeros((), np.int32),
    )
    state = put_partitioned_state(state, mesh)
    step = make_partitioned_train_step(model, tx, mesh, "graph")

    from hydragnn_tpu.utils.sync import fence

    rng = jax.random.PRNGKey(0)
    rng, warm = jax.random.split(rng)
    state, metrics = step(state, pbatch, warm)  # compile
    loss0 = metrics["loss"]
    for _ in range(2):  # settle any backend warmup
        rng, sub = jax.random.split(rng)
        state, metrics = step(state, pbatch, sub)
    fence(metrics["loss"])
    t0 = time.time()
    for i in range(3, steps):
        rng, sub = jax.random.split(rng)
        state, metrics = step(state, pbatch, sub)
    # true completion fence — block_until_ready does not block on tunneled
    # dev backends; the single host readback is amortized over the steps
    fence(metrics["loss"])
    dt = (time.time() - t0) / max(steps - 3, 1)
    print(f"step 0: loss {float(loss0):.6f}")
    print(
        f"step {steps - 1}: loss {float(metrics['loss']):.6f}  "
        f"({dt * 1e3:.1f} ms/step, {sample.pos.shape[0] / dt:.0f} atoms/sec)"
    )


if __name__ == "__main__":
    main()
