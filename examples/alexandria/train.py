"""Alexandria workload: periodic bulk crystals, formation energy (graph) +
magnetic moment (node) multihead.

Mirrors ``examples/alexandria`` in the reference (the Alexandria DFT
database of periodic structures). Offline: random rock-salt/CsCl-like
binary crystals with full 3D periodic radius graphs; formation energy is an
electronegativity-difference mixing rule and moments follow the magnetic
species' local environment.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config, train_example

from hydragnn_tpu.data import GraphData, radius_graph_pbc
from hydragnn_tpu.utils.periodic_table import element

PAIRS = [(26, 8), (27, 8), (28, 8), (22, 7), (23, 7)]  # FeO CoO NiO TiN VN
MOMENTS = {26: 2.2, 27: 1.7, 28: 0.6, 22: 0.0, 23: 0.3}


def make_crystal(rng, radius, max_neighbours):
    """4x4x4 rock-salt sites: every cell dimension exceeds 2*radius so no
    pair is reachable through two periodic images (the PBC builder rejects
    such cells)."""
    za, zb = PAIRS[int(rng.integers(len(PAIRS)))]
    alat = 4.2 + 0.2 * rng.standard_normal()
    pos, z = [], []
    for i in range(4):
        for j in range(4):
            for k in range(4):
                pos.append([i * alat / 2, j * alat / 2, k * alat / 2])
                z.append(za if (i + j + k) % 2 == 0 else zb)
    # random antisite defects make the node head non-trivial
    z = np.asarray(z, np.float64)
    flips = rng.random(len(z)) < 0.1
    z[flips] = np.where(z[flips] == za, zb, za)
    pos = np.asarray(pos, np.float64) + rng.normal(0, 0.04, (len(z), 3))
    cell = np.diag([2 * alat, 2 * alat, 2 * alat])

    en_a = element(int(za)).en_pauling
    en_b = element(int(zb)).en_pauling
    frac_a = float((z == za).mean())
    energy = -abs(en_a - en_b) * 4 * frac_a * (1 - frac_a) - 0.5

    d = GraphData(
        x=z.astype(np.float32).reshape(-1, 1),
        pos=pos.astype(np.float32),
        supercell_size=cell,
    )
    d.edge_index, lengths = radius_graph_pbc(pos, cell, radius, max_neighbours)
    # moment: species value damped by like-neighbor count
    like = np.zeros(len(z))
    for s, r in zip(*d.edge_index):
        like[r] += float(z[s] == z[r])
    moment = np.array([MOMENTS.get(int(zi), 0.0) for zi in z])
    moment = moment * (1.0 - 0.05 * like)
    d.targets = [np.asarray([energy], np.float32),
                 moment.astype(np.float32).reshape(-1, 1)]
    d.target_types = ["graph", "node"]
    return d


def main():
    config = load_config(__file__, "alexandria.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_samples = int(example_arg("num_samples", 600))
    rng = np.random.default_rng(11)
    dataset = [
        make_crystal(rng, arch["radius"], arch["max_neighbours"])
        for _ in range(num_samples)
    ]
    train_example(config, dataset, log_name="alexandria")


if __name__ == "__main__":
    main()
