"""MPtrj workload: relaxation-trajectory frames, E(3)-equivariant EGNN,
energy + forces.

Mirrors ``examples/mptrj`` in the reference (Materials Project relaxation
trajectories driving an EGNN force field,
``/root/reference/examples/mptrj/train.py:57-118``).

Ingestion goes through the REAL MPtrj format: ``--data_dir`` (default
``dataset/mptrj``) is scanned for ``MPtrj*.json`` and parsed with
:func:`load_mptrj`, which reads the actual nested schema
(``{mp_id: {frame_id: {structure: pymatgen-dict, energy_per_atom, force,
stress, magmom}}}``) without pymatgen. Drop the real
``MPtrj_2022.9_full.json`` there and it is used as-is. Offline, the example
first materializes synthetic relaxation trajectories *in that same JSON
schema*, so the real parser is the single code path either way.
"""

import glob
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    example_arg,
    load_config,
    pair_potential_forces,
    random_molecule,
    train_example,
)

from hydragnn_tpu.data.mptrj import load_mptrj, write_mptrj_json

ELEMENTS = [3, 14, 26, 8]  # Li Si Fe O — battery-materials flavour


def trajectory_records(rng, traj_id, frames=6):
    """One synthetic relaxation: every intermediate frame is a record in
    the MPtrj flat schema (energy per atom, forces along the relaxation
    path) — the structure of real MPtrj frames.

    Labels are the closed-form pair potential of each OBSERVED frame
    (energy per atom + its exact analytic forces), and the trajectory
    itself is gradient descent on that same potential — so frames are
    genuine relaxation steps AND every label is a function of the frame
    alone. (The round-4 generator labelled frames with the distance to a
    latent per-trajectory equilibrium the model never observes, which is
    unlearnable beyond dataset statistics — val MAE was flat from epoch
    0. See VERDICT round 4, item 1.)

    Density and potential strength are tuned so most atoms carry O(1)
    forces: a Gaussian cloud at spread 2.0 put typical pair distances past
    the 3.0 A cutoff, so ~80% of force labels were ~0 and 'predict zero'
    was a one-epoch optimum (the round-4.5 flat-validation residual).
    spread = 0.55 n^(1/3) keeps density constant across sizes
    (frac |F|>0.1 = 0.77, F std 1.6 at w_scale 0.25, vs 0.10 before)."""
    n_atoms = int(rng.integers(6, 12))
    z, pos = random_molecule(
        rng, ELEMENTS, n_atoms, spread=0.55 * n_atoms ** (1.0 / 3.0)
    )
    lattice = np.diag([30.0, 30.0, 30.0])  # big box; loader is non-PBC anyway
    records = []
    cur = pos + rng.normal(0, 0.25, pos.shape)
    for fi in range(frames):
        energy, forces = pair_potential_forces(z, cur, w_scale=0.25)
        records.append(
            {
                "mp_id": f"mp-{traj_id}",
                "frame_id": f"mp-{traj_id}-{fi}-0",
                "z": z.astype(np.int64),
                "pos": cur.astype(np.float64) + 15.0,  # centered in the box
                "lattice": lattice,
                "energy": energy / len(z),  # per atom, like real MPtrj
                "forces": forces,
                "magmom": np.zeros(len(z)),
            }
        )
        cur = cur + 0.05 * np.clip(forces, -5.0, 5.0)  # one relaxation step
    return records


def main():
    config = load_config(__file__, "mptrj.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_traj = int(example_arg("num_samples", 120))
    # cap on parsed REAL frames (the full MPtrj json is ~1.6M frames;
    # loading it whole is a deliberate act, not a default)
    max_frames = example_arg("max_frames", 20000)
    max_frames = None if str(max_frames) in ("0", "all") else int(max_frames)
    data_dir = str(example_arg("data_dir", "dataset/mptrj"))
    synthetic_path = os.path.join(data_dir, "MPtrj_synthetic.json")
    marker = synthetic_path + ".meta"
    paths = sorted(glob.glob(os.path.join(data_dir, "MPtrj*.json")))
    real_paths = [p for p in paths if p != synthetic_path]
    if real_paths:
        # real MPtrj files present: never mix a leftover synthetic file in
        paths = real_paths
    # v3: pair-potential labels (learnable from the frame) at constant
    # density + O(1) force scale; the marker keys on generator version +
    # size so relabeling invalidates old files
    marker_want = f"v3:{num_traj}"
    stale_synthetic = paths == [synthetic_path] and (
        not os.path.exists(marker)
        or open(marker).read().strip() != marker_want
    )
    if not paths or stale_synthetic:
        rng = np.random.default_rng(5)
        records = []
        for t in range(num_traj):
            records.extend(trajectory_records(rng, t))
        write_mptrj_json(synthetic_path, records)
        with open(marker, "w") as f:
            f.write(marker_want)
        paths = [synthetic_path]
    dataset = []
    for p in paths:
        remaining = None if max_frames is None else max_frames - len(dataset)
        if remaining is not None and remaining <= 0:
            break
        dataset.extend(
            load_mptrj(
                p,
                radius=arch["radius"],
                max_neighbours=arch["max_neighbours"],
                num_samples=remaining,
            )
        )
    train_example(config, dataset, log_name="mptrj")


if __name__ == "__main__":
    main()
