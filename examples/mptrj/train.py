"""MPtrj workload: relaxation-trajectory frames, E(3)-equivariant EGNN,
energy + forces.

Mirrors ``examples/mptrj`` in the reference (Materials Project relaxation
trajectories driving an EGNN force field). Offline: random clusters relaxed
toward equilibrium in steps; every intermediate frame contributes a sample
whose forces point along the relaxation path — exactly the structure of
real MPtrj frames.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    example_arg,
    load_config,
    molecule_graph,
    random_molecule,
    train_example,
)

ELEMENTS = [3, 14, 26, 8]  # Li Si Fe O — battery-materials flavour


def trajectory(rng, radius, max_neighbours, frames=6):
    z, pos = random_molecule(rng, ELEMENTS, int(rng.integers(6, 12)), spread=2.0)
    eq = pos + rng.normal(0, 0.05, pos.shape)  # the 'relaxed' geometry
    samples = []
    cur = pos + rng.normal(0, 0.35, pos.shape)
    for _ in range(frames):
        disp = cur - eq
        energy = 0.5 * float((disp**2).sum()) / len(z)
        forces = -disp
        samples.append(
            molecule_graph(
                z, cur.astype(np.float32), radius, max_neighbours,
                targets=[np.array([energy]), forces.astype(np.float32)],
                target_types=["graph", "node"],
            )
        )
        cur = cur - 0.4 * disp  # one relaxation step
    return samples


def main():
    config = load_config(__file__, "mptrj.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_traj = int(example_arg("num_samples", 120))
    rng = np.random.default_rng(5)
    dataset = []
    for _ in range(num_traj):
        dataset.extend(trajectory(rng, arch["radius"], arch["max_neighbours"]))
    train_example(config, dataset, log_name="mptrj")


if __name__ == "__main__":
    main()
