"""Open Catalyst 2022 workload: oxide catalyst slabs, total-energy + forces
multihead, same sharded pipeline as OC2020.

Mirrors ``examples/open_catalyst_2022/train.py`` in the reference, which
shares OC2020's ADIOS/pickle/DDStore machinery but predicts total energy
with per-atom forces (S2EF-total task). The pipeline here is literally the
OC2020 module with an oxide structure generator and a forces head.
"""

import importlib.util
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
from common import example_arg, load_config, train_with_loaders

from hydragnn_tpu.data import GraphData, radius_graph_pbc, split_dataset
from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter
from hydragnn_tpu.parallel.distributed import (
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)

_spec = importlib.util.spec_from_file_location(
    "oc20_train", os.path.join(os.path.dirname(_HERE),
                               "open_catalyst_2020", "train.py")
)
_oc20 = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_oc20)

METALS = [22, 26, 30]  # Ti Fe Zn — oxide formers
ALAT = 4.2
VACUUM = 15.0


def make_oxide(rng, radius, max_neighbours):
    """Rock-salt-like metal-oxide slab with relaxational displacements;
    energy is a Coulomb-flavoured pair sum, forces its analytic gradient."""
    metal = METALS[int(rng.integers(len(METALS)))]
    pos, z = [], []
    for layer in range(2):
        for i in range(2):
            for j in range(2):
                pos.append([i * ALAT / 2 * 2, j * ALAT, layer * ALAT / 2])
                z.append(metal if (i + j + layer) % 2 == 0 else 8)
    pos = np.asarray(pos, np.float64)
    disp = rng.normal(0, 0.08, pos.shape)
    pos = pos + disp
    cell = np.diag([2 * ALAT, 2 * ALAT, ALAT / 2 + VACUUM])
    z = np.asarray(z, np.float64)

    # harmonic restoring 'forces' toward the lattice + species energy term
    energy = 0.5 * float((disp**2).sum()) / len(z) - 0.1 * float(
        (z == 8).sum()
    )
    forces = (-disp).astype(np.float32)

    d = GraphData(
        x=z.astype(np.float32).reshape(-1, 1),
        pos=pos.astype(np.float32),
        supercell_size=cell,
    )
    d.edge_index, _ = radius_graph_pbc(pos, cell, radius, max_neighbours)
    d.targets = [np.asarray([energy], np.float32), forces]
    d.target_types = ["graph", "node"]
    return d


def preonly(config, modelname, num_samples):
    world, rank = get_comm_size_and_rank()
    arch = config["NeuralNetwork"]["Architecture"]
    my_ids = list(nsplit(range(num_samples), world))[rank]
    rng = np.random.default_rng(123 + rank)
    samples = [
        make_oxide(rng, arch["radius"], arch["max_neighbours"])
        for _ in my_ids
    ]
    trainset, valset, testset = split_dataset(samples, 0.9, False)
    for name, ds in [("trainset", trainset), ("valset", valset),
                     ("testset", testset)]:
        w = ShardWriter(f"dataset/{modelname}_{name}", rank=rank)
        w.add(ds)
        w.save()
    print(f"rank {rank}: wrote {len(trainset)}/{len(valset)}/{len(testset)}")


def main():
    config = load_config(__file__, str(example_arg("config", "oc22.json")))
    modelname = str(example_arg("modelname", "OC2022"))
    num_samples = int(example_arg("num_samples", 800))
    setup_distributed()

    if example_arg("preonly"):
        preonly(config, modelname, num_samples)
        return

    preload = bool(example_arg("preload"))
    ddstore = bool(example_arg("ddstore"))
    splits = [
        _oc20.load_split(modelname, name, preload, ddstore)
        for name in ("trainset", "valset", "testset")
    ]
    if ddstore:
        for ds in splits:
            ds.epoch_begin()
    try:
        train_with_loaders(config, *splits, log_name=modelname.lower())
    finally:
        if ddstore:
            for ds in splits:
                ds.epoch_end()


if __name__ == "__main__":
    main()
