"""LSMS workload: FePt alloy supercells in the LSMS text format, multihead
free energy (graph) + charge density + magnetic moment (node).

Mirrors ``examples/lsms/lsms.py`` in the reference: the raw→serialized→split
pipeline is driven entirely by the Dataset config through
``hydragnn_tpu.run_training`` (format "LSMS", monolithic "total" path split
into train/val/test pkls).

Offline data: BCC FePt solid solutions where charge transfer and moments are
smooth functions of the local Fe/Pt environment and the free energy is a
pair-mixing enthalpy — same columns the real LSMS output carries.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config

import hydragnn_tpu

FE, PT = 26.0, 78.0
ALAT = 2.87


def _bcc_positions(cells):
    basis = np.array([[0.0, 0.0, 0.0], [0.5, 0.5, 0.5]])
    pos = []
    for x in range(cells):
        for y in range(cells):
            for z in range(cells):
                for b in basis:
                    pos.append((np.array([x, y, z]) + b) * ALAT)
    return np.asarray(pos)


def write_lsms_dataset(path, num_configs, cells=2, seed=0):
    """LSMS text files: line 0 graph features, then
    ``Z index x y z charge moment`` per atom."""
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    pos = _bcc_positions(cells)
    n = len(pos)
    d = np.linalg.norm(pos[:, None] - pos[None, :], axis=-1)
    nn = (d > 0) & (d < ALAT * 0.9)  # first BCC shell
    for c in range(num_configs):
        z = np.where(rng.random(n) < rng.uniform(0.2, 0.8), FE, PT)
        unlike = (z[:, None] != z[None, :]) & nn
        frac_unlike = unlike.sum(1) / np.maximum(nn.sum(1), 1)
        charge = z + 0.4 * (frac_unlike - 0.5)
        moment = np.where(z == FE, 2.2, 0.3) * (1.0 - 0.5 * frac_unlike)
        free_energy = -0.25 * unlike.sum() / n
        lines = [f"{free_energy:.8f}"]
        for i in range(n):
            lines.append(
                f"{z[i]:.1f}\t{i}\t{pos[i,0]:.6f}\t{pos[i,1]:.6f}\t"
                f"{pos[i,2]:.6f}\t{charge[i]:.6f}\t{moment[i]:.6f}"
            )
        with open(os.path.join(path, f"output{c}.txt"), "w") as f:
            f.write("\n".join(lines))


def main():
    config = load_config(__file__, "lsms.json")
    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())
    raw_path = config["Dataset"]["path"]["total"]
    num_configs = int(example_arg("num_samples", 400))
    if not os.path.exists(raw_path) or not os.listdir(raw_path):
        write_lsms_dataset(raw_path, num_configs)
    hydragnn_tpu.run_training(config)


if __name__ == "__main__":
    main()
