"""Multi-dataset GFM workload: ONE model trained across several dataset
families at once.

Mirrors ``examples/multidataset/train.py`` in the reference (the
graph-foundation-model runs mixing ANI-1x/QM7-X/MPtrj/Alexandria shards
with per-dataset DDStore/ADIOS backends and a ``--multi`` flag). Here each
family is generated into its own GraphPack shard store (``--preonly``) and
training concatenates them with ``ConcatDataset`` — the same global-index
semantics the reference gets from joining datasets.

``--num_samples`` (per family) supports the reference's weak-scaling knob
(``train.py:56-66``).

``--stream`` trains through the shard-native streaming data plane
(``hydragnn_tpu/data/stream/``, docs/data.md) instead of materializing
the union: each family's shard store becomes a lazy ``ShardStoreSource``,
the ``Dataset.streaming`` section of gfm.json sets per-family weights and
the shard window, and the bucket plan is auto-tuned from the streamed
size histogram — the production path when the families do not fit in
host RAM.
"""

import os
import sys
import zlib

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import (
    example_arg,
    load_config,
    molecule_graph,
    pairwise_energy,
    random_molecule,
    train_with_loaders,
)

from hydragnn_tpu.data import ConcatDataset, split_dataset
from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter
from hydragnn_tpu.parallel.distributed import (
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)

FAMILIES = {
    "molecules": dict(elements=[1, 6, 7, 8], n_lo=4, n_hi=16, spread=1.5),
    "clusters": dict(elements=[26, 28, 78], n_lo=4, n_hi=10, spread=2.2),
    "oxides": dict(elements=[8, 22, 26], n_lo=6, n_hi=14, spread=2.0),
}


def generate_family(name, spec, num_samples, radius, max_neighbours, rank,
                    world):
    my_ids = list(nsplit(range(num_samples), world))[rank]
    # crc32, not hash(): string hash() is salted per process, which would
    # make "seeded" generation non-reproducible
    rng = np.random.default_rng(zlib.crc32(name.encode()) + rank)
    samples = []
    for _ in my_ids:
        z, pos = random_molecule(
            rng, spec["elements"], int(rng.integers(spec["n_lo"], spec["n_hi"])),
            spread=spec["spread"],
        )
        energy = pairwise_energy(z, pos)
        samples.append(
            molecule_graph(
                z, pos, radius, max_neighbours,
                targets=[np.array([energy])], target_types=["graph"],
            )
        )
    trainset, valset, testset = split_dataset(samples, 0.9, False)
    for split, ds in [("trainset", trainset), ("valset", valset),
                      ("testset", testset)]:
        w = ShardWriter(f"dataset/{name}_{split}", rank=rank)
        w.add(ds)
        w.save()


def main():
    config = load_config(__file__, "gfm.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_samples = int(example_arg("num_samples", 600))
    setup_distributed()
    world, rank = get_comm_size_and_rank()

    if example_arg("preonly"):
        for name, spec in FAMILIES.items():
            generate_family(
                name, spec, num_samples, arch["radius"],
                arch["max_neighbours"], rank, world,
            )
            print(f"rank {rank}: family {name} written")
        return

    if example_arg("stream"):
        from common import train_with_stream

        from hydragnn_tpu.data.stream import ShardStoreSource

        scfg = config.get("Dataset", {}).get("streaming", {})
        fam_weights = scfg.get("weights", {})
        sources = [
            ShardStoreSource(f"dataset/{f}_trainset", name=f)
            for f in FAMILIES
        ]
        weights = [float(fam_weights.get(f, 1.0)) for f in FAMILIES]
        valset = ConcatDataset(
            [ShardDataset(f"dataset/{f}_valset") for f in FAMILIES]
        )
        testset = ConcatDataset(
            [ShardDataset(f"dataset/{f}_testset") for f in FAMILIES]
        )
        train_with_stream(
            config, sources, valset, testset,
            log_name="gfm_multidataset_stream", weights=weights,
        )
        return

    splits = []
    for split in ("trainset", "valset", "testset"):
        splits.append(
            ConcatDataset(
                [ShardDataset(f"dataset/{f}_{split}") for f in FAMILIES]
            )
        )
    train_with_loaders(config, *splits, log_name="gfm_multidataset")


if __name__ == "__main__":
    main()
