"""MD17-style workload: molecular-dynamics conformations of ONE molecule,
multihead energy (graph) + forces (node, 3-vector).

Mirrors ``examples/md17/md17.py`` in the reference (uracil trajectory,
energy label) extended with the forces head the MD17 dataset provides.

Offline data: conformations are equilibrium uracil-like geometry plus
thermal displacements; energy is a harmonic bond potential and forces its
exact analytic gradient — so the two heads are physically consistent.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config, molecule_graph, train_example

# 12-atom planar ring skeleton (uracil-like: C4N2O2H4)
_Z = np.array([6, 6, 7, 6, 7, 6, 8, 8, 1, 1, 1, 1], np.float32)
_EQ = np.array(
    [
        [0.0, 1.4, 0.0], [1.21, 0.7, 0.0], [1.21, -0.7, 0.0],
        [0.0, -1.4, 0.0], [-1.21, -0.7, 0.0], [-1.21, 0.7, 0.0],
        [0.0, 2.6, 0.0], [2.35, -1.35, 0.0],
        [2.15, 1.25, 0.0], [-2.15, 1.25, 0.0], [-2.15, -1.25, 0.0],
        [0.0, -2.6, 0.0],
    ],
    np.float32,
)
_K = 2.0  # harmonic spring constant


def harmonic_energy_forces(pos):
    """E = k/2 sum |r - r_eq|^2 per atom; F = -k (r - r_eq)."""
    disp = pos - _EQ
    energy = 0.5 * _K * float((disp**2).sum()) / len(pos)
    forces = -_K * disp
    return energy, forces


def md17_dataset(num_samples, radius, max_neighbours, seed=0, temp=0.15):
    rng = np.random.default_rng(seed)
    data = []
    for _ in range(num_samples):
        pos = _EQ + rng.normal(0.0, temp, _EQ.shape).astype(np.float32)
        energy, forces = harmonic_energy_forces(pos)
        data.append(
            molecule_graph(
                _Z, pos, radius, max_neighbours,
                targets=[np.array([energy]), forces],
                target_types=["graph", "node"],
            )
        )
    return data


def main():
    config = load_config(__file__, "md17.json")
    arch = config["NeuralNetwork"]["Architecture"]
    num_samples = int(example_arg("num_samples", 800))
    dataset = md17_dataset(num_samples, arch["radius"], arch["max_neighbours"])
    train_example(config, dataset, log_name="md17_test")


if __name__ == "__main__":
    main()
