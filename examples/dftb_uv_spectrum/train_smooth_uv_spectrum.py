"""DFTB UV-spectrum workload: a WIDE vector graph head — the whole smoothed
absorption spectrum regressed at once.

Mirrors ``examples/dftb_uv_spectrum/train_smooth_uv_spectrum.py`` in the
reference (GDB-9-Ex TDDFTB spectra; reference output_dim is 37500 points —
scaled to 150 bins for the offline example, same head architecture).

Offline data: molecules from the SMILES generator; the spectrum is a sum of
Gaussian absorption peaks whose positions/intensities are deterministic
functions of the molecular composition — smooth, multi-peak, learnable.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config, random_smiles, train_example

from hydragnn_tpu.utils.smiles import generate_graphdata_from_smilestr

TYPES = {"C": 0, "H": 1, "O": 2, "N": 3, "F": 4, "S": 5}
NUM_BINS = 150


def synthetic_spectrum(data) -> np.ndarray:
    """Gaussian peaks at composition-determined energies (arb. units)."""
    off = len(TYPES)
    z = data.x[:, off]
    grid = np.linspace(0.0, 10.0, NUM_BINS)
    spectrum = np.zeros(NUM_BINS)
    aromatic = float(data.x[:, off + 1].sum())
    for elem, center, width in [(6, 6.5, 0.8), (7, 4.8, 0.6), (8, 3.9, 0.5),
                                (16, 3.1, 0.5), (9, 7.6, 0.6)]:
        count = float((z == elem).sum())
        if count:
            shift = 0.15 * aromatic  # conjugation red-shifts the peaks
            spectrum += count * np.exp(
                -0.5 * ((grid - center + shift) / width) ** 2
            )
    return (spectrum / max(len(z), 1)).astype(np.float32)


def spectrum_dataset(num_samples, seed=0):
    rng = np.random.default_rng(seed)
    data = []
    for _ in range(num_samples):
        d = generate_graphdata_from_smilestr(random_smiles(rng), [0.0], TYPES)
        d.targets = [synthetic_spectrum(d)]
        d.target_types = ["graph"]
        data.append(d)
    return data


def main():
    config = load_config(__file__, "dftb_smooth_uv_spectrum.json")
    num_samples = int(example_arg("num_samples", 1000))
    dataset = spectrum_dataset(num_samples)
    train_example(config, dataset, log_name="dftb_smooth_uv_spectrum")


if __name__ == "__main__":
    main()
