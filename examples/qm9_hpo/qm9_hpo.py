"""QM9 hyperparameter search with the native HPO engine.

Mirrors ``examples/qm9_hpo/qm9_optuna.py`` / ``qm9_deephyper.py``: the same
search space (model type, hidden dim, conv depth, head geometry) over the
QM9 workload, trials running in-process and returning validation loss.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "qm9"),
)
from common import example_arg, load_config, train_example
from qm9 import qm9_dataset

from hydragnn_tpu.hpo import create_study


def main():
    base = load_config(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "qm9", "qm9.py"), "qm9.json")
    arch = base["NeuralNetwork"]["Architecture"]
    num_samples = int(example_arg("num_samples", 400))
    n_trials = int(example_arg("n_trials", 8))
    dataset = qm9_dataset(num_samples, arch["radius"], arch["max_neighbours"])

    def objective(trial):
        import copy

        config = copy.deepcopy(base)
        a = config["NeuralNetwork"]["Architecture"]
        a["model_type"] = trial.suggest_categorical(
            "model_type", ["PNA", "GIN", "SAGE"]
        )
        a["hidden_dim"] = trial.suggest_int("hidden_dim", 16, 96)
        a["num_conv_layers"] = trial.suggest_int("num_conv_layers", 1, 5)
        nh = trial.suggest_int("num_headlayers", 1, 3)
        dh = trial.suggest_int("dim_headlayers", 16, 96)
        for head in a["output_heads"].values():
            head["num_headlayers"] = nh
            head["dim_headlayers"] = [dh] * nh
        config["NeuralNetwork"]["Training"]["num_epoch"] = int(
            example_arg("num_epoch", 3)
        )
        _, _, val_loss = train_example(
            config, dataset, log_name=f"qm9_hpo_{trial.id}"
        )
        return val_loss

    study = create_study(direction="minimize", sampler="tpe", n_startup=4)
    study.optimize(objective, n_trials=n_trials)
    print(f"best params: {study.best_params}")
    print(f"best value: {study.best_value}")


if __name__ == "__main__":
    main()
