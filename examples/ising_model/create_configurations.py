"""3D Ising configurations in LSMS text format.

Parity with ``examples/ising_model/create_configurations.py`` in the
reference: L^3 lattice spin configurations, dimensionless energy
``E = -(sum_i S_i * (S_i + sum_<j> S_j)) / 6`` with periodic neighbor wrap,
optional random spin-magnitude scaling; one text file per configuration:

    line 0:  total_energy
    line i:  spin  index  x  y  z
"""

import os

import numpy as np


def ising_energy(spins):
    """PBC nearest-neighbor energy, reference normalization (/6)."""
    total = 0.0
    for axis in range(3):
        total += float(
            (spins * (np.roll(spins, 1, axis) + np.roll(spins, -1, axis))).sum()
        )
    total += float((spins * spins).sum())  # the self term of the reference
    return -total / 6.0


def create_dataset(path, num_configs, L=4, scale_spin=False, seed=0):
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    xs, ys, zs = np.meshgrid(range(L), range(L), range(L), indexing="ij")
    coords = np.stack([xs, ys, zs], axis=-1).reshape(-1, 3).astype(np.float64)
    for c in range(num_configs):
        spins = rng.choice([-1.0, 1.0], size=(L, L, L))
        if scale_spin:
            spins = spins * rng.random((L, L, L))
        energy = ising_energy(spins)
        flat = spins.reshape(-1)
        lines = [f"{energy:.8f}"]
        for i, (x, y, z) in enumerate(coords):
            lines.append(f"{flat[i]:.6f}\t{i}\t{x:.1f}\t{y:.1f}\t{z:.1f}")
        with open(os.path.join(path, f"output{c}.txt"), "w") as f:
            f.write("\n".join(lines))


if __name__ == "__main__":
    create_dataset("./dataset/ising_model", 400)
