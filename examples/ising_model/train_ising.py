"""Ising workload: predict total energy of 3D spin lattices.

Mirrors ``examples/ising_model/train_ising.py``: generated configurations
are written as raw text, converted through the serialized-pkl pipeline, and
trained through the full ``run_training`` path.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config
from create_configurations import create_dataset

import hydragnn_tpu


def main():
    config = load_config(__file__, "ising_model.json")
    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())
    raw_path = config["Dataset"]["path"]["total"]
    num_configs = int(example_arg("num_samples", 400))
    if not os.path.exists(raw_path) or not os.listdir(raw_path):
        create_dataset(raw_path, num_configs)
    hydragnn_tpu.run_training(config)


if __name__ == "__main__":
    main()
