"""Open Catalyst 2020 workload — the canonical sharded data-plane pipeline.

Mirrors ``examples/open_catalyst_2020/train.py`` in the reference:

  ``--preonly``   parallel preprocessing: every rank converts its ``nsplit``
                  share of structures to graphs, splits locally 0.9/0.05/0.05,
                  and writes its own shard (AdiosWriter analog,
                  ``train.py:227-301``);
  (default)       training reads the shard store mmap'd (shmem analog);
  ``--preload``   copy shards into RAM (slow filesystems);
  ``--ddstore``   wrap the shards in the distributed in-memory sample store
                  so each process holds one partition and fetches remote
                  samples on demand (``train.py:308-347``);
  ``--ddstore_width=W``  replicate the dataset across blocks of W ranks so
                  every fetch resolves inside the caller's block
                  (``hydragnn/utils/distdataset.py:43-46`` analog).

Ingestion goes through the REAL OC20 format: structures are read from
``.extxyz`` files (``--data_dir`` to point at a directory of real OC20
frames) with the ase-free extxyz parser and converted by
:func:`frame_to_graph`, the ``AtomsToGraphs.convert`` analog
(``/root/reference/examples/open_catalyst_2020/utils/atoms_to_graphs.py:26``)
— PBC radius graph, energy target, edge lengths. Offline, each rank first
materializes synthetic FCC slab+adsorbate structures (periodic in-plane,
adsorption 'energy' a deterministic function of adsorbate identity and
coordination) as extxyz frames, so the real parser is the single code
path either way.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from common import example_arg, load_config, pbc_pair_energy, train_with_loaders

from hydragnn_tpu.data import split_dataset
from hydragnn_tpu.data.extxyz import load_extxyz_dir, write_extxyz
from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter
from hydragnn_tpu.parallel.distributed import (
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)

METALS = [29, 78, 47]  # Cu Pt Ag
ADSORBATES = [1, 8, 6]  # H O C
ALAT = 3.6
VACUUM = 15.0


def make_structure(rng, radius):
    """2-layer 2x2 FCC(100) slab + one adsorbate, as an extxyz frame dict
    (z, pos, cell, energy in info) — the synthetic stand-in for one real
    OC20 frame.

    The energy label is the continuous minimum-image pair potential of the
    observed (jittered) geometry. The round-4 label was a near-discrete
    function of (adsorbate, metal, coordination count) — ~30 distinct
    values at 20k frames — which the model saturated inside epoch 0, so
    validation was flat from the first measurement (VERDICT round 4,
    item 1). A smooth geometric target gives a genuine multi-epoch
    regression task at any dataset size."""
    metal = METALS[int(rng.integers(len(METALS)))]
    ads = ADSORBATES[int(rng.integers(len(ADSORBATES)))]
    pos, z = [], []
    for layer in range(2):
        for i in range(2):
            for j in range(2):
                off = 0.5 if layer % 2 else 0.0
                pos.append([(i + off) * ALAT, (j + off) * ALAT,
                            layer * ALAT * 0.5])
                z.append(metal)
    site = rng.integers(2, size=2)
    pos.append([site[0] * ALAT + 0.5 * ALAT, site[1] * ALAT + 0.5 * ALAT,
                ALAT * 0.5 + 1.6 + rng.uniform(-0.2, 0.4)])
    z.append(ads)
    pos = np.asarray(pos, np.float64) + rng.normal(0, 0.08, (9, 3))
    cell = np.diag([2 * ALAT, 2 * ALAT, ALAT + VACUUM])
    # the potential cutoff IS the config's graph radius, so every
    # contributing pair is an edge the model sees (no irreducible shell
    # outside the graph); 3.5 pulls the interlayer metal pairs (3.12 A) in.
    # Minimum image needs cutoff < in-plane period / 2 = 3.6.
    if not radius < ALAT:
        raise ValueError(f"radius {radius} breaks minimum image (< {ALAT})")
    energy = pbc_pair_energy(z, pos, cell, cutoff=radius, r0=2.0)
    return {
        "z": np.asarray(z, np.int64),
        "pos": pos,
        "cell": cell,
        "info": {"energy": energy},
        "arrays": {},
    }


# bump when the synthetic label generator changes: stale shard stores must
# not be silently reused under a new task definition (the MPtrj v2→v3
# marker pattern). The marker also pins radius — it shapes the stored
# graphs AND the label cutoff.
_GEN_VERSION = "v2"


def _marker_path(modelname):
    return f"dataset/{modelname}_gen.meta"


def _marker_want(config):
    arch = config["NeuralNetwork"]["Architecture"]
    # radius AND max_neighbours shape the stored graphs (and radius the
    # label cutoff): pin both
    return (
        f"{_GEN_VERSION}:radius={arch['radius']}"
        f":max_neighbours={arch['max_neighbours']}"
    )


def preonly(config, modelname, num_samples):
    world, rank = get_comm_size_and_rank()
    arch = config["NeuralNetwork"]["Architecture"]
    data_dir = example_arg("data_dir")
    xyz_dir = str(data_dir) if data_dir else f"dataset/{modelname}_extxyz"
    my_xyz = os.path.join(xyz_dir, f"structures_rank{rank}.extxyz")
    if not data_dir:
        # offline: materialize this rank's share of synthetic structures
        # in the real extxyz format first
        my_ids = list(nsplit(range(num_samples), world))[rank]
        rng = np.random.default_rng(42 + rank)
        os.makedirs(xyz_dir, exist_ok=True)
        write_extxyz(
            my_xyz,
            (make_structure(rng, arch["radius"]) for _ in my_ids),
        )
        files = [my_xyz]
    else:
        # real data: nsplit the frame files across ranks (train.py:67-80)
        all_files = sorted(
            os.path.join(xyz_dir, f) for f in os.listdir(xyz_dir)
            if f.endswith(".extxyz") or f.endswith(".xyz")
        )
        files = list(nsplit(all_files, world))[rank]
    # conversion + the forces_norm_threshold=100 sanity filter live in
    # load_extxyz_dir (one shared implementation, reference train.py:60)
    samples = load_extxyz_dir(
        files=files,
        radius=arch["radius"],
        max_neighbours=arch["max_neighbours"],
        energy_per_atom=False,
    )
    # local 0.9 split, like the reference (train.py:237-242)
    trainset, valset, testset = split_dataset(samples, 0.9, False)
    for name, ds in [("trainset", trainset), ("valset", valset),
                     ("testset", testset)]:
        w = ShardWriter(f"dataset/{modelname}_{name}", rank=rank)
        w.add(ds)
        w.save()
    if rank == 0:
        with open(_marker_path(modelname), "w") as f:
            f.write(_marker_want(config))
    print(f"rank {rank}: wrote {len(trainset)}/{len(valset)}/{len(testset)}")


def load_split(modelname, name, preload=False, ddstore=False, width=None):
    base = ShardDataset(f"dataset/{modelname}_{name}", preload=preload)
    if ddstore:
        from hydragnn_tpu.data.distdataset import (
            DistDataset,
            subgroup_local_indices,
        )

        # each process serves ITS contiguous partition; get() on any other
        # index fetches from the owning process over the store's transport.
        # With --ddstore_width the partition is per-SUBGROUP (blocks of
        # `width` ranks each holding a full replica) so fetches stay
        # node-local, matching the reference's ddstore_width
        # (hydragnn/utils/distdataset.py:43-46).
        world, rank = get_comm_size_and_rank()
        mine = subgroup_local_indices(len(base), rank, world, width)
        local = [base[i] for i in mine]
        return DistDataset(
            local, rank=rank, world=world, subgroup_width=width
        )
    return base


def main():
    config = load_config(__file__, str(example_arg("config", "oc20.json")))
    modelname = str(example_arg("modelname", "OC2020"))
    num_samples = int(example_arg("num_samples", 1000))
    setup_distributed()

    if example_arg("preonly"):
        preonly(config, modelname, num_samples)
        return

    marker = _marker_path(modelname)
    have = open(marker).read().strip() if os.path.exists(marker) else None
    if have != _marker_want(config):
        raise SystemExit(
            f"shard store dataset/{modelname}_* was written by a different "
            f"generator/radius (marker: {have!r}, config wants "
            f"{_marker_want(config)!r}) — re-run with --preonly to "
            "regenerate before training"
        )
    preload = bool(example_arg("preload"))
    ddstore = bool(example_arg("ddstore"))
    width = example_arg("ddstore_width")
    if width is True:  # bare flag: refuse to guess a block width
        raise SystemExit("--ddstore_width needs a value, e.g. --ddstore_width=4")
    width = int(width) if width else None
    if width and not ddstore:
        raise SystemExit("--ddstore_width requires --ddstore")
    trainset = load_split(modelname, "trainset", preload, ddstore, width)
    valset = load_split(modelname, "valset", preload, ddstore, width)
    testset = load_split(modelname, "testset", preload, ddstore, width)
    if ddstore:
        for ds in (trainset, valset, testset):
            ds.epoch_begin()
    try:
        train_with_loaders(
            config, trainset, valset, testset, log_name=modelname.lower()
        )
    finally:
        if ddstore:
            for ds in (trainset, valset, testset):
                ds.epoch_end()


if __name__ == "__main__":
    main()
