"""Hyperparameter optimization (DeepHyper/Optuna analog).

The reference drives HPO through DeepHyper CBO and Optuna
(``examples/qm9_hpo/qm9_deephyper.py:29-120``, ``qm9_optuna.py``,
``examples/multidataset_hpo/gfm_deephyper_multi.py:22-70``). Neither package
is available in this image, so the same API surface is implemented natively:
an Optuna-style ``Study``/``Trial`` with random and TPE samplers plus a
median pruner, and a multi-node trial launcher that runs each trial as a
subprocess (srun or plain python) and parses the validation loss from its
output — the reference's launch pattern. If ``optuna`` is importable its
study can be used instead; nothing here requires it.
"""

from hydragnn_tpu.hpo.search import Study, Trial, TrialPruned, create_study
from hydragnn_tpu.hpo.launcher import (
    NodePool,
    TrialLauncher,
    optimize_concurrent,
    parse_val_loss,
)
