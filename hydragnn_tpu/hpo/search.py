"""Native search engine: Optuna-style Study/Trial, random + TPE samplers,
median pruner.

API parity with the subset of Optuna the reference uses
(``examples/qm9_hpo/qm9_optuna.py``): ``create_study`` →
``study.optimize(objective, n_trials)`` → ``study.best_trial`` /
``best_params`` / ``best_value``; inside the objective,
``trial.suggest_float/suggest_int/suggest_categorical`` and
``trial.report(value, step)`` + ``trial.should_prune()``.

The TPE sampler is the standard tree-structured Parzen estimator recipe:
after ``n_startup`` random trials, observations are split into the top
``gamma`` fraction ("good") and the rest; candidates are drawn from a
Gaussian KDE over the good values and ranked by the good/bad density ratio.
Parameters are treated independently (univariate TPE), which is what Optuna
does by default.
"""

import math
from typing import Any, Dict, List, Optional

import numpy as np


class TrialPruned(Exception):
    """Raised by an objective to abandon a trial early (Optuna analog)."""


class _ParamSpec:
    def __init__(self, kind, low=None, high=None, log=False, choices=None):
        self.kind = kind  # "float" | "int" | "cat"
        self.low = low
        self.high = high
        self.log = log
        self.choices = choices

    def key(self):
        return (self.kind, self.low, self.high, self.log,
                tuple(self.choices) if self.choices else None)


class Trial:
    def __init__(self, study: "Study", number: int):
        self.study = study
        self.number = number
        self.id = number  # DeepHyper-style alias used by the reference
        self.params: Dict[str, Any] = {}
        self.intermediate: Dict[int, float] = {}
        self.value: Optional[float] = None
        self.state = "running"  # running | complete | pruned | failed

    # -- suggest API ------------------------------------------------------
    def suggest_float(self, name, low, high, log=False):
        return self._suggest(name, _ParamSpec("float", low, high, log))

    def suggest_int(self, name, low, high, log=False):
        return int(round(self._suggest(name, _ParamSpec("int", low, high, log))))

    def suggest_categorical(self, name, choices):
        return self._suggest(name, _ParamSpec("cat", choices=list(choices)))

    def _suggest(self, name, spec):
        if name in self.params:
            return self.params[name]
        value = self.study._sample(name, spec)
        self.params[name] = value
        return value

    # -- pruning API ------------------------------------------------------
    def report(self, value, step):
        self.intermediate[int(step)] = float(value)

    def should_prune(self) -> bool:
        return self.study._should_prune(self)


class Study:
    def __init__(self, direction="minimize", sampler="tpe", seed=0,
                 n_startup=10, gamma=0.25, n_candidates=24,
                 pruner_warmup_trials=4, pruner_warmup_steps=1):
        assert direction in ("minimize", "maximize")
        assert sampler in ("random", "tpe")
        self.direction = direction
        self.sampler = sampler
        self.rng = np.random.default_rng(seed)
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.pruner_warmup_trials = pruner_warmup_trials
        self.pruner_warmup_steps = pruner_warmup_steps
        self.trials: List[Trial] = []
        self._specs: Dict[str, _ParamSpec] = {}

    # -- public API -------------------------------------------------------
    def ask(self) -> Trial:
        t = Trial(self, len(self.trials))
        self.trials.append(t)
        return t

    def tell(self, trial: Trial, value, state="complete"):
        trial.state = state
        if value is not None:
            trial.value = float(value)
        # a completed trial must carry a comparable value: diverged (NaN)
        # or valueless objectives would otherwise poison best_trial
        if state == "complete" and (
            trial.value is None or math.isnan(trial.value)
        ):
            trial.state = "failed"

    def optimize(self, objective, n_trials: int):
        for _ in range(n_trials):
            trial = self.ask()
            try:
                value = objective(trial)
                self.tell(trial, value)
            except TrialPruned:
                self.tell(trial, None, state="pruned")
            except Exception:
                self.tell(trial, None, state="failed")
                raise
        return self.best_trial

    @property
    def completed(self) -> List[Trial]:
        return [t for t in self.trials if t.state == "complete"]

    @property
    def best_trial(self) -> Optional[Trial]:
        done = self.completed
        if not done:
            return None
        keyfn = (lambda t: t.value) if self.direction == "minimize" else (
            lambda t: -t.value
        )
        return min(done, key=keyfn)

    @property
    def best_value(self):
        t = self.best_trial
        return None if t is None else t.value

    @property
    def best_params(self):
        t = self.best_trial
        return None if t is None else dict(t.params)

    # -- sampling ---------------------------------------------------------
    def _sample(self, name, spec: _ParamSpec):
        prev = self._specs.get(name)
        if prev is not None and prev.key() != spec.key():
            raise ValueError(f"parameter {name!r} redefined with a new space")
        self._specs[name] = spec
        history = [
            (t.params[name], t.value)
            for t in self.completed
            if name in t.params and t.value is not None
        ]
        if (
            self.sampler == "random"
            or len(history) < self.n_startup
            or spec.kind == "cat" and len(spec.choices) == 1
        ):
            return self._sample_random(spec)
        return self._sample_tpe(spec, history)

    def _sample_random(self, spec):
        if spec.kind == "cat":
            return spec.choices[int(self.rng.integers(len(spec.choices)))]
        lo, hi = float(spec.low), float(spec.high)
        if spec.log:
            v = math.exp(self.rng.uniform(math.log(lo), math.log(hi)))
        else:
            v = self.rng.uniform(lo, hi)
        return v if spec.kind == "float" else int(round(v))

    def _split_good_bad(self, history):
        vals = np.asarray([v for _, v in history], dtype=np.float64)
        order = np.argsort(vals if self.direction == "minimize" else -vals)
        n_good = max(1, int(math.ceil(self.gamma * len(history))))
        good = [history[i][0] for i in order[:n_good]]
        bad = [history[i][0] for i in order[n_good:]] or good
        return good, bad

    def _sample_tpe(self, spec, history):
        good, bad = self._split_good_bad(history)
        if spec.kind == "cat":
            # weighted categorical: smoothed counts in good vs bad
            def probs(obs):
                counts = np.ones(len(spec.choices))
                for o in obs:
                    counts[spec.choices.index(o)] += 1
                return counts / counts.sum()

            ratio = probs(good) / probs(bad)
            return spec.choices[int(np.argmax(ratio * self.rng.random(len(ratio))))]

        def to_u(x):
            return math.log(x) if spec.log else float(x)

        lo_u, hi_u = to_u(spec.low), to_u(spec.high)
        width = (hi_u - lo_u) or 1.0
        good_u = np.asarray([to_u(g) for g in good])
        bad_u = np.asarray([to_u(b) for b in bad])
        # Parzen bandwidth ~ range / n^(1/1.2), floored to keep exploration
        bw_g = max(width / max(len(good_u), 1) ** 0.83, 1e-3 * width)
        bw_b = max(width / max(len(bad_u), 1) ** 0.83, 1e-3 * width)

        def kde(xs, centers, bw):
            d = (xs[:, None] - centers[None, :]) / bw
            return np.exp(-0.5 * d * d).sum(axis=1) / (len(centers) * bw) + 1e-12

        # candidates from the good KDE, clipped to the search interval
        idx = self.rng.integers(len(good_u), size=self.n_candidates)
        cand = np.clip(
            good_u[idx] + self.rng.normal(0, bw_g, self.n_candidates),
            lo_u, hi_u,
        )
        score = kde(cand, good_u, bw_g) / kde(cand, bad_u, bw_b)
        v_u = float(cand[int(np.argmax(score))])
        v = math.exp(v_u) if spec.log else v_u
        return v if spec.kind == "float" else int(round(v))

    # -- pruning ----------------------------------------------------------
    def _should_prune(self, trial: Trial) -> bool:
        """Median rule: prune when the trial's latest intermediate value is
        worse than the median of completed trials at the same step."""
        if not trial.intermediate:
            return False
        if len(self.completed) < self.pruner_warmup_trials:
            return False
        step = max(trial.intermediate)
        if step < self.pruner_warmup_steps:
            return False
        peers = [
            t.intermediate[step]
            for t in self.completed
            if step in t.intermediate
        ]
        if not peers:
            return False
        median = float(np.median(peers))
        value = trial.intermediate[step]
        return value > median if self.direction == "minimize" else value < median


def create_study(direction="minimize", sampler="tpe", seed=0, **kwargs) -> Study:
    return Study(direction=direction, sampler=sampler, seed=seed, **kwargs)
