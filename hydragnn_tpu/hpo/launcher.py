"""Multi-node trial launcher: one training subprocess per trial.

Parity with the reference's DeepHyper multi-node pattern
(``examples/multidataset_hpo/gfm_deephyper_multi.py:22-70``): trial geometry
comes from environment variables, each trial launches an ``srun`` (or plain
``python`` when no scheduler is present) subprocess with hyperparameters as
CLI flags, and the trial metric is the last ``Val Loss: <x>`` printed by the
training script. On TPU pods the launch prefix targets TPU-VM hosts instead
of GPUs-per-node, but the orchestration shape is identical.

Early kill (the HPO half of the elastic-training work, docs/resilience.md):
each trial subprocess writes a heartbeat lease (``HYDRAGNN_HEARTBEAT_FILE``,
served by ``train/elastic.py`` inside the trial) whose payload carries the
step/epoch progress counters and the divergence guard's restore count. The
launcher polls it and KILLS the trial — freeing its node block back to the
pool for the next trial — when the lease goes stale (hung collective, wedged
host) or the guard restores exceed the budget (a diverging config is not
worth its remaining epochs). Every trial outcome lands as a structured
``hpo_trial`` event in ``<log_dir>/trials.jsonl`` (the run-event schema,
``obs/events.py``): completed / failed / killed, with the reason — a
garbled-output trial is marked FAILED there, never silently scored.
"""

import os
import re
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

_VAL_LOSS_RE = re.compile(r"Val Loss: ([-+]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][-+]?\d+)?)")


def parse_val_loss(output: str) -> Optional[float]:
    """Last validation loss a training subprocess printed, or None."""
    matches = _VAL_LOSS_RE.findall(output)
    return float(matches[-1]) if matches else None


class TrialLauncher:
    """Builds and runs per-trial training commands.

    Geometry (all optional, env-driven like the reference):
      ``HPO_NNODES_PER_TRIAL``  nodes per trial (srun -N)
      ``HPO_NRANKS_PER_TRIAL``  processes per trial (srun -n)
      ``HPO_LOG_DIR``           where per-trial stdout/stderr land
    ``use_srun`` defaults to auto-detection via ``SLURM_JOB_ID``.

    Early-kill knobs (module docstring; both optional, env-defaulted):
      ``heartbeat_timeout`` / ``HPO_HEARTBEAT_TIMEOUT_S`` — kill a trial
        whose training PROGRESS (the lease's ``progress_ts``, advanced
        per optimizer step) is older than this many seconds (applies
        once the trial has heartbeat at least once — startup/compile
        time before the first beat or step is covered by ``timeout``
        alone). Staged/fit-chunk trials tick progress once per whole
        dispatch: size the timeout above the worst dispatch wall time;
      ``max_guard_restores`` / ``HPO_MAX_GUARD_RESTORES`` — kill a trial
        whose divergence guard restored more than this many times.
    """

    def __init__(
        self,
        script: str,
        log_dir: Optional[str] = None,
        use_srun: Optional[bool] = None,
        base_env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
        heartbeat_timeout: Optional[float] = None,
        max_guard_restores: Optional[int] = None,
    ):
        self.script = script
        self.log_dir = log_dir or os.environ.get("HPO_LOG_DIR", "./logs/hpo")
        self.nnodes = int(os.environ.get("HPO_NNODES_PER_TRIAL", "1"))
        self.nranks = int(os.environ.get("HPO_NRANKS_PER_TRIAL", "1"))
        self.use_srun = (
            use_srun
            if use_srun is not None
            else "SLURM_JOB_ID" in os.environ
        )
        self.base_env = dict(base_env or {})
        self.timeout = timeout
        if heartbeat_timeout is None:
            env = os.environ.get("HPO_HEARTBEAT_TIMEOUT_S")
            heartbeat_timeout = float(env) if env else None
        self.heartbeat_timeout = heartbeat_timeout
        if max_guard_restores is None:
            env = os.environ.get("HPO_MAX_GUARD_RESTORES")
            max_guard_restores = int(env) if env else None
        self.max_guard_restores = max_guard_restores
        os.makedirs(self.log_dir, exist_ok=True)
        self._events = None
        self._events_lock = threading.Lock()

    def _emit_trial(self, trial_id: int, status: str, **fields):
        """Structured per-trial outcome -> ``<log_dir>/trials.jsonl``
        (schema-valid ``hpo_trial`` events; the study-side record of WHY
        each node-block was freed). Lazy: studies that never launch a
        subprocess never create the file."""
        from hydragnn_tpu.obs.events import RunEventLog

        with self._events_lock:
            if self._events is None:
                self._events = RunEventLog(
                    os.path.join(self.log_dir, "trials.jsonl")
                )
            log = self._events
        log.emit("hpo_trial", trial=int(trial_id), status=status, **fields)

    def build_command(self, trial_id: int, params: Dict[str, object],
                      nodelist: Optional[List[str]] = None) -> List[str]:
        cmd: List[str] = []
        if self.use_srun:
            cmd += ["srun", "-N", str(self.nnodes), "-n", str(self.nranks)]
            if nodelist:
                cmd += [f"--nodelist={','.join(nodelist)}"]
        cmd += [sys.executable, "-u"]
        if sys.flags.no_site:
            # parent launched with -S (site init skipped): children must
            # match or they re-run the site hooks the caller avoided
            cmd.append("-S")
        cmd += [self.script]
        for k, v in params.items():
            cmd.append(f"--{k}={v}")
        cmd.append(f"--log_name_suffix=trial_{trial_id}")
        return cmd

    def _kill_reason(self, hb_path: str, started: float) -> Optional[str]:
        """Early-kill decision for one poll tick (None = keep running)."""
        if self.heartbeat_timeout is None and self.max_guard_restores is None:
            return None
        # the same tolerant reader the lease's writer side uses
        from hydragnn_tpu.train.elastic import _read_json

        hb = _read_json(hb_path)
        if hb is None:
            return None  # no lease yet: startup/compile, timeout covers it
        if (
            self.max_guard_restores is not None
            and int(hb.get("guard_restores", 0)) > self.max_guard_restores
        ):
            return "divergence"
        # staleness reads the TRAINING-PROGRESS timestamp when the trial
        # reports one (elastic note_step/note_epoch): the lease daemon
        # keeps stamping `ts` even while the training thread is wedged in
        # a hung collective — `ts` alone would never detect exactly the
        # hang this kill exists for. Before the first step (compile,
        # data load) only `ts` exists, so a beating-but-not-yet-stepping
        # trial is not killed.
        progress = hb.get("progress_ts") or hb.get("ts", started)
        if (
            self.heartbeat_timeout is not None
            and time.time() - float(progress) > self.heartbeat_timeout
        ):
            return "heartbeat_timeout"
        return None

    def run(self, trial, nodelist: Optional[List[str]] = None) -> float:
        """Launch the trial subprocess; returns val loss (inf on failure).

        The reference returns the string "F" for a failed trial and lets
        DeepHyper discard it; here every non-completed outcome maps to
        +inf (``optimize_concurrent`` tells those as *failed* so the
        sampler never learns from them) AND is recorded as a structured
        ``hpo_trial`` event with the reason. A trial that exits 0 but
        prints no parseable ``Val Loss:`` is a FAILURE (garbled output),
        not a score.
        """
        cmd = self.build_command(trial.number, trial.params, nodelist)
        hb_path = os.path.join(
            self.log_dir, f"heartbeat_{trial.number}.json"
        )
        # a stale lease from a previous study run in the same log_dir
        # (trial numbering restarts at 0) would early-kill the fresh
        # trial before it ever heartbeats — the lease starts clean
        try:
            os.remove(hb_path)
        except OSError:
            pass
        env = {
            **os.environ,
            **self.base_env,
            # the trial-side runtime (train/elastic.py) serves this lease
            "HYDRAGNN_HEARTBEAT_FILE": hb_path,
        }
        out_path = os.path.join(self.log_dir, f"output_{trial.number}.txt")
        started = time.time()
        nodes = list(nodelist or [])
        with open(out_path, "w") as out:
            proc = subprocess.Popen(
                cmd, stdout=out, stderr=subprocess.STDOUT, env=env
            )
            killed_reason = None
            try:
                while True:
                    try:
                        proc.wait(timeout=0.25)
                        break
                    except subprocess.TimeoutExpired:
                        pass
                    elapsed = time.time() - started
                    if self.timeout is not None and elapsed > self.timeout:
                        killed_reason = "timeout"
                    else:
                        killed_reason = self._kill_reason(hb_path, started)
                    if killed_reason is not None:
                        proc.kill()
                        proc.wait(timeout=30)
                        break
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=30)
        if killed_reason is not None:
            self._emit_trial(
                trial.number, "killed", reason=killed_reason,
                wall_s=round(time.time() - started, 3), nodes=nodes,
            )
            return float("inf")
        if proc.returncode != 0:
            self._emit_trial(
                trial.number, "failed",
                reason=f"exit_{proc.returncode}", nodes=nodes,
            )
            return float("inf")
        try:
            with open(out_path) as f:
                text = f.read()
        except OSError:
            text = ""
        val = parse_val_loss(text)
        if val is None:
            # exit 0 with no parseable metric: the reference would feed
            # whatever garbage it matched into the sampler — here it is
            # an explicit failure with its own event, and the caller's
            # +inf contract releases the node block
            self._emit_trial(
                trial.number, "failed", reason="garbled_output",
                nodes=nodes,
            )
            return float("inf")
        self._emit_trial(
            trial.number, "completed", val_loss=float(val),
            wall_s=round(time.time() - started, 3), nodes=nodes,
        )
        return val


class NodePool:
    """Per-trial node-block allocation (the reference pins each DeepHyper
    trial to its own node block via ``--nodelist``,
    ``gfm_deephyper_multi.py:43-70``). ``nodes=None`` (and no
    ``HPO_NODELIST``) disables pinning — trials launch without a
    nodelist."""

    def __init__(self, nodes: Optional[List[str]] = None):
        if nodes is None:
            env = os.environ.get("HPO_NODELIST", "")
            nodes = [n.strip() for n in env.split(",") if n.strip()] or None
        self.free: Optional[List[str]] = list(nodes) if nodes else None

    def slots(self, per_trial: int) -> int:
        if self.free is None:
            return 0
        return len(self.free) // max(per_trial, 1)

    def acquire(self, k: int) -> Optional[List[str]]:
        if self.free is None:
            return None
        if len(self.free) < k:
            raise RuntimeError(
                f"node pool exhausted: need {k}, have {len(self.free)}"
            )
        block, self.free = self.free[:k], self.free[k:]
        return block

    def release(self, block: Optional[List[str]]):
        if block:
            self.free.extend(block)


def optimize_concurrent(
    study,
    launcher: TrialLauncher,
    suggest,
    n_trials: int,
    max_concurrent: Optional[int] = None,
    nodes: Optional[List[str]] = None,
):
    """Concurrent ask/tell search: up to ``max_concurrent`` trial
    subprocesses in flight, each on its own node block — the reference's
    DeepHyper CBO scheduler shape (``gfm_deephyper_multi.py:22-70``: N
    nodes / nodes-per-trial concurrent srun trials, asynchronous
    completion, sampler updated as each trial lands).

    ``suggest(trial)`` draws the hyperparameters (``trial.suggest_*``);
    the launcher turns ``trial.params`` into CLI flags. Failed/timed-out
    trials (+inf) are told as ``failed`` so the sampler never learns from
    them. ``max_concurrent`` defaults to ``HPO_MAX_CONCURRENT``, else the
    node pool's slot count, else 2. Study methods run only on THIS
    thread — worker threads just babysit subprocesses — so the sampler
    needs no locking."""
    from concurrent.futures import (
        FIRST_COMPLETED,
        ThreadPoolExecutor,
        wait,
    )

    pool = NodePool(nodes)
    if max_concurrent is None:
        env = os.environ.get("HPO_MAX_CONCURRENT")
        if env:
            max_concurrent = int(env)
        else:
            max_concurrent = pool.slots(launcher.nnodes) or 2
    if pool.free is not None:
        max_concurrent = min(max_concurrent, pool.slots(launcher.nnodes))
    max_concurrent = max(1, max_concurrent)

    with ThreadPoolExecutor(max_workers=max_concurrent) as ex:
        inflight = {}
        submitted = 0
        try:
            while submitted < n_trials or inflight:
                while (
                    submitted < n_trials
                    and len(inflight) < max_concurrent
                ):
                    trial = study.ask()
                    suggest(trial)
                    block = pool.acquire(launcher.nnodes) if pool.free is not None else None
                    fut = ex.submit(launcher.run, trial, block)
                    inflight[fut] = (trial, block)
                    submitted += 1
                done, _ = wait(inflight, return_when=FIRST_COMPLETED)
                for fut in done:
                    trial, block = inflight.pop(fut)
                    pool.release(block)
                    try:
                        val = fut.result()
                    except Exception:
                        val = float("inf")
                    if val == float("inf"):
                        study.tell(trial, None, state="failed")
                    else:
                        study.tell(trial, val)
        except BaseException:
            # operator interrupt / study crash: queued-but-unstarted
            # trials must not launch AFTER the stop was requested — the
            # pool context below joins only what is already running
            for fut in inflight:
                fut.cancel()
            raise
    return study.best_trial
