"""Multi-node trial launcher: one training subprocess per trial.

Parity with the reference's DeepHyper multi-node pattern
(``examples/multidataset_hpo/gfm_deephyper_multi.py:22-70``): trial geometry
comes from environment variables, each trial launches an ``srun`` (or plain
``python`` when no scheduler is present) subprocess with hyperparameters as
CLI flags, and the trial metric is the last ``Val Loss: <x>`` printed by the
training script. On TPU pods the launch prefix targets TPU-VM hosts instead
of GPUs-per-node, but the orchestration shape is identical.
"""

import os
import re
import subprocess
import sys
from typing import Dict, List, Optional

_VAL_LOSS_RE = re.compile(r"Val Loss: ([-+]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][-+]?\d+)?)")


def parse_val_loss(output: str) -> Optional[float]:
    """Last validation loss a training subprocess printed, or None."""
    matches = _VAL_LOSS_RE.findall(output)
    return float(matches[-1]) if matches else None


class TrialLauncher:
    """Builds and runs per-trial training commands.

    Geometry (all optional, env-driven like the reference):
      ``HPO_NNODES_PER_TRIAL``  nodes per trial (srun -N)
      ``HPO_NRANKS_PER_TRIAL``  processes per trial (srun -n)
      ``HPO_LOG_DIR``           where per-trial stdout/stderr land
    ``use_srun`` defaults to auto-detection via ``SLURM_JOB_ID``.
    """

    def __init__(
        self,
        script: str,
        log_dir: Optional[str] = None,
        use_srun: Optional[bool] = None,
        base_env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ):
        self.script = script
        self.log_dir = log_dir or os.environ.get("HPO_LOG_DIR", "./logs/hpo")
        self.nnodes = int(os.environ.get("HPO_NNODES_PER_TRIAL", "1"))
        self.nranks = int(os.environ.get("HPO_NRANKS_PER_TRIAL", "1"))
        self.use_srun = (
            use_srun
            if use_srun is not None
            else "SLURM_JOB_ID" in os.environ
        )
        self.base_env = dict(base_env or {})
        self.timeout = timeout
        os.makedirs(self.log_dir, exist_ok=True)

    def build_command(self, trial_id: int, params: Dict[str, object],
                      nodelist: Optional[List[str]] = None) -> List[str]:
        cmd: List[str] = []
        if self.use_srun:
            cmd += ["srun", "-N", str(self.nnodes), "-n", str(self.nranks)]
            if nodelist:
                cmd += [f"--nodelist={','.join(nodelist)}"]
        cmd += [sys.executable, "-u"]
        if sys.flags.no_site:
            # parent launched with -S (site init skipped): children must
            # match or they re-run the site hooks the caller avoided
            cmd.append("-S")
        cmd += [self.script]
        for k, v in params.items():
            cmd.append(f"--{k}={v}")
        cmd.append(f"--log_name_suffix=trial_{trial_id}")
        return cmd

    def run(self, trial, nodelist: Optional[List[str]] = None) -> float:
        """Launch the trial subprocess; returns val loss (inf on failure).

        The reference returns the string "F" for a failed trial and lets
        DeepHyper discard it; here failures map to +inf so a minimize-study
        never selects them.
        """
        cmd = self.build_command(trial.number, trial.params, nodelist)
        env = {**os.environ, **self.base_env}
        out_path = os.path.join(self.log_dir, f"output_{trial.number}.txt")
        with open(out_path, "w") as out:
            try:
                proc = subprocess.run(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    env=env,
                    timeout=self.timeout,
                )
            except subprocess.TimeoutExpired as e:
                out.write((e.output or b"").decode(errors="replace"))
                out.write("\n[launcher] trial timed out\n")
                return float("inf")
            text = proc.stdout.decode(errors="replace")
            out.write(text)
        if proc.returncode != 0:
            return float("inf")
        val = parse_val_loss(text)
        return float("inf") if val is None else val


class NodePool:
    """Per-trial node-block allocation (the reference pins each DeepHyper
    trial to its own node block via ``--nodelist``,
    ``gfm_deephyper_multi.py:43-70``). ``nodes=None`` (and no
    ``HPO_NODELIST``) disables pinning — trials launch without a
    nodelist."""

    def __init__(self, nodes: Optional[List[str]] = None):
        if nodes is None:
            env = os.environ.get("HPO_NODELIST", "")
            nodes = [n.strip() for n in env.split(",") if n.strip()] or None
        self.free: Optional[List[str]] = list(nodes) if nodes else None

    def slots(self, per_trial: int) -> int:
        if self.free is None:
            return 0
        return len(self.free) // max(per_trial, 1)

    def acquire(self, k: int) -> Optional[List[str]]:
        if self.free is None:
            return None
        if len(self.free) < k:
            raise RuntimeError(
                f"node pool exhausted: need {k}, have {len(self.free)}"
            )
        block, self.free = self.free[:k], self.free[k:]
        return block

    def release(self, block: Optional[List[str]]):
        if block:
            self.free.extend(block)


def optimize_concurrent(
    study,
    launcher: TrialLauncher,
    suggest,
    n_trials: int,
    max_concurrent: Optional[int] = None,
    nodes: Optional[List[str]] = None,
):
    """Concurrent ask/tell search: up to ``max_concurrent`` trial
    subprocesses in flight, each on its own node block — the reference's
    DeepHyper CBO scheduler shape (``gfm_deephyper_multi.py:22-70``: N
    nodes / nodes-per-trial concurrent srun trials, asynchronous
    completion, sampler updated as each trial lands).

    ``suggest(trial)`` draws the hyperparameters (``trial.suggest_*``);
    the launcher turns ``trial.params`` into CLI flags. Failed/timed-out
    trials (+inf) are told as ``failed`` so the sampler never learns from
    them. ``max_concurrent`` defaults to ``HPO_MAX_CONCURRENT``, else the
    node pool's slot count, else 2. Study methods run only on THIS
    thread — worker threads just babysit subprocesses — so the sampler
    needs no locking."""
    from concurrent.futures import (
        FIRST_COMPLETED,
        ThreadPoolExecutor,
        wait,
    )

    pool = NodePool(nodes)
    if max_concurrent is None:
        env = os.environ.get("HPO_MAX_CONCURRENT")
        if env:
            max_concurrent = int(env)
        else:
            max_concurrent = pool.slots(launcher.nnodes) or 2
    if pool.free is not None:
        max_concurrent = min(max_concurrent, pool.slots(launcher.nnodes))
    max_concurrent = max(1, max_concurrent)

    with ThreadPoolExecutor(max_workers=max_concurrent) as ex:
        inflight = {}
        submitted = 0
        try:
            while submitted < n_trials or inflight:
                while (
                    submitted < n_trials
                    and len(inflight) < max_concurrent
                ):
                    trial = study.ask()
                    suggest(trial)
                    block = pool.acquire(launcher.nnodes) if pool.free is not None else None
                    fut = ex.submit(launcher.run, trial, block)
                    inflight[fut] = (trial, block)
                    submitted += 1
                done, _ = wait(inflight, return_when=FIRST_COMPLETED)
                for fut in done:
                    trial, block = inflight.pop(fut)
                    pool.release(block)
                    try:
                        val = fut.result()
                    except Exception:
                        val = float("inf")
                    if val == float("inf"):
                        study.tell(trial, None, state="failed")
                    else:
                        study.tell(trial, val)
        except BaseException:
            # operator interrupt / study crash: queued-but-unstarted
            # trials must not launch AFTER the stop was requested — the
            # pool context below joins only what is already running
            for fut in inflight:
                fut.cancel()
            raise
    return study.best_trial
