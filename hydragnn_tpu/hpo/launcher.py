"""Multi-node trial launcher: one training subprocess per trial.

Parity with the reference's DeepHyper multi-node pattern
(``examples/multidataset_hpo/gfm_deephyper_multi.py:22-70``): trial geometry
comes from environment variables, each trial launches an ``srun`` (or plain
``python`` when no scheduler is present) subprocess with hyperparameters as
CLI flags, and the trial metric is the last ``Val Loss: <x>`` printed by the
training script. On TPU pods the launch prefix targets TPU-VM hosts instead
of GPUs-per-node, but the orchestration shape is identical.
"""

import os
import re
import subprocess
import sys
from typing import Dict, List, Optional

_VAL_LOSS_RE = re.compile(r"Val Loss: ([-+]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][-+]?\d+)?)")


def parse_val_loss(output: str) -> Optional[float]:
    """Last validation loss a training subprocess printed, or None."""
    matches = _VAL_LOSS_RE.findall(output)
    return float(matches[-1]) if matches else None


class TrialLauncher:
    """Builds and runs per-trial training commands.

    Geometry (all optional, env-driven like the reference):
      ``HPO_NNODES_PER_TRIAL``  nodes per trial (srun -N)
      ``HPO_NRANKS_PER_TRIAL``  processes per trial (srun -n)
      ``HPO_LOG_DIR``           where per-trial stdout/stderr land
    ``use_srun`` defaults to auto-detection via ``SLURM_JOB_ID``.
    """

    def __init__(
        self,
        script: str,
        log_dir: Optional[str] = None,
        use_srun: Optional[bool] = None,
        base_env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ):
        self.script = script
        self.log_dir = log_dir or os.environ.get("HPO_LOG_DIR", "./logs/hpo")
        self.nnodes = int(os.environ.get("HPO_NNODES_PER_TRIAL", "1"))
        self.nranks = int(os.environ.get("HPO_NRANKS_PER_TRIAL", "1"))
        self.use_srun = (
            use_srun
            if use_srun is not None
            else "SLURM_JOB_ID" in os.environ
        )
        self.base_env = dict(base_env or {})
        self.timeout = timeout
        os.makedirs(self.log_dir, exist_ok=True)

    def build_command(self, trial_id: int, params: Dict[str, object],
                      nodelist: Optional[List[str]] = None) -> List[str]:
        cmd: List[str] = []
        if self.use_srun:
            cmd += ["srun", "-N", str(self.nnodes), "-n", str(self.nranks)]
            if nodelist:
                cmd += [f"--nodelist={','.join(nodelist)}"]
        cmd += [sys.executable, "-u"]
        if sys.flags.no_site:
            # parent launched with -S (site init skipped): children must
            # match or they re-run the site hooks the caller avoided
            cmd.append("-S")
        cmd += [self.script]
        for k, v in params.items():
            cmd.append(f"--{k}={v}")
        cmd.append(f"--log_name_suffix=trial_{trial_id}")
        return cmd

    def run(self, trial, nodelist: Optional[List[str]] = None) -> float:
        """Launch the trial subprocess; returns val loss (inf on failure).

        The reference returns the string "F" for a failed trial and lets
        DeepHyper discard it; here failures map to +inf so a minimize-study
        never selects them.
        """
        cmd = self.build_command(trial.number, trial.params, nodelist)
        env = {**os.environ, **self.base_env}
        out_path = os.path.join(self.log_dir, f"output_{trial.number}.txt")
        with open(out_path, "w") as out:
            try:
                proc = subprocess.run(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    env=env,
                    timeout=self.timeout,
                )
            except subprocess.TimeoutExpired as e:
                out.write((e.output or b"").decode(errors="replace"))
                out.write("\n[launcher] trial timed out\n")
                return float("inf")
            text = proc.stdout.decode(errors="replace")
            out.write(text)
        if proc.returncode != 0:
            return float("inf")
        val = parse_val_loss(text)
        return float("inf") if val is None else val
