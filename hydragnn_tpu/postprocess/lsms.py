"""LSMS post-processing: formation-energy conversion + composition cutoff.

Capability parity with the reference's top-level ``utils/lsms`` scripts
(``convert_total_energy_to_formation_gibbs.py``,
``compositional_histogram_cutoff.py``): binary-alloy LSMS text datasets
(one header line holding the total energy, then one row per atom) are
(a) rewritten with total energy replaced by formation Gibbs energy, and
(b) down-selected to at most N samples per composition bin.

Pure host-side numpy; plots are optional (matplotlib gated).
"""

import math
import os
import shutil
from typing import Dict, List, Sequence, Tuple

import numpy as np

# LSMS energies are Rydberg; entropy needs k_B in those units
_KB_JOULE_PER_KELVIN = 1.380649e-23
_JOULE_TO_RYDBERG = 4.5874208973812e17
_KB_RYDBERG_PER_KELVIN = _KB_JOULE_PER_KELVIN * _JOULE_TO_RYDBERG


def _read_lsms(path: str) -> Tuple[str, List[str], np.ndarray]:
    """(total_energy_token, raw_lines, atoms[n, cols]) from an LSMS file:
    header line starts with the total energy, atom rows follow."""
    with open(path) as f:
        lines = f.readlines()
    energy_token = lines[0].split()[0]
    atoms = np.loadtxt(lines[1:])
    if atoms.ndim == 1:
        atoms = atoms[None, :]
    return energy_token, lines, atoms


def _binary_composition(
    atoms: np.ndarray, elements_list: Sequence[float]
) -> Tuple[np.ndarray, np.ndarray]:
    """(elements, counts) over the sorted binary element list, zero-filled
    for missing (pure-phase) species; asserts no foreign elements."""
    elements, counts = np.unique(atoms[:, 0], return_counts=True)
    for e in elements:
        assert e in elements_list, (
            f"sample contains element {e} not in the binary {elements_list}"
        )
    for i, elem in enumerate(elements_list):
        if elem not in elements:
            elements = np.insert(elements, i, elem)
            counts = np.insert(counts, i, 0)
    return elements, counts


def compute_formation_enthalpy(
    elements_list: Sequence[float],
    pure_elements_energy: Dict[float, float],
    total_energy: float,
    atoms: np.ndarray,
):
    """(composition_of_element1, linear_mixing_energy, formation_enthalpy,
    mixing_entropy) for one binary-alloy configuration.

    formation enthalpy = total energy minus the composition-weighted linear
    mix of the pure-phase per-atom energies; the entropy term is the ideal
    mixing (binomial) entropy in Rydberg/K.
    """
    elements, counts = _binary_composition(atoms, elements_list)
    num_atoms = atoms.shape[0]
    composition = counts[0] / num_atoms
    linear_mixing_energy = (
        pure_elements_energy[elements[0]] * composition
        + pure_elements_energy[elements[1]] * (1.0 - composition)
    ) * num_atoms
    formation_enthalpy = total_energy - linear_mixing_energy
    # thermodynamic (not statistical) mixing entropy: k_B ln C(n, n_1)
    entropy = _KB_RYDBERG_PER_KELVIN * (
        math.lgamma(num_atoms + 1)
        - math.lgamma(counts[0] + 1)
        - math.lgamma(num_atoms - counts[0] + 1)
    )
    return composition, linear_mixing_energy, formation_enthalpy, entropy


def convert_raw_data_energy_to_gibbs(
    dir: str,
    elements_list: Sequence[float],
    temperature_kelvin: float = 0.0,
    overwrite_data: bool = False,
    create_plots: bool = True,
):
    """Rewrite every LSMS file with total energy -> formation Gibbs energy.

    Output lands in ``<dir>_gibbs_energy/``. Requires the dataset to contain
    the two pure-phase configurations (their per-atom energies anchor the
    linear mixing line). Binary alloys only, like the reference.
    """
    dir = dir.rstrip("/")
    new_dir = dir + "_gibbs_energy/"
    if os.path.exists(new_dir) and overwrite_data:
        shutil.rmtree(new_dir)
    os.makedirs(new_dir, exist_ok=True)

    elements_list = sorted(elements_list)
    all_files = sorted(os.listdir(dir))

    # pass 1: pure-phase per-atom energies
    pure_elements_energy: Dict[float, float] = {}
    for filename in all_files:
        energy_token, _, atoms = _read_lsms(os.path.join(dir, filename))
        species = np.unique(atoms[:, 0])
        if len(species) == 1:
            pure_elements_energy[species[0]] = float(energy_token) / atoms.shape[0]
    assert len(pure_elements_energy) == 2, (
        "need both pure-element configurations to anchor the mixing line"
    )

    # pass 2: convert + rewrite
    comps = np.zeros(len(all_files))
    enthalpies = np.zeros(len(all_files))
    gibbs = np.zeros(len(all_files))
    for i, filename in enumerate(all_files):
        path = os.path.join(dir, filename)
        energy_token, lines, atoms = _read_lsms(path)
        comp, _lin, enthalpy, entropy = compute_formation_enthalpy(
            elements_list, pure_elements_energy, float(energy_token), atoms
        )
        g = enthalpy - temperature_kelvin * entropy
        comps[i], enthalpies[i], gibbs[i] = comp, enthalpy, g
        lines[0] = lines[0].replace(energy_token, str(g))
        with open(os.path.join(new_dir, filename), "w") as f:
            f.write("".join(lines))

    print("Min formation enthalpy: ", float(gibbs.min()))
    print("Max formation enthalpy: ", float(gibbs.max()))

    if create_plots:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except Exception:
            return gibbs
        for values, ylabel, fname in (
            (enthalpies, "Formation enthalpy (Rydberg)", "formation_enthalpy.png"),
            (gibbs, "Formation Gibbs energy (Rydberg)", "formation_gibbs_energy.png"),
        ):
            plt.figure()
            plt.scatter(comps, values, edgecolor="b", facecolor="none")
            plt.xlabel("Concentration")
            plt.ylabel(ylabel)
            plt.savefig(fname)
            plt.close()
    return gibbs


def find_bin(comp: float, nbins: int) -> int:
    """Composition bin index over [0, 1] (reference semantics: open interval
    membership, overflow to the last bin)."""
    bins = np.linspace(0, 1, nbins)
    for bi in range(len(bins) - 1):
        if bins[bi] < comp < bins[bi + 1]:
            return bi
    return nbins - 1


def compositional_histogram_cutoff(
    dir: str,
    elements_list: Sequence[float],
    histogram_cutoff: int,
    num_bins: int,
    overwrite_data: bool = False,
    create_plots: bool = True,
):
    """Down-select LSMS data: fewer than ``histogram_cutoff`` samples per
    composition bin (increment-then-compare, i.e. a bin saturates at
    ``histogram_cutoff - 1`` — reference semantics), symlinked into
    ``<dir>_histogram_cutoff/``."""
    dir = dir.rstrip("/")
    new_dir = dir + "_histogram_cutoff/"
    if os.path.exists(new_dir):
        if overwrite_data:
            shutil.rmtree(new_dir)
        else:
            print("Exiting: path to histogram cutoff data already exists")
            return None
    os.makedirs(new_dir, exist_ok=True)

    elements_list = sorted(elements_list)
    kept_comps = []
    per_bin = np.zeros(num_bins)
    for filename in sorted(os.listdir(dir)):
        path = os.path.join(dir, filename)
        _, _, atoms = _read_lsms(path)
        _, counts = _binary_composition(atoms, elements_list)
        composition = counts[0] / atoms.shape[0]
        b = find_bin(composition, num_bins)
        per_bin[b] += 1
        if per_bin[b] < histogram_cutoff:
            kept_comps.append(composition)
            os.symlink(os.path.abspath(path), os.path.join(new_dir, filename))

    if create_plots:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except Exception:
            return kept_comps
        plt.figure()
        plt.hist(kept_comps, bins=num_bins)
        plt.savefig("composition_histogram_cutoff.png")
        plt.close()
        plt.figure()
        plt.bar(np.linspace(0, 1, num_bins), per_bin, width=1.0 / num_bins)
        plt.savefig("composition_initial.png")
        plt.close()
    return kept_comps
