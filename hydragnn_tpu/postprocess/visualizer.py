"""Visualizer — matplotlib diagnostics (parity with
``hydragnn/postprocess/visualizer.py:24-742``: parity/scatter plots, error
histograms, loss history, node-count histogram), writing under
``./logs/<name>/``."""

import os
from typing import List, Optional

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        node_feature=None,
        num_heads: int = 1,
        head_dims: Optional[List[int]] = None,
        num_nodes_list=None,
        plot_init_solution: bool = True,
        plot_hist_solution: bool = False,
        create_plots: bool = True,
    ):
        self.name = model_with_config_name
        self.out_dir = os.path.join("./logs", model_with_config_name)
        os.makedirs(self.out_dir, exist_ok=True)
        self.node_feature = node_feature
        self.num_heads = num_heads
        self.head_dims = head_dims or [1] * num_heads
        self.num_nodes_list = num_nodes_list or []
        self.plot_init_solution = plot_init_solution
        self.plot_hist_solution = plot_hist_solution
        self.create_plots = create_plots

    def _save(self, fig, fname):
        fig.savefig(os.path.join(self.out_dir, fname), dpi=120)
        plt.close(fig)

    def num_nodes_plot(self):
        if not self.num_nodes_list:
            return
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(self.num_nodes_list, bins=20)
        ax.set_xlabel("number of nodes")
        ax.set_ylabel("count")
        self._save(fig, "num_nodes.png")

    def create_scatter_plots(
        self, true_values, predicted_values, output_names=None, iepoch=None
    ):
        """Per-head parity scatter (``visualizer.py`` scatter catalog)."""
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 5))
            ax.scatter(t, p, s=4, alpha=0.5)
            lo = min(t.min(), p.min()) if t.size else 0.0
            hi = max(t.max(), p.max()) if t.size else 1.0
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            ax.set_xlabel(f"true {name}")
            ax.set_ylabel(f"predicted {name}")
            self._save(fig, f"scatter_{name}{suffix}.png")

    def create_error_histograms(
        self, true_values, predicted_values, output_names=None
    ):
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 4))
            ax.hist(p - t, bins=40)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            ax.set_xlabel(f"error {name}")
            self._save(fig, f"error_hist_{name}.png")

    def create_plot_global(
        self, true_values, predicted_values, output_names=None
    ):
        """Combined parity panel across all heads."""
        n = len(true_values)
        fig, axes = plt.subplots(1, n, figsize=(5 * n, 5), squeeze=False)
        for ihead in range(n):
            ax = axes[0][ihead]
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            ax.scatter(t, p, s=4, alpha=0.5)
            if t.size:
                lo, hi = min(t.min(), p.min()), max(t.max(), p.max())
                ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            ax.set_title(name)
        self._save(fig, "parity_all_heads.png")

    def plot_history(self, total_loss_train, total_loss_val, total_loss_test):
        fig, ax = plt.subplots(figsize=(6, 4))
        ax.plot(total_loss_train, label="train")
        ax.plot(total_loss_val, label="val")
        ax.plot(total_loss_test, label="test")
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        ax.set_yscale("log")
        ax.legend()
        self._save(fig, "history_loss.png")
