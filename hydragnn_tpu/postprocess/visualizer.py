"""Visualizer — matplotlib diagnostics (parity with
``hydragnn/postprocess/visualizer.py:24-742``: parity/scatter plots, error
histograms, 2-D density contours, conditional-mean error curves, per-node /
vector parity panels, loss history, node-count histogram), writing under
``./logs/<name>/``."""

import os
from typing import List, Optional

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        node_feature=None,
        num_heads: int = 1,
        head_dims: Optional[List[int]] = None,
        num_nodes_list=None,
        plot_init_solution: bool = True,
        plot_hist_solution: bool = False,
        create_plots: bool = True,
    ):
        self.name = model_with_config_name
        self.out_dir = os.path.join("./logs", model_with_config_name)
        os.makedirs(self.out_dir, exist_ok=True)
        self.node_feature = node_feature
        self.num_heads = num_heads
        self.head_dims = head_dims or [1] * num_heads
        self.num_nodes_list = num_nodes_list or []
        self.plot_init_solution = plot_init_solution
        self.plot_hist_solution = plot_hist_solution
        self.create_plots = create_plots

    def _save(self, fig, fname):
        fig.savefig(os.path.join(self.out_dir, fname), dpi=120)
        plt.close(fig)

    def num_nodes_plot(self):
        if not self.num_nodes_list:
            return
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(self.num_nodes_list, bins=20)
        ax.set_xlabel("number of nodes")
        ax.set_ylabel("count")
        self._save(fig, "num_nodes.png")

    def create_scatter_plots(
        self, true_values, predicted_values, output_names=None, iepoch=None
    ):
        """Per-head parity scatter, then the reference's per-head dispatch
        (``visualizer.py:693-727``): vector heads get the per-component
        parity grid, scalar heads get the parity+error-histogram panel AND
        the per-node error histograms — so the deep-analysis catalog is
        produced wherever the epoch driver plots, not only on demand."""
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 5))
            ax.scatter(t, p, s=4, alpha=0.5)
            lo = min(t.min(), p.min()) if t.size else 0.0
            hi = max(t.max(), p.max()) if t.size else 1.0
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            ax.set_xlabel(f"true {name}")
            ax.set_ylabel(f"predicted {name}")
            self._save(fig, f"scatter_{name}{suffix}.png")
            d = self.head_dims[ihead] if ihead < len(self.head_dims) else 1
            if d > 1:
                self.create_parity_plot_vector(
                    true_values, predicted_values, ihead, name, dim=d,
                    iepoch=iepoch,
                )
            else:
                self.create_parity_plot_and_error_histogram_scalar(
                    true_values, predicted_values, ihead, name, iepoch=iepoch
                )
                self.create_error_histogram_per_node(
                    true_values, predicted_values, ihead, name, iepoch=iepoch
                )

    def create_error_histograms(
        self, true_values, predicted_values, output_names=None
    ):
        for ihead in range(len(true_values)):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 4))
            ax.hist(p - t, bins=40)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            ax.set_xlabel(f"error {name}")
            self._save(fig, f"error_hist_{name}.png")

    def create_plot_global(
        self, true_values, predicted_values, output_names=None
    ):
        """Combined parity panel across all heads, plus the reference's
        per-head global analysis (scatter+contour / conditional-mean /
        error-PDF; ``visualizer.py:729-740`` routes every head through
        ``create_plot_global_analysis``)."""
        n = len(true_values)
        fig, axes = plt.subplots(1, n, figsize=(5 * n, 5), squeeze=False)
        for ihead in range(n):
            ax = axes[0][ihead]
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            ax.scatter(t, p, s=4, alpha=0.5)
            if t.size:
                lo, hi = min(t.min(), p.min()), max(t.max(), p.max())
                ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            ax.set_title(name)
        self._save(fig, "parity_all_heads.png")
        self.create_plot_global_analysis(
            true_values, predicted_values, output_names
        )

    def plot_history(
        self,
        total_loss_train,
        total_loss_val,
        total_loss_test,
        task_loss_train=None,
        task_loss_val=None,
        task_loss_test=None,
        task_weights=None,
        task_names=None,
    ):
        """Loss history: total losses, optional per-task panels, and the
        raw series pickled next to the figure (``visualizer.py:629-690``)."""
        import pickle

        with open(os.path.join(self.out_dir, "history_loss.pckl"), "wb") as f:
            pickle.dump(
                [
                    np.asarray(total_loss_train),
                    np.asarray(total_loss_val),
                    np.asarray(total_loss_test),
                    None if task_loss_train is None else np.asarray(task_loss_train),
                    None if task_loss_val is None else np.asarray(task_loss_val),
                    None if task_loss_test is None else np.asarray(task_loss_test),
                    task_weights,
                    task_names,
                ],
                f,
            )
        num_tasks = (
            np.asarray(task_loss_train).shape[1]
            if task_loss_train is not None and np.asarray(task_loss_train).size
            else 0
        )
        ncol = max(num_tasks, 1)
        nrow = 2 if num_tasks else 1
        fig, axs = plt.subplots(
            nrow, ncol, figsize=(5 * ncol, 4 * nrow), squeeze=False
        )
        ax = axs[0][0]
        ax.plot(total_loss_train, label="train")
        ax.plot(total_loss_val, ":", label="val")
        ax.plot(total_loss_test, "--", label="test")
        ax.set_title("total loss")
        ax.set_xlabel("epoch")
        ax.set_yscale("log")
        ax.legend()
        for c in range(1, ncol):
            axs[0][c].axis("off")
        for ivar in range(num_tasks):
            ax = axs[1][ivar]
            tt = np.asarray(task_loss_train)
            ax.plot(tt[:, ivar], label="train")
            if task_loss_val is not None:
                ax.plot(np.asarray(task_loss_val)[:, ivar], ":", label="val")
            if task_loss_test is not None:
                ax.plot(np.asarray(task_loss_test)[:, ivar], "--", label="test")
            name = (
                task_names[ivar]
                if task_names and ivar < len(task_names)
                else f"task{ivar}"
            )
            w = (
                f", w={task_weights[ivar]:.3g}"
                if task_weights is not None and ivar < len(task_weights)
                else ""
            )
            ax.set_title(f"{name}{w}")
            ax.set_xlabel("epoch")
            ax.set_yscale("log")
            if ivar == 0:
                ax.legend()
        fig.tight_layout()
        self._save(fig, "history_loss.png")

    # ---- analysis helpers (visualizer.py:83-105) -------------------------
    @staticmethod
    def _hist2d_contour(data1, data2, bins=40):
        """(xcenters, ycenters, H) density for a parity contour plot."""
        data1 = np.asarray(data1).reshape(-1)
        data2 = np.asarray(data2).reshape(-1)
        H, xe, ye = np.histogram2d(data1, data2, bins=bins)
        return 0.5 * (xe[:-1] + xe[1:]), 0.5 * (ye[:-1] + ye[1:]), H.T

    @staticmethod
    def _err_condmean(true, err, bins=25):
        """Conditional mean of |error| vs the true value — the reference's
        ``__err_condmean`` diagnostic (bias as a function of target)."""
        true = np.asarray(true).reshape(-1)
        err = np.abs(np.asarray(err).reshape(-1))
        if true.size == 0:
            return np.zeros(0), np.zeros(0)
        edges = np.linspace(true.min(), true.max() + 1e-12, bins + 1)
        which = np.clip(np.digitize(true, edges) - 1, 0, bins - 1)
        sums = np.bincount(which, weights=err, minlength=bins)
        cnts = np.maximum(np.bincount(which, minlength=bins), 1)
        centers = 0.5 * (edges[:-1] + edges[1:])
        return centers, sums / cnts

    @staticmethod
    def add_identity(ax, *line_args, **line_kwargs):
        """y=x reference line that tracks axis limits
        (``visualizer.py:614-627``)."""
        (identity,) = ax.plot([], [], *line_args, **line_kwargs)

        def callback(axes):
            lo = max(axes.get_xlim()[0], axes.get_ylim()[0])
            hi = min(axes.get_xlim()[1], axes.get_ylim()[1])
            identity.set_data([lo, hi], [lo, hi])

        callback(ax)
        ax.callbacks.connect("xlim_changed", callback)
        ax.callbacks.connect("ylim_changed", callback)
        return ax

    def _analysis_column(self, axcol, t, p, title, weight=1.0, density=True):
        """One (scatter+contour, conditional-mean, error-PDF) column — the
        repeated unit of the reference's analysis grids
        (``visualizer.py:134-279``)."""
        ax = axcol[0]
        if t.size:
            ax.scatter(t, p, s=4, alpha=0.35, edgecolor="b", facecolor="none")
            if density and t.size > 10 and np.ptp(t) > 0 and np.ptp(p) > 0:
                xc, yc, H = self._hist2d_contour(t, p)
                ax.contour(xc, yc, np.log1p(H), levels=8, linewidths=0.7)
            self.add_identity(ax, "r--", linewidth=1)
        ax.set_title(f"{title}, number of samples = {t.size}")
        ax.set_xlabel("True")
        ax.set_ylabel("Predicted")
        ax = axcol[1]
        centers, cm = self._err_condmean(t, (p - t) * weight)
        ax.plot(centers, cm, "ro")
        ax.set_title("Conditional mean abs. error")
        ax.set_xlabel("True")
        ax.set_ylabel("abs. error")
        ax = axcol[2]
        if t.size:
            hist1d, edges = np.histogram(p - t, bins=40, density=True)
            ax.plot(0.5 * (edges[:-1] + edges[1:]), hist1d, "ro")
        ax.set_title(f"{title}: error PDF")
        ax.set_xlabel("Error")
        ax.set_ylabel("PDF")

    def create_plot_global_analysis(
        self, true_values, predicted_values, output_names=None
    ):
        """Per-head analysis figure, reference-density
        (``visualizer.py:134-279``): scalar heads get the 1x3-column
        (parity scatter + density contour, conditional mean |error|,
        error PDF); vector heads get the full 3x3 grid analysing vector
        LENGTH, component SUM, and raw COMPONENTS each through that same
        column. One file per head (``<name>_scatter_condm_err.png``),
        plus the combined cross-head overview."""
        n = len(true_values)
        for ihead in range(n):
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            d = self.head_dims[ihead] if ihead < len(self.head_dims) else 1
            t = np.asarray(true_values[ihead])
            p = np.asarray(predicted_values[ihead])
            if d <= 1:
                t, p = t.reshape(-1), p.reshape(-1)
                fig, axs = plt.subplots(3, 1, figsize=(5.5, 13))
                self._analysis_column(axs, t, p, "Scalar output")
            else:
                t, p = t.reshape(-1, d), p.reshape(-1, d)
                fig, axs = plt.subplots(3, 3, figsize=(18, 16))
                vlen_t = np.linalg.norm(t, axis=1)
                vlen_p = np.linalg.norm(p, axis=1)
                self._analysis_column(
                    axs[:, 0], vlen_t, vlen_p, "Vector output: length",
                    weight=1.0 / np.sqrt(d),
                )
                self._analysis_column(
                    axs[:, 1], t.sum(1), p.sum(1), "Vector output: sum",
                    weight=1.0 / d,
                )
                self._analysis_column(
                    axs[:, 2], t.reshape(-1), p.reshape(-1),
                    "Vector output: components",
                )
            fig.tight_layout()
            self._save(fig, f"{name}_scatter_condm_err.png")

        # combined cross-head overview (one column per head)
        fig, axes = plt.subplots(3, n, figsize=(5 * n, 12), squeeze=False)
        for ihead in range(n):
            t = np.asarray(true_values[ihead]).reshape(-1)
            p = np.asarray(predicted_values[ihead]).reshape(-1)
            name = (
                output_names[ihead]
                if output_names and ihead < len(output_names)
                else f"head{ihead}"
            )
            self._analysis_column(axes[:, ihead], t, p, name)
        fig.tight_layout()
        self._save(fig, "global_analysis.png")

    def create_parity_plot_vector(
        self, true_values, predicted_values, ihead=0, output_name=None,
        dim=None, iepoch=None,
    ):
        """Vector-output parity: one panel per component
        (``visualizer.py:467-517``)."""
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        t = np.asarray(true_values[ihead])
        p = np.asarray(predicted_values[ihead])
        d = dim or self.head_dims[ihead]
        t = t.reshape(-1, d)
        p = p.reshape(-1, d)
        name = output_name or f"head{ihead}"
        fig, axes = plt.subplots(1, d, figsize=(5 * d, 5), squeeze=False)
        for c in range(d):
            ax = axes[0][c]
            ax.scatter(t[:, c], p[:, c], s=4, alpha=0.5)
            self.add_identity(ax, "r--", linewidth=1)
            ax.set_title(f"{name}[{c}]")
        self._save(fig, f"parity_vector_{name}{suffix}.png")

    def create_parity_plot_and_error_histogram_scalar(
        self, true_values, predicted_values, ihead=0, output_name=None,
        iepoch=None,
    ):
        """Scalar-head combined panel: parity scatter beside its error
        histogram (``visualizer.py:281-385``)."""
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        t = np.asarray(true_values[ihead]).reshape(-1)
        p = np.asarray(predicted_values[ihead]).reshape(-1)
        name = output_name or f"head{ihead}"
        fig, axes = plt.subplots(1, 2, figsize=(10, 5), squeeze=False)
        ax = axes[0][0]
        ax.scatter(t, p, s=4, alpha=0.5)
        if t.size:
            self.add_identity(ax, "r--", linewidth=1)
        ax.set_xlabel(f"true {name}")
        ax.set_ylabel(f"predicted {name}")
        ax = axes[0][1]
        ax.hist(p - t, bins=40)
        ax.set_xlabel(f"error {name}")
        self._save(fig, f"parity_and_hist_{name}{suffix}.png")

    def create_parity_plot_per_node_vector(
        self, true_values, predicted_values, ihead=0, output_name=None,
        dim=None, iepoch=None,
    ):
        """Vector node-head parity grouped by node position within the
        graph: one row per node, one column per component (fixed-size
        graphs; ``visualizer.py:519-612``)."""
        del iepoch  # accepted for dispatch-signature symmetry
        if not self.num_nodes_list or len(set(self.num_nodes_list)) != 1:
            return  # variable graph size: per-node grouping undefined
        num_nodes = int(self.num_nodes_list[0])
        d = dim or self.head_dims[ihead]
        t = np.asarray(true_values[ihead]).reshape(-1, d)
        p = np.asarray(predicted_values[ihead]).reshape(-1, d)
        if t.shape[0] % num_nodes != 0:
            return
        t = t.reshape(-1, num_nodes, d)
        p = p.reshape(-1, num_nodes, d)
        name = output_name or f"head{ihead}"
        fig, axes = plt.subplots(
            num_nodes, d, figsize=(4 * d, 3 * num_nodes), squeeze=False
        )
        for node in range(num_nodes):
            for c in range(d):
                ax = axes[node][c]
                ax.scatter(t[:, node, c], p[:, node, c], s=4, alpha=0.5)
                self.add_identity(ax, "r--", linewidth=1)
                ax.set_title(f"node {node} [{c}]")
        self._save(fig, f"parity_per_node_vector_{name}.png")

    def create_error_histogram_per_node(
        self, true_values, predicted_values, ihead=0, output_name=None,
        iepoch=None,
    ):
        """Node-head error histogram grouped by node position within the
        graph (fixed-size graphs; ``visualizer.py:387-465``)."""
        del iepoch  # accepted for dispatch-signature symmetry
        if not self.num_nodes_list or len(set(self.num_nodes_list)) != 1:
            return  # variable graph size: per-node grouping undefined
        num_nodes = int(self.num_nodes_list[0])
        t = np.asarray(true_values[ihead]).reshape(-1)
        p = np.asarray(predicted_values[ihead]).reshape(-1)
        if t.size % num_nodes != 0:
            return
        err = (p - t).reshape(-1, num_nodes)
        cols = min(num_nodes, 4)
        rows = -(-num_nodes // cols)
        name = output_name or f"head{ihead}"
        fig, axes = plt.subplots(
            rows, cols, figsize=(4 * cols, 3 * rows), squeeze=False
        )
        for node in range(num_nodes):
            ax = axes[node // cols][node % cols]
            ax.hist(err[:, node], bins=30)
            ax.set_title(f"node {node}")
        self._save(fig, f"error_hist_per_node_{name}.png")
