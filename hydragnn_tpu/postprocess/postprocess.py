"""Prediction post-processing (parity with
``hydragnn/postprocess/postprocess.py:13-54``)."""

from typing import List

import numpy as np


def output_denormalize(y_minmax: List, true_values, predicted_values):
    """Invert the min-max normalization per head
    (``postprocess.py:13-26``)."""
    for ihead in range(len(y_minmax)):
        ymin, ymax = y_minmax[ihead][0], y_minmax[ihead][1]
        for arrs in (predicted_values, true_values):
            arrs[ihead] = np.asarray(arrs[ihead]) * (ymax - ymin) + ymin
    return true_values, predicted_values


def unscale_features_by_num_nodes(
    feature_names, values, num_nodes_list, scaled_suffix="_scaled_num_nodes"
):
    """Undo per-node feature scaling (``postprocess.py:29-54``)."""
    out = list(values)
    for i, name in enumerate(feature_names):
        if scaled_suffix in name:
            out[i] = np.asarray(out[i]) * np.asarray(num_nodes_list).reshape(-1, 1)
    return out
