"""Distributed runtime bootstrap & host-side collectives.

Replaces the reference's dual NCCL/Gloo + mpi4py stack
(``hydragnn/utils/distributed.py:120-191``, SURVEY.md §5) with ONE path:
``jax.distributed.initialize`` for multi-host bootstrap (env-driven, with
SLURM/OpenMPI auto-detection like the reference's scheduler sniffing at
``distributed.py:87-104``), XLA collectives inside jitted programs for all
gradient/metric reductions, and ``multihost_utils`` for the few host-side
data-plane reductions (dataset statistics).
"""

import os
from typing import Tuple

import numpy as np


_initialized = False


def setup_distributed() -> Tuple[int, int]:
    """Bootstrap multi-host JAX if a cluster environment is detected.

    Returns (world_size, rank) in terms of *processes* (hosts). On a single
    host this is (1, 0) and no initialization is needed — the device mesh
    still spans all local devices.

    Scheduler detection parallels ``setup_ddp`` (``distributed.py:120-191``):
    SLURM (SLURM_PROCID/SLURM_NTASKS), OpenMPI (OMPI_COMM_WORLD_*), or
    explicit HYDRAGNN_TPU_COORDINATOR / num_processes / process_id env vars.
    JAX's own TPU-pod auto-detection handles TPU VMs natively.
    """
    global _initialized
    import jax

    if _initialized:
        return jax.process_count(), jax.process_index()

    coordinator = os.getenv("HYDRAGNN_TPU_COORDINATOR")
    num_procs = os.getenv("HYDRAGNN_TPU_NUM_PROCESSES")
    proc_id = os.getenv("HYDRAGNN_TPU_PROCESS_ID")
    if coordinator is None and os.getenv("SLURM_NTASKS"):
        num_procs = os.getenv("SLURM_NTASKS")
        proc_id = os.getenv("SLURM_PROCID")
        nodelist = os.getenv("SLURM_NODELIST", "")
        head = parse_slurm_nodelist(nodelist)[0] if nodelist else None
        port = os.getenv("HYDRAGNN_TPU_PORT", "12355")
        coordinator = f"{head}:{port}" if head else None
    elif coordinator is None and os.getenv("OMPI_COMM_WORLD_SIZE"):
        num_procs = os.getenv("OMPI_COMM_WORLD_SIZE")
        proc_id = os.getenv("OMPI_COMM_WORLD_RANK")

    if num_procs is not None and int(num_procs) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_procs),
            process_id=int(proc_id) if proc_id is not None else None,
        )
        _initialized = True
    return jax.process_count(), jax.process_index()


def get_comm_size_and_rank() -> Tuple[int, int]:
    import jax

    try:
        return jax.process_count(), jax.process_index()
    except Exception:
        return 1, 0


def nsplit(seq, n):
    """Split ``seq`` into ``n`` nearly-even chunks (``distributed.py:287-289``)."""
    k, m = divmod(len(seq), n)
    return (
        seq[i * k + min(i, m) : (i + 1) * k + min(i + 1, m)] for i in range(n)
    )


def check_remaining(elapsed_per_epoch: float) -> bool:
    """SLURM wall-clock guard (``distributed.py:317-342``): True if there is
    enough queue time left for one more epoch. Non-SLURM -> always True."""
    job = os.getenv("SLURM_JOB_ID")
    if job is None:
        return True
    import subprocess

    try:
        out = subprocess.run(
            ["squeue", "-h", "-j", job, "-o", "%L"],
            capture_output=True,
            text=True,
            timeout=30,
        ).stdout.strip()
    except Exception:
        return True
    seconds = _parse_slurm_timeleft(out)
    return seconds is None or seconds > 1.2 * elapsed_per_epoch


def _parse_slurm_timeleft(s: str):
    # formats: D-HH:MM:SS, HH:MM:SS, MM:SS, SS, INVALID
    if not s or "INVALID" in s.upper():
        return None
    days = 0
    if "-" in s:
        d, s = s.split("-", 1)
        days = int(d)
    parts = [int(p) for p in s.split(":")]
    while len(parts) < 3:
        parts.insert(0, 0)
    h, m, sec = parts[-3:]
    return ((days * 24 + h) * 60 + m) * 60 + sec


def parse_slurm_nodelist(nodelist: str):
    """Expand 'frontier[00001-00005,00007]' style lists
    (``distributed.py:53-84``)."""
    if "[" not in nodelist:
        return nodelist.split(",")
    prefix, rest = nodelist.split("[", 1)
    body = rest.rstrip("]").split("]")[0]
    nodes = []
    for piece in body.split(","):
        if "-" in piece:
            lo, hi = piece.split("-")
            width = len(lo)
            for v in range(int(lo), int(hi) + 1):
                nodes.append(f"{prefix}{v:0{width}d}")
        else:
            nodes.append(prefix + piece)
    return nodes


def host_allreduce(arr: np.ndarray, op: str = "sum") -> np.ndarray:
    """Host-side all-reduce across processes for data-plane statistics
    (degree histograms, feature min/max) — the role mpi4py plays in the
    reference's data layer (SURVEY.md §2.3). Single-process: identity."""
    import jax

    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils
    import jax.numpy as jnp

    arr = np.asarray(arr)
    if op == "sum":
        return np.asarray(
            multihost_utils.process_allgather(jnp.asarray(arr)).sum(axis=0)
        )
    if op == "max":
        return np.asarray(
            multihost_utils.process_allgather(jnp.asarray(arr)).max(axis=0)
        )
    if op == "min":
        return np.asarray(
            multihost_utils.process_allgather(jnp.asarray(arr)).min(axis=0)
        )
    raise ValueError(f"unknown op {op}")


def host_allgather_int(value: int):
    """Per-process int -> list over all processes (ordered by process id)."""
    import jax

    if jax.process_count() == 1:
        return [int(value)]
    from jax.experimental import multihost_utils
    import jax.numpy as jnp

    out = multihost_utils.process_allgather(jnp.asarray([value]))
    return [int(v) for v in np.asarray(out).ravel()]


def print_peak_memory(verbosity: int = 0, prefix: str = ""):
    """Device-memory report (analog of ``print_peak_memory``,
    ``distributed.py:277-284``).

    One device lacking ``memory_stats()`` must not hide the rest
    (``continue``, not ``return`` — the old early-return skipped every
    remaining device). Output goes through the obs layer: a
    ``device_memory`` event when telemetry is live, plus the rank-0
    console line (always — a diagnostic named print_* must not be a
    silent no-op at the default verbosity; non-zero ranks report via the
    event stream only)."""
    import jax

    from hydragnn_tpu.obs import runtime as obs
    from hydragnn_tpu.utils.print_utils import print_master

    devices = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        peak = int(stats.get("peak_bytes_in_use", 0))
        devices.append(
            {
                "device": str(d),
                "peak_bytes_in_use": peak,
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            }
        )
        print_master(
            f"{prefix} {d}: peak {peak / 2**20:.1f} MB",
            verbosity_level=verbosity,
        )
    if devices:
        obs.emit("device_memory", prefix=prefix, devices=devices)
