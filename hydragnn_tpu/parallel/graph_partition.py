"""Graph-partition parallelism — the long-context analog for GNNs.

The reference cannot split one graph across devices at all: its scaling axis
is data parallelism over many small graphs (DDP, ``utils/distributed.py``),
and "large" means *many samples* (DDStore/ADIOS streaming). The TPU-native
framework goes further: ONE giant graph (a large atomistic system, a mesh, a
polymer) is sharded node-wise over a mesh axis, the exact structural analog of
sequence/context parallelism for transformers (ring attention's KV exchange
becomes halo exchange of remote-sender node features; SURVEY.md §5 names
static-shape bucketing as the in-domain replacement — this module is the
scale-out half of that story).

Design:

* **Ownership** — nodes are split into ``P`` contiguous shards after a
  locality-preserving reorder (Morton/Z-curve over positions, so radius-graph
  neighbors tend to share a shard and the halo stays small). Every directed
  edge is owned by its *receiver's* shard, so all receiver-side aggregations
  (the message-passing hot path) are shard-local segment ops. On the 2-D
  ``("data", "model")`` mesh (``parallel/mesh.py``) ownership lives on the
  ``model`` axis: each model group holds one graph's shards, and the batch
  placement + in-program ``with_sharding_constraint`` on the node table,
  edge features and halo buffers let XLA place the all_to_all/psum
  collectives against that layout instead of replicating.
* **Halo exchange** (``halo_extend``) — before every conv layer, each shard
  gathers the rows remote peers need (a host-precomputed, statically padded
  send list) and trades them with ONE ``lax.all_to_all`` over ICI. Convs run
  unmodified on the extended table ``[local ; halo]``; the local slice is
  kept. Autodiff through the collective yields the reverse scatter-add —
  gradients flow across shards with no hand-written backward.
* **Halo reduce** (``halo_reduce``) — the transpose operation, for the two
  stacks that aggregate at *senders* (EGNN / equivariant SchNet coordinate
  updates): partial sums landing on halo rows are all_to_all'd back to their
  owner shard and scatter-added into the local rows.
* **Exact numerics** — BatchNorm statistics, global pooling and every loss
  numerator/denominator are ``psum``'d over the axis (``models/common.py``,
  ``models/base.py``), so a partitioned model computes bit-for-bit the same
  math as the unpartitioned one; the tests assert output/gradient parity.

No counterpart exists in the reference (capability superset); the closest
public pattern is jraph's sharded_graphnet / DGL's DistDGL halo design.
"""

import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.parallel.mesh import GRAPH_AXIS


# --------------------------------------------------------------------------
# device-side collectives (called inside shard_map / the model)
# --------------------------------------------------------------------------


def halo_extend(x, halo_send, axis_name):
    """Extend the local node table with fresh halo rows from peer shards.

    ``x``: ``[NL, ...]`` local rows. ``halo_send``: ``[P, H]`` int32 — row ids
    this shard must send to each peer (padded entries point at the dummy
    row). Returns ``[NL + P*H, ...]``: local rows, then peer ``p``'s rows at
    ``NL + p*H + h`` — the layout the partitioner's remapped sender indices
    reference.
    """
    sends = x[halo_send]  # [P, H, ...]
    recv = jax.lax.all_to_all(sends, axis_name, split_axis=0, concat_axis=0)
    return jnp.concatenate([x, recv.reshape((-1,) + x.shape[1:])], axis=0)


def halo_reduce(y_ext, halo_send, axis_name):
    """Fold sender-side partial aggregations back onto their owner shards.

    ``y_ext``: ``[NL + P*H, ...]`` — a segment reduction over the extended
    table where rows ``NL + p*H + h`` hold partial sums belonging to peer
    ``p``'s node ``halo_send[p, h]`` (as seen on peer ``p``). Sends each halo
    block to its owner and scatter-adds into the local rows. Returns
    ``[NL + P*H, ...]`` with complete local rows and a zeroed halo region.
    """
    p, h = halo_send.shape
    nl = y_ext.shape[0] - p * h
    local = y_ext[:nl]
    halo = y_ext[nl:].reshape((p, h) + y_ext.shape[1:])
    back = jax.lax.all_to_all(halo, axis_name, split_axis=0, concat_axis=0)
    local = local.at[halo_send.reshape(-1)].add(
        back.reshape((p * h,) + y_ext.shape[1:])
    )
    return jnp.concatenate([local, jnp.zeros_like(y_ext[nl:])], axis=0)


# --------------------------------------------------------------------------
# host-side partitioner
# --------------------------------------------------------------------------


def _morton_order(pos: np.ndarray) -> np.ndarray:
    """Z-curve ordering of 3-D positions — cheap locality-preserving reorder
    so contiguous node chunks are spatially compact (small halo cut)."""
    q = pos - pos.min(axis=0, keepdims=True)
    denom = np.maximum(q.max(axis=0, keepdims=True), 1e-12)
    bits = 10
    cells = np.minimum((q / denom * ((1 << bits) - 1)).astype(np.uint64), (1 << bits) - 1)

    def spread(v):
        v = v & np.uint64(0x3FF)
        v = (v | (v << np.uint64(16))) & np.uint64(0x030000FF)
        v = (v | (v << np.uint64(8))) & np.uint64(0x0300F00F)
        v = (v | (v << np.uint64(4))) & np.uint64(0x030C30C3)
        v = (v | (v << np.uint64(2))) & np.uint64(0x09249249)
        return v

    code = spread(cells[:, 0]) | (spread(cells[:, 1]) << np.uint64(1)) | (
        spread(cells[:, 2]) << np.uint64(2)
    )
    return np.argsort(code, kind="stable")


class _HaloTable:
    """Vectorized halo bookkeeping for one item kind (nodes or edges).

    Built from (consumer part, global item id) request pairs; deduplicates,
    assigns dense per-(owner, consumer) slots, and produces the ``[P, P, H]``
    send table plus a vectorized ``extended_ids`` lookup — no Python loops
    over items, so partitioning stays O(sort) for giant graphs.
    """

    def __init__(self, req_q, req_item, part_of, local_of, P, multiple, dummy,
                 min_h: int = 0):
        num_items = part_of.shape[0]
        req_q = np.asarray(req_q, np.int64)
        req_item = np.asarray(req_item, np.int64)
        owner = part_of[req_item]
        remote = owner != req_q
        key = req_q[remote] * num_items + req_item[remote]
        uniq = np.unique(key)  # sorted
        uq = uniq // num_items
        uitem = uniq % num_items
        up = part_of[uitem]
        # dense slot index within each (owner p, consumer q) group
        order = np.lexsort((uitem, uq, up))
        sp, sq = up[order], uq[order]
        change = np.r_[True, (sp[1:] != sp[:-1]) | (sq[1:] != sq[:-1])]
        group_id = np.cumsum(change) - 1
        group_start = np.nonzero(change)[0]
        slot_sorted = np.arange(order.shape[0]) - group_start[group_id]
        counts = np.bincount(group_id) if order.shape[0] else np.zeros(1, np.int64)
        natural = max(int(counts.max()) if order.shape[0] else 0, 1)
        self.h = max(int(-(-natural // multiple) * multiple), int(min_h))
        self.send = np.full((P, P, self.h), dummy, np.int32)
        self.send[sp, sq, slot_sorted] = local_of[uitem[order]].astype(np.int32)
        self._uniq = uniq
        self._slot = np.empty(uniq.shape[0], np.int64)
        self._slot[order] = slot_sorted
        self._num_items = num_items
        self._part_of = part_of
        self._local_of = local_of

    def extended_ids(self, q, items, base: int) -> np.ndarray:
        """Remap global item ids to consumer-local extended coordinates:
        local id when owned by ``q``, else ``base + owner*H + slot``."""
        q = np.asarray(q, np.int64)
        items = np.asarray(items, np.int64)
        owner = self._part_of[items]
        out = self._local_of[items].astype(np.int64)
        remote = owner != q
        if remote.any():
            key = q[remote] * self._num_items + items[remote]
            idx = np.searchsorted(self._uniq, key)
            out[remote] = base + owner[remote] * self.h + self._slot[idx]
        return out.astype(np.int32)


class PartitionInfo:
    """Static partition geometry + the inverse maps to un-partition outputs."""

    def __init__(self, num_parts, nl, el, halo, node_perm, part_of_node,
                 local_of_node, n_real, halo_edges=0, tl=0, k_in=0, k_out=0):
        self.num_parts = num_parts
        self.nl = nl  # local node budget (incl. 1 dummy row)
        self.el = el  # local edge budget
        self.halo = halo  # per-peer halo budget H
        self.node_perm = node_perm  # [n] global node id -> (implicit) order
        self.part_of_node = part_of_node  # [n] owning shard per global node
        self.local_of_node = local_of_node  # [n] local row per global node
        self.n_real = n_real
        self.halo_edges = halo_edges  # per-peer EDGE halo budget (triplets)
        self.tl = tl  # local triplet budget
        self.k_in = k_in  # dense neighbor-list widths (0 = lists not built)
        self.k_out = k_out

    @property
    def budgets(self) -> dict:
        return {
            "nl": self.nl,
            "el": self.el,
            "halo": self.halo,
            "halo_edges": self.halo_edges,
            "tl": self.tl,
            "k_in": self.k_in,
            "k_out": self.k_out,
        }

    def gather_nodes(self, per_part_rows: np.ndarray) -> np.ndarray:
        """``[P*NL, ...]`` stacked per-part rows -> ``[n, ...]`` in the
        original global node order (drops dummy/halo padding)."""
        flat_idx = self.part_of_node * self.nl + self.local_of_node
        return np.asarray(per_part_rows)[flat_idx]


def partition_graph(
    sample,
    num_parts: int,
    head_types: Tuple[str, ...] = (),
    head_dims: Tuple[int, ...] = (),
    order: str = "morton",
    node_multiple: int = 8,
    edge_multiple: int = 8,
    halo_multiple: int = 8,
    need_triplets: bool = False,
    need_neighbors: bool = False,
    budgets: Optional[dict] = None,
) -> Tuple[GraphBatch, PartitionInfo]:
    """Split one giant graph into ``num_parts`` static-shape shards.

    ``sample`` exposes numpy ``x [n,F]``, ``pos [n,3]``, ``edge_index [2,e]``,
    optional ``edge_attr``, and (per ``head_types``) ``targets``. Returns a
    ``GraphBatch`` whose leading axes concatenate the per-part arrays (part
    ``p`` owns rows ``[p*NL, (p+1)*NL)`` etc.) — sharding every leaf on axis 0
    over a ``num_parts``-sized mesh axis gives each device exactly its shard.

    Per-shard layout: rows ``[0, NL-1)`` local nodes (dummy at ``NL-1``);
    edges are owned by the receiver's shard; remapped sender ids >= NL
    reference the halo region filled by ``halo_extend`` at run time. The
    local graph id 0 is the real graph (``n_node[0]`` = GLOBAL real node
    count, see ``HydraBase.__call__``), id 1 absorbs padding.
    """
    x = np.asarray(sample.x, dtype=np.float32)
    pos = (
        np.asarray(sample.pos, dtype=np.float32)
        if getattr(sample, "pos", None) is not None
        else np.zeros((x.shape[0], 3), np.float32)
    )
    edge_index = np.asarray(sample.edge_index)
    edge_attr = getattr(sample, "edge_attr", None)
    if edge_attr is not None:
        edge_attr = np.asarray(edge_attr, dtype=np.float32)
    n = x.shape[0]
    e = edge_index.shape[1]
    P = int(num_parts)

    if order == "morton" and pos is not None:
        perm = _morton_order(pos)
    else:
        perm = np.arange(n)

    # contiguous chunks of the ordering -> parts
    part_sizes = [(n + P - 1 - p) // P for p in range(P)]  # near-even
    part_of_node = np.empty(n, dtype=np.int64)
    local_of_node = np.empty(n, dtype=np.int64)
    start = 0
    for p, sz in enumerate(part_sizes):
        ids = perm[start : start + sz]
        part_of_node[ids] = p
        local_of_node[ids] = np.arange(sz)
        start += sz

    def _round_up(v, m):
        return int(-(-v // m) * m)

    budgets = budgets or {}
    nl = max(_round_up(max(part_sizes) + 1, node_multiple), budgets.get("nl", 0))

    # edge ownership by receiver
    send_g, recv_g = edge_index[0], edge_index[1]
    e_part = part_of_node[recv_g]
    e_counts = np.bincount(e_part, minlength=P)
    el = max(
        _round_up(max(int(e_counts.max()), 1), edge_multiple),
        budgets.get("el", 0),
    )

    # local edge row of every global edge (receiver-owner layout; matches
    # the ascending-nonzero order of the edge build loop below)
    local_of_edge = np.empty(max(e, 1), dtype=np.int64)
    for p in range(P):
        eidx = np.nonzero(e_part == p)[0]
        local_of_edge[eidx] = np.arange(eidx.shape[0])

    # halo: for each (owner p -> consumer q) the unique remote NODES the
    # consumer needs — remote senders of its edges plus (DimeNet) remote
    # j/k nodes of its triplets (the 2-hop halo)
    node_req_q = [e_part]
    node_req_item = [send_g]
    trip = None
    if need_triplets:
        from hydragnn_tpu.models.dimenet import compute_triplets

        t_i, t_j, t_k, t_kj, t_ji = compute_triplets(edge_index, n)
        t_part = e_part[t_ji]  # triplet lives with its (j->i) edge
        node_req_q += [t_part, t_part]
        node_req_item += [t_j, t_k]
        trip = (t_i, t_j, t_k, t_kj, t_ji, t_part)

    node_halo = _HaloTable(
        np.concatenate(node_req_q),
        np.concatenate(node_req_item),
        part_of_node,
        local_of_node,
        P,
        halo_multiple,
        dummy=nl - 1,
        min_h=budgets.get("halo", 0),
    )
    halo = node_halo.h

    edge_halo = None
    if need_triplets:
        # remote (k->j) edges whose STATE the consumer gathers (x_kj)
        edge_halo = _HaloTable(
            trip[5], trip[3], e_part, local_of_edge, P, halo_multiple, dummy=0,
            min_h=budgets.get("halo_edges", 0),
        )

    # ---- per-part arrays -------------------------------------------------
    F = x.shape[1]
    xs = np.zeros((P, nl, F), np.float32)
    ps = np.zeros((P, nl, 3), np.float32)
    node_graph = np.full((P, nl), 1, np.int32)
    node_mask = np.zeros((P, nl), bool)
    n_node = np.zeros((P, 2), np.int32)
    n_edge = np.zeros((P, 2), np.int32)
    graph_mask = np.zeros((P, 2), bool)
    senders = np.full((P, el), nl - 1, np.int32)
    receivers = np.full((P, el), nl - 1, np.int32)
    edge_mask = np.zeros((P, el), bool)
    e_attr = (
        np.zeros((P, el, edge_attr.shape[1]), np.float32)
        if edge_attr is not None
        else None
    )
    # padded slots point at the dummy row so halo_reduce's scatter-add and
    # halo_extend's sends never touch a real node
    halo_send = node_halo.send
    nig = np.zeros((P, nl), np.int32)  # node_index_in_graph (global position)

    for p in range(P):
        ids = np.nonzero(part_of_node == p)[0]
        order_ids = ids[np.argsort(local_of_node[ids])]
        sz = order_ids.shape[0]
        xs[p, :sz] = x[order_ids]
        ps[p, :sz] = pos[order_ids]
        node_graph[p, :sz] = 0
        node_mask[p, :sz] = True
        nig[p, :sz] = order_ids
        n_node[p, 0] = n  # GLOBAL count: local pool sums psum to the true mean
        n_node[p, 1] = nl - sz
        graph_mask[p, 0] = True

    for p in range(P):
        eidx = np.nonzero(e_part == p)[0]
        k = eidx.shape[0]
        senders[p, :k] = node_halo.extended_ids(
            np.full(k, p, np.int64), send_g[eidx], base=nl
        )
        receivers[p, :k] = local_of_node[recv_g[eidx]].astype(np.int32)
        edge_mask[p, :k] = True
        n_edge[p, 0] = k
        n_edge[p, 1] = el - k
        if e_attr is not None:
            e_attr[p, :k] = edge_attr[eidx]

    # ---- triplet arrays (DimeNet), fully vectorized ---------------------
    trip_extras = {}
    if trip is not None:
        t_i, t_j, t_k, t_kj, t_ji, t_part = trip
        t_counts = np.bincount(t_part, minlength=P)
        tl = max(_round_up(max(int(t_counts.max()), 1), 8), budgets.get("tl", 0))
        tr_i = np.full((P, tl), nl - 1, np.int32)
        tr_j = np.full((P, tl), nl - 1, np.int32)
        tr_k = np.full((P, tl), nl - 1, np.int32)
        tr_kj = np.zeros((P, tl), np.int32)
        tr_ji = np.zeros((P, tl), np.int32)
        tr_mask = np.zeros((P, tl), bool)
        # dense row within each part: rank of each triplet in a stable
        # part-ordered sort
        order_t = np.argsort(t_part, kind="stable")
        starts = np.concatenate([[0], np.cumsum(t_counts)[:-1]])
        rows = np.arange(order_t.shape[0]) - starts[t_part[order_t]]
        qs = t_part[order_t]
        tr_i[qs, rows] = local_of_node[t_i[order_t]].astype(np.int32)
        tr_j[qs, rows] = node_halo.extended_ids(qs, t_j[order_t], base=nl)
        tr_k[qs, rows] = node_halo.extended_ids(qs, t_k[order_t], base=nl)
        tr_kj[qs, rows] = edge_halo.extended_ids(qs, t_kj[order_t], base=el)
        tr_ji[qs, rows] = local_of_edge[t_ji[order_t]].astype(np.int32)
        tr_mask[qs, rows] = True
        trip_extras = {
            "trip_i": tr_i,
            "trip_j": tr_j,
            "trip_k": tr_k,
            "trip_kj": tr_kj,
            "trip_ji": tr_ji,
            "trip_mask": tr_mask,
            "halo_send_edges": edge_halo.send.reshape(P * P, edge_halo.h),
        }

    # ---- dense neighbor lists (scatter-free aggregation) -----------------
    # Built against each shard's EXTENDED node table (local rows + halo
    # region), so the conv's dense path gathers halo senders exactly like
    # the segment path does; gradients reach halo rows through the custom
    # VJP's reverse lists and flow back to owners via halo_extend's AD.
    nbr_extras = {}
    if need_neighbors:
        from hydragnn_tpu.ops.dense_agg import (
            build_neighbor_lists,
            max_degree,
        )

        ext_n = nl + P * halo
        k_in = budgets.get("k_in", 1)
        k_out = budgets.get("k_out", 1)
        for p in range(P):
            ki, ko = max_degree(senders[p], receivers[p], edge_mask[p])
            k_in, k_out = max(k_in, ki), max(k_out, ko)
        stacked = None
        for p in range(P):
            lists = build_neighbor_lists(
                senders[p], receivers[p], edge_mask[p], ext_n, k_in, k_out
            )
            if stacked is None:
                stacked = {
                    k: np.zeros((P,) + v.shape, v.dtype)
                    for k, v in lists.items()
                }
            for k, v in lists.items():
                stacked[k][p] = v
        nbr_extras = stacked

    # ---- targets ---------------------------------------------------------
    targets = []
    for ih, (t, d) in enumerate(zip(head_types, head_dims)):
        tgt = np.asarray(sample.targets[ih], np.float32)
        if t == "graph":
            arr = np.zeros((P, 2, d), np.float32)
            arr[:, 0] = tgt.reshape(-1)
        else:
            arr = np.zeros((P, nl, d), np.float32)
            for p in range(P):
                ids = np.nonzero(part_of_node == p)[0]
                order_ids = ids[np.argsort(local_of_node[ids])]
                arr[p, : order_ids.shape[0]] = tgt[order_ids].reshape(-1, d)
        targets.append(arr)

    def flat(a):
        return a.reshape((-1,) + a.shape[2:])

    batch = GraphBatch(
        x=flat(xs),
        pos=flat(ps),
        senders=flat(senders),
        receivers=flat(receivers),
        edge_attr=flat(e_attr) if e_attr is not None else None,
        node_graph=flat(node_graph),
        n_node=flat(n_node),
        n_edge=flat(n_edge),
        node_mask=flat(node_mask),
        edge_mask=flat(edge_mask),
        graph_mask=flat(graph_mask),
        targets=tuple(flat(t) for t in targets),
        extras={
            "halo_send": halo_send.reshape(P * P, halo),
            "node_index_in_graph": flat(nig),
            # triplet index tables are [P, TL] -> flattened like every other
            # leaf; halo_send_edges is already [P*P, HE]
            **{
                k: (v if k == "halo_send_edges" else flat(v))
                for k, v in trip_extras.items()
            },
            **{k: flat(v) for k, v in nbr_extras.items()},
        },
    )
    info = PartitionInfo(
        P, nl, el, halo, perm, part_of_node, local_of_node, n,
        halo_edges=edge_halo.h if edge_halo is not None else 0,
        tl=trip_extras["trip_i"].shape[1] if trip_extras else 0,
        k_in=nbr_extras["nbr_idx"].shape[2] if nbr_extras else 0,
        k_out=nbr_extras["rev_idx"].shape[2] if nbr_extras else 0,
    )
    return batch, info


# --------------------------------------------------------------------------
# shard_map step builders
# --------------------------------------------------------------------------


def _batch_spec(batch, axis):
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(lambda _: P(axis), batch)


def _constrain_partitioned(batch, mesh, axis):
    """Pin the partitioned batch's placement INSIDE the jitted program:
    ``with_sharding_constraint`` on every leading-axis-stacked leaf — the
    node table (``x``/``pos``), the edge features/indices, and the halo
    send tables — so XLA places the shard_map's all_to_all/psum
    collectives against the declared layout instead of replicating first
    and resharding at the shard_map boundary. On the 2-D mesh the
    partition axis is ``model``; unmentioned axes (``data``) replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda a: jax.lax.with_sharding_constraint(a, sharding), batch
    )


def _put_global(a, sharding):
    """Place an array (present in full on every process) under a global
    sharding. device_put cannot target non-addressable devices, so on
    multi-host each process contributes its addressable shards via
    make_array_from_callback."""
    if jax.process_count() == 1:
        return jax.device_put(jnp.asarray(a), sharding)
    a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, sharding, lambda idx: a[idx])


def put_partitioned_batch(batch: GraphBatch, mesh, axis: str = GRAPH_AXIS) -> GraphBatch:
    """Device placement: every leaf sharded on axis 0 so each device holds
    exactly its shard's rows (multi-host safe)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda a: _put_global(a, sharding), batch)


def put_partitioned_state(state, mesh):
    """Replicate the train state onto the mesh with the SAME sharding the
    partitioned step's outputs carry (``NamedSharding(mesh, P())``).

    Skipping this costs one full extra XLA compile: the first step returns
    P()-annotated arrays, and feeding those back into a jit that was traced
    for differently-annotated inputs is a sharding-signature cache miss
    (measured ~5 s duplicate compile on v5e). Multi-host safe (values are
    identical on every process, e.g. seeded init).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(state, sharding)
    return jax.tree_util.tree_map(
        lambda a: _put_global(jax.device_get(a), sharding), state
    )


def make_partitioned_apply(model, mesh, axis: str = GRAPH_AXIS):
    """Jitted partitioned forward: (variables, batch) -> per-shard outputs.

    Graph-head rows come back replicated-identical on every shard; node-head
    rows are per-shard (un-partition with ``PartitionInfo.gather_nodes``).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def fwd(variables, batch):
        batch = _constrain_partitioned(batch, mesh, axis)

        def shard_fn(variables, batch):
            return model.apply(variables, batch, train=False)

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), _batch_spec(batch, axis)),
            out_specs=P(axis),
            check_rep=False,
        )(variables, batch)

    return jax.jit(fwd)


def make_partitioned_train_step(model, tx, mesh, axis: str = GRAPH_AXIS):
    """One fused XLA program: partitioned forward + psum'd loss + backward
    (all_to_all transposes inserted by AD) + grad psum + optimizer update.

    The differentiated objective is the per-shard share ``loss / P`` — with
    ``check_rep=False`` every collective transposes to its true adjoint, so
    ``psum`` of the per-shard grads reconstructs the exact global gradient
    (asserted against the single-device model in
    ``tests/test_graph_partition.py``).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis_size = int(mesh.shape[axis])

    def step(state, batch, rng):
        batch = _constrain_partitioned(batch, mesh, axis)

        def shard_fn(params, batch_stats, opt_state, step_no, batch, rng):
            # decorrelate dropout masks across shards (rng enters replicated)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def loss_fn(p):
                variables = {"params": p}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                    outputs, mut = model.apply(
                        variables,
                        batch,
                        train=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": rng},
                    )
                    new_bs = mut["batch_stats"]
                else:
                    outputs = model.apply(
                        variables, batch, train=True, rngs={"dropout": rng}
                    )
                    new_bs = batch_stats
                tot, tasks = model.loss(outputs, batch)
                return tot / axis_size, (tuple(tasks), new_bs, tot)

            (_, (tasks, new_bs, tot)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            grads = jax.lax.psum(grads, axis)
            updates, new_opt = tx.update(grads, opt_state, params)
            import optax

            new_params = optax.apply_updates(params, updates)
            metrics = {
                "loss": tot,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
            }
            return new_params, new_bs, new_opt, step_no + 1, metrics

        new_params, new_bs, new_opt, step_no, metrics = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                P(),
                P(),
                P(),
                P(),
                _batch_spec(batch, axis),
                P(),
            ),
            out_specs=(P(), P(), P(), P(), P()),
            check_rep=False,
        )(state.params, state.batch_stats, state.opt_state, state.step, batch, rng)
        return (
            state.replace(
                params=new_params,
                batch_stats=new_bs,
                opt_state=new_opt,
                step=step_no,
            ),
            metrics,
        )

    return jax.jit(step, donate_argnums=(0,))


def make_partitioned_eval_step(model, mesh, axis: str = GRAPH_AXIS):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def eval_step(params, batch_stats, batch):
        batch = _constrain_partitioned(batch, mesh, axis)

        def shard_fn(params, batch_stats, batch):
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
            outputs = model.apply(variables, batch, train=False)
            tot, tasks = model.loss(outputs, batch)
            return {
                "loss": tot,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
                "outputs": outputs,
            }

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), P(), _batch_spec(batch, axis)),
            out_specs={
                "loss": P(),
                "tasks": P(),
                "outputs": jax.tree_util.tree_map(
                    lambda _: P(axis), tuple(range(model.num_heads))
                ),
            },
            check_rep=False,
        )(params, batch_stats, batch)

    return jax.jit(eval_step)
