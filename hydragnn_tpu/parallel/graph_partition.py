"""Graph-partition parallelism — the long-context analog for GNNs.

The reference cannot split one graph across devices at all: its scaling axis
is data parallelism over many small graphs (DDP, ``utils/distributed.py``),
and "large" means *many samples* (DDStore/ADIOS streaming). The TPU-native
framework goes further: ONE giant graph (a large atomistic system, a mesh, a
polymer) is sharded node-wise over a mesh axis, the exact structural analog of
sequence/context parallelism for transformers (ring attention's KV exchange
becomes halo exchange of remote-sender node features; SURVEY.md §5 names
static-shape bucketing as the in-domain replacement — this module is the
scale-out half of that story).

Design:

* **Ownership** — nodes are split into ``P`` contiguous shards after a
  locality-preserving reorder (Morton/Z-curve over positions, so radius-graph
  neighbors tend to share a shard and the halo stays small). Every directed
  edge is owned by its *receiver's* shard, so all receiver-side aggregations
  (the message-passing hot path) are shard-local segment ops.
* **Halo exchange** (``halo_extend``) — before every conv layer, each shard
  gathers the rows remote peers need (a host-precomputed, statically padded
  send list) and trades them with ONE ``lax.all_to_all`` over ICI. Convs run
  unmodified on the extended table ``[local ; halo]``; the local slice is
  kept. Autodiff through the collective yields the reverse scatter-add —
  gradients flow across shards with no hand-written backward.
* **Halo reduce** (``halo_reduce``) — the transpose operation, for the two
  stacks that aggregate at *senders* (EGNN / equivariant SchNet coordinate
  updates): partial sums landing on halo rows are all_to_all'd back to their
  owner shard and scatter-added into the local rows.
* **Exact numerics** — BatchNorm statistics, global pooling and every loss
  numerator/denominator are ``psum``'d over the axis (``models/common.py``,
  ``models/base.py``), so a partitioned model computes bit-for-bit the same
  math as the unpartitioned one; the tests assert output/gradient parity.

No counterpart exists in the reference (capability superset); the closest
public pattern is jraph's sharded_graphnet / DGL's DistDGL halo design.
"""

import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import GraphBatch


# --------------------------------------------------------------------------
# device-side collectives (called inside shard_map / the model)
# --------------------------------------------------------------------------


def halo_extend(x, halo_send, axis_name):
    """Extend the local node table with fresh halo rows from peer shards.

    ``x``: ``[NL, ...]`` local rows. ``halo_send``: ``[P, H]`` int32 — row ids
    this shard must send to each peer (padded entries point at the dummy
    row). Returns ``[NL + P*H, ...]``: local rows, then peer ``p``'s rows at
    ``NL + p*H + h`` — the layout the partitioner's remapped sender indices
    reference.
    """
    sends = x[halo_send]  # [P, H, ...]
    recv = jax.lax.all_to_all(sends, axis_name, split_axis=0, concat_axis=0)
    return jnp.concatenate([x, recv.reshape((-1,) + x.shape[1:])], axis=0)


def halo_reduce(y_ext, halo_send, axis_name):
    """Fold sender-side partial aggregations back onto their owner shards.

    ``y_ext``: ``[NL + P*H, ...]`` — a segment reduction over the extended
    table where rows ``NL + p*H + h`` hold partial sums belonging to peer
    ``p``'s node ``halo_send[p, h]`` (as seen on peer ``p``). Sends each halo
    block to its owner and scatter-adds into the local rows. Returns
    ``[NL + P*H, ...]`` with complete local rows and a zeroed halo region.
    """
    p, h = halo_send.shape
    nl = y_ext.shape[0] - p * h
    local = y_ext[:nl]
    halo = y_ext[nl:].reshape((p, h) + y_ext.shape[1:])
    back = jax.lax.all_to_all(halo, axis_name, split_axis=0, concat_axis=0)
    local = local.at[halo_send.reshape(-1)].add(
        back.reshape((p * h,) + y_ext.shape[1:])
    )
    return jnp.concatenate([local, jnp.zeros_like(y_ext[nl:])], axis=0)


# --------------------------------------------------------------------------
# host-side partitioner
# --------------------------------------------------------------------------


def _morton_order(pos: np.ndarray) -> np.ndarray:
    """Z-curve ordering of 3-D positions — cheap locality-preserving reorder
    so contiguous node chunks are spatially compact (small halo cut)."""
    q = pos - pos.min(axis=0, keepdims=True)
    denom = np.maximum(q.max(axis=0, keepdims=True), 1e-12)
    bits = 10
    cells = np.minimum((q / denom * ((1 << bits) - 1)).astype(np.uint64), (1 << bits) - 1)

    def spread(v):
        v = v & np.uint64(0x3FF)
        v = (v | (v << np.uint64(16))) & np.uint64(0x030000FF)
        v = (v | (v << np.uint64(8))) & np.uint64(0x0300F00F)
        v = (v | (v << np.uint64(4))) & np.uint64(0x030C30C3)
        v = (v | (v << np.uint64(2))) & np.uint64(0x09249249)
        return v

    code = spread(cells[:, 0]) | (spread(cells[:, 1]) << np.uint64(1)) | (
        spread(cells[:, 2]) << np.uint64(2)
    )
    return np.argsort(code, kind="stable")


class PartitionInfo:
    """Static partition geometry + the inverse maps to un-partition outputs."""

    def __init__(self, num_parts, nl, el, halo, node_perm, part_of_node, local_of_node, n_real):
        self.num_parts = num_parts
        self.nl = nl  # local node budget (incl. 1 dummy row)
        self.el = el  # local edge budget
        self.halo = halo  # per-peer halo budget H
        self.node_perm = node_perm  # [n] global node id -> (implicit) order
        self.part_of_node = part_of_node  # [n] owning shard per global node
        self.local_of_node = local_of_node  # [n] local row per global node
        self.n_real = n_real

    def gather_nodes(self, per_part_rows: np.ndarray) -> np.ndarray:
        """``[P*NL, ...]`` stacked per-part rows -> ``[n, ...]`` in the
        original global node order (drops dummy/halo padding)."""
        flat_idx = self.part_of_node * self.nl + self.local_of_node
        return np.asarray(per_part_rows)[flat_idx]


def partition_graph(
    sample,
    num_parts: int,
    head_types: Tuple[str, ...] = (),
    head_dims: Tuple[int, ...] = (),
    order: str = "morton",
    node_multiple: int = 8,
    edge_multiple: int = 8,
    halo_multiple: int = 8,
) -> Tuple[GraphBatch, PartitionInfo]:
    """Split one giant graph into ``num_parts`` static-shape shards.

    ``sample`` exposes numpy ``x [n,F]``, ``pos [n,3]``, ``edge_index [2,e]``,
    optional ``edge_attr``, and (per ``head_types``) ``targets``. Returns a
    ``GraphBatch`` whose leading axes concatenate the per-part arrays (part
    ``p`` owns rows ``[p*NL, (p+1)*NL)`` etc.) — sharding every leaf on axis 0
    over a ``num_parts``-sized mesh axis gives each device exactly its shard.

    Per-shard layout: rows ``[0, NL-1)`` local nodes (dummy at ``NL-1``);
    edges are owned by the receiver's shard; remapped sender ids >= NL
    reference the halo region filled by ``halo_extend`` at run time. The
    local graph id 0 is the real graph (``n_node[0]`` = GLOBAL real node
    count, see ``HydraBase.__call__``), id 1 absorbs padding.
    """
    x = np.asarray(sample.x, dtype=np.float32)
    pos = (
        np.asarray(sample.pos, dtype=np.float32)
        if getattr(sample, "pos", None) is not None
        else np.zeros((x.shape[0], 3), np.float32)
    )
    edge_index = np.asarray(sample.edge_index)
    edge_attr = getattr(sample, "edge_attr", None)
    if edge_attr is not None:
        edge_attr = np.asarray(edge_attr, dtype=np.float32)
    n = x.shape[0]
    e = edge_index.shape[1]
    P = int(num_parts)

    if order == "morton" and pos is not None:
        perm = _morton_order(pos)
    else:
        perm = np.arange(n)

    # contiguous chunks of the ordering -> parts
    part_sizes = [(n + P - 1 - p) // P for p in range(P)]  # near-even
    part_of_node = np.empty(n, dtype=np.int64)
    local_of_node = np.empty(n, dtype=np.int64)
    start = 0
    for p, sz in enumerate(part_sizes):
        ids = perm[start : start + sz]
        part_of_node[ids] = p
        local_of_node[ids] = np.arange(sz)
        start += sz

    def _round_up(v, m):
        return int(-(-v // m) * m)

    nl = _round_up(max(part_sizes) + 1, node_multiple)

    # edge ownership by receiver
    send_g, recv_g = edge_index[0], edge_index[1]
    e_part = part_of_node[recv_g]
    e_counts = np.bincount(e_part, minlength=P)
    el = _round_up(max(int(e_counts.max()), 1), edge_multiple)

    # halo: for each (owner p -> consumer q) the unique remote senders
    remote = part_of_node[send_g] != e_part
    halo_slot = {}  # (q, p, global sender) -> h
    halo_lists = [[[] for _ in range(P)] for _ in range(P)]  # [p][q] -> locals of p
    for idx in np.nonzero(remote)[0]:
        q = int(e_part[idx])
        p = int(part_of_node[send_g[idx]])
        key = (q, p, int(send_g[idx]))
        if key not in halo_slot:
            halo_slot[key] = len(halo_lists[p][q])
            halo_lists[p][q].append(int(local_of_node[send_g[idx]]))
    max_h = max(
        (len(halo_lists[p][q]) for p in range(P) for q in range(P)), default=0
    )
    halo = _round_up(max(max_h, 1), halo_multiple)

    # ---- per-part arrays -------------------------------------------------
    F = x.shape[1]
    xs = np.zeros((P, nl, F), np.float32)
    ps = np.zeros((P, nl, 3), np.float32)
    node_graph = np.full((P, nl), 1, np.int32)
    node_mask = np.zeros((P, nl), bool)
    n_node = np.zeros((P, 2), np.int32)
    n_edge = np.zeros((P, 2), np.int32)
    graph_mask = np.zeros((P, 2), bool)
    senders = np.full((P, el), nl - 1, np.int32)
    receivers = np.full((P, el), nl - 1, np.int32)
    edge_mask = np.zeros((P, el), bool)
    e_attr = (
        np.zeros((P, el, edge_attr.shape[1]), np.float32)
        if edge_attr is not None
        else None
    )
    # padded slots point at the dummy row so halo_reduce's scatter-add and
    # halo_extend's sends never touch a real node
    halo_send = np.full((P, P, halo), nl - 1, np.int32)
    nig = np.zeros((P, nl), np.int32)  # node_index_in_graph (global position)

    for p in range(P):
        ids = np.nonzero(part_of_node == p)[0]
        order_ids = ids[np.argsort(local_of_node[ids])]
        sz = order_ids.shape[0]
        xs[p, :sz] = x[order_ids]
        ps[p, :sz] = pos[order_ids]
        node_graph[p, :sz] = 0
        node_mask[p, :sz] = True
        nig[p, :sz] = order_ids
        n_node[p, 0] = n  # GLOBAL count: local pool sums psum to the true mean
        n_node[p, 1] = nl - sz
        graph_mask[p, 0] = True
        for q in range(P):
            lst = halo_lists[p][q]
            if lst:
                halo_send[p, q, : len(lst)] = np.asarray(lst, np.int32)

    for p in range(P):
        eidx = np.nonzero(e_part == p)[0]
        k = eidx.shape[0]
        r_loc = local_of_node[recv_g[eidx]].astype(np.int32)
        s_parts = part_of_node[send_g[eidx]]
        s_loc = np.empty(k, np.int32)
        local_mask = s_parts == p
        s_loc[local_mask] = local_of_node[send_g[eidx[local_mask]]].astype(np.int32)
        for j in np.nonzero(~local_mask)[0]:
            sp = int(s_parts[j])
            h = halo_slot[(p, sp, int(send_g[eidx[j]]))]
            s_loc[j] = nl + sp * halo + h
        senders[p, :k] = s_loc
        receivers[p, :k] = r_loc
        edge_mask[p, :k] = True
        n_edge[p, 0] = k
        n_edge[p, 1] = el - k
        if e_attr is not None:
            e_attr[p, :k] = edge_attr[eidx]

    # ---- targets ---------------------------------------------------------
    targets = []
    for ih, (t, d) in enumerate(zip(head_types, head_dims)):
        tgt = np.asarray(sample.targets[ih], np.float32)
        if t == "graph":
            arr = np.zeros((P, 2, d), np.float32)
            arr[:, 0] = tgt.reshape(-1)
        else:
            arr = np.zeros((P, nl, d), np.float32)
            for p in range(P):
                ids = np.nonzero(part_of_node == p)[0]
                order_ids = ids[np.argsort(local_of_node[ids])]
                arr[p, : order_ids.shape[0]] = tgt[order_ids].reshape(-1, d)
        targets.append(arr)

    def flat(a):
        return a.reshape((-1,) + a.shape[2:])

    batch = GraphBatch(
        x=flat(xs),
        pos=flat(ps),
        senders=flat(senders),
        receivers=flat(receivers),
        edge_attr=flat(e_attr) if e_attr is not None else None,
        node_graph=flat(node_graph),
        n_node=flat(n_node),
        n_edge=flat(n_edge),
        node_mask=flat(node_mask),
        edge_mask=flat(edge_mask),
        graph_mask=flat(graph_mask),
        targets=tuple(flat(t) for t in targets),
        extras={
            "halo_send": halo_send.reshape(P * P, halo),
            "node_index_in_graph": flat(nig),
        },
    )
    info = PartitionInfo(
        P, nl, el, halo, perm, part_of_node, local_of_node, n
    )
    return batch, info


# --------------------------------------------------------------------------
# shard_map step builders
# --------------------------------------------------------------------------


def _batch_spec(batch, axis):
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(lambda _: P(axis), batch)


def put_partitioned_batch(batch: GraphBatch, mesh, axis: str = "graph") -> GraphBatch:
    """Device placement: every leaf sharded on axis 0 so each device holds
    exactly its shard's rows."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), sharding), batch
    )


def make_partitioned_apply(model, mesh, axis: str = "graph"):
    """Jitted partitioned forward: (variables, batch) -> per-shard outputs.

    Graph-head rows come back replicated-identical on every shard; node-head
    rows are per-shard (un-partition with ``PartitionInfo.gather_nodes``).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def fwd(variables, batch):
        def shard_fn(variables, batch):
            return model.apply(variables, batch, train=False)

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), _batch_spec(batch, axis)),
            out_specs=P(axis),
            check_rep=False,
        )(variables, batch)

    return jax.jit(fwd)


def make_partitioned_train_step(model, tx, mesh, axis: str = "graph"):
    """One fused XLA program: partitioned forward + psum'd loss + backward
    (all_to_all transposes inserted by AD) + grad psum + optimizer update.

    The differentiated objective is the per-shard share ``loss / P`` — with
    ``check_rep=False`` every collective transposes to its true adjoint, so
    ``psum`` of the per-shard grads reconstructs the exact global gradient
    (asserted against the single-device model in
    ``tests/test_graph_partition.py``).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis_size = int(mesh.shape[axis])

    def step(state, batch, rng):
        def shard_fn(params, batch_stats, opt_state, step_no, batch, rng):
            # decorrelate dropout masks across shards (rng enters replicated)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def loss_fn(p):
                variables = {"params": p}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                    outputs, mut = model.apply(
                        variables,
                        batch,
                        train=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": rng},
                    )
                    new_bs = mut["batch_stats"]
                else:
                    outputs = model.apply(
                        variables, batch, train=True, rngs={"dropout": rng}
                    )
                    new_bs = batch_stats
                tot, tasks = model.loss(outputs, batch)
                return tot / axis_size, (tuple(tasks), new_bs, tot)

            (_, (tasks, new_bs, tot)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            grads = jax.lax.psum(grads, axis)
            updates, new_opt = tx.update(grads, opt_state, params)
            import optax

            new_params = optax.apply_updates(params, updates)
            metrics = {
                "loss": tot,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
            }
            return new_params, new_bs, new_opt, step_no + 1, metrics

        new_params, new_bs, new_opt, step_no, metrics = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                P(),
                P(),
                P(),
                P(),
                _batch_spec(batch, axis),
                P(),
            ),
            out_specs=(P(), P(), P(), P(), P()),
            check_rep=False,
        )(state.params, state.batch_stats, state.opt_state, state.step, batch, rng)
        return (
            state.replace(
                params=new_params,
                batch_stats=new_bs,
                opt_state=new_opt,
                step=step_no,
            ),
            metrics,
        )

    return jax.jit(step, donate_argnums=(0,))


def make_partitioned_eval_step(model, mesh, axis: str = "graph"):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def eval_step(params, batch_stats, batch):
        def shard_fn(params, batch_stats, batch):
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
            outputs = model.apply(variables, batch, train=False)
            tot, tasks = model.loss(outputs, batch)
            return {
                "loss": tot,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
                "outputs": outputs,
            }

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), P(), _batch_spec(batch, axis)),
            out_specs={
                "loss": P(),
                "tasks": P(),
                "outputs": jax.tree_util.tree_map(
                    lambda _: P(axis), tuple(range(model.num_heads))
                ),
            },
            check_rep=False,
        )(params, batch_stats, batch)

    return jax.jit(eval_step)
