"""Per-axis collective-byte accounting from compiled HLO.

The cost/memory analyses captured by ``obs/introspect.py`` say how much a
compiled program computes and holds — but not how much it COMMUNICATES,
which is the number a 2-D mesh lives or dies by (a bad partition rule
shows up as an all-gather storm long before it shows up in step time on a
small config). XLA's cost model has no collective breakdown, so this
module reads the compiled module text instead: every
``all-reduce``/``all-gather``/``all-to-all``/``reduce-scatter`` op's
result bytes are attributed to the mesh axis its ``replica_groups``
reduce over, and the per-axis totals land in the ``compile`` event and
the ``hydragnn_train_collective_bytes{axis=...}`` gauges.

Attribution: for a row-major ``(d, m)`` mesh, device ``i`` sits at
``(i // m, i % m)`` — groups of ``m`` consecutive ids are a ``model``
reduction, groups of ``d`` ids strided by ``m`` are ``data``, one group
of everything is ``global``; anything else reports as ``other`` (a
subset-mesh program, a permute). Both replica-group spellings XLA emits
are parsed: explicit ``{{0,2},{1,3}}`` lists and the iota form
``[G,S]<=[dims]T(perm)``.
"""

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_OP_RE = re.compile(
    r"=\s+(?P<type>[^=]*?)\s+"
    r"(?P<op>all-reduce|all-gather|all-to-all|reduce-scatter)"
    r"(?P<start>-start)?\("
)
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+)\[([0-9,]*)\]")
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[0-9, {}]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(?P<gs>[0-9,]+)\]<=\[(?P<dims>[0-9,]+)\]"
    r"(?:T\((?P<perm>[0-9,]+)\))?"
)


def _type_bytes(type_str: str, is_start: bool = False) -> int:
    """Result bytes of one op's printed type. Async ``*-start`` ops print
    a tuple of (operand..., result...) buffers — counting the whole tuple
    would double-count vs the sync spelling, so only the result half
    (the trailing shapes) is summed for them."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        sizes.append(n * _DTYPE_BYTES[dtype])
    if is_start and len(sizes) >= 2 and len(sizes) % 2 == 0:
        sizes = sizes[len(sizes) // 2 :]
    elif is_start and len(sizes) >= 2:
        sizes = sizes[-1:]
    return sum(sizes)


def _parse_groups(line: str) -> Optional[List[Tuple[int, ...]]]:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        gshape = [int(v) for v in m.group("gs").split(",")]
        dims = [int(v) for v in m.group("dims").split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group("perm"):
            ids = ids.transpose([int(v) for v in m.group("perm").split(",")])
        groups = ids.reshape(gshape[0], -1)
        return [tuple(int(v) for v in g) for g in groups]
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        groups = []
        for part in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
            ids = [int(v) for v in part.replace(" ", "").split(",") if v != ""]
            if ids:
                groups.append(tuple(ids))
        return groups or None
    return None


def axis_groups(axes: Sequence[str], shape: Sequence[int]) -> Dict[str, set]:
    """Canonical replica groups per mesh axis: group = the devices that
    vary along that axis with every other coordinate fixed."""
    shape = tuple(int(s) for s in shape)
    ids = np.arange(int(np.prod(shape))).reshape(shape)
    out: Dict[str, set] = {}
    for i, name in enumerate(axes):
        rows = np.moveaxis(ids, i, -1).reshape(-1, shape[i])
        out[str(name)] = {frozenset(int(v) for v in r) for r in rows}
    return out


def classify_groups(
    groups: List[Tuple[int, ...]], axes: Sequence[str], shape: Sequence[int]
) -> str:
    """Mesh-axis name for one op's replica groups; ``global`` for one
    group spanning the mesh, ``other`` when no axis matches."""
    total = int(np.prod([int(s) for s in shape]))
    got = {frozenset(g) for g in groups}
    if got == {frozenset(range(total))}:
        # a full-mesh reduction IS the single non-trivial axis when the
        # others are degenerate; otherwise it is a cross-axis global
        nontrivial = [a for a, s in zip(axes, shape) if int(s) > 1]
        return str(nontrivial[0]) if len(nontrivial) == 1 else "global"
    for name, canonical in axis_groups(axes, shape).items():
        if got == canonical:
            return name
    return "other"


def parse_collectives(
    hlo_text: str, axes: Sequence[str], shape: Sequence[int]
) -> List[Dict]:
    """One record per collective op in a compiled module:
    ``{"op": kind, "axis": mesh-axis, "bytes": result_bytes}``.

    The per-op form is shardlint's compiled-HLO fingerprint
    (``analysis/hlo.py``): a future refactor that makes XLA insert an
    implicit-resharding all-gather changes the record *set*, not just a
    per-axis total a shrinking all-reduce could mask. ``*-done`` halves
    of async pairs are skipped (the ``-start`` carries the bytes), same
    as the summed view."""
    records: List[Dict] = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        nbytes = _type_bytes(
            m.group("type"), is_start=m.group("start") is not None
        )
        if nbytes == 0:
            continue
        groups = _parse_groups(line)
        axis = (
            classify_groups(groups, axes, shape)
            if groups is not None
            else "other"
        )
        records.append(
            {"op": m.group("op"), "axis": axis, "bytes": float(nbytes)}
        )
    return records


def collective_bytes_by_axis(
    hlo_text: str, axes: Sequence[str], shape: Sequence[int]
) -> Dict[str, float]:
    """``{axis: result_bytes_per_device_per_dispatch}`` summed over every
    collective in one compiled module. Result bytes (the op's output
    shape), not wire bytes — a stable, backend-independent proxy the
    1-D/2-D A/B in ``bench.py --mesh`` compares on."""
    totals: Dict[str, float] = {}
    for rec in parse_collectives(hlo_text, axes, shape):
        totals[rec["axis"]] = totals.get(rec["axis"], 0.0) + rec["bytes"]
    return totals
