"""Regex partition-rule engine: param names -> mesh placements.

The 2-D ``("data", "model")`` mesh (``mesh.py``) needs a PLACEMENT POLICY:
which parameter leaves split over the ``model`` axis (hidden/head matmul
kernels), which stay replicated (biases, normalization scales/statistics,
attention vectors), and how ZeRO layers the ``data`` axis on top for
optimizer moments. The policy is a list of ``(regex, action)`` rules
matched against each leaf's ``/``-joined tree path (the SNIPPETS-[1]
``match_partition_rules`` pattern) — ONE table covers params, batch_stats
and the optimizer state, because optax moment trees mirror the parameter
tree and therefore carry the same leaf names (``.../mu/.../kernel``).

Contract (enforced, not hoped):

* scalars and size-1 leaves are never partitioned;
* a matched weight whose target dimension does not divide the mesh axis
  falls back to replication (recorded — see :func:`summarize_shardings`);
* an UNMATCHED non-scalar leaf is an error: a new parameter appearing in
  a model must be placed deliberately, not replicated by accident and
  discovered as an OOM three PRs later.

Actions are symbolic so one rule covers every rank a name appears at:

* ``"cols"``      — shard the LAST dim over ``model`` (output features);
* ``"rows"``      — shard dim ``-2`` over ``model`` (input features);
* ``"replicate"`` — replicate everywhere;
* an explicit ``PartitionSpec`` (advanced; must not exceed the leaf rank).

``Training.partition_rules`` (a list of ``[regex, action]`` pairs) is
prepended to :data:`DEFAULT_PARAM_RULES`, so configs can override
placement per-name without forking the table.
"""

import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

ACTIONS = ("cols", "rows", "replicate")

# (regex, action) — first match wins; matched with re.search against the
# "/"-joined path, so anchor with (^|/) to match a leaf NAME.
DEFAULT_PARAM_RULES: Tuple[Tuple[str, str], ...] = (
    # per-feature vectors, normalization scales/statistics, attention
    # vectors, split-linear per-site biases (incl. the UQ initial-bias
    # "final_bias" of models/common.MLP): replicated
    (
        r"(^|/)(final_)?(bias|scale|mean|var|b_l|b_r|bias2|att|freq)"
        r"(_\d+)?$",
        "replicate",
    ),
    # feature->scalar gates (EGNN/SchNet coordinate updates): a width-1
    # output cannot split
    (r"(^|/)coord_mlp_\d+$", "replicate"),
    # optimizer hyperparams (inject_hyperparams) stay replicated
    (r"(^|/)hyperparams(/|$)", "replicate"),
    # matmul weights: split OUTPUT features over the model axis
    (
        r"(^|/)(final_)?(kernel|w_l|w_r|lin1|lin2|embedding|embed)"
        r"(_\d+)?$",
        "cols",
    ),
)


def _key_name(entry) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_paths_and_leaves(tree, sep: str = "/"):
    """``[(path_str, leaf), ...]`` in flatten order — the names the rule
    regexes match against (``opt_state/0/mu/encoder_conv_0/lin/kernel``)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(sep.join(_key_name(k) for k in path), leaf) for path, leaf in flat]


def named_tree_map(fn: Callable, tree, sep: str = "/"):
    """``tree_map`` whose fn also receives the leaf's joined path name —
    the SNIPPETS-[1] helper, built on jax's keypath API."""
    import jax

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(sep.join(_key_name(k) for k in path), leaf),
        tree,
    )


def resolve_rules(training_config: Optional[dict] = None):
    """Config-extended rule table: ``Training.partition_rules`` entries
    (``[regex, action]`` pairs) take precedence over the defaults."""
    extra = []
    if training_config:
        for pair in training_config.get("partition_rules", []) or []:
            regex, action = pair[0], pair[1]
            if action not in ACTIONS:
                raise ValueError(
                    f"partition rule {regex!r}: unknown action {action!r} "
                    f"(expected one of {ACTIONS})"
                )
            extra.append((str(regex), action))
    return tuple(extra) + DEFAULT_PARAM_RULES


def _spec(*dims):
    """PartitionSpec with trailing Nones stripped (P('data', None) and
    P('data') are distinct objects; callers and tests compare the short
    form)."""
    from jax.sharding import PartitionSpec as P

    dims = list(dims)
    while dims and dims[-1] is None:
        dims.pop()
    return P(*dims)


def _fit_action(action, leaf, mesh) -> Tuple:
    """(PartitionSpec, fell_back) for one matched leaf. Falls back to
    replication when the mesh lacks a ``model`` axis or the target dim
    does not divide it — never errors on a matched leaf."""
    from jax.sharding import PartitionSpec as P

    if isinstance(action, P):
        axes = dict(mesh.shape)
        dims = tuple(action)
        if len(dims) > getattr(leaf, "ndim", 0):
            return _spec(), True  # spec exceeds the leaf rank: replicate
        for dim, name in enumerate(dims):
            if name is None:
                continue
            if name not in axes or leaf.shape[dim] % axes[name] != 0:
                return _spec(), True
        return action, False
    ndim = getattr(leaf, "ndim", 0)
    if action == "replicate":
        return _spec(), False
    msize = dict(mesh.shape).get("model", 0)
    if msize <= 1:
        return _spec(), False
    if action == "cols":
        if ndim >= 1 and leaf.shape[-1] % msize == 0 and leaf.shape[-1] >= msize:
            return _spec(*([None] * (ndim - 1) + ["model"])), False
        return _spec(), True
    if action == "rows":
        if ndim >= 2 and leaf.shape[-2] % msize == 0:
            return _spec(*([None] * (ndim - 2) + ["model", None])), False
        return _spec(), True
    raise ValueError(f"unknown partition action {action!r}")


def match_partition_rules(tree, mesh, rules=None, strict: bool = True):
    """Pytree of ``NamedSharding`` over ``tree`` per the rule table.

    Scalars/size-1 leaves are replicated without consulting the rules
    (the SNIPPETS-[1] guard). ``strict`` raises on any unmatched
    non-scalar leaf, listing every offender at once.
    """
    import jax
    from jax.sharding import NamedSharding

    rules = tuple(rules) if rules is not None else DEFAULT_PARAM_RULES
    compiled = [(re.compile(rx), action) for rx, action in rules]
    unmatched: List[str] = []

    def place(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return NamedSharding(mesh, _spec())
        for rx, action in compiled:
            if rx.search(name) is not None:
                spec, _ = _fit_action(action, leaf, mesh)
                return NamedSharding(mesh, spec)
        unmatched.append(f"{name} {tuple(shape)}")
        return NamedSharding(mesh, _spec())

    out = named_tree_map(place, tree)
    if strict and unmatched:
        raise ValueError(
            "no partition rule matched these leaves (add a rule to "
            "Training.partition_rules or DEFAULT_PARAM_RULES): "
            + ", ".join(unmatched)
        )
    return out


def _zero_overlay(tree, shardings, mesh):
    """ZeRO layer: shard dim 0 over ``data`` for weight-like (ndim >= 2)
    leaves whose dim 0 divides the axis — on TOP of any model-axis spec.
    1-D leaves (biases — the old heuristic's silent-shard bug) replicate."""
    import jax
    from jax.sharding import NamedSharding

    dsize = dict(mesh.shape).get("data", 0)
    if dsize <= 1:
        return shardings

    def overlay(leaf, sh):
        ndim = getattr(leaf, "ndim", 0)
        spec = tuple(sh.spec)
        if (
            ndim >= 2
            and leaf.shape[0] % dsize == 0
            and leaf.shape[0] >= dsize
            and (len(spec) == 0 or spec[0] is None)
        ):
            dims = ["data"] + list(spec[1:] if spec else []) + [None] * max(
                0, ndim - max(len(spec), 1)
            )
            return NamedSharding(mesh, _spec(*dims[:ndim]))
        return sh

    return jax.tree_util.tree_map(overlay, tree, shardings)


def state_shardings(state, mesh, zero_stage: int = 0, rules=None):
    """Placement for a full ``TrainState``: params/batch_stats/opt_state
    via the rule table (moment trees carry param leaf names), plus the
    ZeRO ``data``-axis overlay on optimizer moments (stage >= 1) and
    parameters (stage 3). Returns a ``TrainState`` of ``NamedSharding``.

    Strictness is load-bearing only where placement has a choice: on a
    mesh WITH a model axis an unmatched leaf raises (it must be placed
    deliberately); on a pure data mesh the only possible outcome is
    replication, so an unmatched name must not break a working 1-D
    config."""
    strict = dict(mesh.shape).get("model", 0) > 1
    shardings = match_partition_rules(state, mesh, rules=rules, strict=strict)
    if zero_stage >= 1:
        shardings = shardings.replace(
            opt_state=_zero_overlay(state.opt_state, shardings.opt_state, mesh)
        )
        if zero_stage >= 3:
            shardings = shardings.replace(
                params=_zero_overlay(state.params, shardings.params, mesh)
            )
    return shardings


def zero_data_shardings(tree, mesh, rules=None):
    """Data-axis-only placement for ad-hoc trees (the
    ``shard_over_data_axis`` compat surface): weight-like leaves (ndim >=
    2, dim 0 divisible) shard dim 0 over ``data``; 1-D leaves and
    scalars replicate. Name-matched ``replicate`` rules are honored when
    the tree carries names."""
    import jax
    from jax.sharding import NamedSharding

    rules = tuple(rules) if rules is not None else DEFAULT_PARAM_RULES
    replicate_rx = [
        re.compile(rx) for rx, action in rules if action == "replicate"
    ]
    dsize = dict(mesh.shape).get("data", 0)

    def place(name, leaf):
        ndim = getattr(leaf, "ndim", 0)
        shape = getattr(leaf, "shape", ())
        if (
            ndim < 2
            or dsize <= 1
            or shape[0] % dsize != 0
            or any(rx.search(name) for rx in replicate_rx)
        ):
            return NamedSharding(mesh, _spec())
        return NamedSharding(mesh, _spec("data"))

    return named_tree_map(place, tree)


def put_tree(tree, shardings):
    """Place every leaf DIRECTLY at its target sharding — no host-side
    replicate-then-reshard (which would transiently hold the full state
    on every device, defeating both ZeRO and model sharding at init).

    Single-process: one pytree ``device_put``. Multi-process: every host
    holds identical full values (seeded init / checkpoint restore), so
    each contributes its addressable shards via
    ``make_array_from_callback`` (``device_put`` cannot target
    non-addressable devices)."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def put(leaf, sh):
        a = np.asarray(leaf)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])

    return jax.tree_util.tree_map(put, tree, shardings)


def summarize_shardings(tree, shardings) -> Dict:
    """Compact placement report for the ``param_sharding`` run event:
    leaf/byte totals split sharded vs replicated, plus per-axis sharded
    bytes — enough to catch "everything silently replicated" regressions
    from the event stream alone."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    shs = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    )
    total = sharded = 0
    sharded_bytes = replicated_bytes = 0
    by_axis: Dict[str, int] = {}
    for leaf, sh in zip(leaves, shs):
        total += 1
        nbytes = int(
            np.prod(getattr(leaf, "shape", ()) or (1,))
        ) * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        axes = [a for a in tuple(sh.spec) if a is not None]
        if axes:
            sharded += 1
            sharded_bytes += nbytes
            for a in axes:
                by_axis[str(a)] = by_axis.get(str(a), 0) + nbytes
        else:
            replicated_bytes += nbytes
    return {
        "total_leaves": total,
        "sharded": sharded,
        "replicated": total - sharded,
        "sharded_bytes": sharded_bytes,
        "replicated_bytes": replicated_bytes,
        "axis_bytes": by_axis,
    }
