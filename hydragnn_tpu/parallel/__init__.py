from hydragnn_tpu.parallel.distributed import (
    check_remaining,
    get_comm_size_and_rank,
    host_allreduce,
    nsplit,
    parse_slurm_nodelist,
    print_peak_memory,
    setup_distributed,
)
from hydragnn_tpu.parallel.mesh import (
    DATA_AXIS,
    GRAPH_AXIS,
    KNOWN_AXES,
    MESH_AXES,
    MODEL_AXIS,
    best_mesh_shape,
    data_axis_multiple,
    default_mesh,
    jit_replicated,
    make_mesh,
    make_mesh2d,
    mesh_shape_list,
    resolve_mesh,
    shard_optimizer_state,
)
from hydragnn_tpu.parallel.rules import (
    DEFAULT_PARAM_RULES,
    match_partition_rules,
    state_shardings,
    summarize_shardings,
)
from hydragnn_tpu.parallel.graph_partition import (
    PartitionInfo,
    halo_extend,
    halo_reduce,
    make_partitioned_apply,
    make_partitioned_eval_step,
    make_partitioned_train_step,
    partition_graph,
    put_partitioned_batch,
    put_partitioned_state,
)
