from hydragnn_tpu.parallel.distributed import (
    check_remaining,
    get_comm_size_and_rank,
    host_allreduce,
    nsplit,
    parse_slurm_nodelist,
    print_peak_memory,
    setup_distributed,
)
from hydragnn_tpu.parallel.mesh import default_mesh, make_mesh, shard_optimizer_state
from hydragnn_tpu.parallel.graph_partition import (
    PartitionInfo,
    halo_extend,
    halo_reduce,
    make_partitioned_apply,
    make_partitioned_eval_step,
    make_partitioned_train_step,
    partition_graph,
    put_partitioned_batch,
    put_partitioned_state,
)
