from hydragnn_tpu.parallel.distributed import (
    check_remaining,
    get_comm_size_and_rank,
    host_allreduce,
    nsplit,
    parse_slurm_nodelist,
    print_peak_memory,
    setup_distributed,
)
from hydragnn_tpu.parallel.mesh import default_mesh, make_mesh, shard_optimizer_state
