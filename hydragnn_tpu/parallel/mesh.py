"""Device mesh construction & sharding policy.

The scaling design (SURVEY.md §2.3/§5) grew from a 1-D ``("data",)`` mesh
to a 2-D ``("data", "model")`` mesh (docs/parallelism.md):

* ``data`` — batch leading axes sharded, gradient all-reduce inserted by
  XLA over ICI (intra-slice) / DCN (across slices);
* ``model`` — hidden/head matmul weights column-split per the regex rule
  engine (``parallel/rules.py``), and graph-partition mode's node/edge
  ownership (``parallel/graph_partition.py``) — one graph's message
  passing spans the chips of a model group.

``resolve_mesh`` is the driver's single entry point: it honors
``HYDRAGNN_MESH="d,m"`` / ``Training.model_parallel`` and derives the
largest ``(d, m)`` factorization that fits the available devices
(:func:`best_mesh_shape`) — the SAME derivation the elastic re-mesh runs
against the surviving world, so a 2-D world heals exactly the way the
1-D one does.

On a multi-host TPU pod, ``jax.devices()`` spans every host; each host
feeds its local shard of the batch (the loaders shard sample indices per
process, DistributedSampler-style) and
``make_array_from_process_local_data`` builds the global sharded batch.
"""

import os
from typing import Optional, Tuple

import numpy as np

# ---- axis names -----------------------------------------------------------
# THE spellings of the mesh axes. Everything outside ``parallel/`` must
# route through these constants instead of re-typing the string — the
# shardlint ``hardcoded-mesh-axis`` rule (analysis/rules_sharding.py)
# enforces it, so a renamed or fat-fingered axis is a NameError at import
# time, not a silently-replicated PartitionSpec three PRs later.
DATA_AXIS = "data"  # batch leading axes; gradient all-reduce
MODEL_AXIS = "model"  # column-split weights; graph-partition ownership (2-D)
GRAPH_AXIS = "graph"  # legacy 1-D graph-partition mesh axis
MESH_AXES: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS)
# every axis name a PartitionSpec/collective in this repo may legally
# name (the shardlint ``unknown-spec-axis`` rule checks literals against
# this set)
KNOWN_AXES = frozenset({DATA_AXIS, MODEL_AXIS, GRAPH_AXIS})

# the driver-resolved mesh, consulted by the loaders (leading-axis padding
# must divide the DATA axis, not the raw device count) and by the obs
# introspection layer (collective-bytes axis attribution)
_active_mesh = None
# mesh generation: starts at the resumed checkpoint's recorded value and
# increments on every re-derive, so successive elastic shrinks emit
# distinguishable world_resize events (the 1-D elastic path's gen analog).
# Recorded back into the train meta by epoch_driver._build_train_meta.
_mesh_gen = 0


def current_mesh_gen() -> int:
    return _mesh_gen


def set_active_mesh(mesh):
    """Register the run's mesh as ambient context (loaders' padding
    multiple, introspection's collective-axis attribution). Idempotent;
    pass None to clear."""
    global _active_mesh
    _active_mesh = mesh
    try:
        from hydragnn_tpu.obs import introspect

        if mesh is None:
            introspect.set_mesh_context(None, None)
        else:
            introspect.set_mesh_context(
                tuple(mesh.axis_names), tuple(mesh.devices.shape)
            )
    except Exception:
        pass


def active_mesh():
    return _active_mesh


def data_axis_multiple() -> int:
    """The divisor batch leading axes must honor: the active mesh's
    ``data`` axis size when one is registered, else every local device
    (the historical default — identical when the default 1-D mesh is in
    use, and the only safe answer when no mesh was resolved yet)."""
    if _active_mesh is not None:
        return int(dict(_active_mesh.shape).get(DATA_AXIS, 1))
    import jax

    try:
        return jax.device_count()
    except Exception:
        return 1


def best_mesh_shape(n_devices: int, model_parallel: int) -> Tuple[int, int]:
    """Largest ``(data, model)`` factorization fitting ``n_devices`` while
    preserving the requested model width — the elastic re-mesh rule. The
    model axis is a CAPACITY requirement (params/graph shards must fit a
    model group), so a shrunken world keeps ``m`` and drops data replicas:
    8 devices at m=2 -> (4, 2); a 7-survivor world -> (3, 2) on 6 devices,
    never (7, 1)."""
    m = max(1, min(int(model_parallel), int(n_devices)))
    d = max(1, int(n_devices) // m)
    return d, m


def default_mesh(min_devices: int = 2):
    """1-D data-parallel mesh over all devices; None on a single device (jit
    without a mesh is already optimal there)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def make_mesh2d(data: int, model: int, axes: Tuple[str, str] = MESH_AXES):
    """2-D ``(data, model)`` mesh over the first ``data*model`` devices.
    Device order is row-major — one model group is ``model`` CONSECUTIVE
    devices (the ICI-nearest neighbors on a TPU slice, where the
    latency-sensitive halo/all-gather traffic belongs)."""
    import jax
    from jax.sharding import Mesh

    d, m = int(data), int(model)
    devices = jax.devices()
    if d * m > len(devices):
        raise ValueError(
            f"mesh {d}x{m} needs {d * m} devices, have {len(devices)}"
        )
    return Mesh(np.asarray(devices[: d * m]).reshape(d, m), axes)


def mesh_shape_list(mesh):
    """``[d, m]`` for events/checkpoint metadata (1-D meshes report
    ``[d, 1]``); None for no mesh."""
    if mesh is None:
        return None
    shape = dict(mesh.shape)
    return [
        int(shape.get(DATA_AXIS, 1)),
        int(shape.get(MODEL_AXIS, shape.get(GRAPH_AXIS, 1))),
    ]


def requested_mesh(training_config: Optional[dict]):
    """(d_or_None, m) requested via ``HYDRAGNN_MESH="d,m"`` (env wins) or
    ``Training.model_parallel`` / ``Training.mesh_shape`` ([d, m]).
    Parsing routes through :func:`~hydragnn_tpu.utils.envparse.env_mesh`,
    so a malformed value ("4x2") errors naming the VARIABLE."""
    from hydragnn_tpu.utils.envparse import env_mesh

    env = env_mesh("HYDRAGNN_MESH")
    if env is not None:
        return env
    cfg = training_config or {}
    shape = cfg.get("mesh_shape")
    if shape:
        if len(shape) != 2:
            raise ValueError(
                f"Training.mesh_shape must be [data, model], got {shape!r}"
            )
        return int(shape[0]), int(shape[1])
    return None, int(cfg.get("model_parallel", 1) or 1)


def resolve_mesh(training_config: Optional[dict] = None, min_devices: int = 2):
    """The driver's mesh: 2-D when model parallelism is requested, the
    historical 1-D data mesh otherwise, None on a single device. A
    requested shape that no longer fits (elastic shrink, a smaller dev
    box) re-derives via :func:`best_mesh_shape` instead of failing —
    that IS the re-mesh path. The result is registered as the active
    ambient mesh (:func:`set_active_mesh`)."""
    import jax

    n = len(jax.devices())
    d_req, m_req = requested_mesh(training_config)
    if m_req <= 1 and d_req is not None:
        # an EXPLICIT 1-D width ("4,1") is honored, not widened to every
        # device — a deliberately narrow benchmark layout must not
        # silently train on a different world size
        d = min(int(d_req), n)
        mesh = make_mesh(d) if d >= 2 else None
    elif m_req <= 1:
        mesh = default_mesh(min_devices)
    else:
        d, m = best_mesh_shape(n, m_req)
        if d_req is not None and d_req * m <= n:
            d = int(d_req)
        if d * m < 2:
            mesh = None  # single device: jit without a mesh is optimal
        elif m == 1:
            mesh = make_mesh(d * m)
        else:
            mesh = make_mesh2d(d, m)
    set_active_mesh(mesh)
    return mesh


def shard_over_data_axis(tree, mesh):
    """Shard ``tree`` over the data axis — compat shim over the rule
    engine (``parallel/rules.py``, docs/MIGRATION.md).

    The old shape heuristic sharded ANY leaf whose dim 0 divided the
    axis size, so a size-8 bias on an 8-way mesh sharded silently —
    tiny latency-bound all-gathers at every use and a layout no other
    placement decision agreed on. Placement now routes through
    :func:`~hydragnn_tpu.parallel.rules.zero_data_shardings`: weight-like
    leaves (ndim >= 2, dim 0 divisible) shard, 1-D leaves and anything a
    ``replicate`` rule names stay replicated."""
    from hydragnn_tpu.parallel import rules

    return rules.put_tree(tree, rules.zero_data_shardings(tree, mesh))


def shard_optimizer_state(opt_state, mesh):
    """ZeRO-1/2 parity: shard optimizer-state leaves over the data axis
    (``utils/optimizer.py:48-139`` analog). Gradient partitioning (the
    stage-1/2 distinction) is not a user decision here — XLA schedules
    the gradient reduction as reduce-scatter + all-gather itself when
    profitable."""
    return shard_over_data_axis(opt_state, mesh)


def shard_parameters(params, mesh):
    """ZeRO-3 parity: shard the PARAMETERS too (DeepSpeed stage 3,
    ``run_training.py:134-151``). XLA inserts the per-use all-gathers;
    see docs/MIGRATION.md for the measured why-and-when (GNN parameter
    bytes are tiny next to activations, so this is a parity/completeness
    knob, not a memory necessity)."""
    return shard_over_data_axis(params, mesh)


def jit_replicated(fn, **kwargs):
    """``jax.jit`` with an EXPLICIT replicated output contract on the
    active mesh (plain jit when none is registered) — the sanctioned
    spelling for device-dispatching programs outside ``train/steps.py``'s
    sharding plan (serve dispatch, ad-hoc eval programs). Shardlint's
    ``jit-missing-shardings`` rule flags bare ``jax.jit`` at those sites;
    this helper IS the fix: the contract is declared here once instead of
    silently inherited from whatever placement the inputs carried."""
    import jax

    mesh = active_mesh()
    # membership, not truthiness: out_shardings=None (jit's explicit
    # "infer from inputs") and empty PartitionSpecs are falsy but ARE a
    # caller-declared contract this helper must not override
    if (
        mesh is not None
        and "in_shardings" not in kwargs
        and "out_shardings" not in kwargs
    ):
        from jax.sharding import NamedSharding, PartitionSpec

        kwargs["out_shardings"] = NamedSharding(mesh, PartitionSpec())
    return jax.jit(fn, **kwargs)


def announce_mesh(mesh, trainer=None, resume_meta=None, started_ts=None):
    """Emit the run's ``mesh_shape`` event (+ ``param_sharding`` when the
    trainer has a placement summary), and — when a resumed checkpoint
    recorded a DIFFERENT mesh — the re-derive ``world_resize`` with the
    new shape: the 2-D analog of the elastic 1-D re-shard, measured from
    process start to the emission (teardown + restore + re-derivation).
    No-ops when telemetry is inactive (the obs hook contract)."""
    import time

    import jax

    from hydragnn_tpu.obs import runtime as obs

    shape = mesh_shape_list(mesh)
    obs.emit(
        "mesh_shape",
        axes=list(mesh.axis_names) if mesh is not None else [],
        shape=shape or [],
        devices=len(jax.devices()),
    )
    summary = getattr(trainer, "sharding_summary", lambda: None)()
    if summary:
        obs.emit("param_sharding", **summary)

    def _meta_shape(v):
        # flax state-dict restore turns lists into {'0': ..., '1': ...}
        if v is None:
            return None
        if isinstance(v, dict):
            return [int(v[k]) for k in sorted(v, key=int)]
        return [int(x) for x in v]

    global _mesh_gen
    old = _meta_shape((resume_meta or {}).get("mesh"))
    _mesh_gen = int((resume_meta or {}).get("mesh_gen", 0) or 0)
    if old and shape and list(old) != list(shape):
        from hydragnn_tpu.train import elastic

        elastic.note_mesh_shape(shape)
        recovery = (
            max(time.monotonic() - started_ts, 0.0)
            if started_ts is not None
            else 0.0
        )
        _mesh_gen += 1
        obs.world_resized(
            old_world=int(np.prod(old)),
            new_world=int(np.prod(shape)),
            gen=_mesh_gen,
            recovery_s=round(recovery, 3),
            mesh_shape=shape,
            source="re-derive",
        )
    elif shape:
        from hydragnn_tpu.train import elastic

        elastic.note_mesh_shape(shape)
