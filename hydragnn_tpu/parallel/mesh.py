"""Device mesh construction & sharding policy.

The scaling design (SURVEY.md §2.3/§5): data parallelism is a 1-D ``data``
axis over all devices — batch leading axes sharded, parameters replicated,
gradient all-reduce inserted by XLA over ICI (intra-slice) / DCN (across
slices). Optimizer-state sharding (ZeRO parity) shards the optimizer moments
over the same axis.

On a multi-host TPU pod, ``jax.devices()`` spans every host; each host feeds
its local shard of the batch (the loaders shard sample indices per process,
DistributedSampler-style) and ``make_array_from_process_local_data`` builds
the global sharded batch.
"""

from typing import Optional

import numpy as np


def default_mesh(min_devices: int = 2):
    """1-D data-parallel mesh over all devices; None on a single device (jit
    without a mesh is already optimal there)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    return Mesh(np.asarray(devices), ("data",))


def make_mesh(n_devices: Optional[int] = None, axis: str = "data"):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def shard_over_data_axis(tree, mesh):
    """Shard tree leaves over the data axis where dim 0 divides, replicate
    the rest. ONE placement rule for every ZeRO stage — optimizer moments
    (stage 1/2) and parameters (stage 3) must agree on which leaves shard
    or the update step pays avoidable reshards."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_size = mesh.shape["data"]

    def place(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] % axis_size == 0:
            return jax.device_put(leaf, NamedSharding(mesh, P("data")))
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(place, tree)


def shard_optimizer_state(opt_state, mesh):
    """ZeRO-1/2 parity: shard optimizer-state leaves over the data axis
    (``utils/optimizer.py:48-139`` analog). Gradient partitioning (the
    stage-1/2 distinction) is not a user decision here — XLA schedules
    the gradient reduction as reduce-scatter + all-gather itself when
    profitable."""
    return shard_over_data_axis(opt_state, mesh)


def shard_parameters(params, mesh):
    """ZeRO-3 parity: shard the PARAMETERS too (DeepSpeed stage 3,
    ``run_training.py:134-151``). XLA inserts the per-use all-gathers;
    see docs/MIGRATION.md for the measured why-and-when (GNN parameter
    bytes are tiny next to activations, so this is a parity/completeness
    knob, not a memory necessity)."""
    return shard_over_data_axis(params, mesh)
