"""hydragnn_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework for multi-headed
graph convolutional neural networks.

Capability target: LemonAndRabbit/HydraGNN (reference layout documented in
SURVEY.md). Public facade mirrors the reference's two entry points
(``hydragnn/__init__.py:1-3``): ``run_training`` and ``run_prediction``.

Design stance (TPU-first, not a port):
  * graphs are batched into statically-shaped, padded ``GraphBatch`` pytrees
    (XLA needs static shapes; padding absorbs variable graph sizes),
  * message passing is expressed as gather + segment reductions that XLA fuses
    onto the MXU/VPU,
  * data parallelism is ``jax.jit`` over a ``jax.sharding.Mesh`` with the batch
    sharded on the ``data`` axis — gradient sync is an XLA all-reduce over ICI,
    never NCCL,
  * the train step (forward + loss + grad + update) is ONE compiled XLA program.
"""

from hydragnn_tpu.run_training import run_training
from hydragnn_tpu.run_prediction import run_prediction
from hydragnn_tpu import (
    graph,
    models,
    data,
    train,
    parallel,
    serve,
    utils,
    postprocess,
)

__version__ = "0.1.0"
