"""run_prediction facade (reference: ``hydragnn/run_prediction.py:48-107``).

Loads the trained model named by the config's derived log name, runs the test
split, returns (total_rmse, per-head rmse list, true values, predictions)
with optional denormalization.
"""

import json


def run_prediction(config, use_devices=None):
    # use_devices was accepted and silently ignored since the facade was
    # first ported; silently dropping a device request is worse than
    # refusing it, so it now fails loudly. Device selection belongs to
    # JAX: set JAX_PLATFORMS / jax.distributed.initialize() instead.
    if use_devices is not None:
        raise TypeError(
            "run_prediction(use_devices=...) is deprecated and was never "
            "honored; remove the argument and control device placement "
            "via JAX_PLATFORMS (or jax.distributed for multi-host runs)"
        )
    if isinstance(config, str):
        with open(config, "r") as f:
            config = json.load(f)
    from hydragnn_tpu.train.driver import run_prediction_impl

    return run_prediction_impl(config)
