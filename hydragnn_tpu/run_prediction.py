"""run_prediction facade (reference: ``hydragnn/run_prediction.py:48-107``).

Loads the trained model named by the config's derived log name, runs the test
split, returns (total_rmse, per-head rmse list, true values, predictions)
with optional denormalization.
"""

import json


def run_prediction(config, use_devices=None):
    if isinstance(config, str):
        with open(config, "r") as f:
            config = json.load(f)
    from hydragnn_tpu.train.driver import run_prediction_impl

    return run_prediction_impl(config)
