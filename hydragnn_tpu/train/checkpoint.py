"""Checkpoint save/load.

Parity with ``hydragnn/utils/model.py:60-119``: one logical checkpoint file
``./logs/<name>/<name>.pk`` written by process 0, holding model params, batch
stats AND optimizer state (the reference saves
``{model_state_dict, optimizer_state_dict}``). Under sharded training the
leaves are gathered to host before writing — the single-file contract is kept
even with ZeRO-style sharded optimizer state (reference consolidates via
``consolidate_state_dict``; here ``jax.device_get`` does the same job).

Format: an 8-byte magic+version header and a CRC32 of the payload, then
flax msgpack (framework-neutral, no pickle of code objects). Writes are
atomic (tmp + rename) so a killed job can't leave a truncated checkpoint
that parses; loads verify the checksum and fail loudly on corruption.
Legacy headerless files from earlier rounds still load.
"""

import binascii
import os
import struct
from typing import Any, Dict

import jax
import numpy as np
from flax import serialization

_MAGIC = b"HGTPCKPT"  # 8 bytes; last byte bumps with the format
_VERSION = 1


def _consolidate(leaf):
    """Bring one leaf fully to host. Multi-host + sharded (ZeRO optimizer
    moments over the data axis): device_get cannot read non-addressable
    shards, so reshard to replicated first — the role DeepSpeed's
    ``consolidate_state_dict`` plays in the reference (``model.py:60-74``)."""
    if (
        isinstance(leaf, jax.Array)
        and jax.process_count() > 1
        and not leaf.is_fully_replicated
    ):
        mesh = getattr(leaf.sharding, "mesh", None)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            leaf = jax.jit(
                lambda x: x, out_shardings=NamedSharding(mesh, P())
            )(leaf)
    return jax.device_get(leaf)


def _state_dict(state) -> Dict[str, Any]:
    return jax.tree_util.tree_map(
        _consolidate,
        {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        },
    )


def save_model(state_or_dict, name: str, path: str = "./logs/"):
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    # consolidation involves resharding COLLECTIVES — every process must
    # participate, only rank 0 writes the file
    sd = (
        state_or_dict
        if isinstance(state_or_dict, dict)
        else _state_dict(state_or_dict)
    )
    if rank != 0:
        return
    out_dir = os.path.join(path, name)
    os.makedirs(out_dir, exist_ok=True)
    # to_state_dict flattens custom containers (optax states) to plain dicts
    sd = serialization.to_state_dict(sd)
    blob = serialization.msgpack_serialize(
        jax.tree_util.tree_map(np.asarray, sd)
    )
    header = _MAGIC + struct.pack(
        "<II", _VERSION, binascii.crc32(blob) & 0xFFFFFFFF
    )
    final = os.path.join(out_dir, name + ".pk")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header + blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)  # atomic: never a half-written checkpoint


def load_state_dict(name: str, path: str = "./logs/") -> Dict[str, Any]:
    fname = os.path.join(path, name, name + ".pk")
    with open(fname, "rb") as f:
        raw = f.read()
    if raw[: len(_MAGIC)] == _MAGIC:
        version, crc = struct.unpack_from("<II", raw, len(_MAGIC))
        if version > _VERSION:
            raise ValueError(
                f"checkpoint {fname} has format version {version}; this "
                f"build reads up to {_VERSION}"
            )
        blob = raw[len(_MAGIC) + 8 :]
        if (binascii.crc32(blob) & 0xFFFFFFFF) != crc:
            raise ValueError(
                f"checkpoint {fname} is corrupt (CRC mismatch) — refusing "
                "to restore silently bad weights"
            )
    else:
        blob = raw  # legacy headerless msgpack from earlier rounds
    return serialization.msgpack_restore(blob)


def restore_into(template, restored):
    """Re-impose the template pytree structure (opt_state NamedTuples etc.)
    onto the raw msgpack dict — the analog of the reference's DDP "module."
    prefix fixup on old checkpoints (``model.py:109-114``)."""
    return serialization.from_state_dict(template, restored)


def restore_params_only(state, restored: Dict[str, Any]):
    """Cross-config resume: restore model params + batch stats from a
    checkpoint while keeping the fresh optimizer state — the supported
    path when the training config changed between save and resume (new
    optimizer/schedule; the reference reloads ``model_state_dict`` the
    same way and rebuilds the optimizer, ``model.py:98-119``). Model
    architecture must still match; a changed architecture fails loudly in
    ``from_state_dict``."""
    new_params = serialization.from_state_dict(state.params, restored["params"])
    new_stats = serialization.from_state_dict(
        state.batch_stats, restored.get("batch_stats", state.batch_stats)
    )
    return state.replace(params=new_params, batch_stats=new_stats)


def checkpoint_exists(name: str, path: str = "./logs/") -> bool:
    return os.path.exists(os.path.join(path, name, name + ".pk"))
