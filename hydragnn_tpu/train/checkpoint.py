"""Checkpoint save/load.

Parity with ``hydragnn/utils/model.py:60-119``: one logical checkpoint file
``./logs/<name>/<name>.pk`` written by process 0, holding model params, batch
stats AND optimizer state (the reference saves
``{model_state_dict, optimizer_state_dict}``). Under sharded training the
leaves are gathered to host before writing — the single-file contract is kept
even with ZeRO-style sharded optimizer state (reference consolidates via
``consolidate_state_dict``; here ``jax.device_get`` does the same job).

Format: an 8-byte magic+version header and a CRC32 of the payload, then
flax msgpack (framework-neutral, no pickle of code objects). Writes are
atomic (tmp + rename) so a killed job can't leave a truncated checkpoint
that parses; loads verify the checksum and fail loudly on corruption.
Legacy headerless files from earlier rounds still load.

Format v2 (resilience pass) adds two orthogonal pieces:

- an optional ``train_meta`` payload section carrying training-loop state
  (epoch index, host PRNG key, scheduler/early-stop/best-checkpoint
  counters, loader epoch) so ``Training.continue`` resumes mid-run at the
  exact epoch instead of restarting. v1 and legacy files still load — they
  simply carry no ``train_meta`` and resume falls back to weights-only.
- rolling keep-last-K retention: each ``save_model`` can also retain the
  written bytes as an INDEPENDENT ``<name>.roll-<seq>.pk`` file (never a
  hard link — see ``_retain_rolling``) and prune beyond the retention
  count.
  ``load_state_dict`` walks back to the newest intact rolling file when the
  primary is corrupt, truncated, or missing — a bad byte costs one save
  interval of progress, not the job.
"""

import binascii
import glob
import os
import re
import struct
from typing import Any, Dict, List, Optional

import jax
import numpy as np
from flax import serialization

from hydragnn_tpu.utils import faults

_MAGIC = b"HGTPCKPT"  # 8 bytes; last byte bumps with the format
_VERSION = 2  # v2 = v1 + optional "train_meta" payload section
_ROLL_RE = re.compile(r"\.roll-(\d+)\.pk$")

TRAIN_META_KEY = "train_meta"


def _consolidate(leaf):
    """Bring one leaf fully to host. Multi-host + sharded (ZeRO optimizer
    moments over the data axis): device_get cannot read non-addressable
    shards, so reshard to replicated first — the role DeepSpeed's
    ``consolidate_state_dict`` plays in the reference (``model.py:60-74``)."""
    if (
        isinstance(leaf, jax.Array)
        and jax.process_count() > 1
        and not leaf.is_fully_replicated
    ):
        mesh = getattr(leaf.sharding, "mesh", None)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # cold path (checkpoint consolidation, multi-host only) and the
            # out_shardings target varies with each leaf's mesh — caching a
            # wrapper here would key on a dead closure
            leaf = jax.jit(  # jaxlint: disable=jit-in-loop
                lambda x: x, out_shardings=NamedSharding(mesh, P())
            )(leaf)
    return jax.device_get(leaf)


def _state_dict(state) -> Dict[str, Any]:
    return jax.tree_util.tree_map(
        _consolidate,
        {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        },
    )


def _resolve_keep_last(keep_last: Optional[int]) -> int:
    """Retention policy: explicit argument > ``HYDRAGNN_CKPT_KEEP`` env >
    0 (no rolling copies — the pre-v2 behavior, and what ad-hoc callers
    like the unit tests get)."""
    if keep_last is not None:
        return max(int(keep_last), 0)
    return max(int(os.getenv("HYDRAGNN_CKPT_KEEP", "0")), 0)


def _rolling_paths(out_dir: str, name: str) -> List[str]:
    """Rolling files for ``name`` sorted newest (highest seq) first."""
    paths = glob.glob(os.path.join(out_dir, name + ".roll-*.pk"))
    with_seq = []
    for p in paths:
        m = _ROLL_RE.search(p)
        if m:
            with_seq.append((int(m.group(1)), p))
    return [p for _, p in sorted(with_seq, reverse=True)]


def rolling_checkpoints(name: str, path: str = "./logs/") -> List[str]:
    """Public view of the retained rolling checkpoints, newest first."""
    return _rolling_paths(os.path.join(path, name), name)


def _retain_rolling(out_dir: str, name: str, payload: bytes, keep: int):
    """Write the save's bytes as an INDEPENDENT rolling file (no hard
    link: a shared inode would mean corruption of the primary also eats
    the newest fallback — the exact event the rolling history exists
    for) and prune past the retention count."""
    rolls = _rolling_paths(out_dir, name)
    seq = 0
    if rolls:
        seq = int(_ROLL_RE.search(rolls[0]).group(1)) + 1
    roll = os.path.join(out_dir, f"{name}.roll-{seq:06d}.pk")
    tmp = roll + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, roll)
    for old in _rolling_paths(out_dir, name)[keep:]:
        try:
            os.remove(old)
        except OSError:
            pass  # a vanished/busy old rolling file is not worth a crash


def save_model(
    state_or_dict,
    name: str,
    path: str = "./logs/",
    train_meta: Optional[Dict[str, Any]] = None,
    keep_last: Optional[int] = None,
):
    """Write the checkpoint atomically; optionally embed training-loop
    state (``train_meta``) and retain a rolling history of the last
    ``keep_last`` saves (see module docstring)."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    # consolidation involves resharding COLLECTIVES — every process must
    # participate, only rank 0 writes the file
    sd = (
        state_or_dict
        if isinstance(state_or_dict, dict)
        else _state_dict(state_or_dict)
    )
    if rank != 0:
        return
    out_dir = os.path.join(path, name)
    os.makedirs(out_dir, exist_ok=True)
    # to_state_dict flattens custom containers (optax states) to plain dicts
    sd = serialization.to_state_dict(sd)
    if train_meta is not None:
        sd = dict(sd)
        sd[TRAIN_META_KEY] = serialization.to_state_dict(train_meta)
    blob = serialization.msgpack_serialize(
        jax.tree_util.tree_map(np.asarray, sd)
    )
    header = _MAGIC + struct.pack(
        "<II", _VERSION, binascii.crc32(blob) & 0xFFFFFFFF
    )
    final = os.path.join(out_dir, name + ".pk")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header + blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)  # atomic: never a half-written checkpoint
    keep = _resolve_keep_last(keep_last)
    if keep > 0:
        _retain_rolling(out_dir, name, header + blob, keep)
    from hydragnn_tpu.obs import runtime as obs

    obs.checkpoint_saved(
        name,
        kind="best" if name.endswith("-best") else "primary",
        resumable=train_meta is not None,
        bytes=len(header) + len(blob),
    )
    faults.corrupt_checkpoint(final)


def _parse_checkpoint_bytes(raw: bytes, fname: str) -> Dict[str, Any]:
    """Header/CRC validation + msgpack restore for one checkpoint file's
    bytes. Raises ``ValueError`` on corruption/truncation (including a
    truncated legacy blob) and on a from-the-future format version."""
    if raw[: len(_MAGIC)] == _MAGIC:
        if len(raw) < len(_MAGIC) + 8:
            raise ValueError(
                f"checkpoint {fname} is corrupt (truncated inside the "
                "header)"
            )
        version, crc = struct.unpack_from("<II", raw, len(_MAGIC))
        if version > _VERSION:
            raise ValueError(
                f"checkpoint {fname} has format version {version}; this "
                f"build reads up to {_VERSION}"
            )
        blob = raw[len(_MAGIC) + 8 :]
        if (binascii.crc32(blob) & 0xFFFFFFFF) != crc:
            raise ValueError(
                f"checkpoint {fname} is corrupt (CRC mismatch) — refusing "
                "to restore silently bad weights"
            )
    else:
        blob = raw  # legacy headerless msgpack from earlier rounds
    try:
        return serialization.msgpack_restore(blob)
    except Exception as e:
        raise ValueError(
            f"checkpoint {fname} is corrupt (unreadable payload: {e})"
        ) from e


def load_state_dict(
    name: str, path: str = "./logs/", fallback: bool = True
) -> Dict[str, Any]:
    """Load ``<path>/<name>/<name>.pk``. On corruption, truncation, or a
    missing primary file, walk back to the newest INTACT rolling
    checkpoint (``fallback=True``, the default) instead of aborting the
    job; with no intact rolling file the original error propagates. A
    from-the-future format version is always refused — silently resuming
    older weights in that situation would not be an accident, it would be
    a downgrade."""
    from hydragnn_tpu.obs import runtime as obs

    fname = os.path.join(path, name, name + ".pk")
    try:
        with open(fname, "rb") as f:
            raw = f.read()
        restored = _parse_checkpoint_bytes(raw, fname)
        obs.checkpoint_restored(name, source="primary")
        return restored
    except (ValueError, OSError) as primary_err:
        is_version_refusal = (
            isinstance(primary_err, ValueError)
            and "format version" in str(primary_err)
        )
        if not fallback or is_version_refusal:
            raise
        for roll in _rolling_paths(os.path.join(path, name), name):
            try:
                with open(roll, "rb") as f:
                    raw = f.read()
                restored = _parse_checkpoint_bytes(raw, roll)
            except (ValueError, OSError):
                continue  # this rolling file is bad too — keep walking
            import warnings

            warnings.warn(
                f"checkpoint {fname} unreadable ({primary_err}); restored "
                f"last-good rolling checkpoint {os.path.basename(roll)}"
            )
            obs.checkpoint_restored(
                name, source=f"rolling:{os.path.basename(roll)}"
            )
            return restored
        raise


def pop_train_meta(restored: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Detach the v2 training-loop state from a loaded state dict (v1 and
    legacy checkpoints return ``None``). Call before ``restore_into`` when
    resuming; ``restore_into`` also strips the key defensively."""
    if isinstance(restored, dict):
        return restored.pop(TRAIN_META_KEY, None)
    return None


def restore_into(template, restored):
    """Re-impose the template pytree structure (opt_state NamedTuples etc.)
    onto the raw msgpack dict — the analog of the reference's DDP "module."
    prefix fixup on old checkpoints (``model.py:109-114``)."""
    if isinstance(restored, dict) and TRAIN_META_KEY in restored:
        restored = {
            k: v for k, v in restored.items() if k != TRAIN_META_KEY
        }
    return serialization.from_state_dict(template, restored)


def restore_params_only(state, restored: Dict[str, Any]):
    """Cross-config resume: restore model params + batch stats from a
    checkpoint while keeping the fresh optimizer state — the supported
    path when the training config changed between save and resume (new
    optimizer/schedule; the reference reloads ``model_state_dict`` the
    same way and rebuilds the optimizer, ``model.py:98-119``). Model
    architecture must still match; a changed architecture fails loudly in
    ``from_state_dict``."""
    new_params = serialization.from_state_dict(state.params, restored["params"])
    new_stats = serialization.from_state_dict(
        state.batch_stats, restored.get("batch_stats", state.batch_stats)
    )
    return state.replace(params=new_params, batch_stats=new_stats)


def checkpoint_exists(name: str, path: str = "./logs/") -> bool:
    return os.path.exists(os.path.join(path, name, name + ".pk"))
