"""Checkpoint save/load.

Parity with ``hydragnn/utils/model.py:60-119``: one logical checkpoint file
``./logs/<name>/<name>.pk`` written by process 0, holding model params, batch
stats AND optimizer state (the reference saves
``{model_state_dict, optimizer_state_dict}``). Under sharded training the
leaves are gathered to host before writing — the single-file contract is kept
even with ZeRO-style sharded optimizer state (reference consolidates via
``consolidate_state_dict``; here ``jax.device_get`` does the same job).

Format: an 8-byte magic+version header and a CRC32 of the payload, then
flax msgpack (framework-neutral, no pickle of code objects). Writes are
atomic (tmp + rename) so a killed job can't leave a truncated checkpoint
that parses; loads verify the checksum and fail loudly on corruption.
Legacy headerless files from earlier rounds still load.

Format v2 (resilience pass) adds two orthogonal pieces:

- an optional ``train_meta`` payload section carrying training-loop state
  (epoch index, host PRNG key, scheduler/early-stop/best-checkpoint
  counters, loader epoch) so ``Training.continue`` resumes mid-run at the
  exact epoch instead of restarting. v1 and legacy files still load — they
  simply carry no ``train_meta`` and resume falls back to weights-only.
- rolling keep-last-K retention: each ``save_model`` can also retain the
  written bytes as an INDEPENDENT ``<name>.roll-<seq>.pk`` file (never a
  hard link — see ``_retain_rolling``) and prune beyond the retention
  count.
  ``load_state_dict`` walks back to the newest intact rolling file when the
  primary is corrupt, truncated, or missing — a bad byte costs one save
  interval of progress, not the job.
"""

import binascii
import glob
import os
import re
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
from flax import serialization

from hydragnn_tpu.utils import faults

_MAGIC = b"HGTPCKPT"  # 8 bytes; last byte bumps with the format
_VERSION = 2  # v2 = v1 + optional "train_meta" payload section
_ROLL_RE = re.compile(r"\.roll-(\d+)\.pk$")

TRAIN_META_KEY = "train_meta"


def _consolidate(leaf):
    """Bring one leaf fully to host. Multi-host + sharded (ZeRO optimizer
    moments over the data axis): device_get cannot read non-addressable
    shards, so reshard to replicated first — the role DeepSpeed's
    ``consolidate_state_dict`` plays in the reference (``model.py:60-74``)."""
    if (
        isinstance(leaf, jax.Array)
        and jax.process_count() > 1
        and not leaf.is_fully_replicated
    ):
        mesh = getattr(leaf.sharding, "mesh", None)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # cold path (checkpoint consolidation, multi-host only) and the
            # out_shardings target varies with each leaf's mesh — caching a
            # wrapper here would key on a dead closure
            leaf = jax.jit(  # jaxlint: disable=jit-in-loop
                lambda x: x, out_shardings=NamedSharding(mesh, P())
            )(leaf)
    return jax.device_get(leaf)


def _state_dict(state) -> Dict[str, Any]:
    return jax.tree_util.tree_map(
        _consolidate,
        {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        },
    )


def _resolve_keep_last(keep_last: Optional[int]) -> int:
    """Retention policy: explicit argument > ``HYDRAGNN_CKPT_KEEP`` env >
    0 (no rolling copies — the pre-v2 behavior, and what ad-hoc callers
    like the unit tests get)."""
    if keep_last is not None:
        return max(int(keep_last), 0)
    return max(int(os.getenv("HYDRAGNN_CKPT_KEEP", "0")), 0)


def _rolling_paths(out_dir: str, name: str) -> List[str]:
    """Rolling files for ``name`` sorted newest (highest seq) first."""
    paths = glob.glob(os.path.join(out_dir, name + ".roll-*.pk"))
    with_seq = []
    for p in paths:
        m = _ROLL_RE.search(p)
        if m:
            with_seq.append((int(m.group(1)), p))
    return [p for _, p in sorted(with_seq, reverse=True)]


def rolling_checkpoints(name: str, path: str = "./logs/") -> List[str]:
    """Public view of the retained rolling checkpoints, newest first."""
    return _rolling_paths(os.path.join(path, name), name)


def _retain_rolling(out_dir: str, name: str, payload: bytes, keep: int):
    """Write the save's bytes as an INDEPENDENT rolling file (no hard
    link: a shared inode would mean corruption of the primary also eats
    the newest fallback — the exact event the rolling history exists
    for) and prune past the retention count."""
    rolls = _rolling_paths(out_dir, name)
    seq = 0
    if rolls:
        seq = int(_ROLL_RE.search(rolls[0]).group(1)) + 1
    roll = os.path.join(out_dir, f"{name}.roll-{seq:06d}.pk")
    tmp = roll + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())  # a crash right here is WHEN the fallback
    os.replace(tmp, roll)  # copies get read — they must be durable too
    for old in _rolling_paths(out_dir, name)[keep:]:
        try:
            os.remove(old)
        except OSError:
            pass  # a vanished/busy old rolling file is not worth a crash


def save_model(
    state_or_dict,
    name: str,
    path: str = "./logs/",
    train_meta: Optional[Dict[str, Any]] = None,
    keep_last: Optional[int] = None,
    writer: Optional["AsyncCheckpointWriter"] = None,
):
    """Write the checkpoint atomically; optionally embed training-loop
    state (``train_meta``) and retain a rolling history of the last
    ``keep_last`` saves (see module docstring).

    With a ``writer``, only the device->host snapshot (consolidation
    collectives + ``device_get``) stays on the calling thread — the step
    boundary pays for the copy and nothing else; serialize + CRC + fsync
    + rename run on the writer's background thread (see
    :class:`AsyncCheckpointWriter`)."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    t0 = time.perf_counter()
    # consolidation involves resharding COLLECTIVES — every process must
    # participate, only rank 0 writes the file
    sd = (
        state_or_dict
        if isinstance(state_or_dict, dict)
        else _state_dict(state_or_dict)
    )
    if rank != 0:
        return
    # to_state_dict flattens custom containers (optax states) to plain
    # dicts. Async snapshots need an OWNED host copy of every leaf:
    # np.asarray of a jax.Array can be a zero-copy view (CPU backend),
    # and the training loop donates the state buffers into the very next
    # step — serializing a view of a donated buffer would produce a
    # CRC-valid torn checkpoint. The copy IS the async path's documented
    # critical-path cost; the sync path keeps the cheap view (it
    # serializes before returning, nothing can donate underneath it).
    sd = serialization.to_state_dict(sd)
    if train_meta is not None:
        sd = dict(sd)
        sd[TRAIN_META_KEY] = serialization.to_state_dict(train_meta)
    to_host = (
        (lambda a: np.array(a, copy=True)) if writer is not None
        else np.asarray
    )
    sd = jax.tree_util.tree_map(to_host, sd)
    snapshot_s = time.perf_counter() - t0
    keep = _resolve_keep_last(keep_last)
    resumable = train_meta is not None
    if writer is None:
        _serialize_and_write(sd, path, name, keep, resumable, snapshot_s)
        return
    queued_ts = time.perf_counter()
    writer.submit(
        lambda: _serialize_and_write(
            sd, path, name, keep, resumable, snapshot_s,
            queued_ts=queued_ts,
        )
    )


def _serialize_and_write(
    sd: Dict[str, Any],
    path: str,
    name: str,
    keep: int,
    resumable: bool,
    snapshot_s: float,
    queued_ts: Optional[float] = None,
):
    """msgpack + CRC header + tmp/fsync/rename (+ rolling retention) for
    an already-host-resident state dict. Runs inline for sync saves, on
    the background thread for async ones; the ``checkpoint_saved`` event
    carries the overlap split either way."""
    t0 = time.perf_counter()
    out_dir = os.path.join(path, name)
    os.makedirs(out_dir, exist_ok=True)
    blob = serialization.msgpack_serialize(sd)
    header = _MAGIC + struct.pack(
        "<II", _VERSION, binascii.crc32(blob) & 0xFFFFFFFF
    )
    final = os.path.join(out_dir, name + ".pk")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header + blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)  # atomic: never a half-written checkpoint
    if keep > 0:
        _retain_rolling(out_dir, name, header + blob, keep)
    from hydragnn_tpu.obs import runtime as obs

    obs.checkpoint_saved(
        name,
        kind="best" if name.endswith("-best") else "primary",
        resumable=resumable,
        bytes=len(header) + len(blob),
        snapshot_s=round(snapshot_s, 6),
        write_s=round(time.perf_counter() - t0, 6),
        **(
            {}
            if queued_ts is None
            else {
                "async": True,
                # time the save spent waiting in the bounded queue before
                # the writer thread picked it up (backpressure visibility)
                "queued_s": round(t0 - queued_ts, 6),
            }
        ),
    )
    faults.corrupt_checkpoint(final)


class AsyncCheckpointWriter:
    """Bounded background writer: checkpoint serialization and I/O off
    the training critical path.

    The contract (``docs/resilience.md`` "Async checkpointing"):

    - :meth:`submit` enqueues one already-snapshotted write; with
      ``max_pending`` saves already in flight it BLOCKS (backpressure —
      a slow filesystem must throttle the run, not buy unbounded host
      memory buffering stale snapshots);
    - writes execute strictly in submission order on one thread, so the
      rolling-retention sequence numbers stay monotonic;
    - a failed background write is LOUD: the exception re-raises on the
      next :meth:`submit` or :meth:`drain` — durability silently lost is
      the one failure mode this subsystem exists to prevent;
    - :meth:`drain` is the shutdown/preemption barrier: it returns only
      when every queued write has been fsync'd + renamed (the elastic
      watchdog drains before hard-exiting a survivor, and the epoch
      driver drains at end of run). A kill mid-write costs nothing —
      the write goes through the same tmp+fsync+rename protocol, so the
      previous checkpoint (and its CRC-verified rolling fallbacks) stay
      intact.
    """

    def __init__(self, max_pending: int = 2):
        import queue

        self.max_pending = max(int(max_pending), 1)
        self._q = queue.Queue(maxsize=self.max_pending)
        self._thread = threading.Thread(
            target=self._run, name="hydragnn-async-ckpt", daemon=True
        )
        self._state_lock = threading.Lock()  # _started/_closed/_pending/_errors
        self._started = False
        self._closed = False
        self._pending = 0
        self._errors: List[BaseException] = []

    def submit(self, job: Callable[[], None]):
        # surface any earlier background failure BEFORE booking this job:
        # raising after the increment would leak a pending count no worker
        # ever decrements, wedging every later drain()
        self._raise_pending()
        # the real bound is the PENDING count, not the queue: a job the
        # worker already popped still holds its (multi-GB) host snapshot,
        # so queue.maxsize alone would admit max_pending+1 snapshots
        while True:
            with self._state_lock:
                if self._closed:
                    raise RuntimeError("AsyncCheckpointWriter is closed")
                if self._pending < self.max_pending:
                    if not self._started:
                        self._started = True
                        self._thread.start()
                    self._pending += 1
                    break
            time.sleep(0.005)
        self._q.put(job)

    def _run(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                job()
            except BaseException as e:  # surfaced on next submit/drain
                with self._state_lock:
                    self._errors.append(e)
            finally:
                with self._state_lock:
                    self._pending -= 1

    def _raise_pending(self):
        with self._state_lock:
            if not self._errors:
                return
            err = self._errors.pop(0)
        raise RuntimeError(
            "background checkpoint write failed — the run has NO newer "
            "durable checkpoint than the last successful save"
        ) from err

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted write completed (or ``timeout``
        seconds elapsed; returns False on timeout). Raises if any write
        failed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._state_lock:
                pending = self._pending
            if pending == 0:
                self._raise_pending()
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)

    def close(self, timeout: float = 60.0):
        """Drain, stop the thread, refuse further submits. Bounded: if the
        drain times out (a write wedged on a hung filesystem), the daemon
        worker is abandoned rather than blocked on — close() must return
        within ~timeout, not trade one hang for another."""
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            started = self._started
        if started and self.drain(timeout=timeout):
            self._q.put(None)  # queue is empty post-drain: cannot block
            self._thread.join(timeout=timeout)
        self._raise_pending()


_ASYNC_WRITER: Optional[AsyncCheckpointWriter] = None
_ASYNC_WRITER_LOCK = threading.Lock()


def async_checkpoint_enabled(training_config: dict) -> bool:
    """``HYDRAGNN_ASYNC_CKPT`` env > ``Training.async_checkpoint`` config;
    default off — async durability semantics (a just-"saved" checkpoint
    becomes durable only once the writer catches up) are opt-in."""
    from hydragnn_tpu.train.common import _env_flag

    return _env_flag(
        "HYDRAGNN_ASYNC_CKPT", training_config, "async_checkpoint"
    )


def get_async_writer() -> AsyncCheckpointWriter:
    """Process-wide writer singleton (one background thread total — saves
    from the epoch driver and the wall-clock path share the ordering)."""
    global _ASYNC_WRITER
    with _ASYNC_WRITER_LOCK:
        if _ASYNC_WRITER is None:
            _ASYNC_WRITER = AsyncCheckpointWriter(
                max_pending=int(os.getenv("HYDRAGNN_ASYNC_CKPT_PENDING", "2"))
            )
        return _ASYNC_WRITER


def resolve_async_writer(
    training_config: dict,
) -> Optional[AsyncCheckpointWriter]:
    if not async_checkpoint_enabled(training_config):
        return None
    return get_async_writer()


def drain_async(timeout: Optional[float] = None) -> bool:
    """Barrier over the process-wide writer (no-op True when async
    checkpointing never started)."""
    with _ASYNC_WRITER_LOCK:
        writer = _ASYNC_WRITER
    if writer is None:
        return True
    return writer.drain(timeout=timeout)


def _parse_checkpoint_bytes(raw: bytes, fname: str) -> Dict[str, Any]:
    """Header/CRC validation + msgpack restore for one checkpoint file's
    bytes. Raises ``ValueError`` on corruption/truncation (including a
    truncated legacy blob) and on a from-the-future format version."""
    if raw[: len(_MAGIC)] == _MAGIC:
        if len(raw) < len(_MAGIC) + 8:
            raise ValueError(
                f"checkpoint {fname} is corrupt (truncated inside the "
                "header)"
            )
        version, crc = struct.unpack_from("<II", raw, len(_MAGIC))
        if version > _VERSION:
            raise ValueError(
                f"checkpoint {fname} has format version {version}; this "
                f"build reads up to {_VERSION}"
            )
        blob = raw[len(_MAGIC) + 8 :]
        if (binascii.crc32(blob) & 0xFFFFFFFF) != crc:
            raise ValueError(
                f"checkpoint {fname} is corrupt (CRC mismatch) — refusing "
                "to restore silently bad weights"
            )
    else:
        blob = raw  # legacy headerless msgpack from earlier rounds
    try:
        return serialization.msgpack_restore(blob)
    except Exception as e:
        raise ValueError(
            f"checkpoint {fname} is corrupt (unreadable payload: {e})"
        ) from e


def load_state_dict(
    name: str, path: str = "./logs/", fallback: bool = True
) -> Dict[str, Any]:
    """Load ``<path>/<name>/<name>.pk``. On corruption, truncation, or a
    missing primary file, walk back to the newest INTACT rolling
    checkpoint (``fallback=True``, the default) instead of aborting the
    job; with no intact rolling file the original error propagates. A
    from-the-future format version is always refused — silently resuming
    older weights in that situation would not be an accident, it would be
    a downgrade."""
    from hydragnn_tpu.obs import runtime as obs

    fname = os.path.join(path, name, name + ".pk")
    try:
        with open(fname, "rb") as f:
            raw = f.read()
        restored = _parse_checkpoint_bytes(raw, fname)
        obs.checkpoint_restored(name, source="primary")
        return restored
    except (ValueError, OSError) as primary_err:
        is_version_refusal = (
            isinstance(primary_err, ValueError)
            and "format version" in str(primary_err)
        )
        if not fallback or is_version_refusal:
            raise
        for roll in _rolling_paths(os.path.join(path, name), name):
            try:
                with open(roll, "rb") as f:
                    raw = f.read()
                restored = _parse_checkpoint_bytes(raw, roll)
            except (ValueError, OSError):
                continue  # this rolling file is bad too — keep walking
            import warnings

            warnings.warn(
                f"checkpoint {fname} unreadable ({primary_err}); restored "
                f"last-good rolling checkpoint {os.path.basename(roll)}"
            )
            obs.checkpoint_restored(
                name, source=f"rolling:{os.path.basename(roll)}"
            )
            return restored
        raise


def pop_train_meta(restored: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Detach the v2 training-loop state from a loaded state dict (v1 and
    legacy checkpoints return ``None``). Call before ``restore_into`` when
    resuming; ``restore_into`` also strips the key defensively."""
    if isinstance(restored, dict):
        return restored.pop(TRAIN_META_KEY, None)
    return None


def restore_into(template, restored):
    """Re-impose the template pytree structure (opt_state NamedTuples etc.)
    onto the raw msgpack dict — the analog of the reference's DDP "module."
    prefix fixup on old checkpoints (``model.py:109-114``)."""
    if isinstance(restored, dict) and TRAIN_META_KEY in restored:
        restored = {
            k: v for k, v in restored.items() if k != TRAIN_META_KEY
        }
    return serialization.from_state_dict(template, restored)


def restore_params_only(state, restored: Dict[str, Any]):
    """Cross-config resume: restore model params + batch stats from a
    checkpoint while keeping the fresh optimizer state — the supported
    path when the training config changed between save and resume (new
    optimizer/schedule; the reference reloads ``model_state_dict`` the
    same way and rebuilds the optimizer, ``model.py:98-119``). Model
    architecture must still match; a changed architecture fails loudly in
    ``from_state_dict``."""
    new_params = serialization.from_state_dict(state.params, restored["params"])
    new_stats = serialization.from_state_dict(
        state.batch_stats, restored.get("batch_stats", state.batch_stats)
    )
    return state.replace(params=new_params, batch_stats=new_stats)


def checkpoint_exists(name: str, path: str = "./logs/") -> bool:
    return os.path.exists(os.path.join(path, name, name + ".pk"))
