"""Checkpoint save/load.

Parity with ``hydragnn/utils/model.py:60-119``: one logical checkpoint file
``./logs/<name>/<name>.pk`` written by process 0, holding model params, batch
stats AND optimizer state (the reference saves
``{model_state_dict, optimizer_state_dict}``). Under sharded training the
leaves are gathered to host before writing — the single-file contract is kept
even with ZeRO-style sharded optimizer state (reference consolidates via
``consolidate_state_dict``; here ``jax.device_get`` does the same job).

Format: flax msgpack (framework-neutral, no pickle of code objects).
"""

import os
from typing import Any, Dict

import jax
import numpy as np
from flax import serialization


def _state_dict(state) -> Dict[str, Any]:
    return {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "step": jax.device_get(state.step),
    }


def save_model(state_or_dict, name: str, path: str = "./logs/"):
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    if rank != 0:
        return
    sd = (
        state_or_dict
        if isinstance(state_or_dict, dict)
        else _state_dict(state_or_dict)
    )
    out_dir = os.path.join(path, name)
    os.makedirs(out_dir, exist_ok=True)
    # to_state_dict flattens custom containers (optax states) to plain dicts
    sd = serialization.to_state_dict(sd)
    blob = serialization.msgpack_serialize(
        jax.tree_util.tree_map(np.asarray, sd)
    )
    with open(os.path.join(out_dir, name + ".pk"), "wb") as f:
        f.write(blob)


def load_state_dict(name: str, path: str = "./logs/") -> Dict[str, Any]:
    fname = os.path.join(path, name, name + ".pk")
    with open(fname, "rb") as f:
        return serialization.msgpack_restore(f.read())


def restore_into(template, restored):
    """Re-impose the template pytree structure (opt_state NamedTuples etc.)
    onto the raw msgpack dict — the analog of the reference's DDP "module."
    prefix fixup on old checkpoints (``model.py:109-114``)."""
    return serialization.from_state_dict(template, restored)


def checkpoint_exists(name: str, path: str = "./logs/") -> bool:
    return os.path.exists(os.path.join(path, name, name + ".pk"))
