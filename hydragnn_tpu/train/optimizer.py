"""Optimizer factory.

Parity with ``hydragnn/utils/optimizer.py:11-158``: SGD / Adam / Adadelta /
Adagrad / Adamax / AdamW / RMSprop / (Fused)LAMB selected by
``Training.Optimizer.type`` with torch-default hyperparameters.

ZeRO parity note: the reference's ``ZeroRedundancyOptimizer`` and DeepSpeed
stages shard optimizer state across ranks (``optimizer.py:48-139``,
``run_training.py:134-150``). In JAX that is a SHARDING decision, not a
different optimizer: when ``use_zero_redundancy`` is set the trainer places
optimizer-state leaves sharded over the mesh's data axis
(``hydragnn_tpu/parallel/mesh.py``), and XLA's all-gathers do the rest —
no separate optimizer implementation is needed.

The learning rate is exposed through ``optax.inject_hyperparams`` so the
plateau scheduler can adjust it between epochs by rewriting one scalar in the
optimizer state (no recompilation).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax


def _base_factory(opt_type: str) -> Callable:
    # torch-default hyperparameters per optimizer
    table = {
        "SGD": lambda lr: optax.sgd(lr),
        "Adam": lambda lr: optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8),
        "Adadelta": lambda lr: optax.adadelta(lr, rho=0.9, eps=1e-6),
        "Adagrad": lambda lr: optax.adagrad(lr, eps=1e-10),
        "Adamax": lambda lr: optax.adamax(lr, b1=0.9, b2=0.999, eps=1e-8),
        "AdamW": lambda lr: optax.adamw(
            lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01
        ),
        "RMSprop": lambda lr: optax.rmsprop(lr, decay=0.99, eps=1e-8),
        # FusedLAMB (DeepSpeed CUDA op) -> optax.lamb: same update rule,
        # fused by XLA instead of a hand-written kernel
        "FusedLAMB": lambda lr: optax.lamb(lr),
        "LAMB": lambda lr: optax.lamb(lr),
    }
    if opt_type not in table:
        raise ValueError(f"Optimizer type not supported: {opt_type}")
    return table[opt_type]


def freeze_mask_fn(params) -> dict:
    """Trainable-mask for ``freeze_conv_layers`` (``models/Base.py:132-136``):
    everything under the encoder conv/bn scope is frozen; heads stay live."""
    def mask_one(path, _):
        top = path[0].key if hasattr(path[0], "key") else str(path[0])
        return not str(top).startswith("encoder_")

    return jax.tree_util.tree_map_with_path(mask_one, params)


def select_optimizer(
    training_config: dict,
    params=None,
    freeze_conv: bool = False,
) -> optax.GradientTransformation:
    opt_cfg = training_config.get("Optimizer", {})
    opt_type = opt_cfg.get("type", "AdamW")
    lr = opt_cfg.get("learning_rate", 1e-3)
    base = _base_factory(opt_type)

    if freeze_conv:
        assert params is not None, "freeze_conv requires params to build the mask"
        trainable = freeze_mask_fn(params)
        labels = jax.tree_util.tree_map(
            lambda t: "trainable" if t else "frozen", trainable
        )

        def factory(learning_rate):
            return optax.multi_transform(
                {
                    "trainable": base(learning_rate),
                    "frozen": optax.set_to_zero(),
                },
                param_labels=labels,
            )

    else:

        def factory(learning_rate):
            return base(learning_rate)

    return optax.inject_hyperparams(factory)(learning_rate=lr)


def get_learning_rate(opt_state) -> float:
    return float(opt_state.hyperparams["learning_rate"])


def set_learning_rate(opt_state, lr: float):
    hp = dict(opt_state.hyperparams)
    hp["learning_rate"] = jnp.asarray(lr, dtype=jnp.float32)
    return opt_state._replace(hyperparams=hp)
