"""Shared training-state containers and config/env knobs.

Split out of ``trainer.py`` (round-3 verdict item 10): these pieces are
used by the step builder, the epoch driver, the partitioned trainer and
the predict paths alike.
"""

import os
from typing import Any

import jax.numpy as jnp
from flax import struct


class TrainState(struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray


class SchedState(struct.PyTreeNode):
    """Device-resident scheduler/guard state for the on-device fit loop:
    ReduceLROnPlateau (best/bad-epochs), EarlyStopping (best/counter/flag)
    and the epoch index — all scalars living in HBM so whole-training
    dispatches never bounce scheduler decisions off the host."""

    plateau_best: jnp.ndarray  # f32
    plateau_bad: jnp.ndarray  # i32
    early_best: jnp.ndarray  # f32
    early_count: jnp.ndarray  # i32
    stopped: jnp.ndarray  # bool
    epoch: jnp.ndarray  # i32
    best_val: jnp.ndarray  # f32, for best-state tracking

    @classmethod
    def init(cls):
        return cls(
            plateau_best=jnp.asarray(jnp.inf, jnp.float32),
            plateau_bad=jnp.zeros((), jnp.int32),
            early_best=jnp.asarray(jnp.inf, jnp.float32),
            early_count=jnp.zeros((), jnp.int32),
            stopped=jnp.zeros((), bool),
            epoch=jnp.zeros((), jnp.int32),
            best_val=jnp.asarray(jnp.inf, jnp.float32),
        )


def _nbatch(loader):
    n = len(loader)
    cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
    if cap is not None:
        n = min(n, int(cap))
    return n


def _env_flag(env_name: str, config: dict, config_key: str, default=False):
    """Boolean knob with the framework's env-overrides-config convention
    (the reference's ``HYDRAGNN_*`` channel layered over its JSON config)."""
    return bool(int(os.getenv(env_name, str(int(config.get(config_key, default))))))


def _is_oom(exc: BaseException) -> bool:
    """Memory exhaustion, host or device: MemoryError, or the runtime's
    RESOURCE_EXHAUSTED / out-of-memory errors (jaxlib raises RuntimeError
    subclasses, not MemoryError). Shared by every staging fallback."""
    msg = str(exc)
    return (
        isinstance(exc, MemoryError)
        or "RESOURCE_EXHAUSTED" in msg
        or "out of memory" in msg.lower()
    )
