"""Host->device wire format: compaction and multi-host index offsetting.

Split out of ``trainer.py`` (round-3 verdict item 10). Two halves of one
contract: what the host ships (:func:`_offset_local_shard`, and the
compaction applied by ``Trainer._compact_for_transfer``) and what the
jitted program undoes (:func:`_decompact_traced`).
"""

import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.graph.batch import GraphBatch


def _offset_local_shard(batch: GraphBatch, rank: int) -> GraphBatch:
    """Multi-host assembly correctness: each process collates its local
    shard with LOCAL row indices, but the globally-assembled arrays have
    global row semantics inside jit — every index array must be offset by
    this process's position, or shard p's gathers silently read shard 0's
    rows (caught by the cross-process loss-parity test). Handles plain
    [..., E] and stacked [K, ..., E] layouts alike (offsets are per-shard
    constants)."""
    n_off = rank * batch.x.shape[-2]
    e_off = rank * batch.senders.shape[-1]
    g_off = rank * batch.n_node.shape[-1]
    rep = dict(
        senders=np.asarray(batch.senders, np.int64) + n_off,
        receivers=np.asarray(batch.receivers, np.int64) + n_off,
        node_graph=np.asarray(batch.node_graph, np.int64) + g_off,
    )
    rep = {k: v.astype(np.int32) for k, v in rep.items()}
    if batch.extras:
        ex = dict(batch.extras)
        for key in ("trip_i", "trip_j", "trip_k", "nbr_idx"):
            if key in ex:
                ex[key] = (np.asarray(ex[key], np.int64) + n_off).astype(
                    np.int32
                )
        for key in ("trip_kj", "trip_ji", "nbr_edge", "out_edge"):
            if key in ex:
                ex[key] = (np.asarray(ex[key], np.int64) + e_off).astype(
                    np.int32
                )
        for key, k_key in (
            ("rev_idx", "nbr_idx"),  # flat (receiver * k_in + slot)
            ("edge_slot", "nbr_idx"),
            ("out_slot", "out_edge"),  # flat (sender * k_out + slot)
        ):
            if key in ex:
                # flat (row * K + slot): global row offset scales by K
                k = ex[k_key].shape[-1]
                ex[key] = (
                    np.asarray(ex[key], np.int64) + n_off * k
                ).astype(np.int32)
        rep["extras"] = ex
    return batch.replace(**rep)


def _decompact_traced(batch: GraphBatch) -> GraphBatch:
    """Inverse of the wire compaction, INSIDE the jitted program (free —
    XLA fuses the casts; eager device casts would cost a dispatch each):
    upcast int16 index arrays, synthesize zero positions for the [1, 3]
    placeholder shipped when the model never reads ``pos``."""
    rep = {}
    if batch.senders.dtype != jnp.int32:
        rep = dict(
            senders=batch.senders.astype(jnp.int32),
            receivers=batch.receivers.astype(jnp.int32),
            node_graph=batch.node_graph.astype(jnp.int32),
        )
    if batch.pos.shape[-2] == 1 and batch.x.shape[-2] != 1:
        # NaN, not zeros: a conv that reads positions while declaring
        # conv_needs_pos=False would otherwise train on plausible all-zero
        # coordinates; NaN makes that bug blow up in the first loss value
        rep["pos"] = jnp.full(batch.x.shape[:-1] + (3,), jnp.nan, jnp.float32)
    return batch.replace(**rep) if rep else batch
