"""Config-driven giant-graph training — the high-level surface for
graph-partition parallelism.

``run_training`` routes here when ``Architecture.partition_axis`` is set:
every dataset sample is ONE giant graph, partitioned node-wise across the
mesh (``parallel/graph_partition``). The trainer mirrors ``Trainer``'s
method surface (``init_state`` / ``train_epoch`` / ``evaluate`` /
``predict``) so the shared epoch driver (``train_validate_test``),
checkpointing and visualizer work unchanged.

No reference counterpart: HydraGNN's ``run_training`` can only scale over
many small graphs.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.models.create import init_model_params
from hydragnn_tpu.obs import runtime as obs
from hydragnn_tpu.obs.introspect import instrument
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import Trainer, TrainState, _nbatch
from hydragnn_tpu.utils import tracer as tr


def scan_budgets(datasets, num_parts, head_types, head_dims, need_triplets=False,
                 need_neighbors=False):
    """Union of the natural partition budgets over several datasets — pass
    the result to every split's ``PartitionedLoader`` so train/val/test
    share ONE compiled step/eval executable."""
    from hydragnn_tpu.parallel.graph_partition import partition_graph

    budgets = {}
    for ds in datasets:
        for s in ds:
            _, info = partition_graph(
                s, num_parts, tuple(head_types), tuple(head_dims),
                need_triplets=need_triplets, need_neighbors=need_neighbors,
            )
            for k, v in info.budgets.items():
                budgets[k] = max(budgets.get(k, 0), v)
    return budgets


class PartitionedLoader:
    """One giant graph per step. Samples are partitioned host-side ONCE with
    dataset-wide static budgets (max over samples, or the caller's
    ``budgets`` union across splits), so every step reuses a single compiled
    executable; results are cached."""

    def __init__(
        self,
        dataset,
        num_parts: int,
        head_types,
        head_dims,
        need_triplets: bool = False,
        need_neighbors: bool = False,
        shuffle: bool = True,
        seed: int = 42,
        axis: str = "graph",
        budgets: dict = None,
    ):
        from hydragnn_tpu.parallel.graph_partition import partition_graph

        self.dataset = dataset
        self.num_parts = num_parts
        self.head_types = tuple(head_types)
        self.head_dims = tuple(head_dims)
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.axis = axis

        if budgets is None:
            budgets = scan_budgets(
                [dataset], num_parts, self.head_types, self.head_dims,
                need_triplets, need_neighbors,
            )
        self._batches = []
        self.infos = []
        for s in dataset:
            b, info = partition_graph(
                s, num_parts, self.head_types, self.head_dims,
                need_triplets=need_triplets, need_neighbors=need_neighbors,
                budgets=budgets,
            )
            self._batches.append(b)
            self.infos.append(info)
        self.budgets = budgets

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def _order(self):
        n = len(self._batches)
        if self.shuffle:
            return np.random.default_rng(self.seed + self.epoch).permutation(n)
        return np.arange(n)

    def __len__(self):
        return len(self._batches)

    def __iter__(self):
        for i in self._order():
            yield self._batches[int(i)]


class PartitionedTrainer:
    """Drop-in trainer for partitioned giant-graph workloads.

    ``model`` carries ``partition_axis``; ``ref_model`` is its unpartitioned
    twin used only for parameter init (flax init cannot trace collectives
    outside shard_map; parameters are identical between the two).
    """

    def __init__(
        self,
        model,
        ref_model,
        training_config: dict,
        mesh,
        axis: str = "graph",
        verbosity: int = 0,
        freeze_conv: bool = False,
    ):
        self.model = model
        self.ref_model = ref_model
        self.training_config = training_config
        self.mesh = mesh
        self.axis = axis
        self.verbosity = verbosity
        self.freeze_conv = freeze_conv
        self.tx = None
        self._train_step = None
        self._eval_step = None
        # process-global optimizer-step counter: drives the fault-injection
        # hooks and the elastic heartbeat, same contract as Trainer
        self._host_step = 0
        opt_cfg = training_config.get("Optimizer", {})
        if opt_cfg.get("use_zero_redundancy") or int(
            opt_cfg.get("zero_stage") or 0
        ) >= 1:
            import warnings

            warnings.warn(
                "ZeRO sharding (use_zero_redundancy / zero_stage) is not "
                "applied in graph-partition mode: the mesh axis shards the "
                "GRAPH, not the batch, so optimizer state (and stage-3 "
                "parameters) stay replicated",
                stacklevel=2,
            )

    def init_state(self, sample, seed: int = 0) -> TrainState:
        """Parameters from the unpartitioned twin on a single collated copy
        of ``sample`` (one raw GraphData-like giant graph) — the production
        collation path, so DimeNet triplet tables come along automatically."""
        from hydragnn_tpu.data.dataobj import GraphData
        from hydragnn_tpu.data.loaders import _collate_with_extras, compute_layout
        from hydragnn_tpu.parallel.graph_partition import (
            make_partitioned_eval_step,
            make_partitioned_train_step,
            put_partitioned_state,
        )

        need_triplets = any(
            c.__name__ == "DIMEStack" for c in type(self.ref_model).__mro__
        )
        g = GraphData(
            x=np.asarray(sample.x),
            pos=None if getattr(sample, "pos", None) is None else np.asarray(sample.pos),
            edge_index=np.asarray(sample.edge_index),
            edge_attr=None
            if getattr(sample, "edge_attr", None) is None
            else np.asarray(sample.edge_attr),
        )
        g.targets = list(sample.targets)
        g.target_types = list(self.model.output_type)
        layout = compute_layout([[g]], batch_size=1, need_triplets=need_triplets)
        example_batch = _collate_with_extras([g], layout)

        variables = init_model_params(
            self.ref_model,
            jax.tree_util.tree_map(jnp.asarray, example_batch),
            seed=seed,
        )
        params = variables["params"]
        self.tx = select_optimizer(
            self.training_config, params=params, freeze_conv=self.freeze_conv
        )
        state = TrainState(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=self.tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        state = put_partitioned_state(state, self.mesh)
        # same XLA introspection as the data-parallel steps (steps.py):
        # per-bucket compiled cost/memory lands in the compile events
        self._train_step = instrument(
            "partitioned_train_step",
            make_partitioned_train_step(
                self.model, self.tx, self.mesh, self.axis
            ),
        )
        self._eval_step = instrument(
            "partitioned_eval_step",
            make_partitioned_eval_step(self.model, self.mesh, self.axis),
        )
        return state

    def put_batch(self, batch):
        from hydragnn_tpu.parallel.graph_partition import put_partitioned_batch

        return put_partitioned_batch(batch, self.mesh, self.axis)

    def place_state(self, state):
        """Re-impose the step's sharding after a checkpoint restore (see
        Trainer.place_state / put_partitioned_state). The
        use_zero_redundancy warning fires in ``__init__``, which every
        construction path goes through."""
        from hydragnn_tpu.parallel.graph_partition import put_partitioned_state

        return put_partitioned_state(state, self.mesh)

    # ---- epoch loops (Trainer surface) ---------------------------------
    @staticmethod
    def _acc_add(acc, metrics):
        """Collect per-step metrics without a host readback (device parts,
        stacked + fetched once per epoch, float64 host summation); on
        multi-host, eager ops on non-addressable jit outputs are disallowed
        so the (permitted) per-step host fetch is used instead. See
        Trainer._acc_add."""
        if jax.process_count() > 1:
            part = np.concatenate(
                [
                    [np.asarray(metrics["loss"], np.float64)],
                    [1.0],
                    np.asarray(metrics["tasks"], np.float64),
                ]
            )
        else:
            part = jnp.concatenate(
                [
                    metrics["loss"].astype(jnp.float32)[None],
                    jnp.ones((1,), jnp.float32),
                    metrics["tasks"].astype(jnp.float32),
                ]
            )
        acc = [] if acc is None else acc
        acc.append(part)
        return acc

    # identical readback contract (stack, ONE explicit device_get, float64
    # host sum) — shared with the data-parallel trainer so the two cannot
    # drift apart
    _acc_read = staticmethod(Trainer._acc_read)

    def train_epoch(self, state, loader, rng):
        from hydragnn_tpu.train import elastic
        from hydragnn_tpu.utils import faults

        acc = None
        nbatch = _nbatch(loader)
        tr.start("train")
        # one global read per epoch, per-step hooks only when live — the
        # same contract as Trainer.train_epoch
        _telemetry = obs.active()
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            batch = self.put_batch(batch)
            rng, sub = jax.random.split(rng)
            t0 = time.perf_counter() if _telemetry is not None else 0.0
            # straggler injection inside the timed window, so the delay
            # reaches on_step -> flight-recorder stall detection
            faults.slow_step(self._host_step)
            state, metrics = self._train_step(state, batch, sub)
            if _telemetry is not None:
                _telemetry.on_step(time.perf_counter() - t0)
            acc = self._acc_add(acc, metrics)
            faults.kill_at_step(self._host_step)
            faults.lose_host_at_step(self._host_step)
            self._host_step += 1
            elastic.note_step(self._host_step)
        loss, tasks = self._acc_read(acc)
        tr.stop("train")
        return state, rng, loss, tasks

    def evaluate(self, state, loader, desc="validate"):
        acc = None
        nbatch = _nbatch(loader)
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            batch = self.put_batch(batch)
            metrics = self._eval_step(state.params, state.batch_stats, batch)
            acc = self._acc_add(acc, metrics)
        return self._acc_read(acc)

    def predict(self, state, loader):
        """Per-sample outputs gathered back to global node order."""
        num_heads = self.model.num_heads
        head_types = self.model.output_type
        acc = None
        true_values = [[] for _ in range(num_heads)]
        predicted_values = [[] for _ in range(num_heads)]
        infos = getattr(loader, "infos", None)
        order = (
            loader._order() if hasattr(loader, "_order") else range(len(loader))
        )
        for i in (int(j) for j in order):
            batch = loader._batches[i]
            info = infos[i]
            dev = self.put_batch(batch)
            metrics = self._eval_step(state.params, state.batch_stats, dev)
            # loss/tasks accumulate on device, ONE readback at the end —
            # the per-sample float()/np.asarray() this replaces cost a
            # host round trip per giant graph (jaxlint:
            # host-sync-in-hot-loop)
            acc = self._acc_add(acc, metrics)
            # sample collection needs the outputs on host: one EXPLICIT
            # bulk fetch (device_get is transfer-guard-sanctioned), then
            # pure numpy below — targets/gather tables are host data
            outputs = jax.device_get(metrics["outputs"])
            for ihead in range(num_heads):
                # NLL mode appends a log-variance channel to every head's
                # output — collected values are the mean prediction only
                d = self.model.output_dim[ihead]
                if head_types[ihead] == "graph":
                    # replicated: shard 0's real-graph row
                    pred = outputs[ihead].reshape(
                        info.num_parts, 2, -1
                    )[0, 0][:d].reshape(-1, 1)
                    true = batch.targets[ihead].reshape(
                        info.num_parts, 2, -1
                    )[0, 0].reshape(-1, 1)
                else:
                    pred = info.gather_nodes(
                        outputs[ihead]
                    )[..., :d].reshape(-1, 1)
                    true = info.gather_nodes(
                        batch.targets[ihead]
                    ).reshape(-1, 1)
                predicted_values[ihead].append(pred)
                true_values[ihead].append(true)
        loss, tasks = self._acc_read(acc)
        true_values = [np.concatenate(v, axis=0) for v in true_values]
        predicted_values = [np.concatenate(v, axis=0) for v in predicted_values]
        return (loss, np.atleast_1d(tasks), true_values, predicted_values)
