"""The epoch driver (``train_validate_test.py:54-250`` analog).

Split out of ``trainer.py`` (round-3 verdict item 10). Orchestrates the
``Trainer``'s execution modes — streaming per-batch, HBM-staged epochs,
whole-training ``fit_staged`` chunks — plus the host-side per-epoch work:
plateau LR (host path), early stopping, best-checkpoint persistence,
TensorBoard scalars, SLURM wall-clock guard, visualizer hooks.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.obs import runtime as obs
from hydragnn_tpu.train import elastic
from hydragnn_tpu.train.checkpoint import (
    drain_async,
    resolve_async_writer,
    save_model,
)
from hydragnn_tpu.train.common import SchedState, TrainState, _env_flag, _is_oom
from hydragnn_tpu.train.optimizer import (
    get_learning_rate,
    set_learning_rate,
)
from hydragnn_tpu.train.scheduler import (
    BestCheckpoint,
    EarlyStopping,
    ReduceLROnPlateau,
)
from hydragnn_tpu.utils.print_utils import print_distributed

_FIT_SCHED_FIELDS = (
    "plateau_best",
    "plateau_bad",
    "early_best",
    "early_count",
    "stopped",
    "epoch",
    "best_val",
)


def _mesh_meta(trainer):
    """``[d, m]`` of the trainer's mesh for the checkpoint meta — the
    record the elastic re-mesh compares the re-derived shape against
    (``parallel/mesh.py:announce_mesh``). None when unmeshed."""
    from hydragnn_tpu.parallel.mesh import mesh_shape_list

    return mesh_shape_list(getattr(trainer, "mesh", None))


def _build_train_meta(epoch, rng, scheduler, early, ckpt, guard, sched=None,
                      stream=None, mesh=None):
    """Checkpoint-v2 training-loop state: everything a preempted job needs
    to resume at epoch ``epoch + 1`` instead of epoch 0. ``stream`` is
    the streaming loader's mix cursor (data/stream/mix.py) — present only
    on streaming runs, it pins per-source shard/offset positions so the
    resumed run draws the exact sample sequence the uninterrupted run
    would have. ``mesh`` is the run's ``[d, m]`` mesh shape — a resumed
    run on a shrunken world diffs it against its re-derived mesh and
    emits the ``world_resize``."""
    meta = {
        "format": 2,
        "epoch": int(epoch),
        "rng": np.asarray(rng),
        "plateau": scheduler.state_dict(),
    }
    if stream is not None:
        meta["stream"] = stream
    if mesh is not None:
        from hydragnn_tpu.parallel.mesh import current_mesh_gen

        meta["mesh"] = [int(v) for v in mesh]
        meta["mesh_gen"] = current_mesh_gen()
    if early is not None:
        meta["early"] = early.state_dict()
    if ckpt is not None:
        meta["best_ckpt"] = ckpt.state_dict()
    if guard is not None:
        meta["guard"] = guard.state_dict()
    if sched is not None:
        # fit_staged's device-resident SchedState, host-ified per field so
        # a chunked whole-training run resumes at the chunk boundary
        meta["fit_sched"] = {
            k: np.asarray(getattr(sched, k)) for k in _FIT_SCHED_FIELDS
        }
    return meta


def _restore_fit_sched(meta_fit_sched) -> SchedState:
    return SchedState(
        **{
            k: jnp.asarray(np.asarray(meta_fit_sched[k]))
            for k in _FIT_SCHED_FIELDS
        }
    )


def train_validate_test(
    trainer,
    state: TrainState,
    train_loader,
    val_loader,
    test_loader,
    config_nn: dict,
    log_name: str,
    verbosity: int = 0,
    writer=None,
    create_plots: bool = False,
    plot_init_solution: bool = False,
    resume_meta=None,
    checkpoint_path: str = "./logs/",
):
    """Epoch driver (``train_validate_test.py:54-250``).

    ``resume_meta`` is the checkpoint-v2 training-loop state extracted by
    the caller (``checkpoint.pop_train_meta``): when present the run
    resumes at the exact saved epoch with the saved PRNG key and
    scheduler/early-stop/best-checkpoint counters, instead of restarting
    from epoch 0 with restored weights only.
    """
    training = config_nn["Training"]
    num_epoch = training["num_epoch"]
    early = EarlyStopping(training.get("patience", 5)) if training.get(
        "EarlyStopping", False
    ) else None
    # best-validation checkpoints get their OWN file (<name>-best): the
    # primary <name>.pk is the resumable latest-state checkpoint, and the
    # two writers must not destroy each other's saves
    ckpt = (
        BestCheckpoint(
            log_name + "-best",
            warmup=training.get("checkpoint_warmup", 10),
        )
        if training.get("Checkpoint", False)
        else None
    )
    scheduler = ReduceLROnPlateau(lr=get_learning_rate(state.opt_state))
    # configured seed (env > config > the historical 1337 default) — two
    # runs differing only in ``Training.random_seed`` get independent
    # shuffles/dropout; a resume below still restores the SAVED key, so
    # the seed only ever picks the trajectory of a fresh run
    seed = int(
        os.getenv(
            "HYDRAGNN_SEED", str(training.get("random_seed", 1337))
        )
    )
    rng = jax.random.PRNGKey(seed)
    guard = getattr(trainer, "guard", None)

    # preemption-resume cadence: save a resumable (weights + loop state)
    # checkpoint every N epochs (host path) / every chunk (fit path),
    # keeping the last ``checkpoint_keep_last`` as rolling fallbacks
    resume_every = int(
        os.getenv(
            "HYDRAGNN_RESUME_EVERY", str(training.get("resume_every", 1))
        )
    )
    keep_last = int(
        os.getenv(
            "HYDRAGNN_CKPT_KEEP", str(training.get("checkpoint_keep_last", 3))
        )
    )
    # async checkpointing (HYDRAGNN_ASYNC_CKPT / Training.async_checkpoint):
    # the resume-cadence saves keep only the device->host snapshot on the
    # epoch loop; serialize+CRC+fsync+rename move to the background writer.
    # Drained at end of run (and by the elastic watchdog on preemption).
    ckpt_writer = resolve_async_writer(training)

    # canary publication (HYDRAGNN_PUBLISH_DIR / Training.publish_dir):
    # each resume-cadence save also snapshots the checkpoint into the
    # serving side's CandidateChannel for SLO-gated canary promotion
    # (serve/canary.py). Rank 0 only, and — crucially — the publish
    # thunk rides the SAME async writer queue as the save: the writer
    # executes in strict submission order, so the snapshot is only ever
    # taken of a fully durable (fsync'd + renamed) checkpoint.
    publish_dir = os.getenv(
        "HYDRAGNN_PUBLISH_DIR", training.get("publish_dir") or ""
    ) or None
    publish_every = int(
        os.getenv(
            "HYDRAGNN_PUBLISH_EVERY", str(training.get("publish_every", 1))
        )
    )
    publish_keep = int(
        os.getenv(
            "HYDRAGNN_PUBLISH_KEEP",
            str(training.get("publish_keep_last", 4)),
        )
    )

    def _publish_candidate(epoch, val_loss=None):
        if publish_dir is None:
            return
        if publish_every > 0 and (epoch + 1) % publish_every != 0:
            return
        from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

        _, rank = get_comm_size_and_rank()
        if rank != 0:
            return

        def _do_publish():
            from hydragnn_tpu.serve.registry import publish_candidate

            try:
                manifest = publish_candidate(
                    publish_dir,
                    log_name,
                    checkpoint_path,
                    keep_last=publish_keep,
                    epoch=int(epoch),
                    val_loss=(
                        None if val_loss is None else float(val_loss)
                    ),
                )
            except OSError as e:
                # a full/unwritable publish dir must not kill a run that
                # would otherwise finish — serving just sees no candidate
                print_distributed(
                    verbosity, f"candidate publish failed: {e}"
                )
                return
            obs.emit(
                "candidate_published",
                candidate=int(manifest["seq"]),
                checkpoint=manifest["checkpoint"],
                epoch=int(epoch),
            )

        if ckpt_writer is not None:
            ckpt_writer.submit(_do_publish)
        else:
            _do_publish()

    def _stream_state():
        """Streaming loaders expose their mix cursor; everything else
        contributes no ``stream`` section to the resume meta."""
        sd = getattr(train_loader, "state_dict", None)
        return sd() if callable(sd) else None

    # the driver's end-of-run save reuses the newest loop state; seed it
    # with the incoming meta so a continue-of-a-finished-run (no epochs
    # left) does not strip resume state from the checkpoint.
    # final_state_saved tracks whether the CURRENT state already sits in
    # the primary checkpoint — the driver skips its (collective-heavy)
    # duplicate end-of-run save when it does.
    trainer.final_train_meta = resume_meta
    trainer.final_state_saved = False
    start_epoch = 0
    if resume_meta:
        start_epoch = int(resume_meta["epoch"]) + 1
        if resume_meta.get("rng") is not None:
            rng = jnp.asarray(np.asarray(resume_meta["rng"]), jnp.uint32)
        if resume_meta.get("plateau") is not None:
            scheduler.load_state_dict(resume_meta["plateau"])
        if early is not None and resume_meta.get("early") is not None:
            early.load_state_dict(resume_meta["early"])
        if ckpt is not None and resume_meta.get("best_ckpt") is not None:
            ckpt.load_state_dict(resume_meta["best_ckpt"])
        if guard is not None and resume_meta.get("guard") is not None:
            guard.load_state_dict(resume_meta["guard"])
        if early is not None and early.early_stop:
            # the run already stopped; training even one more epoch would
            # overwrite the checkpoint with post-stop state
            print_distributed(
                verbosity,
                "Resume: early stopping had already triggered — "
                "nothing left to train",
            )
            start_epoch = num_epoch
        if resume_meta.get("stream") is not None and hasattr(
            train_loader, "load_state_dict"
        ):
            # restore the streaming mix cursor BEFORE the first epoch so
            # the resumed run draws the exact sample sequence the
            # uninterrupted one would have (bitwise-identical trajectory)
            train_loader.load_state_dict(resume_meta["stream"])
        print_distributed(
            verbosity,
            f"Resuming training at epoch {start_epoch} "
            f"(lr {scheduler.lr:.3e})",
        )
        obs.emit(
            "resume", start_epoch=int(start_epoch), lr=float(scheduler.lr)
        )
        # nothing left to train -> the just-restored state IS the
        # checkpoint content; the driver need not rewrite it
        trainer.final_state_saved = start_epoch >= num_epoch

    visualizer = None
    if create_plots:
        from hydragnn_tpu.postprocess.visualizer import Visualizer

        node_feature = []
        nodes_num_list = []
        for d in test_loader.dataset:
            node_feature.extend(np.asarray(d.x).tolist())
            nodes_num_list.append(d.num_nodes)
        visualizer = Visualizer(
            log_name,
            node_feature=node_feature,
            num_heads=trainer.model.num_heads,
            head_dims=list(trainer.model.output_dim),
            num_nodes_list=nodes_num_list,
        )
        visualizer.num_nodes_plot()
        if plot_init_solution:
            _, _, true_values, predicted_values = trainer.predict(
                state, test_loader
            )
            visualizer.create_scatter_plots(
                true_values,
                predicted_values,
                output_names=config_nn["Variables_of_interest"].get(
                    "output_names"
                ),
                iepoch=-1,
            )

    total_loss_train = np.zeros(num_epoch)
    total_loss_val = np.zeros(num_epoch)
    total_loss_test = np.zeros(num_epoch)
    num_tasks = trainer.model.num_heads
    task_loss_train = np.zeros((num_epoch, num_tasks))
    task_weights = list(getattr(trainer.model, "loss_weights", []) or [])
    task_names = config_nn["Variables_of_interest"].get("output_names")
    skip_valtest = int(os.getenv("HYDRAGNN_VALTEST", "1")) == 0

    # device-resident mode: stage the (collated) training set in HBM once;
    # every epoch is then a single scan dispatch with no H2D traffic
    staged = None
    if _env_flag("HYDRAGNN_DEVICE_RESIDENT", training, "device_resident_dataset"):
        try:
            staged = trainer.stage_batches(list(train_loader))
        except ValueError:
            # bucketed layouts emit mixed batch shapes, which cannot stack
            # into one HBM-resident scan — train on the streaming path
            print_distributed(
                verbosity,
                "device_resident_dataset: batches are not shape-uniform "
                "(bucketed layout?) — falling back to streaming",
            )
            staged = None

    # whole-training dispatch: fit_chunk_epochs > 0 runs training in chunks
    # of N epochs, each chunk ONE XLA program (on-device plateau LR, early
    # stop, best-state tracking); host work between chunks only — logging,
    # TensorBoard, checkpoint, SLURM wall-clock guard
    fit_chunk = int(
        os.getenv(
            "HYDRAGNN_FIT_CHUNK", str(training.get("fit_chunk_epochs", 0))
        )
    )

    def _log_epoch(ep, train_loss, val_loss, test_loss, train_tasks,
                   t_train=None, mode="stream"):
        total_loss_train[ep] = train_loss
        total_loss_val[ep] = val_loss
        total_loss_test[ep] = test_loss
        tt = np.atleast_1d(np.asarray(train_tasks))
        task_loss_train[ep, : min(len(tt), num_tasks)] = tt[:num_tasks]
        timing = ""
        if t_train:
            try:
                n = len(train_loader.dataset)
            except TypeError:
                n = 0
            gps = f", {n / t_train:.0f} graphs/sec" if n else ""
            timing = f", Train Time: {t_train:.2f}s{gps}"
        print_distributed(
            verbosity,
            f"Epoch: {ep:04d}, Train Loss: {train_loss:.8f}, "
            f"Val Loss: {val_loss:.8f}, Test Loss: {test_loss:.8f}"
            f"{timing}",
        )
        if writer is not None:
            writer.add_scalar("train error", train_loss, ep)
            writer.add_scalar("validate error", val_loss, ep)
            writer.add_scalar("test error", test_loss, ep)
            for itask, tl in enumerate(np.atleast_1d(train_tasks)):
                writer.add_scalar(f"train error of task {itask}", float(tl), ep)
        if obs.active() is not None:
            # throughput + padding-waste accounting only when telemetry is
            # live — the stats walk the loader's epoch plan. Both rates
            # are PER-HOST (this process's shard), so graphs/s and nodes/s
            # stay mutually consistent under multi-host sharding.
            graphs_per_sec = nodes_per_sec = waste = None
            stats = None
            if hasattr(train_loader, "epoch_padding_stats"):
                try:
                    stats = train_loader.epoch_padding_stats()
                except Exception:
                    stats = None
            if stats is not None and stats[1]:
                waste = 1.0 - stats[0] / stats[1]
            if t_train:
                try:
                    n = len(train_loader.dataset)
                except TypeError:
                    n = 0
                shards = getattr(train_loader, "num_shards", 1) or 1
                if n:
                    graphs_per_sec = -(-n // shards) / t_train
                if stats is not None:
                    nodes_per_sec = stats[0] / t_train
            obs.epoch_complete(
                ep, train_loss, val_loss, test_loss, seconds=t_train,
                graphs_per_sec=graphs_per_sec, nodes_per_sec=nodes_per_sec,
                padding_waste=waste, mode=mode,
            )

    ran_fit = staged is not None and fit_chunk > 0
    if ran_fit:
        staged_val = (
            None if skip_valtest else trainer.stage_batches(list(val_loader))
        )
        staged_test = (
            None if skip_valtest else trainer.stage_batches(list(test_loader))
        )
        from hydragnn_tpu.parallel.distributed import check_remaining

        sched = None
        best_state = None
        # honor the best already ON DISK across a resume: without this a
        # worse post-resume epoch would overwrite the saved best weights
        best_saved = (
            float(ckpt.best)
            if ckpt is not None and ckpt.best is not None
            else np.inf
        )
        epoch0 = start_epoch
        if resume_meta and resume_meta.get("fit_sched") is not None:
            sched = _restore_fit_sched(resume_meta["fit_sched"])
            # best_state reseeds from the RESUME-POINT weights, which did
            # not achieve the restored best_val — restart best tracking so
            # those weights are never mislabeled as best
            sched = sched.replace(
                best_val=jnp.asarray(jnp.inf, jnp.float32)
            )
            if trainer.mesh is not None:
                sched = jax.tree_util.tree_map(jnp.asarray, sched)
        # full sample->batch reshuffle at chunk boundaries (the staged scan
        # only permutes batch ORDER within a chunk; this restores the
        # reference DistributedSampler's per-epoch sample shuffling at
        # chunk granularity, at the price of re-staging H2D per chunk)
        restage = _env_flag(
            "HYDRAGNN_RESTAGE_PER_CHUNK", training, "restage_per_chunk"
        )
        if guard is not None and guard.last_good is None:
            guard.commit(state)  # chunk-granular last-good seed
        while epoch0 < num_epoch:
            n = min(fit_chunk, num_epoch - epoch0)
            # chunk-granular epoch announcement: the fit path dispatches
            # whole chunks, so HYDRAGNN_PROFILE_AT_STEP resolves against
            # the chunk's starting epoch here
            obs.epoch_start(epoch0)
            elastic.note_epoch(epoch0)
            if restage and epoch0 > 0:
                train_loader.set_epoch(epoch0)
                # release the old stack FIRST — holding it through the
                # re-stage would double the training set's HBM footprint
                staged = None
                staged = trainer.stage_batches(list(train_loader))
            t0 = time.time()
            # pad_to keeps every chunk at the same scan length — the short
            # final chunk must not recompile the whole-training program
            state, best_state, sched, rng, series = trainer.fit_staged(
                state,
                staged,
                n,
                rng,
                staged_val=staged_val,
                staged_test=staged_test,
                sched=sched,
                best_state=best_state,
                pad_to=fit_chunk,
            )
            chunk_time = time.time() - t0
            obs.emit(
                "fit_chunk",
                epoch_start=int(epoch0),
                epochs=int(n),
                wall_time_s=round(chunk_time, 6),
            )
            # whole-chunk dispatches have no per-step hook: trace-capture
            # ticks (and env-armed profiling) advance per chunk here, and
            # a post-resize elastic run reports its recovery at the first
            # completed chunk (the fit path's "first optimizer step")
            obs.dispatch_boundary()
            elastic.note_step()
            for i in range(n):
                if np.isnan(series["train_loss"][i]):
                    continue
                # the chunk is ONE dispatch; chunk_time / n is the honest
                # per-epoch attribution (and the only one available — the
                # fit path used to report no train time or graphs/sec)
                _log_epoch(
                    epoch0 + i,
                    series["train_loss"][i],
                    series["val_loss"][i],
                    series["test_loss"][i],
                    series["train_tasks"][i],
                    t_train=chunk_time / n,
                    mode="fit",
                )
            if guard is not None:
                # chunk-granular divergence guard: trailing NaN rows with
                # early-stop NOT fired mean the chunk diverged (stop-skip
                # rows are NaN by design, so gate on `stopped`). Restore
                # last-good with halved LR and RETRY the chunk — bounded
                # by the guard's restore budget — and keep the poisoned
                # state out of the best/resume checkpoints below.
                last = series["train_loss"][n - 1]
                stopped_now = bool(np.asarray(sched.stopped))
                if not stopped_now and not np.isfinite(last):
                    print_distributed(
                        verbosity,
                        f"Chunk at epoch {epoch0}: non-finite loss — "
                        "restoring last-good state with halved LR",
                    )
                    state = guard.on_bad_epoch(state)
                    trainer.final_state_saved = False
                    continue
                guard.commit(state)
            # persist the best state after every chunk that improved it —
            # a preempted job resumes from the last improvement, like the
            # reference's per-epoch BestCheckpoint (utils/model.py:207-248)
            if ckpt is not None:
                bv = float(np.asarray(sched.best_val))
                if np.isfinite(bv) and bv < best_saved:
                    save_model(best_state, ckpt.name, ckpt.path)
                    best_saved = bv
                    # keep the host-side tracker in sync so the resume
                    # meta carries the on-disk best across a preemption
                    ckpt.best = bv
            epoch0 += n
            # resumable chunk-boundary checkpoint: weights + loop state,
            # so a preempted whole-training run resumes at this chunk
            if resume_every > 0:
                # the host scheduler/early objects never step on the fit
                # path — mirror the DEVICE state into them so the meta
                # stays truthful even if the resumed run lands on the
                # streaming path (e.g. fit_chunk removed from the config)
                scheduler.lr = float(get_learning_rate(state.opt_state))
                pb = float(np.asarray(sched.plateau_best))
                scheduler.best = pb if np.isfinite(pb) else None
                scheduler.num_bad_epochs = int(np.asarray(sched.plateau_bad))
                if early is not None:
                    eb = float(np.asarray(sched.early_best))
                    early.best = eb if np.isfinite(eb) else None
                    early.counter = int(np.asarray(sched.early_count))
                    early.early_stop = bool(np.asarray(sched.stopped))
                fit_meta = _build_train_meta(
                    epoch0 - 1, rng, scheduler, early, ckpt, guard,
                    sched=sched, stream=_stream_state(),
                    mesh=_mesh_meta(trainer),
                )
                save_model(
                    state, log_name, checkpoint_path,
                    train_meta=fit_meta, keep_last=keep_last,
                    writer=ckpt_writer,
                )
                trainer.final_train_meta = fit_meta
                trainer.final_state_saved = True
                _publish_candidate(
                    epoch0 - 1, val_loss=series["val_loss"][n - 1]
                )
            if bool(np.asarray(sched.stopped)):
                ep_stop = epoch0 - n + int(np.argmax(series["stopped"]))
                print_distributed(
                    verbosity, f"Early stopping at epoch {ep_stop}"
                )
                obs.emit("early_stop", epoch=int(ep_stop))
                break
            # the next unit of work is an indivisible fit_chunk-epoch
            # dispatch — reserve a whole chunk's wall time, not one epoch's
            if not check_remaining(chunk_time):
                print_distributed(
                    verbosity, "Stopping: not enough job wall-clock time left"
                )
                obs.emit("wallclock_stop", epoch=int(epoch0 - 1))
                break

    epoch_time = 0.0
    staged_evals = None
    if guard is not None and guard.last_good is None:
        # seed last-good with the starting state so a non-finite FIRST
        # epoch on the staged path is a bounded restore, not an unbounded
        # silent NaN run (the streaming path seeds inside train_epoch)
        guard.commit(state)
    host_epochs = range(start_epoch, num_epoch) if not ran_fit else range(0)
    for epoch in host_epochs:
        t0 = time.time()
        trainer.final_state_saved = False  # state is about to change
        # resets the telemetry step-in-epoch counter (the anchor for
        # HYDRAGNN_PROFILE_AT_STEP=<epoch>:<step> trace arming)
        obs.epoch_start(epoch)
        elastic.note_epoch(epoch)
        train_loader.set_epoch(epoch)
        if staged is not None:
            state, rng, train_loss, train_tasks = trainer.train_epoch_staged(
                state, staged, rng
            )
            # the staged epoch is one dispatch with no per-step hook: a
            # post-resize elastic run reports recovery here (the
            # streaming path reports from the trainer's step loop)
            elastic.note_step()
        else:
            state, rng, train_loss, train_tasks = trainer.train_epoch(
                state, train_loader, rng
            )
        t_train = time.time() - t0
        if skip_valtest:
            val_loss, val_tasks = train_loss, train_tasks
            test_loss, test_tasks = train_loss, train_tasks
        else:
            # the goodput ledger's eval span: val+test wall lands in the
            # `eval` category (compile time and data waits inside the
            # span stay in theirs)
            obs.eval_start()
            try:
                if staged is not None:
                    # device-resident epoch driver: evals run staged too
                    # (one dispatch + one readback per split, no per-batch
                    # H2D). Any staging/dispatch memory failure downgrades
                    # PERMANENTLY to the streaming evaluate — the eval
                    # sets have their own footprint on top of the staged
                    # training set.
                    if staged_evals is None:
                        try:
                            vb, tb = list(val_loader), list(test_loader)
                            if not vb or not tb:
                                raise ValueError("empty eval loader")
                            staged_evals = (
                                trainer.stage_batches(vb),
                                trainer.stage_batches(tb),
                            )
                        except Exception as e:
                            if isinstance(e, ValueError) or _is_oom(e):
                                staged_evals = False
                            else:
                                raise
                    if staged_evals:
                        try:
                            val_loss, val_tasks = trainer.evaluate_staged(
                                state, staged_evals[0]
                            )
                            test_loss, test_tasks = trainer.evaluate_staged(
                                state, staged_evals[1]
                            )
                        except Exception as e:
                            if _is_oom(e):
                                staged_evals = False
                            else:
                                raise
                    if not staged_evals:
                        val_loss, val_tasks = trainer.evaluate(
                            state, val_loader
                        )
                        test_loss, test_tasks = trainer.evaluate(
                            state, test_loader
                        )
                else:
                    val_loss, val_tasks = trainer.evaluate(state, val_loader)
                    test_loss, test_tasks = trainer.evaluate(
                        state, test_loader
                    )
            finally:
                obs.eval_complete()

        if guard is not None:
            if not (np.isfinite(train_loss) and np.isfinite(val_loss)):
                # the epoch-granular guard: staged/on-device epochs have no
                # per-step visibility, so a poisoned epoch restores
                # last-good with halved LR (bounded; guard raises past it)
                # and its metrics never reach the scheduler
                print_distributed(
                    verbosity,
                    f"Epoch {epoch:04d}: non-finite loss "
                    f"(train {train_loss}, val {val_loss}) — restoring "
                    "last-good state with halved LR",
                )
                state = guard.on_bad_epoch(state)
                continue
            guard.commit(state)
            # a guard restore halves the LR inside opt_state; resync the
            # host scheduler so its next step() cannot force the LR back
            # up to the pre-divergence value
            scheduler.lr = float(get_learning_rate(state.opt_state))

        new_lr = scheduler.step(val_loss)
        if abs(new_lr - get_learning_rate(state.opt_state)) > 1e-12:
            state = state.replace(
                opt_state=set_learning_rate(state.opt_state, new_lr)
            )

        _log_epoch(
            epoch, train_loss, val_loss, test_loss, train_tasks,
            t_train=t_train,
            mode="staged" if staged is not None else "stream",
        )

        if visualizer is not None and visualizer.plot_hist_solution:
            _, _, tv, pv = trainer.predict(state, test_loader)
            visualizer.plot_history(
                total_loss_train[: epoch + 1],
                total_loss_val[: epoch + 1],
                total_loss_test[: epoch + 1],
            )

        if ckpt is not None:
            ckpt(state, epoch, val_loss, save_model)
        stopping = early is not None and early(val_loss)
        if resume_every > 0 and (
            (epoch + 1) % resume_every == 0
            or stopping
            or epoch == num_epoch - 1
        ):
            meta = _build_train_meta(
                epoch, rng, scheduler, early, ckpt, guard,
                stream=_stream_state(), mesh=_mesh_meta(trainer),
            )
            save_model(
                state, log_name, checkpoint_path,
                train_meta=meta, keep_last=keep_last,
                writer=ckpt_writer,
            )
            # the driver's final save reuses this so a COMPLETED run's
            # checkpoint still carries loop state (continue = no-op resume)
            trainer.final_train_meta = meta
            trainer.final_state_saved = True
            _publish_candidate(epoch, val_loss=val_loss)
        if stopping:
            print_distributed(verbosity, f"Early stopping at epoch {epoch}")
            obs.emit("early_stop", epoch=int(epoch))
            break

        epoch_time = time.time() - t0
        from hydragnn_tpu.parallel.distributed import check_remaining

        if not check_remaining(epoch_time):
            # wall-clock preemption is exactly when a resumable checkpoint
            # matters — save one even off the resume_every cadence
            if resume_every > 0 and not trainer.final_state_saved:
                meta = _build_train_meta(
                    epoch, rng, scheduler, early, ckpt, guard,
                    stream=_stream_state(), mesh=_mesh_meta(trainer),
                )
                save_model(
                    state, log_name, checkpoint_path,
                    train_meta=meta, keep_last=keep_last,
                    writer=ckpt_writer,
                )
                trainer.final_train_meta = meta
                trainer.final_state_saved = True
                _publish_candidate(epoch, val_loss=val_loss)
            print_distributed(
                verbosity, "Stopping: not enough job wall-clock time left"
            )
            obs.emit("wallclock_stop", epoch=int(epoch))
            break

    # async-checkpoint barrier: train_validate_test returning means every
    # save it initiated is durable on disk (fsync'd + renamed) — callers
    # (the driver's final save, a restarting supervisor) rely on that
    drain_async()

    if visualizer is not None:
        _, _, true_values, predicted_values = trainer.predict(state, test_loader)
        visualizer.plot_history(
            total_loss_train,
            total_loss_val,
            total_loss_test,
            task_loss_train=task_loss_train,
            task_weights=task_weights,
            task_names=task_names,
        )
        visualizer.create_plot_global(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
        visualizer.create_scatter_plots(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
    return state
