"""The epoch driver (``train_validate_test.py:54-250`` analog).

Split out of ``trainer.py`` (round-3 verdict item 10). Orchestrates the
``Trainer``'s execution modes — streaming per-batch, HBM-staged epochs,
whole-training ``fit_staged`` chunks — plus the host-side per-epoch work:
plateau LR (host path), early stopping, best-checkpoint persistence,
TensorBoard scalars, SLURM wall-clock guard, visualizer hooks.
"""

import os
import time

import jax
import numpy as np

from hydragnn_tpu.train.checkpoint import save_model
from hydragnn_tpu.train.common import TrainState, _env_flag, _is_oom
from hydragnn_tpu.train.optimizer import (
    get_learning_rate,
    set_learning_rate,
)
from hydragnn_tpu.train.scheduler import (
    BestCheckpoint,
    EarlyStopping,
    ReduceLROnPlateau,
)
from hydragnn_tpu.utils.print_utils import print_distributed


def train_validate_test(
    trainer,
    state: TrainState,
    train_loader,
    val_loader,
    test_loader,
    config_nn: dict,
    log_name: str,
    verbosity: int = 0,
    writer=None,
    create_plots: bool = False,
    plot_init_solution: bool = False,
):
    """Epoch driver (``train_validate_test.py:54-250``)."""
    training = config_nn["Training"]
    num_epoch = training["num_epoch"]
    early = EarlyStopping(training.get("patience", 5)) if training.get(
        "EarlyStopping", False
    ) else None
    ckpt = (
        BestCheckpoint(log_name, warmup=training.get("checkpoint_warmup", 10))
        if training.get("Checkpoint", False)
        else None
    )
    scheduler = ReduceLROnPlateau(lr=get_learning_rate(state.opt_state))
    rng = jax.random.PRNGKey(1337)

    visualizer = None
    if create_plots:
        from hydragnn_tpu.postprocess.visualizer import Visualizer

        node_feature = []
        nodes_num_list = []
        for d in test_loader.dataset:
            node_feature.extend(np.asarray(d.x).tolist())
            nodes_num_list.append(d.num_nodes)
        visualizer = Visualizer(
            log_name,
            node_feature=node_feature,
            num_heads=trainer.model.num_heads,
            head_dims=list(trainer.model.output_dim),
            num_nodes_list=nodes_num_list,
        )
        visualizer.num_nodes_plot()
        if plot_init_solution:
            _, _, true_values, predicted_values = trainer.predict(
                state, test_loader
            )
            visualizer.create_scatter_plots(
                true_values,
                predicted_values,
                output_names=config_nn["Variables_of_interest"].get(
                    "output_names"
                ),
                iepoch=-1,
            )

    total_loss_train = np.zeros(num_epoch)
    total_loss_val = np.zeros(num_epoch)
    total_loss_test = np.zeros(num_epoch)
    num_tasks = trainer.model.num_heads
    task_loss_train = np.zeros((num_epoch, num_tasks))
    task_weights = list(getattr(trainer.model, "loss_weights", []) or [])
    task_names = config_nn["Variables_of_interest"].get("output_names")
    skip_valtest = int(os.getenv("HYDRAGNN_VALTEST", "1")) == 0

    # device-resident mode: stage the (collated) training set in HBM once;
    # every epoch is then a single scan dispatch with no H2D traffic
    staged = None
    if _env_flag("HYDRAGNN_DEVICE_RESIDENT", training, "device_resident_dataset"):
        try:
            staged = trainer.stage_batches(list(train_loader))
        except ValueError:
            # bucketed layouts emit mixed batch shapes, which cannot stack
            # into one HBM-resident scan — train on the streaming path
            print_distributed(
                verbosity,
                "device_resident_dataset: batches are not shape-uniform "
                "(bucketed layout?) — falling back to streaming",
            )
            staged = None

    # whole-training dispatch: fit_chunk_epochs > 0 runs training in chunks
    # of N epochs, each chunk ONE XLA program (on-device plateau LR, early
    # stop, best-state tracking); host work between chunks only — logging,
    # TensorBoard, checkpoint, SLURM wall-clock guard
    fit_chunk = int(
        os.getenv(
            "HYDRAGNN_FIT_CHUNK", str(training.get("fit_chunk_epochs", 0))
        )
    )

    def _log_epoch(ep, train_loss, val_loss, test_loss, train_tasks,
                   t_train=None):
        total_loss_train[ep] = train_loss
        total_loss_val[ep] = val_loss
        total_loss_test[ep] = test_loss
        tt = np.atleast_1d(np.asarray(train_tasks))
        task_loss_train[ep, : min(len(tt), num_tasks)] = tt[:num_tasks]
        timing = ""
        if t_train:
            try:
                n = len(train_loader.dataset)
            except TypeError:
                n = 0
            gps = f", {n / t_train:.0f} graphs/sec" if n else ""
            timing = f", Train Time: {t_train:.2f}s{gps}"
        print_distributed(
            verbosity,
            f"Epoch: {ep:04d}, Train Loss: {train_loss:.8f}, "
            f"Val Loss: {val_loss:.8f}, Test Loss: {test_loss:.8f}"
            f"{timing}",
        )
        if writer is not None:
            writer.add_scalar("train error", train_loss, ep)
            writer.add_scalar("validate error", val_loss, ep)
            writer.add_scalar("test error", test_loss, ep)
            for itask, tl in enumerate(np.atleast_1d(train_tasks)):
                writer.add_scalar(f"train error of task {itask}", float(tl), ep)

    ran_fit = staged is not None and fit_chunk > 0
    if ran_fit:
        staged_val = (
            None if skip_valtest else trainer.stage_batches(list(val_loader))
        )
        staged_test = (
            None if skip_valtest else trainer.stage_batches(list(test_loader))
        )
        from hydragnn_tpu.parallel.distributed import check_remaining

        sched = None
        best_state = None
        best_saved = np.inf
        epoch0 = 0
        # full sample->batch reshuffle at chunk boundaries (the staged scan
        # only permutes batch ORDER within a chunk; this restores the
        # reference DistributedSampler's per-epoch sample shuffling at
        # chunk granularity, at the price of re-staging H2D per chunk)
        restage = _env_flag(
            "HYDRAGNN_RESTAGE_PER_CHUNK", training, "restage_per_chunk"
        )
        while epoch0 < num_epoch:
            n = min(fit_chunk, num_epoch - epoch0)
            if restage and epoch0 > 0:
                train_loader.set_epoch(epoch0)
                # release the old stack FIRST — holding it through the
                # re-stage would double the training set's HBM footprint
                staged = None
                staged = trainer.stage_batches(list(train_loader))
            t0 = time.time()
            # pad_to keeps every chunk at the same scan length — the short
            # final chunk must not recompile the whole-training program
            state, best_state, sched, rng, series = trainer.fit_staged(
                state,
                staged,
                n,
                rng,
                staged_val=staged_val,
                staged_test=staged_test,
                sched=sched,
                best_state=best_state,
                pad_to=fit_chunk,
            )
            chunk_time = time.time() - t0
            for i in range(n):
                if np.isnan(series["train_loss"][i]):
                    continue
                _log_epoch(
                    epoch0 + i,
                    series["train_loss"][i],
                    series["val_loss"][i],
                    series["test_loss"][i],
                    series["train_tasks"][i],
                )
            # persist the best state after every chunk that improved it —
            # a preempted job resumes from the last improvement, like the
            # reference's per-epoch BestCheckpoint (utils/model.py:207-248)
            if ckpt is not None:
                bv = float(np.asarray(sched.best_val))
                if np.isfinite(bv) and bv < best_saved:
                    save_model(best_state, log_name, ckpt.path)
                    best_saved = bv
            epoch0 += n
            if bool(np.asarray(sched.stopped)):
                ep_stop = epoch0 - n + int(np.argmax(series["stopped"]))
                print_distributed(
                    verbosity, f"Early stopping at epoch {ep_stop}"
                )
                break
            # the next unit of work is an indivisible fit_chunk-epoch
            # dispatch — reserve a whole chunk's wall time, not one epoch's
            if not check_remaining(chunk_time):
                print_distributed(
                    verbosity, "Stopping: not enough job wall-clock time left"
                )
                break

    epoch_time = 0.0
    staged_evals = None
    for epoch in range(num_epoch if not ran_fit else 0):
        t0 = time.time()
        train_loader.set_epoch(epoch)
        if staged is not None:
            state, rng, train_loss, train_tasks = trainer.train_epoch_staged(
                state, staged, rng
            )
        else:
            state, rng, train_loss, train_tasks = trainer.train_epoch(
                state, train_loader, rng
            )
        t_train = time.time() - t0
        if skip_valtest:
            val_loss, val_tasks = train_loss, train_tasks
            test_loss, test_tasks = train_loss, train_tasks
        elif staged is not None:
            # device-resident epoch driver: evals run staged too (one
            # dispatch + one readback per split, no per-batch H2D). Any
            # staging/dispatch memory failure downgrades PERMANENTLY to the
            # streaming evaluate — the eval sets have their own footprint
            # on top of the staged training set.
            if staged_evals is None:
                try:
                    vb, tb = list(val_loader), list(test_loader)
                    if not vb or not tb:
                        raise ValueError("empty eval loader")
                    staged_evals = (
                        trainer.stage_batches(vb),
                        trainer.stage_batches(tb),
                    )
                except Exception as e:
                    if isinstance(e, ValueError) or _is_oom(e):
                        staged_evals = False
                    else:
                        raise
            if staged_evals:
                try:
                    val_loss, val_tasks = trainer.evaluate_staged(
                        state, staged_evals[0]
                    )
                    test_loss, test_tasks = trainer.evaluate_staged(
                        state, staged_evals[1]
                    )
                except Exception as e:
                    if _is_oom(e):
                        staged_evals = False
                    else:
                        raise
            if not staged_evals:
                val_loss, val_tasks = trainer.evaluate(state, val_loader)
                test_loss, test_tasks = trainer.evaluate(state, test_loader)
        else:
            val_loss, val_tasks = trainer.evaluate(state, val_loader)
            test_loss, test_tasks = trainer.evaluate(state, test_loader)

        new_lr = scheduler.step(val_loss)
        if abs(new_lr - get_learning_rate(state.opt_state)) > 1e-12:
            state = state.replace(
                opt_state=set_learning_rate(state.opt_state, new_lr)
            )

        _log_epoch(
            epoch, train_loss, val_loss, test_loss, train_tasks,
            t_train=t_train,
        )

        if visualizer is not None and visualizer.plot_hist_solution:
            _, _, tv, pv = trainer.predict(state, test_loader)
            visualizer.plot_history(
                total_loss_train[: epoch + 1],
                total_loss_val[: epoch + 1],
                total_loss_test[: epoch + 1],
            )

        if ckpt is not None:
            ckpt(state, epoch, val_loss, save_model)
        if early is not None and early(val_loss):
            print_distributed(verbosity, f"Early stopping at epoch {epoch}")
            break

        epoch_time = time.time() - t0
        from hydragnn_tpu.parallel.distributed import check_remaining

        if not check_remaining(epoch_time):
            print_distributed(
                verbosity, "Stopping: not enough job wall-clock time left"
            )
            break

    if visualizer is not None:
        _, _, true_values, predicted_values = trainer.predict(state, test_loader)
        visualizer.plot_history(
            total_loss_train,
            total_loss_val,
            total_loss_test,
            task_loss_train=task_loss_train,
            task_weights=task_weights,
            task_names=task_names,
        )
        visualizer.create_plot_global(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
        visualizer.create_scatter_plots(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
    return state
