"""Jitted training-program builder.

Split out of ``trainer.py`` (round-3 verdict item 10): everything that
gets traced/compiled lives here — the fused train step (forward + masked
multi-task loss + backward + optimizer + BN stats in ONE XLA program,
replacing the reference's per-op hot loop
``train_validate_test.py:437-540``), the multi-step scan, the staged
epoch scan, the whole-training ``fit_scan`` with on-device plateau-LR /
early-stop / best-state tracking, and the eval/predict scans.

:func:`build_steps` returns a :class:`CompiledSteps` namespace; the
``Trainer`` stores it and exposes the same ``_train_step`` etc.
attributes it always had.
"""

import os

import jax
import jax.numpy as jnp
import optax

from hydragnn_tpu.obs.introspect import instrument
from hydragnn_tpu.train.common import SchedState
from hydragnn_tpu.train.transfer import _decompact_traced


class CompiledSteps:
    """Plain namespace of the jitted programs for one (model, tx) pair."""

    __slots__ = (
        "train_step",
        "train_multi",
        "epoch_scan",
        "eval_epoch",
        "predict_scan",
        "fit_scan",
        "eval_step",
        "eval_multi",
    )


def _sharding_plan(mesh, state_shardings):
    """Explicit in/out shardings for every compiled program on a mesh.

    The programs used to ASSUME replicated params (no shardings: XLA
    inherited whatever placement the committed inputs carried). On the
    2-D mesh that assumption is wrong — params split over ``model`` per
    the rule engine — so every program declares its contract: state at
    the rule-engine placement, batches sharded over ``data`` (leading
    axis; the scan axis of stacked data stays unsharded), scalars/rngs/
    metrics replicated. Donated buffers keep identical in/out shardings,
    so donation survives the declarations (the jaxlint missing-donate
    gate stays clean by construction)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.parallel.mesh import DATA_AXIS

    rep = NamedSharding(mesh, P())
    batch = NamedSharding(mesh, P(DATA_AXIS))
    stacked = NamedSharding(mesh, P(None, DATA_AXIS))
    st = state_shardings
    return {
        "train_step": dict(
            in_shardings=(st, batch, rep), out_shardings=(st, rep)
        ),
        "train_multi": dict(
            in_shardings=(st, stacked, rep), out_shardings=(st, rep)
        ),
        "epoch_scan": dict(
            in_shardings=(st, stacked, rep, rep), out_shardings=(st, rep)
        ),
        "eval_epoch": dict(
            in_shardings=(st.params, st.batch_stats, stacked),
            out_shardings=rep,
        ),
        "predict_scan": dict(
            in_shardings=(st.params, st.batch_stats, stacked),
            out_shardings=rep,
        ),
        "fit_scan": dict(
            in_shardings=(
                st, st, rep, stacked, stacked, stacked, rep, rep, rep
            ),
            out_shardings=(st, st, rep, rep),
        ),
        "eval_step": dict(
            in_shardings=(st.params, st.batch_stats, batch),
            out_shardings=rep,
        ),
        "eval_multi": dict(
            in_shardings=(st.params, st.batch_stats, stacked),
            out_shardings=rep,
        ),
    }


def build_steps(
    model, tx, training_config: dict, mesh=None, state_shardings=None
) -> CompiledSteps:
    # mixed precision (no reference counterpart — HydraGNN trains pure
    # f32): master params stay f32 for the optimizer; forward/backward
    # runs in bfloat16. Positions stay f32 (geometry — distances/angles
    # — is precision-critical), BatchNorm statistics and loss reductions
    # are forced to f32 in models/common.py, and segment scatters upcast
    # to f32 (graph/segment.py). The QM9-scale step is scatter/
    # op-latency-bound, not matmul-bound, so bf16 buys little there;
    # expect wins on matmul-bound configurations (wide hidden dims,
    # dense-mode batches). Enablement is the param-precision policy in
    # models/create.py (HYDRAGNN_MIXED_PRECISION env > explicit bool >
    # "auto" per-model width table); accuracy-validated
    # (tests/test_mixed_precision.py) — measure with a true completion
    # fence before enabling (see BASELINE.md measurement note).
    from hydragnn_tpu.models.create import resolve_precision

    precision = resolve_precision(model, training_config)
    mixed = precision["mixed"]
    # the goodput/MFU ledger judges achieved FLOPs against the precision-
    # matched peak (bf16 vs f32 column of obs/ledger.PEAK_FLOPS)
    from hydragnn_tpu.obs import ledger as _ledger

    _ledger.note_precision(mixed, source=precision["source"])
    # divergence guard (train/guard.py): when on, every train step also
    # reports a device-computed "finite" scalar — loss AND all gradient
    # leaves finite — so the host can skip a poisoned update without
    # reading back whole tensors. Compiled in only when enabled: the
    # reduction over every gradient leaf is not free.
    from hydragnn_tpu.train.guard import guard_enabled

    guarded = guard_enabled(training_config)

    def _cast_bf16(tree):
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16)
            if hasattr(a, "dtype") and a.dtype == jnp.float32
            else a,
            tree,
        )

    def train_step(state, batch, rng):
        batch = _decompact_traced(batch)
        if mixed:
            batch = batch.replace(
                x=batch.x.astype(jnp.bfloat16),
                edge_attr=None
                if batch.edge_attr is None
                else batch.edge_attr.astype(jnp.bfloat16),
            )

        def loss_fn(params):
            if mixed:
                params = _cast_bf16(params)
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                outputs, mut = model.apply(
                    variables,
                    batch,
                    train=True,
                    mutable=["batch_stats"],
                    rngs={"dropout": rng},
                )
                new_bs = mut["batch_stats"]
            else:
                outputs = model.apply(
                    variables, batch, train=True, rngs={"dropout": rng}
                )
                new_bs = state.batch_stats
            tot, tasks = model.loss(outputs, batch)
            return tot, (tuple(tasks), new_bs)

        (loss, (tasks, new_bs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            params=new_params,
            batch_stats=new_bs,
            opt_state=new_opt,
            step=state.step + 1,
        )
        metrics = {
            "loss": loss,
            "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
            "num_graphs": batch.graph_mask.sum(),
        }
        if guarded:
            metrics["finite"] = jax.tree_util.tree_reduce(
                lambda ok, g: ok & jnp.isfinite(g).all(),
                grads,
                jnp.isfinite(loss),
            )
        return new_state, metrics

    def eval_step(params, batch_stats, batch):
        batch = _decompact_traced(batch)
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        outputs = model.apply(variables, batch, train=False)
        tot, tasks = model.loss(outputs, batch)
        return {
            "loss": tot,
            "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
            "num_graphs": batch.graph_mask.sum(),
            "outputs": outputs,
        }

    def _microbatch(data, idx):
        """Gather microbatch ``idx`` out of an HBM-staged stack."""
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, idx, keepdims=False),
            data,
        )

    def epoch_scan(state, data, perm, rngs):
        """A whole epoch in ONE XLA program over an HBM-staged dataset.

        ``data`` is a ``stack_batches`` result living in device memory
        (see ``Trainer.stage_batches``); ``perm`` reorders the microbatches
        each epoch. Each scan step gathers one microbatch out of HBM and
        runs the fused train step — zero host round-trips inside the
        epoch. This is the TPU answer to datasets that fit in HBM
        (QM9-scale and below): stage once, then epochs are pure compute."""

        def body(s, inp):
            idx, r = inp
            return train_step(s, _microbatch(data, idx), r)

        return jax.lax.scan(body, state, (perm, rngs))

    sch_cfg = training_config.get("scheduler", {})
    plateau_factor = float(sch_cfg.get("factor", 0.5))
    plateau_patience = int(sch_cfg.get("patience", 5))
    plateau_threshold = float(sch_cfg.get("threshold", 1e-4))
    plateau_min_lr = float(sch_cfg.get("min_lr", 1e-5))
    early_enabled = bool(training_config.get("EarlyStopping", False))
    early_patience = int(training_config.get("patience", 5))
    # best-state tracking starts after this many epochs (the reference
    # BestCheckpoint warmup, ``utils/model.py:207-248``; default 10 when
    # checkpointing is on, else track from the start)
    best_warmup = int(
        training_config.get(
            "checkpoint_warmup",
            10 if training_config.get("Checkpoint", False) else 0,
        )
    )

    def eval_multi(params, batch_stats, data, nb=None):
        """Scan ``eval_step`` over a stacked batch: metrics stacked per
        microbatch ([K]/[K, T] — `_acc_add(multi=True)` format). The eval
        counterpart of ``multi_train_step``: streaming validation/test
        was still paying one dispatch RPC per batch after training
        learned to stack (at-scale QM9, evals cost as much wall as the
        whole stacked train epoch). The ONE scan-eval implementation —
        ``eval_epoch`` is a reduction over it."""

        def body(_, idx):
            m = eval_step(params, batch_stats, _microbatch(data, idx))
            return _, (m["loss"], m["tasks"], m["num_graphs"])

        if nb is None:
            nb = jax.tree_util.tree_leaves(data)[0].shape[0]
        _, (loss, tasks, g) = jax.lax.scan(body, None, jnp.arange(nb))
        return {"loss": loss, "tasks": tasks, "num_graphs": g}

    def eval_epoch(params, batch_stats, data):
        """Mean loss/tasks over a staged (stacked) eval set, no outputs.
        Honors ``HYDRAGNN_MAX_NUM_BATCH`` like every other eval path."""
        nb = jax.tree_util.tree_leaves(data)[0].shape[0]
        cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
        if cap is not None:
            nb = min(nb, int(cap))
        m = eval_multi(params, batch_stats, data, nb=nb)
        g = m["num_graphs"].astype(jnp.float32)
        denom = jnp.maximum(g.sum(), 1.0)
        return (
            (m["loss"] * g).sum() / denom,
            (m["tasks"] * g[:, None]).sum(0) / denom,
        )

    num_tasks = len(model.output_type)

    def fit_scan(
        state, best_state, sched, train_data, val_data, test_data,
        perms, rngs, active,
    ):
        """Whole-training dispatch: scan over epochs, each epoch a scan
        over HBM-staged microbatches; plateau LR, early stopping and
        best-state tracking run on device (``SchedState``). One D2H
        readback per CALL, not per epoch — on hosts where readback
        latency is milliseconds that's cosmetic, on tunneled dev chips
        it's the difference between launch-bound and compute-bound.

        ``val_data``/``test_data`` may be the train set (the reference's
        ``HYDRAGNN_VALTEST=0`` semantics are handled by the caller).
        Epochs after the early stop fire — and epochs whose ``active``
        flag is False (scan-length padding so every chunk reuses one
        compiled program) — are skipped via ``lax.cond`` (their metric
        slots return NaN)."""

        def epoch_body(carry, inp):
            state, best_state, sched = carry
            perm, erngs, act = inp

            def run(args):
                state, best_state, sched = args
                state, m = epoch_scan(state, train_data, perm, erngs)
                g = m["num_graphs"].astype(jnp.float32)
                denom = jnp.maximum(g.sum(), 1.0)
                train_loss = (m["loss"] * g).sum() / denom
                train_tasks = (m["tasks"] * g[:, None]).sum(0) / denom
                # None val/test = the reference's HYDRAGNN_VALTEST=0
                # semantics: reuse the train loss, skip the eval pass
                if val_data is None:
                    val_loss = train_loss
                else:
                    val_loss, _ = eval_epoch(
                        state.params, state.batch_stats, val_data
                    )
                if test_data is None:
                    test_loss = val_loss
                else:
                    test_loss, _ = eval_epoch(
                        state.params, state.batch_stats, test_data
                    )
                # ---- ReduceLROnPlateau (scheduler.py semantics)
                is_better = val_loss < sched.plateau_best * (
                    1.0 - plateau_threshold
                )
                pbest = jnp.where(is_better, val_loss, sched.plateau_best)
                pbad = jnp.where(is_better, 0, sched.plateau_bad + 1)
                hp = state.opt_state.hyperparams
                lr = hp["learning_rate"]
                drop = pbad > plateau_patience
                new_lr = jnp.where(
                    drop,
                    jnp.maximum(lr * plateau_factor, plateau_min_lr),
                    lr,
                )
                pbad = jnp.where(drop, 0, pbad)
                opt_state = state.opt_state._replace(
                    hyperparams={**hp, "learning_rate": new_lr}
                )
                state = state.replace(opt_state=opt_state)
                # ---- EarlyStopping (utils/model.py:189-204 semantics)
                e_better = val_loss < sched.early_best
                e_best = jnp.where(e_better, val_loss, sched.early_best)
                e_count = jnp.where(e_better, 0, sched.early_count + 1)
                stopped = (
                    (e_count >= early_patience)
                    if early_enabled
                    else jnp.zeros((), bool)
                )
                # ---- best-state snapshot (Checkpoint-on-best analog,
                # warmup-gated like utils/model.py:207-248)
                improved = (val_loss < sched.best_val) & (
                    sched.epoch >= best_warmup
                )
                new_best_val = jnp.where(improved, val_loss, sched.best_val)
                best_state = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(improved, new, old),
                    state,
                    best_state,
                )
                sched = SchedState(
                    plateau_best=pbest,
                    plateau_bad=pbad,
                    early_best=e_best,
                    early_count=e_count,
                    stopped=stopped,
                    epoch=sched.epoch + 1,
                    best_val=new_best_val,
                )
                # one packed row per epoch so the whole series is ONE
                # D2H array: [train, val, test, lr, stopped, tasks...]
                row = jnp.concatenate(
                    [
                        jnp.stack(
                            [train_loss, val_loss, test_loss,
                             new_lr.astype(jnp.float32),
                             stopped.astype(jnp.float32)]
                        ),
                        train_tasks.astype(jnp.float32),
                    ]
                )
                return (state, best_state, sched), row

            def skip(args):
                state, best_state, sched = args
                nan = jnp.asarray(jnp.nan, jnp.float32)
                lr = state.opt_state.hyperparams["learning_rate"]
                row = jnp.concatenate(
                    [
                        jnp.stack(
                            [nan, nan, nan, lr.astype(jnp.float32),
                             sched.stopped.astype(jnp.float32)]
                        ),
                        jnp.full((num_tasks,), jnp.nan, jnp.float32),
                    ]
                )
                return (state, best_state, sched), row

            return jax.lax.cond(
                jnp.logical_or(sched.stopped, jnp.logical_not(act)),
                skip,
                run,
                (state, best_state, sched),
            )

        (state, best_state, sched), series = jax.lax.scan(
            epoch_body, (state, best_state, sched), (perms, rngs, active)
        )
        return state, best_state, sched, series

    def multi_train_step(state, batches, rngs):
        """K optimizer steps in ONE XLA program (``lax.scan`` over a
        stacked batch). Amortizes dispatch latency: at QM9 scale a single
        step's device time is well under the host's per-dispatch cost, so
        the eager-style loop is launch-bound (measured ~2.3 ms/step wall
        vs ~0.6 ms device on v5e). Metrics come back stacked ``[K, ...]``
        so epoch accumulation stays exact."""

        def body(s, inp):
            b, r = inp
            return train_step(s, b, r)

        return jax.lax.scan(body, state, (batches, rngs))

    def predict_scan(params, batch_stats, data):
        """Full-set prediction in one program: stacked per-microbatch
        (loss, tasks, num_graphs, outputs) — callers do ONE readback."""

        def body(_, idx):
            m = eval_step(params, batch_stats, _microbatch(data, idx))
            return _, (
                m["loss"], m["tasks"], m["num_graphs"], m["outputs"]
            )

        nb = jax.tree_util.tree_leaves(data)[0].shape[0]
        return jax.lax.scan(body, None, jnp.arange(nb))[1]

    # every hot-path program is wrapped for XLA introspection
    # (obs/introspect.py): when telemetry is live, each novel compiled
    # shape signature has its cost_analysis()/memory_analysis() captured
    # once as a `compile` event + per-bucket gauges; otherwise the
    # wrappers are pure passthroughs (.lower() etc. still forward, so
    # benchmarks and the recompile sentinel see the jit they always saw)
    plan = (
        _sharding_plan(mesh, state_shardings)
        if mesh is not None and state_shardings is not None
        else {}
    )

    def _jit(name, fn, **kwargs):
        return instrument(name, jax.jit(fn, **plan.get(name, {}), **kwargs))

    steps = CompiledSteps()
    steps.train_step = _jit("train_step", train_step, donate_argnums=(0,))
    steps.train_multi = _jit(
        "train_multi", multi_train_step, donate_argnums=(0,)
    )
    # opt-in NaN sentinel (the numlint suite's runtime half): wrap the
    # per-step train programs so a diverged step fails IMMEDIATELY with
    # the first non-finite head/param subtree named, instead of epochs
    # later as a NaN loss curve. Opt-in because localization reads the
    # outputs back per step — a debug harness, not a production default
    from hydragnn_tpu.utils.envparse import env_int

    if env_int("HYDRAGNN_NAN_SENTINEL", 0):
        from hydragnn_tpu.analysis.guards import nan_sentinel

        steps.train_step = nan_sentinel(
            steps.train_step, scope="train_step"
        )
        steps.train_multi = nan_sentinel(
            steps.train_multi, scope="train_multi"
        )
    steps.epoch_scan = _jit("epoch_scan", epoch_scan, donate_argnums=(0,))
    steps.eval_epoch = _jit("eval_epoch", eval_epoch)
    steps.predict_scan = _jit("predict_scan", predict_scan)
    # donate state + sched; best_state is NOT donated (its initial value
    # may alias state's buffers)
    steps.fit_scan = _jit("fit_scan", fit_scan, donate_argnums=(0, 2))
    steps.eval_step = _jit("eval_step", eval_step)
    steps.eval_multi = _jit("eval_multi", eval_multi)
    return steps
