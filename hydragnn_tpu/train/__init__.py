from hydragnn_tpu.train.trainer import TrainState, Trainer, train_validate_test
from hydragnn_tpu.train.optimizer import (
    select_optimizer,
    get_learning_rate,
    set_learning_rate,
)
from hydragnn_tpu.train.scheduler import (
    BestCheckpoint,
    EarlyStopping,
    ReduceLROnPlateau,
)
from hydragnn_tpu.train.checkpoint import (
    checkpoint_exists,
    load_state_dict,
    restore_into,
    save_model,
)
from hydragnn_tpu.train.partitioned import (
    PartitionedLoader,
    PartitionedTrainer,
    scan_budgets,
)
