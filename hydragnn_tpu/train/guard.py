"""Divergence guard: survive NaN/Inf steps instead of training on garbage.

One bad batch (exploding gradients, a corrupt sample, an fp overflow) makes
the loss or gradients non-finite; the optimizer update then poisons every
parameter and the remaining epochs train on NaNs. The reference framework
survives this only by operator vigilance; here it is mechanical:

- every guarded optimizer step reports a device-computed ``finite`` scalar
  (loss AND gradients all finite — wired in ``steps.py`` when
  ``Training.divergence_guard`` is on);
- a non-finite step is SKIPPED: the pre-step state snapshot is restored,
  so the poisoned update never lands;
- after ``max_bad_steps`` consecutive bad steps the guard restores the
  last-good state (committed at each finite epoch boundary) with the
  learning rate halved — the standard divergence response;
- restores are bounded (``max_restores``); past the bound the guard fails
  loudly with the full history instead of looping forever.

Costs when enabled: one snapshot copy + one scalar device fetch per step
(serializes dispatch), and ``steps_per_dispatch`` is forced to 1 so a bad
step can be isolated. Off by default for exactly that reason; enable with
``Training.divergence_guard: true`` or ``HYDRAGNN_DIVERGENCE_GUARD=1``.
"""

import os

import jax
import jax.numpy as jnp

from hydragnn_tpu.obs import runtime as obs
from hydragnn_tpu.train.optimizer import get_learning_rate, set_learning_rate


def guard_enabled(training_config: dict) -> bool:
    from hydragnn_tpu.train.common import _env_flag

    return _env_flag(
        "HYDRAGNN_DIVERGENCE_GUARD", training_config, "divergence_guard"
    )


class DivergenceGuard:
    """Host-side guard state for the streaming training loop.

    Knobs (env over config, the framework convention):
    - ``max_bad_steps`` / ``HYDRAGNN_GUARD_MAX_BAD_STEPS`` (default 3):
      consecutive non-finite steps tolerated (each skipped) before a
      last-good restore.
    - ``max_restores`` / ``HYDRAGNN_GUARD_MAX_RESTORES`` (default 2):
      restores allowed before failing loudly.
    """

    def __init__(self, training_config: dict):
        self.max_bad_steps = int(
            os.getenv(
                "HYDRAGNN_GUARD_MAX_BAD_STEPS",
                str(training_config.get("guard_max_bad_steps", 3)),
            )
        )
        self.max_restores = int(
            os.getenv(
                "HYDRAGNN_GUARD_MAX_RESTORES",
                str(training_config.get("guard_max_restores", 2)),
            )
        )
        self.lr_factor = float(training_config.get("guard_lr_factor", 0.5))
        self.bad_streak = 0
        self.skipped = 0
        self.restores = 0
        self.last_good = None
        # one jitted whole-tree copy: the train step DONATES its input
        # state, so both the per-step snapshot and the last-good state
        # need their own buffers; eager per-leaf copies would cost a
        # dispatch per leaf on high-latency backends
        self._copy = jax.jit(
            lambda t: jax.tree_util.tree_map(jnp.copy, t)
        )

    def snapshot(self, state):
        """Pre-step copy — the thing restored when THIS step goes bad."""
        return self._copy(state)

    def commit(self, state):
        """Mark ``state`` as last-good (call at finite epoch boundaries).
        Resets the bad streak: surviving an epoch means the earlier bad
        steps were transient, not a divergence."""
        self.last_good = self._copy(state)
        self.bad_streak = 0

    def on_bad_step(self, prev_state):
        """A step came back non-finite. Returns the state training must
        continue from: the pre-step snapshot (skip semantics) or, after
        ``max_bad_steps`` consecutive bad steps, the last-good state with
        the LR halved. Raises ``RuntimeError`` past the restore bound."""
        self.bad_streak += 1
        self.skipped += 1
        obs.guard_skip("step", self.skipped, streak=self.bad_streak)
        if self.bad_streak < self.max_bad_steps or self.last_good is None:
            return prev_state
        return self._restore()

    def on_bad_epoch(self, fallback_state):
        """Epoch-granular guard for staged/on-device paths (no per-step
        visibility there): a non-finite epoch loss restores last-good with
        halved LR. With nothing committed yet ``fallback_state`` is kept,
        but still COUNTS against the restore bound — an unbounded silent
        NaN run must be impossible regardless of call order."""
        self.skipped += 1
        obs.guard_skip("epoch", self.skipped)
        if self.last_good is None:
            self.restores += 1
            if self.restores > self.max_restores:
                raise RuntimeError(
                    "divergence guard: training produced non-finite "
                    f"losses for {self.restores} epochs with no finite "
                    "epoch ever committed — the run is broken from the "
                    "start; inspect the data/LR"
                )
            from hydragnn_tpu.train import elastic

            elastic.note_guard_restore()
            return fallback_state
        return self._restore()

    def _restore(self):
        import time

        self.restores += 1
        if self.restores > self.max_restores:
            raise RuntimeError(
                f"divergence guard: {self.restores - 1} last-good restores "
                f"did not stabilize training ({self.skipped} non-finite "
                "steps skipped) — refusing to keep spending the allocation; "
                "inspect the data/LR, or raise guard_max_restores"
            )
        self.bad_streak = 0
        t0 = time.perf_counter()
        restored = self._copy(self.last_good)
        lr = get_learning_rate(restored.opt_state) * self.lr_factor
        restored = restored.replace(
            opt_state=set_learning_rate(restored.opt_state, lr)
        )
        # keep halving across successive restores, not oscillating back up
        self.last_good = self._copy(restored)
        # the measured restore wall is the goodput ledger's
        # guard_recovery signal (obs/ledger.py)
        obs.guard_restore(
            self.restores, lr, seconds=time.perf_counter() - t0
        )
        # the heartbeat lease carries a guard_restores counter — the HPO
        # launcher's divergence early-kill signal (train/elastic.py)
        from hydragnn_tpu.train import elastic

        elastic.note_guard_restore()
        return restored

    def state_dict(self) -> dict:
        """Counters only — snapshots are device state and re-form on
        resume (checkpoint v2 embeds this so a resumed run keeps its
        restore budget)."""
        return {
            "skipped": int(self.skipped),
            "restores": int(self.restores),
        }

    def load_state_dict(self, sd: dict) -> None:
        self.skipped = int(sd.get("skipped", 0))
        self.restores = int(sd.get("restores", 0))
