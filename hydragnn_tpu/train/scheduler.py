"""LR scheduling + training guards.

``ReduceLROnPlateau`` matches torch's semantics as used by the reference
(``run_training.py:99-105``: mode=min, factor=0.5, patience=5, min_lr=1e-5).
``EarlyStopping`` and best-val ``Checkpoint``-gating mirror
``hydragnn/utils/model.py:189-248``.
"""


class ReduceLROnPlateau:
    def __init__(
        self,
        lr: float,
        mode: str = "min",
        factor: float = 0.5,
        patience: int = 5,
        threshold: float = 1e-4,
        min_lr: float = 0.00001,
    ):
        self.lr = lr
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.min_lr = min_lr
        self.best = None
        self.num_bad_epochs = 0

    def _is_better(self, metric):
        if self.best is None:
            return True
        if self.mode == "min":
            return metric < self.best * (1.0 - self.threshold)
        return metric > self.best * (1.0 + self.threshold)

    def step(self, metric) -> float:
        """Feed the epoch's validation loss; returns the (possibly reduced)
        learning rate."""
        if self._is_better(metric):
            self.best = metric
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.lr = max(self.lr * self.factor, self.min_lr)
            self.num_bad_epochs = 0
        return self.lr

    def state_dict(self) -> dict:
        """Mutable counters only (hyperparameters come from the config the
        resuming run was launched with) — checkpoint format v2 persists
        this so a preemption-resumed run keeps the plateau history."""
        return {
            "lr": float(self.lr),
            "best": None if self.best is None else float(self.best),
            "num_bad_epochs": int(self.num_bad_epochs),
        }

    def load_state_dict(self, sd: dict) -> None:
        self.lr = float(sd["lr"])
        best = sd.get("best")
        self.best = None if best is None else float(best)
        self.num_bad_epochs = int(sd["num_bad_epochs"])


class EarlyStopping:
    """Stop when validation loss hasn't improved for ``patience`` epochs
    (``utils/model.py:189-204``)."""

    def __init__(self, patience: int = 5, min_delta: float = 0.0):
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.counter = 0
        self.early_stop = False

    def __call__(self, val_loss: float) -> bool:
        if self.best is None or val_loss < self.best - self.min_delta:
            self.best = val_loss
            self.counter = 0
        else:
            self.counter += 1
            if self.counter >= self.patience:
                self.early_stop = True
        return self.early_stop

    def state_dict(self) -> dict:
        return {
            "best": None if self.best is None else float(self.best),
            "counter": int(self.counter),
            "early_stop": bool(self.early_stop),
        }

    def load_state_dict(self, sd: dict) -> None:
        best = sd.get("best")
        self.best = None if best is None else float(best)
        self.counter = int(sd["counter"])
        self.early_stop = bool(sd["early_stop"])


class BestCheckpoint:
    """Save-on-best-validation with warmup epochs (``utils/model.py:207-248``)."""

    def __init__(self, name: str, warmup: int = 10, path: str = "./logs/"):
        self.name = name
        self.warmup = warmup
        self.path = path
        self.best = None

    def __call__(self, state_dict, epoch: int, val_loss: float, save_fn) -> bool:
        if epoch < self.warmup:
            return False
        if self.best is None or val_loss < self.best:
            self.best = val_loss
            save_fn(state_dict, self.name, self.path)
            return True
        return False

    def state_dict(self) -> dict:
        return {"best": None if self.best is None else float(self.best)}

    def load_state_dict(self, sd: dict) -> None:
        best = sd.get("best")
        self.best = None if best is None else float(best)
