"""Prediction / test-pass paths (the reference's ``test()`` with sample
collection, ``train_validate_test.py:588-698``).

Split out of ``trainer.py`` (round-3 verdict item 10) as a mixin: the
``Trainer`` composes it, so ``trainer.predict(...)`` is unchanged.
"""

import os

import jax
import numpy as np

from hydragnn_tpu.obs.introspect import record_function
from hydragnn_tpu.train.common import _env_flag, _is_oom, _nbatch


class PredictMixin:
    # allow roughly half a v5e HBM for (staged test set + stacked outputs);
    # beyond that the streaming path is the safe default. Best-effort only:
    # it cannot see HBM already held by staged training data / params — the
    # caller additionally catches the device's own RESOURCE_EXHAUSTED.
    # Default only: HYDRAGNN_PREDICT_STAGE_BUDGET / the training config's
    # ``predict_stage_budget_bytes`` override it (_predict_stage_budget).
    _PREDICT_STAGE_BUDGET_BYTES = 8 * 1024**3

    def _predict_stage_budget(self) -> int:
        """Staging budget in bytes: ``HYDRAGNN_PREDICT_STAGE_BUDGET`` env
        (accepts scientific notation, e.g. ``4e9``) > training config
        ``predict_stage_budget_bytes`` > the 8 GiB class default. Chips
        are not all v5e-sized — a v4 host wants a bigger stage, a CPU CI
        host a far smaller one."""
        env = os.getenv("HYDRAGNN_PREDICT_STAGE_BUDGET")
        if env is not None:
            try:
                return int(float(env))
            except ValueError:
                raise ValueError(
                    "HYDRAGNN_PREDICT_STAGE_BUDGET must be a byte count, "
                    f"got {env!r}"
                ) from None
        cfg = self.training_config.get("predict_stage_budget_bytes")
        if cfg is not None:
            return int(cfg)
        return self._PREDICT_STAGE_BUDGET_BYTES

    def predict(self, state, loader):
        """Full test pass with sample collection — the reference's ``test()``
        with return_samples (``train_validate_test.py:588-698``). Returns
        (avg loss, per-task avg, true_values, predicted_values) with per-head
        flattened [num_values, 1] arrays."""
        num_heads = self.model.num_heads
        acc = None
        true_values = [[] for _ in range(num_heads)]
        predicted_values = [[] for _ in range(num_heads)]
        nbatch = _nbatch(loader)

        # device-resident fast path (single-process): run the whole test
        # set as ONE scan and do ONE readback — per-batch output fetches
        # cost a full host round trip each on tunneled backends. Own knob
        # (default: follows the training-set flag) because the TEST set +
        # stacked outputs have their own HBM footprint; non-uniform batch
        # shapes or an over-budget stage fall back to streaming.
        device_resident = _env_flag(
            "HYDRAGNN_PREDICT_DEVICE_RESIDENT",
            self.training_config,
            "predict_device_resident",
            default=_env_flag(
                "HYDRAGNN_DEVICE_RESIDENT",
                self.training_config,
                "device_resident_dataset",
            ),
        )
        if device_resident and (self.mesh is None or jax.process_count() == 1):
            # resolve the budget OUTSIDE the fallback try: a malformed
            # HYDRAGNN_PREDICT_STAGE_BUDGET must fail loudly here, not be
            # swallowed as a "ragged shapes" fallback below
            budget = self._predict_stage_budget()
            host_batches = []
            for ibatch, batch in enumerate(loader):
                if ibatch >= nbatch:
                    break
                host_batches.append(batch)
            try:
                # only the two documented failure modes trigger the
                # fallback: ragged shapes (stack raises ValueError) and the
                # host-side budget estimate (MemoryError)
                stacked = self._stack_for_predict(host_batches, budget)
            except (ValueError, MemoryError):
                loader = host_batches
            else:
                try:
                    return self._predict_device_resident(
                        state, host_batches, stacked
                    )
                except Exception as e:
                    # memory exhaustion (host or device) falls back to
                    # streaming; anything else is a genuine bug
                    if _is_oom(e):
                        loader = host_batches
                    else:
                        raise
                finally:
                    # don't hold the second full host copy of the test set
                    # through a (memory-pressured) streaming fallback
                    del stacked

        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            dev_batch = self.put_batch(batch)
            # annotated so an on-demand device trace (/profile?steps=N)
            # shows predict dispatches as a named region, not anonymous
            # XLA launches
            with record_function("hydragnn.predict_batch"):
                metrics = self._eval_step(
                    state.params, state.batch_stats, dev_batch
                )
            # loss/tasks/num_graphs accumulate ON DEVICE as one packed
            # vector per batch (Trainer._acc_add) — the per-batch
            # float()/np.asarray() fetches this replaces each cost a full
            # host round trip and serialized the dispatch pipeline
            # (jaxlint: host-sync-in-hot-loop)
            acc = self._acc_add(acc, metrics, multi=False)
            outputs = metrics["outputs"]
            if self.mesh is not None and jax.process_count() > 1:
                # global data-sharded arrays span non-addressable devices;
                # bring back THIS process's shard — rows then line up with
                # the local host batch masks (per-rank collection, like the
                # reference's per-rank test() loop)
                from jax.experimental import multihost_utils
                from jax.sharding import PartitionSpec as P

                from hydragnn_tpu.parallel.mesh import DATA_AXIS

                outputs = multihost_utils.global_array_to_host_local_array(
                    outputs, self.mesh, jax.tree_util.tree_map(
                        lambda _: P(DATA_AXIS), outputs
                    )
                )
            outputs = jax.device_get(outputs)
            self._collect_head_values(
                batch, outputs, true_values, predicted_values
            )
        loss, tasks = self._acc_read(acc)  # the pass's ONE metric readback
        return self._predict_finish(loss, tasks, true_values, predicted_values)

    def _collect_head_values(
        self, batch, outputs, true_values, predicted_values
    ):
        """Append one batch's masked per-head (true, pred) rows — shared by
        the streaming and device-resident predict paths."""
        graph_mask = np.asarray(batch.graph_mask)
        node_mask = np.asarray(batch.node_mask)
        for ihead in range(self.model.num_heads):
            mask = (
                graph_mask
                if self.model.output_type[ihead] == "graph"
                else node_mask
            )
            true = np.asarray(batch.targets[ihead])[mask]
            # NLL mode appends a log-variance channel — collected values
            # are the mean prediction only
            pred = np.asarray(outputs[ihead])[mask][..., : true.shape[-1]]
            pred = pred.reshape(-1, 1)
            true = true.reshape(-1, 1)
            predicted_values[ihead].append(pred)
            true_values[ihead].append(true)

    def _stack_for_predict(self, host_batches, budget=None):
        """Stack + host-side budget estimate for the staged predict path.
        Raises ValueError (ragged shapes) or MemoryError (over budget).
        ``budget`` should be resolved by the caller via
        :meth:`_predict_stage_budget` BEFORE entering any fallback
        handler — resolving it here would let a malformed env override
        masquerade as a ragged-shape ValueError."""
        from hydragnn_tpu.graph.batch import stack_batches

        stacked = stack_batches(host_batches)  # ValueError if ragged
        stage_bytes = sum(
            a.nbytes
            for a in jax.tree_util.tree_leaves(stacked)
            if hasattr(a, "nbytes")
        )
        nb = len(host_batches)
        out_rows = {
            "graph": host_batches[0].graph_mask.shape[0],
            "node": host_batches[0].node_mask.shape[0],
        }
        out_bytes = sum(
            nb * out_rows[t] * d * 4
            for t, d in zip(self.model.output_type, self.model.output_dim)
        )
        if budget is None:
            budget = self._predict_stage_budget()
        if stage_bytes + out_bytes > budget:
            raise MemoryError(
                f"staged predict would need {stage_bytes + out_bytes} bytes "
                f"(budget {budget})"
            )
        return stacked

    def _predict_device_resident(self, state, host_batches, stacked):
        """One-scan, one-readback predict over a staged test set."""
        num_heads = self.model.num_heads
        staged = self.put_batch_stacked(stacked)
        with record_function("hydragnn.predict_scan"):
            loss_b, tasks_b, g_b, outputs_b = jax.device_get(
                self._predict_scan(state.params, state.batch_stats, staged)
            )
        g_arr = np.asarray(g_b, np.float64)
        n = max(float(g_arr.sum()), 1.0)
        loss = float(np.asarray(loss_b, np.float64) @ g_arr) / n
        tasks = (np.asarray(tasks_b, np.float64) * g_arr[:, None]).sum(0) / n
        true_values = [[] for _ in range(num_heads)]
        predicted_values = [[] for _ in range(num_heads)]
        for ib, batch in enumerate(host_batches):
            self._collect_head_values(
                batch,
                [outputs_b[ihead][ib] for ihead in range(num_heads)],
                true_values,
                predicted_values,
            )
        return self._predict_finish(loss, tasks, true_values, predicted_values)

    def _predict_finish(self, loss, tasks, true_values, predicted_values):
        """Shared tail of both predict paths: concat, optional test-data
        dump, already-averaged metrics."""
        true_values = [np.concatenate(v, axis=0) for v in true_values]
        predicted_values = [np.concatenate(v, axis=0) for v in predicted_values]
        dump = os.getenv("HYDRAGNN_DUMP_TESTDATA")
        if dump:
            # per-rank test-prediction dump (train_validate_test.py:602);
            # an explicit path gets the rank embedded so multi-host ranks
            # cannot clobber each other
            rank = jax.process_index()
            if dump == "1":
                path = f"testdata_rank{rank}.npz"
            elif jax.process_count() > 1:
                root, ext = os.path.splitext(dump)
                path = f"{root}_rank{rank}{ext or '.npz'}"
            else:
                path = dump
            np.savez(
                path,
                **{f"true_{i}": v for i, v in enumerate(true_values)},
                **{f"pred_{i}": v for i, v in enumerate(predicted_values)},
            )
        return (loss, np.atleast_1d(tasks), true_values, predicted_values)
