"""End-to-end orchestration behind ``run_training`` / ``run_prediction``.

Parity with ``hydragnn/run_training.py:49-182`` and
``hydragnn/run_prediction.py:48-107``: distributed setup -> data loading &
splitting -> config derivation -> model + optimizer -> epoch driver ->
checkpoint, and the prediction path that reloads the trained model and
returns (error, per-task error, true values, predictions) with optional
denormalization.
"""

import os

import numpy as np

from hydragnn_tpu.data.loaders import dataset_loading_and_splitting
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.parallel.distributed import setup_distributed
from hydragnn_tpu.parallel.mesh import announce_mesh, resolve_mesh
from hydragnn_tpu.train.checkpoint import (
    checkpoint_exists,
    load_state_dict,
    pop_train_meta,
    restore_into,
    rolling_checkpoints,
    save_model,
)
from hydragnn_tpu.obs import runtime as obs
from hydragnn_tpu.train.trainer import Trainer, train_validate_test
from hydragnn_tpu.utils import tracer as tr
from hydragnn_tpu.utils.config import (
    get_log_name_config,
    save_config,
    update_config,
)
from hydragnn_tpu.utils.compile_cache import enable_compile_cache
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.timers import Timer, print_timers


def _arch_for_factory(config) -> dict:
    arch = dict(config["NeuralNetwork"]["Architecture"])
    training = config["NeuralNetwork"]["Training"]
    arch["loss_function_type"] = training.get("loss_function_type", "mse")
    arch["conv_checkpointing"] = training.get("conv_checkpointing", False)
    return arch


def _get_summary_writer(log_name):
    """Rank-0 scalar writer. Historically this returned a bare TensorBoard
    ``SummaryWriter`` — or silently None when torch was missing, i.e. no
    scalars at all. Now it is the :class:`~hydragnn_tpu.obs.scalars.
    ScalarWriter` fan-out: an always-on JSONL/CSV backend plus TensorBoard
    when importable (its absence warned exactly once, on rank 0)."""
    from hydragnn_tpu.obs.scalars import ScalarWriter

    return ScalarWriter.for_run(log_name)


def _build_model_and_trainer(config, train_loader, verbosity):
    arch = _arch_for_factory(config)
    if arch.get("partition_axis"):
        return _build_partitioned(config, arch, train_loader, verbosity)
    model = create_model_config(arch, verbosity)
    # 2-D ("data", "model") when Training.model_parallel / HYDRAGNN_MESH
    # asks for it, the historical 1-D data mesh otherwise; a shape that
    # no longer fits the visible devices re-derives (parallel/mesh.py)
    mesh = resolve_mesh(config["NeuralNetwork"]["Training"])
    trainer = Trainer(
        model,
        config["NeuralNetwork"]["Training"],
        mesh=mesh,
        verbosity=verbosity,
        freeze_conv=arch.get("freeze_conv_layers", False),
    )
    example_batch = next(iter(train_loader))
    state = trainer.init_state(example_batch, seed=0)
    from hydragnn_tpu.models.create import print_model

    print_model(model, {"params": state.params}, verbosity)
    return model, trainer, state


def _partition_geometry(config) -> tuple:
    """``(num_parts, axis)`` for graph-partition mode. With model
    parallelism configured (``Training.model_parallel`` / HYDRAGNN_MESH),
    node/edge ownership lives on the 2-D mesh's ``model`` axis and each
    graph splits into one model group's worth of shards; otherwise the
    legacy 1-D partition mesh spans every device under the config's
    ``partition_axis`` name."""
    import jax

    from hydragnn_tpu.parallel.mesh import (
        GRAPH_AXIS,
        MODEL_AXIS,
        best_mesh_shape,
        requested_mesh,
    )

    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"].get("Training", {})
    _, m_req = requested_mesh(training)
    if m_req > 1:
        _, m = best_mesh_shape(len(jax.devices()), m_req)
        return m, MODEL_AXIS
    return len(jax.devices()), arch.get("partition_axis") or GRAPH_AXIS


def _build_partitioned(config, arch, train_loader, verbosity):
    """Giant-graph mode: every sample is ONE graph sharded node-wise over
    the partition axis — the ``model`` axis of the 2-D mesh when model
    parallelism is configured (``_partition_geometry``), else the legacy
    1-D mesh over every device named by ``Architecture.partition_axis``."""
    import jax

    from hydragnn_tpu.parallel.mesh import (
        MODEL_AXIS,
        best_mesh_shape,
        make_mesh,
        make_mesh2d,
        set_active_mesh,
    )
    from hydragnn_tpu.train.partitioned import PartitionedTrainer

    parts, axis = _partition_geometry(config)
    ref_arch = dict(arch)
    ref_arch.pop("partition_axis")
    arch = dict(arch)
    arch["partition_axis"] = axis
    model = create_model_config(arch, verbosity)
    ref_model = create_model_config(ref_arch, verbosity)
    if axis == MODEL_AXIS:
        d, m = best_mesh_shape(len(jax.devices()), parts)
        mesh = make_mesh2d(d, m)
        if d > 1:
            import warnings

            warnings.warn(
                f"graph-partition mode on a {d}x{m} mesh: each graph "
                f"splits across the {m}-wide model axis and the {d} data "
                "rows run REPLICATED work (one giant graph per step has "
                "no batch to shard). If the graph fits fewer shards than "
                "devices, prefer the 1-D partition mesh "
                "(model_parallel unset) to split it over every device",
                stacklevel=2,
            )
    else:
        mesh = make_mesh(None, axis)  # every device
    set_active_mesh(mesh)
    trainer = PartitionedTrainer(
        model,
        ref_model,
        config["NeuralNetwork"]["Training"],
        mesh=mesh,
        axis=axis,
        verbosity=verbosity,
        freeze_conv=arch.get("freeze_conv_layers", False),
    )
    state = trainer.init_state(train_loader.dataset[0], seed=0)
    return model, trainer, state


def make_partitioned_loaders(config, train_loader, val_loader, test_loader):
    """Swap the padded-batch GraphLoaders for PartitionedLoaders when the
    config asks for partition mode (post-``update_config``, so output
    types/dims are derived)."""
    arch = config["NeuralNetwork"]["Architecture"]
    if not arch.get("partition_axis"):
        return train_loader, val_loader, test_loader
    from hydragnn_tpu.train.partitioned import PartitionedLoader, scan_budgets

    head_types = tuple(arch["output_type"])
    head_dims = tuple(arch["output_dim"])
    need_triplets = arch["model_type"] == "DimeNet"
    need_neighbors = bool(arch.get("dense_aggregation"))
    # shards-per-graph = the partition axis size (the 2-D mesh's model
    # axis under model parallelism, every device on the legacy 1-D mesh)
    n_dev, part_axis = _partition_geometry(config)
    # ONE budget union across splits -> one compiled executable for all
    budgets = scan_budgets(
        [train_loader.dataset, val_loader.dataset, test_loader.dataset],
        n_dev,
        head_types,
        head_dims,
        need_triplets,
        need_neighbors,
    )
    out = []
    for loader, shuffle in (
        (train_loader, True),
        (val_loader, False),
        (test_loader, False),
    ):
        out.append(
            PartitionedLoader(
                loader.dataset,
                n_dev,
                head_types,
                head_dims,
                need_triplets=need_triplets,
                need_neighbors=need_neighbors,
                shuffle=shuffle,
                axis=part_axis,
                budgets=budgets,
            )
        )
    return tuple(out)


def run_training_impl(config):
    import time as _time

    started_ts = _time.monotonic()
    timer = Timer("run_training")
    timer.start()
    enable_compile_cache()
    setup_distributed()
    # resolve the mesh BEFORE data loading: the loaders' leading-axis
    # padding must divide the mesh's DATA axis (parallel/mesh.py
    # data_axis_multiple), which on a 2-D mesh is smaller than the raw
    # device count. _build_model_and_trainer re-resolves the same shape.
    resolve_mesh(config["NeuralNetwork"]["Training"])
    # elastic/heartbeat runtime (train/elastic.py): started right after
    # the distributed bootstrap so the lease exists before the long
    # data-load/compile phases — None unless HYDRAGNN_ELASTIC_DIR or
    # HYDRAGNN_HEARTBEAT_FILE opts in
    from hydragnn_tpu.train import elastic

    elastic_rt = elastic.maybe_elastic()
    tr.initialize()
    verbosity = config.get("Verbosity", {}).get("level", 0)

    from hydragnn_tpu.data.stream import (
        build_stream_loaders,
        streaming_requested,
    )

    probe_loader = None
    if streaming_requested(config):
        # streaming data plane (docs/data.md): the train split never
        # materializes — config derivation (output dims, PNA degrees,
        # graph-size variability) runs over a cursor-neutral probe window
        # instead of the whole dataset
        train_loader, val_loader, test_loader, probe_loader = (
            build_stream_loaders(config)
        )
        config = update_config(config, probe_loader, val_loader, test_loader)
    else:
        train_loader, val_loader, test_loader = (
            dataset_loading_and_splitting(config)
        )
        config = update_config(config, train_loader, val_loader, test_loader)
        train_loader, val_loader, test_loader = make_partitioned_loaders(
            config, train_loader, val_loader, test_loader
        )
    log_name = get_log_name_config(config)
    setup_log(log_name)
    save_config(config, log_name)
    # unified telemetry (rank 0): events.jsonl + training metrics, plus the
    # live /metrics+/healthz endpoint when HYDRAGNN_OBS_PORT or
    # config["Telemetry"]["port"] opts in; HYDRAGNN_TELEMETRY=0 disables
    telemetry = obs.init_run_telemetry(config, log_name)
    if getattr(train_loader, "plan_event", None):
        # the bucket plan was built before telemetry existed; land its
        # record now that the event stream is live
        obs.emit("bucket_plan", **train_loader.plan_event)

    writer = None
    try:
        # the streaming train loader's __iter__ advances the mix cursor —
        # the probe loader (same layout, materialized window) feeds
        # init_state's example batch instead
        model, trainer, state = _build_model_and_trainer(
            config, probe_loader or train_loader, verbosity
        )

        training = config["NeuralNetwork"]["Training"]
        resume_meta = None
        if "continue" in training and training["continue"]:
            model_name = training.get("startfrom", log_name)
            # a lost/deleted primary with intact rolling copies is still
            # resumable — load_state_dict walks back to the newest good one
            if checkpoint_exists(model_name) or rolling_checkpoints(model_name):
                restored = load_state_dict(model_name)
                # v2 checkpoints carry the training-loop state — honored ONLY
                # when continuing THIS run (preemption resume). A 'startfrom'
                # of some other run is a warm start: its epoch counter must
                # not eat this run's training budget, so the meta is stripped
                # and training runs from epoch 0 on the restored weights.
                meta = pop_train_meta(restored)
                if model_name == log_name:
                    resume_meta = meta
                state = trainer.place_state(restore_into(state, restored))

        # mesh_shape + param_sharding run events; when the resumed
        # checkpoint recorded a DIFFERENT mesh (elastic shrink: the
        # surviving world re-derived the largest fitting (d, m)), this
        # also emits the world_resize with the new shape — the 2-D
        # analog of PR 8's 1-D re-shard
        announce_mesh(
            trainer.mesh, trainer=trainer, resume_meta=resume_meta,
            started_ts=started_ts,
        )

        writer = _get_summary_writer(log_name)
        vis_cfg = config.get("Visualization", {})
        state = train_validate_test(
            trainer,
            state,
            train_loader,
            val_loader,
            test_loader,
            config["NeuralNetwork"],
            log_name,
            verbosity,
            writer=writer,
            create_plots=vis_cfg.get("create_plots", False),
            plot_init_solution=vis_cfg.get("plot_init_solution", False),
            resume_meta=resume_meta,
        )
        # the epoch driver saves a resumable checkpoint at the final epoch
        # on its own; repeating the (collective-heavy) consolidation here
        # would only rewrite identical bytes
        if not getattr(trainer, "final_state_saved", False):
            save_model(
                state,
                log_name,
                train_meta=getattr(trainer, "final_train_meta", None),
            )
        timer.stop()
        print_timers(verbosity)
        tr.save(f"./logs/{log_name}/trace")
        # end-of-run region attribution: the scalar fan-out is ALWAYS-ON
        # (it must not depend on the event/metrics telemetry being
        # enabled), the event-stream copy rides along when telemetry is on
        regions = tr.totals()
        if regions:
            if writer is not None:
                num_epoch = config["NeuralNetwork"]["Training"]["num_epoch"]
                writer.add_regions(regions, step=num_epoch)
            if telemetry is not None:
                telemetry.emit(
                    "tracer_totals",
                    regions={k: round(v, 6) for k, v in regions.items()},
                )
    except BaseException:
        # the event stream must record that the run died — a log that only
        # ever says "complete" is useless for postmortems. The whole
        # post-init span is covered: a failure in the final save / tracer
        # dump must not leave /healthz reporting ok with no run_end.
        try:
            try:
                # pending async checkpoint writes are the run's last
                # durable progress — land them even on the failure path
                from hydragnn_tpu.train.checkpoint import drain_async

                drain_async(timeout=60.0)
            except Exception:
                pass  # the original failure is the one to surface
            if writer is not None:
                writer.close()
        finally:
            if elastic_rt is not None:
                elastic_rt.stop()
            obs.deactivate(status="failed")
        raise
    try:
        if writer is not None:
            writer.close()
    finally:
        # run_end must land even if a scalar backend fails to close
        if elastic_rt is not None:
            elastic_rt.stop()
        obs.deactivate(status="complete")
    return state


def run_prediction_impl(config):
    enable_compile_cache()
    setup_distributed()
    verbosity = config.get("Verbosity", {}).get("level", 0)

    train_loader, val_loader, test_loader = dataset_loading_and_splitting(config)
    config = update_config(config, train_loader, val_loader, test_loader)
    train_loader, val_loader, test_loader = make_partitioned_loaders(
        config, train_loader, val_loader, test_loader
    )
    log_name = get_log_name_config(config)

    model, trainer, state = _build_model_and_trainer(
        config, train_loader, verbosity
    )
    # an explicit error, not an assert: asserts vanish under ``python -O``
    # and a prediction run silently using random weights is the worst
    # possible failure mode
    if not checkpoint_exists(log_name):
        raise FileNotFoundError(f"No trained model found: {log_name}")
    # fallback=False: rolling last-good recovery is for RESUMING training;
    # a prediction must never silently report results from older weights
    state = trainer.place_state(
        restore_into(state, load_state_dict(log_name, fallback=False))
    )

    error, tasks_error, true_values, predicted_values = trainer.predict(
        state, test_loader
    )

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    if voi.get("denormalize_output") and "y_minmax" in voi:
        from hydragnn_tpu.postprocess.postprocess import output_denormalize

        true_values, predicted_values = output_denormalize(
            voi["y_minmax"], true_values, predicted_values
        )

    return error, list(np.atleast_1d(tasks_error)), true_values, predicted_values
