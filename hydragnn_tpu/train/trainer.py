"""The jitted training core + epoch driver.

TPU-first redesign of ``hydragnn/train/train_validate_test.py``: instead of an
imperative hot loop (zero_grad / forward / backward / step as separate CUDA
launches, ``:437-540``), ONE XLA program per training step — forward, masked
multi-task loss, backward, optimizer update and BatchNorm-stat update fused by
the compiler. Data parallelism comes from sharding the batch over the mesh's
``data`` axis; gradient all-reduce is inserted by XLA over ICI (no NCCL, no
DDP hooks).

Epoch-level control flow (LR plateau, early stop, best-checkpoint, SLURM
wall-clock guard, val/test skip knobs) matches the reference driver
(``train_validate_test.py:54-250``) including the ``HYDRAGNN_MAX_NUM_BATCH``
and ``HYDRAGNN_VALTEST`` env knobs.
"""

import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.create import init_model_params
from hydragnn_tpu.train.checkpoint import save_model
from hydragnn_tpu.train.optimizer import (
    get_learning_rate,
    select_optimizer,
    set_learning_rate,
)
from hydragnn_tpu.train.scheduler import (
    BestCheckpoint,
    EarlyStopping,
    ReduceLROnPlateau,
)
from hydragnn_tpu.utils import tracer as tr
from hydragnn_tpu.utils.print_utils import iterate_tqdm, print_distributed


class TrainState(struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray


def _nbatch(loader):
    n = len(loader)
    cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
    if cap is not None:
        n = min(n, int(cap))
    return n


class Trainer:
    def __init__(
        self,
        model,
        training_config: dict,
        mesh=None,
        verbosity: int = 0,
        freeze_conv: bool = False,
    ):
        self.model = model
        self.training_config = training_config
        self.mesh = mesh
        self.verbosity = verbosity
        self.freeze_conv = freeze_conv
        self.tx = None
        self._train_step = None
        self._eval_step = None
        self._batch_sharding = None

    # ---- state ---------------------------------------------------------
    def init_state(self, example_batch: GraphBatch, seed: int = 0) -> TrainState:
        if self.mesh is None or jax.process_count() == 1:
            init_batch = self.put_batch(example_batch)
        else:
            # multi-host: init on a process-local copy — parameters depend
            # only on shapes and the seed, so every process derives identical
            # values (flax init cannot trace non-addressable global shards)
            init_batch = jax.tree_util.tree_map(jnp.asarray, example_batch)
        variables = init_model_params(self.model, init_batch, seed=seed)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self.tx = select_optimizer(
            self.training_config, params=params, freeze_conv=self.freeze_conv
        )
        opt_state = self.tx.init(params)
        state = TrainState(
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )
        state = self.place_state(state)
        self._build_steps()
        return state

    def place_state(self, state: TrainState) -> TrainState:
        """Replicate the state onto the mesh with the step's input sharding —
        used at init AND after checkpoint restore (a host-restored state fed
        straight in costs a duplicate sharding-signature compile)."""
        if self.mesh is None:
            return jax.tree_util.tree_map(jnp.asarray, state)
        from jax.sharding import NamedSharding, PartitionSpec as P

        if jax.process_count() > 1:
            # replicated GLOBAL arrays assembled from the (identical)
            # host-local values on every process
            from jax.experimental import multihost_utils

            state = jax.tree_util.tree_map(np.asarray, state)
            return multihost_utils.host_local_array_to_global_array(
                state, self.mesh, P()
            )
        return jax.device_put(state, NamedSharding(self.mesh, P()))

    def put_batch(self, batch: GraphBatch) -> GraphBatch:
        """Host batch -> device(s). Under a mesh, every leading axis (nodes /
        edges / graphs / triplets) is sharded over the ``data`` axis — the
        layout pads each to a multiple of the axis size.

        Multi-host (``jax.process_count() > 1``): each process passes ITS
        loader's local shard (the DistributedSampler split) and the global
        sharded batch is assembled with ``make_array_from_process_local_data``
        — the reference's per-rank DataLoader semantics
        (``preprocess/load_data.py:237-245``) with XLA owning the transport.
        """
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if self._batch_sharding is None:
                self._batch_sharding = NamedSharding(self.mesh, P("data"))
            if jax.process_count() > 1:
                return jax.tree_util.tree_map(
                    lambda a: jax.make_array_from_process_local_data(
                        self._batch_sharding, np.asarray(a)
                    ),
                    batch,
                )
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(jnp.asarray(a), self._batch_sharding),
                batch,
            )
        return jax.tree_util.tree_map(jnp.asarray, batch)

    # ---- compiled steps ------------------------------------------------
    def _build_steps(self):
        model = self.model
        tx = self.tx
        # mixed precision (no reference counterpart — HydraGNN trains pure
        # f32): master params stay f32 for the optimizer; forward/backward
        # runs in bfloat16. Positions stay f32 (geometry — distances/angles
        # — is precision-critical), BatchNorm statistics and loss reductions
        # are forced to f32 in models/common.py, and segment scatters upcast
        # to f32 (graph/segment.py). Measured on v5e (bench.py config): the
        # QM9-scale step is scatter/latency-bound, not matmul-bound (~8 of
        # ~49 f32 TFLOP/s), so bf16 LOSES there (29k vs 376k graphs/s at
        # hidden 64; 258k vs 356k at hidden 512 — XLA's bf16 gather/scatter
        # layouts are the cost). Accuracy-validated opt-in
        # (tests/test_mixed_precision.py); expect wins only on matmul-bound
        # configurations/topologies — measure before enabling.
        mixed = bool(self.training_config.get("mixed_precision", False))

        def _cast_bf16(tree):
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if hasattr(a, "dtype") and a.dtype == jnp.float32
                else a,
                tree,
            )

        def train_step(state, batch, rng):
            if mixed:
                batch = batch.replace(
                    x=batch.x.astype(jnp.bfloat16),
                    edge_attr=None
                    if batch.edge_attr is None
                    else batch.edge_attr.astype(jnp.bfloat16),
                )

            def loss_fn(params):
                if mixed:
                    params = _cast_bf16(params)
                variables = {"params": params}
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                    outputs, mut = model.apply(
                        variables,
                        batch,
                        train=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": rng},
                    )
                    new_bs = mut["batch_stats"]
                else:
                    outputs = model.apply(
                        variables, batch, train=True, rngs={"dropout": rng}
                    )
                    new_bs = state.batch_stats
                tot, tasks = model.loss(outputs, batch)
                return tot, (tuple(tasks), new_bs)

            (loss, (tasks, new_bs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                params=new_params,
                batch_stats=new_bs,
                opt_state=new_opt,
                step=state.step + 1,
            )
            metrics = {
                "loss": loss,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
                "num_graphs": batch.graph_mask.sum(),
            }
            return new_state, metrics

        def eval_step(params, batch_stats, batch):
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
            outputs = model.apply(variables, batch, train=False)
            tot, tasks = model.loss(outputs, batch)
            return {
                "loss": tot,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
                "num_graphs": batch.graph_mask.sum(),
                "outputs": outputs,
            }

        self._train_step = jax.jit(train_step, donate_argnums=(0,))
        self._eval_step = jax.jit(eval_step)

    # ---- epoch loops ---------------------------------------------------
    def train_epoch(self, state, loader, rng):
        tot = 0.0
        tasks = None
        n = 0.0
        nbatch = _nbatch(loader)
        tr.start("train")
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            tr.start("dataload")
            batch = self.put_batch(batch)
            tr.stop("dataload")
            rng, sub = jax.random.split(rng)
            tr.start("train_step")
            state, metrics = self._train_step(state, batch, sub)
            tr.stop("train_step")
            g = float(metrics["num_graphs"])
            tot += float(metrics["loss"]) * g
            t = np.asarray(metrics["tasks"]) * g
            tasks = t if tasks is None else tasks + t
            n += g
        tr.stop("train")
        n = max(n, 1.0)
        return state, rng, tot / n, (tasks / n if tasks is not None else np.zeros(0))

    def evaluate(self, state, loader, desc="validate"):
        tot = 0.0
        tasks = None
        n = 0.0
        nbatch = _nbatch(loader)
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            batch = self.put_batch(batch)
            metrics = self._eval_step(state.params, state.batch_stats, batch)
            g = float(metrics["num_graphs"])
            tot += float(metrics["loss"]) * g
            t = np.asarray(metrics["tasks"]) * g
            tasks = t if tasks is None else tasks + t
            n += g
        n = max(n, 1.0)
        return tot / n, (tasks / n if tasks is not None else np.zeros(0))

    def predict(self, state, loader):
        """Full test pass with sample collection — the reference's ``test()``
        with return_samples (``train_validate_test.py:588-698``). Returns
        (avg loss, per-task avg, true_values, predicted_values) with per-head
        flattened [num_values, 1] arrays."""
        num_heads = self.model.num_heads
        head_types = self.model.output_type
        tot = 0.0
        tasks = None
        n = 0.0
        true_values = [[] for _ in range(num_heads)]
        predicted_values = [[] for _ in range(num_heads)]
        nbatch = _nbatch(loader)
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            dev_batch = self.put_batch(batch)
            metrics = self._eval_step(
                state.params, state.batch_stats, dev_batch
            )
            g = float(metrics["num_graphs"])
            tot += float(metrics["loss"]) * g
            t = np.asarray(metrics["tasks"]) * g
            tasks = t if tasks is None else tasks + t
            n += g
            outputs = metrics["outputs"]
            if self.mesh is not None and jax.process_count() > 1:
                # global data-sharded arrays span non-addressable devices;
                # bring back THIS process's shard — rows then line up with
                # the local host batch masks (per-rank collection, like the
                # reference's per-rank test() loop)
                from jax.experimental import multihost_utils
                from jax.sharding import PartitionSpec as P

                outputs = multihost_utils.global_array_to_host_local_array(
                    outputs, self.mesh, jax.tree_util.tree_map(
                        lambda _: P("data"), outputs
                    )
                )
            outputs = jax.device_get(outputs)
            graph_mask = np.asarray(batch.graph_mask)
            node_mask = np.asarray(batch.node_mask)
            for ihead in range(num_heads):
                mask = graph_mask if head_types[ihead] == "graph" else node_mask
                pred = np.asarray(outputs[ihead])[mask].reshape(-1, 1)
                true = np.asarray(batch.targets[ihead])[mask].reshape(-1, 1)
                predicted_values[ihead].append(pred)
                true_values[ihead].append(true)
        n = max(n, 1.0)
        true_values = [np.concatenate(v, axis=0) for v in true_values]
        predicted_values = [np.concatenate(v, axis=0) for v in predicted_values]
        dump = os.getenv("HYDRAGNN_DUMP_TESTDATA")
        if dump:
            # per-rank test-prediction dump (train_validate_test.py:602);
            # an explicit path gets the rank embedded so multi-host ranks
            # cannot clobber each other
            rank = jax.process_index()
            if dump == "1":
                path = f"testdata_rank{rank}.npz"
            elif jax.process_count() > 1:
                root, ext = os.path.splitext(dump)
                path = f"{root}_rank{rank}{ext or '.npz'}"
            else:
                path = dump
            np.savez(
                path,
                **{f"true_{i}": v for i, v in enumerate(true_values)},
                **{f"pred_{i}": v for i, v in enumerate(predicted_values)},
            )
        return (
            tot / n,
            (tasks / n if tasks is not None else np.zeros(0)),
            true_values,
            predicted_values,
        )


def train_validate_test(
    trainer: Trainer,
    state: TrainState,
    train_loader,
    val_loader,
    test_loader,
    config_nn: dict,
    log_name: str,
    verbosity: int = 0,
    writer=None,
    create_plots: bool = False,
    plot_init_solution: bool = False,
):
    """Epoch driver (``train_validate_test.py:54-250``)."""
    training = config_nn["Training"]
    num_epoch = training["num_epoch"]
    early = EarlyStopping(training.get("patience", 5)) if training.get(
        "EarlyStopping", False
    ) else None
    ckpt = (
        BestCheckpoint(log_name, warmup=training.get("checkpoint_warmup", 10))
        if training.get("Checkpoint", False)
        else None
    )
    scheduler = ReduceLROnPlateau(lr=get_learning_rate(state.opt_state))
    rng = jax.random.PRNGKey(1337)

    visualizer = None
    if create_plots:
        from hydragnn_tpu.postprocess.visualizer import Visualizer

        node_feature = []
        nodes_num_list = []
        for d in test_loader.dataset:
            node_feature.extend(np.asarray(d.x).tolist())
            nodes_num_list.append(d.num_nodes)
        visualizer = Visualizer(
            log_name,
            node_feature=node_feature,
            num_heads=trainer.model.num_heads,
            head_dims=list(trainer.model.output_dim),
            num_nodes_list=nodes_num_list,
        )
        visualizer.num_nodes_plot()
        if plot_init_solution:
            _, _, true_values, predicted_values = trainer.predict(
                state, test_loader
            )
            visualizer.create_scatter_plots(
                true_values,
                predicted_values,
                output_names=config_nn["Variables_of_interest"].get(
                    "output_names"
                ),
                iepoch=-1,
            )

    total_loss_train = np.zeros(num_epoch)
    total_loss_val = np.zeros(num_epoch)
    total_loss_test = np.zeros(num_epoch)
    skip_valtest = int(os.getenv("HYDRAGNN_VALTEST", "1")) == 0

    epoch_time = 0.0
    for epoch in range(num_epoch):
        t0 = time.time()
        train_loader.set_epoch(epoch)
        state, rng, train_loss, train_tasks = trainer.train_epoch(
            state, train_loader, rng
        )
        if skip_valtest:
            val_loss, val_tasks = train_loss, train_tasks
            test_loss, test_tasks = train_loss, train_tasks
        else:
            val_loss, val_tasks = trainer.evaluate(state, val_loader)
            test_loss, test_tasks = trainer.evaluate(state, test_loader)

        new_lr = scheduler.step(val_loss)
        if abs(new_lr - get_learning_rate(state.opt_state)) > 1e-12:
            state = state.replace(
                opt_state=set_learning_rate(state.opt_state, new_lr)
            )

        total_loss_train[epoch] = train_loss
        total_loss_val[epoch] = val_loss
        total_loss_test[epoch] = test_loss
        print_distributed(
            verbosity,
            f"Epoch: {epoch:04d}, Train Loss: {train_loss:.8f}, "
            f"Val Loss: {val_loss:.8f}, Test Loss: {test_loss:.8f}",
        )
        if writer is not None:
            writer.add_scalar("train error", train_loss, epoch)
            writer.add_scalar("validate error", val_loss, epoch)
            writer.add_scalar("test error", test_loss, epoch)
            for itask, tl in enumerate(np.atleast_1d(train_tasks)):
                writer.add_scalar(f"train error of task {itask}", float(tl), epoch)

        if visualizer is not None and visualizer.plot_hist_solution:
            _, _, tv, pv = trainer.predict(state, test_loader)
            visualizer.plot_history(
                total_loss_train[: epoch + 1],
                total_loss_val[: epoch + 1],
                total_loss_test[: epoch + 1],
            )

        if ckpt is not None:
            ckpt(state, epoch, val_loss, save_model)
        if early is not None and early(val_loss):
            print_distributed(verbosity, f"Early stopping at epoch {epoch}")
            break

        epoch_time = time.time() - t0
        from hydragnn_tpu.parallel.distributed import check_remaining

        if not check_remaining(epoch_time):
            print_distributed(
                verbosity, "Stopping: not enough job wall-clock time left"
            )
            break

    if visualizer is not None:
        _, _, true_values, predicted_values = trainer.predict(state, test_loader)
        visualizer.plot_history(
            total_loss_train,
            total_loss_val,
            total_loss_test,
        )
        visualizer.create_plot_global(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
        visualizer.create_scatter_plots(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
    return state
