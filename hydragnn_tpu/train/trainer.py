"""The jitted training core + epoch driver.

TPU-first redesign of ``hydragnn/train/train_validate_test.py``: instead of an
imperative hot loop (zero_grad / forward / backward / step as separate CUDA
launches, ``:437-540``), ONE XLA program per training step — forward, masked
multi-task loss, backward, optimizer update and BatchNorm-stat update fused by
the compiler. Data parallelism comes from sharding the batch over the mesh's
``data`` axis; gradient all-reduce is inserted by XLA over ICI (no NCCL, no
DDP hooks).

Epoch-level control flow (LR plateau, early stop, best-checkpoint, SLURM
wall-clock guard, val/test skip knobs) matches the reference driver
(``train_validate_test.py:54-250``) including the ``HYDRAGNN_MAX_NUM_BATCH``
and ``HYDRAGNN_VALTEST`` env knobs.
"""

import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.create import init_model_params
from hydragnn_tpu.train.checkpoint import save_model
from hydragnn_tpu.train.optimizer import (
    get_learning_rate,
    select_optimizer,
    set_learning_rate,
)
from hydragnn_tpu.train.scheduler import (
    BestCheckpoint,
    EarlyStopping,
    ReduceLROnPlateau,
)
from hydragnn_tpu.utils import tracer as tr
from hydragnn_tpu.utils.print_utils import iterate_tqdm, print_distributed


class TrainState(struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray


class SchedState(struct.PyTreeNode):
    """Device-resident scheduler/guard state for the on-device fit loop:
    ReduceLROnPlateau (best/bad-epochs), EarlyStopping (best/counter/flag)
    and the epoch index — all scalars living in HBM so whole-training
    dispatches never bounce scheduler decisions off the host."""

    plateau_best: jnp.ndarray  # f32
    plateau_bad: jnp.ndarray  # i32
    early_best: jnp.ndarray  # f32
    early_count: jnp.ndarray  # i32
    stopped: jnp.ndarray  # bool
    epoch: jnp.ndarray  # i32
    best_val: jnp.ndarray  # f32, for best-state tracking

    @classmethod
    def init(cls):
        return cls(
            plateau_best=jnp.asarray(jnp.inf, jnp.float32),
            plateau_bad=jnp.zeros((), jnp.int32),
            early_best=jnp.asarray(jnp.inf, jnp.float32),
            early_count=jnp.zeros((), jnp.int32),
            stopped=jnp.zeros((), bool),
            epoch=jnp.zeros((), jnp.int32),
            best_val=jnp.asarray(jnp.inf, jnp.float32),
        )


def _nbatch(loader):
    n = len(loader)
    cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
    if cap is not None:
        n = min(n, int(cap))
    return n


def _env_flag(env_name: str, config: dict, config_key: str, default=False):
    """Boolean knob with the framework's env-overrides-config convention
    (the reference's ``HYDRAGNN_*`` channel layered over its JSON config)."""
    return bool(int(os.getenv(env_name, str(int(config.get(config_key, default))))))


def _is_oom(exc: BaseException) -> bool:
    """Memory exhaustion, host or device: MemoryError, or the runtime's
    RESOURCE_EXHAUSTED / out-of-memory errors (jaxlib raises RuntimeError
    subclasses, not MemoryError). Shared by every staging fallback."""
    msg = str(exc)
    return (
        isinstance(exc, MemoryError)
        or "RESOURCE_EXHAUSTED" in msg
        or "out of memory" in msg.lower()
    )


def _offset_local_shard(batch: GraphBatch, rank: int) -> GraphBatch:
    """Multi-host assembly correctness: each process collates its local
    shard with LOCAL row indices, but the globally-assembled arrays have
    global row semantics inside jit — every index array must be offset by
    this process's position, or shard p's gathers silently read shard 0's
    rows (caught by the cross-process loss-parity test). Handles plain
    [..., E] and stacked [K, ..., E] layouts alike (offsets are per-shard
    constants)."""
    n_off = rank * batch.x.shape[-2]
    e_off = rank * batch.senders.shape[-1]
    g_off = rank * batch.n_node.shape[-1]
    rep = dict(
        senders=np.asarray(batch.senders, np.int64) + n_off,
        receivers=np.asarray(batch.receivers, np.int64) + n_off,
        node_graph=np.asarray(batch.node_graph, np.int64) + g_off,
    )
    rep = {k: v.astype(np.int32) for k, v in rep.items()}
    if batch.extras:
        ex = dict(batch.extras)
        for key in ("trip_i", "trip_j", "trip_k", "nbr_idx"):
            if key in ex:
                ex[key] = (np.asarray(ex[key], np.int64) + n_off).astype(
                    np.int32
                )
        for key in ("trip_kj", "trip_ji", "nbr_edge"):
            if key in ex:
                ex[key] = (np.asarray(ex[key], np.int64) + e_off).astype(
                    np.int32
                )
        if "rev_idx" in ex:
            # flat (row * k_in + slot): global row offset scales by k_in
            k_in = ex["nbr_idx"].shape[-1]
            ex["rev_idx"] = (
                np.asarray(ex["rev_idx"], np.int64) + n_off * k_in
            ).astype(np.int32)
        if "tripnbr_idx" in ex:
            # member lists reference triplet-table rows
            t_off = rank * ex["trip_mask"].shape[-1]
            ex["tripnbr_idx"] = (
                np.asarray(ex["tripnbr_idx"], np.int64) + t_off
            ).astype(np.int32)
        rep["extras"] = ex
    return batch.replace(**rep)


def _decompact_traced(batch: GraphBatch) -> GraphBatch:
    """Inverse of the wire compaction, INSIDE the jitted program (free —
    XLA fuses the casts; eager device casts would cost a dispatch each):
    upcast int16 index arrays, synthesize zero positions for the [1, 3]
    placeholder shipped when the model never reads ``pos``."""
    rep = {}
    if batch.senders.dtype != jnp.int32:
        rep = dict(
            senders=batch.senders.astype(jnp.int32),
            receivers=batch.receivers.astype(jnp.int32),
            node_graph=batch.node_graph.astype(jnp.int32),
        )
    if batch.pos.shape[-2] == 1 and batch.x.shape[-2] != 1:
        # NaN, not zeros: a conv that reads positions while declaring
        # conv_needs_pos=False would otherwise train on plausible all-zero
        # coordinates; NaN makes that bug blow up in the first loss value
        rep["pos"] = jnp.full(batch.x.shape[:-1] + (3,), jnp.nan, jnp.float32)
    return batch.replace(**rep) if rep else batch


class Trainer:
    def __init__(
        self,
        model,
        training_config: dict,
        mesh=None,
        verbosity: int = 0,
        freeze_conv: bool = False,
    ):
        self.model = model
        self.training_config = training_config
        self.mesh = mesh
        self.verbosity = verbosity
        self.freeze_conv = freeze_conv
        self.tx = None
        self._train_step = None
        self._train_multi = None
        self._epoch_scan = None
        self._fit_scan = None
        self._predict_scan = None
        self._eval_step = None
        self._batch_sharding = None
        self._stacked_sharding = None
        # one dispatch runs this many optimizer steps via lax.scan (1 = the
        # plain per-batch path); settable in config or HYDRAGNN_STEPS_PER_DISPATCH
        self.steps_per_dispatch = int(
            os.getenv(
                "HYDRAGNN_STEPS_PER_DISPATCH",
                str(training_config.get("steps_per_dispatch", 1)),
            )
        )

    # ---- state ---------------------------------------------------------
    def init_state(self, example_batch: GraphBatch, seed: int = 0) -> TrainState:
        if self.mesh is None or jax.process_count() == 1:
            init_batch = self.put_batch(example_batch)
        else:
            # multi-host: init on a process-local copy — parameters depend
            # only on shapes and the seed, so every process derives identical
            # values (flax init cannot trace non-addressable global shards)
            init_batch = jax.tree_util.tree_map(jnp.asarray, example_batch)
        variables = init_model_params(self.model, init_batch, seed=seed)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self.tx = select_optimizer(
            self.training_config, params=params, freeze_conv=self.freeze_conv
        )
        opt_state = self.tx.init(params)
        state = TrainState(
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )
        state = self.place_state(state)
        self._build_steps()
        return state

    def place_state(self, state: TrainState) -> TrainState:
        """Replicate the state onto the mesh with the step's input sharding —
        used at init AND after checkpoint restore (a host-restored state fed
        straight in costs a duplicate sharding-signature compile)."""
        if self.mesh is None:
            return jax.tree_util.tree_map(jnp.asarray, state)
        from jax.sharding import NamedSharding, PartitionSpec as P

        if jax.process_count() > 1:
            # replicated GLOBAL arrays assembled from the (identical)
            # host-local values on every process. Note: under ZeRO the
            # opt_state is transiently replicated here before resharding —
            # multi-host direct placement would need per-leaf global
            # assembly; single-process (below) places directly.
            from jax.experimental import multihost_utils

            state = jax.tree_util.tree_map(np.asarray, state)
            state = multihost_utils.host_local_array_to_global_array(
                state, self.mesh, P()
            )
            return self._maybe_shard_zero(state)
        if self._zero_enabled():
            # place opt-state leaves DIRECTLY at their target sharding —
            # replicate-then-reshard would transiently hold the full
            # optimizer state on every device, defeating ZeRO at init
            from hydragnn_tpu.parallel.mesh import shard_optimizer_state

            opt = shard_optimizer_state(state.opt_state, self.mesh)
            placed = jax.device_put(
                state.replace(opt_state=None), NamedSharding(self.mesh, P())
            )
            return placed.replace(opt_state=opt)
        return jax.device_put(state, NamedSharding(self.mesh, P()))

    def _zero_enabled(self) -> bool:
        """``Training.Optimizer.use_zero_redundancy`` — the reference's
        ZeroRedundancyOptimizer / DeepSpeed-ZeRO switch
        (``utils/optimizer.py:142-151``). A sharding decision, not a
        different optimizer — XLA inserts the all-gathers."""
        return bool(
            self.training_config.get("Optimizer", {}).get(
                "use_zero_redundancy", False
            )
        )

    def _maybe_shard_zero(self, state: TrainState) -> TrainState:
        if not self._zero_enabled():
            return state
        from hydragnn_tpu.parallel.mesh import shard_optimizer_state

        return state.replace(
            opt_state=shard_optimizer_state(state.opt_state, self.mesh)
        )

    def _compact_for_transfer(
        self, batch: GraphBatch, allow_pos_placeholder: bool = True
    ):
        """Shrink the host->device wire format (streaming is H2D-bound;
        undone INSIDE the jitted step by ``_decompact_traced``):

        - index arrays (senders/receivers/node_graph) travel as int16 when
          the node/graph counts fit, and are cast back to int32 on device —
          the jitted step still sees int32, so nothing else changes;
        - ``pos`` is replaced by a ``[..., 1, 3]`` placeholder when the
          model never reads positions (no distance/coordinate convs, no
          equivariance); the step synthesizes a device-side fill. Disabled
          under a mesh (``allow_pos_placeholder=False``): a 1-row axis
          cannot shard over the data axis.

        Applies to single-process transfers (plain and mesh-sharded); the
        multi-host assembly path ships uncompacted. ``compact_transfer`` /
        ``HYDRAGNN_COMPACT_TRANSFER`` (default on) disables it entirely.
        """
        if not _env_flag(
            "HYDRAGNN_COMPACT_TRANSFER", self.training_config,
            "compact_transfer", default=True,
        ):
            return batch
        # shape[-2] of x is the node count for both plain [N, F] and
        # stacked [K, N, F] layouts; n_node's last axis is the graph count
        if batch.x.shape[-2] < 2**15 and batch.n_node.shape[-1] < 2**15:
            batch = batch.replace(
                senders=np.asarray(batch.senders, np.int16),
                receivers=np.asarray(batch.receivers, np.int16),
                node_graph=np.asarray(batch.node_graph, np.int16),
            )
        needs_pos = getattr(self.model, "conv_needs_pos", True) or getattr(
            self.model, "equivariance", False
        )
        if not needs_pos and allow_pos_placeholder:
            placeholder = np.zeros(batch.pos.shape[:-2] + (1, 3), np.float32)
            batch = batch.replace(pos=placeholder)
        return batch

    def put_batch(self, batch: GraphBatch) -> GraphBatch:
        """Host batch -> device(s). Under a mesh, every leading axis (nodes /
        edges / graphs / triplets) is sharded over the ``data`` axis — the
        layout pads each to a multiple of the axis size.

        Multi-host (``jax.process_count() > 1``): each process passes ITS
        loader's local shard (the DistributedSampler split) and the global
        sharded batch is assembled with ``make_array_from_process_local_data``
        — the reference's per-rank DataLoader semantics
        (``preprocess/load_data.py:237-245``) with XLA owning the transport.
        """
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if self._batch_sharding is None:
                self._batch_sharding = NamedSharding(self.mesh, P("data"))
            if jax.process_count() > 1:
                batch = _offset_local_shard(batch, jax.process_index())
                return jax.tree_util.tree_map(
                    lambda a: jax.make_array_from_process_local_data(
                        self._batch_sharding, np.asarray(a)
                    ),
                    batch,
                )
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(jnp.asarray(a), self._batch_sharding),
                self._compact_for_transfer(batch, allow_pos_placeholder=False),
            )
        return jax.tree_util.tree_map(
            jnp.asarray, self._compact_for_transfer(batch)
        )

    def put_batch_stacked(self, stacked: GraphBatch) -> GraphBatch:
        """Like :meth:`put_batch` for a ``stack_batches`` result: the scan
        axis stays unsharded, each microbatch's leading axis shards over
        ``data``."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if self._stacked_sharding is None:
                self._stacked_sharding = NamedSharding(self.mesh, P(None, "data"))
            if jax.process_count() > 1:
                stacked = _offset_local_shard(stacked, jax.process_index())
                return jax.tree_util.tree_map(
                    lambda a: jax.make_array_from_process_local_data(
                        self._stacked_sharding, np.asarray(a)
                    ),
                    stacked,
                )
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(jnp.asarray(a), self._stacked_sharding),
                self._compact_for_transfer(
                    stacked, allow_pos_placeholder=False
                ),
            )
        return jax.tree_util.tree_map(
            jnp.asarray, self._compact_for_transfer(stacked)
        )

    # ---- compiled steps ------------------------------------------------
    def _build_steps(self):
        model = self.model
        tx = self.tx
        # mixed precision (no reference counterpart — HydraGNN trains pure
        # f32): master params stay f32 for the optimizer; forward/backward
        # runs in bfloat16. Positions stay f32 (geometry — distances/angles
        # — is precision-critical), BatchNorm statistics and loss reductions
        # are forced to f32 in models/common.py, and segment scatters upcast
        # to f32 (graph/segment.py). The QM9-scale step is scatter/
        # op-latency-bound, not matmul-bound, so bf16 buys little there;
        # expect wins on matmul-bound configurations (wide hidden dims,
        # dense-mode batches). Accuracy-validated opt-in
        # (tests/test_mixed_precision.py) — measure with a true completion
        # fence before enabling (see BASELINE.md measurement note).
        mixed = bool(self.training_config.get("mixed_precision", False))

        def _cast_bf16(tree):
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if hasattr(a, "dtype") and a.dtype == jnp.float32
                else a,
                tree,
            )

        def train_step(state, batch, rng):
            batch = _decompact_traced(batch)
            if mixed:
                batch = batch.replace(
                    x=batch.x.astype(jnp.bfloat16),
                    edge_attr=None
                    if batch.edge_attr is None
                    else batch.edge_attr.astype(jnp.bfloat16),
                )

            def loss_fn(params):
                if mixed:
                    params = _cast_bf16(params)
                variables = {"params": params}
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                    outputs, mut = model.apply(
                        variables,
                        batch,
                        train=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": rng},
                    )
                    new_bs = mut["batch_stats"]
                else:
                    outputs = model.apply(
                        variables, batch, train=True, rngs={"dropout": rng}
                    )
                    new_bs = state.batch_stats
                tot, tasks = model.loss(outputs, batch)
                return tot, (tuple(tasks), new_bs)

            (loss, (tasks, new_bs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                params=new_params,
                batch_stats=new_bs,
                opt_state=new_opt,
                step=state.step + 1,
            )
            metrics = {
                "loss": loss,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
                "num_graphs": batch.graph_mask.sum(),
            }
            return new_state, metrics

        def eval_step(params, batch_stats, batch):
            batch = _decompact_traced(batch)
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
            outputs = model.apply(variables, batch, train=False)
            tot, tasks = model.loss(outputs, batch)
            return {
                "loss": tot,
                "tasks": jnp.stack(tasks) if tasks else jnp.zeros((0,)),
                "num_graphs": batch.graph_mask.sum(),
                "outputs": outputs,
            }

        def _microbatch(data, idx):
            """Gather microbatch ``idx`` out of an HBM-staged stack."""
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, idx, keepdims=False),
                data,
            )

        def epoch_scan(state, data, perm, rngs):
            """A whole epoch in ONE XLA program over an HBM-staged dataset.

            ``data`` is a ``stack_batches`` result living in device memory
            (see :meth:`stage_batches`); ``perm`` reorders the microbatches
            each epoch. Each scan step gathers one microbatch out of HBM and
            runs the fused train step — zero host round-trips inside the
            epoch. This is the TPU answer to datasets that fit in HBM
            (QM9-scale and below): stage once, then epochs are pure compute."""

            def body(s, inp):
                idx, r = inp
                return train_step(s, _microbatch(data, idx), r)

            return jax.lax.scan(body, state, (perm, rngs))

        sch_cfg = self.training_config.get("scheduler", {})
        plateau_factor = float(sch_cfg.get("factor", 0.5))
        plateau_patience = int(sch_cfg.get("patience", 5))
        plateau_threshold = float(sch_cfg.get("threshold", 1e-4))
        plateau_min_lr = float(sch_cfg.get("min_lr", 1e-5))
        early_enabled = bool(self.training_config.get("EarlyStopping", False))
        early_patience = int(self.training_config.get("patience", 5))
        # best-state tracking starts after this many epochs (the reference
        # BestCheckpoint warmup, ``utils/model.py:207-248``; default 10 when
        # checkpointing is on, else track from the start)
        best_warmup = int(
            self.training_config.get(
                "checkpoint_warmup",
                10 if self.training_config.get("Checkpoint", False) else 0,
            )
        )

        def eval_epoch(params, batch_stats, data):
            """Mean loss/tasks over a staged (stacked) eval set, no outputs.
            Honors ``HYDRAGNN_MAX_NUM_BATCH`` like every other eval path."""

            def body(_, idx):
                m = eval_step(params, batch_stats, _microbatch(data, idx))
                return _, (m["loss"], m["tasks"], m["num_graphs"])

            nb = jax.tree_util.tree_leaves(data)[0].shape[0]
            cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
            if cap is not None:
                nb = min(nb, int(cap))
            _, (loss, tasks, g) = jax.lax.scan(
                body, None, jnp.arange(nb)
            )
            g = g.astype(jnp.float32)
            denom = jnp.maximum(g.sum(), 1.0)
            return (loss * g).sum() / denom, (tasks * g[:, None]).sum(0) / denom

        num_tasks = len(model.output_type)

        def fit_scan(
            state, best_state, sched, train_data, val_data, test_data,
            perms, rngs, active,
        ):
            """Whole-training dispatch: scan over epochs, each epoch a scan
            over HBM-staged microbatches; plateau LR, early stopping and
            best-state tracking run on device (``SchedState``). One D2H
            readback per CALL, not per epoch — on hosts where readback
            latency is milliseconds that's cosmetic, on tunneled dev chips
            it's the difference between launch-bound and compute-bound.

            ``val_data``/``test_data`` may be the train set (the reference's
            ``HYDRAGNN_VALTEST=0`` semantics are handled by the caller).
            Epochs after the early stop fire — and epochs whose ``active``
            flag is False (scan-length padding so every chunk reuses one
            compiled program) — are skipped via ``lax.cond`` (their metric
            slots return NaN)."""

            def epoch_body(carry, inp):
                state, best_state, sched = carry
                perm, erngs, act = inp

                def run(args):
                    state, best_state, sched = args
                    state, m = epoch_scan(state, train_data, perm, erngs)
                    g = m["num_graphs"].astype(jnp.float32)
                    denom = jnp.maximum(g.sum(), 1.0)
                    train_loss = (m["loss"] * g).sum() / denom
                    train_tasks = (m["tasks"] * g[:, None]).sum(0) / denom
                    # None val/test = the reference's HYDRAGNN_VALTEST=0
                    # semantics: reuse the train loss, skip the eval pass
                    if val_data is None:
                        val_loss = train_loss
                    else:
                        val_loss, _ = eval_epoch(
                            state.params, state.batch_stats, val_data
                        )
                    if test_data is None:
                        test_loss = val_loss
                    else:
                        test_loss, _ = eval_epoch(
                            state.params, state.batch_stats, test_data
                        )
                    # ---- ReduceLROnPlateau (scheduler.py semantics)
                    is_better = val_loss < sched.plateau_best * (
                        1.0 - plateau_threshold
                    )
                    pbest = jnp.where(is_better, val_loss, sched.plateau_best)
                    pbad = jnp.where(is_better, 0, sched.plateau_bad + 1)
                    hp = state.opt_state.hyperparams
                    lr = hp["learning_rate"]
                    drop = pbad > plateau_patience
                    new_lr = jnp.where(
                        drop,
                        jnp.maximum(lr * plateau_factor, plateau_min_lr),
                        lr,
                    )
                    pbad = jnp.where(drop, 0, pbad)
                    opt_state = state.opt_state._replace(
                        hyperparams={**hp, "learning_rate": new_lr}
                    )
                    state = state.replace(opt_state=opt_state)
                    # ---- EarlyStopping (utils/model.py:189-204 semantics)
                    e_better = val_loss < sched.early_best
                    e_best = jnp.where(e_better, val_loss, sched.early_best)
                    e_count = jnp.where(e_better, 0, sched.early_count + 1)
                    stopped = (
                        (e_count >= early_patience)
                        if early_enabled
                        else jnp.zeros((), bool)
                    )
                    # ---- best-state snapshot (Checkpoint-on-best analog,
                    # warmup-gated like utils/model.py:207-248)
                    improved = (val_loss < sched.best_val) & (
                        sched.epoch >= best_warmup
                    )
                    new_best_val = jnp.where(improved, val_loss, sched.best_val)
                    best_state = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(improved, new, old),
                        state,
                        best_state,
                    )
                    sched = SchedState(
                        plateau_best=pbest,
                        plateau_bad=pbad,
                        early_best=e_best,
                        early_count=e_count,
                        stopped=stopped,
                        epoch=sched.epoch + 1,
                        best_val=new_best_val,
                    )
                    # one packed row per epoch so the whole series is ONE
                    # D2H array: [train, val, test, lr, stopped, tasks...]
                    row = jnp.concatenate(
                        [
                            jnp.stack(
                                [train_loss, val_loss, test_loss,
                                 new_lr.astype(jnp.float32),
                                 stopped.astype(jnp.float32)]
                            ),
                            train_tasks.astype(jnp.float32),
                        ]
                    )
                    return (state, best_state, sched), row

                def skip(args):
                    state, best_state, sched = args
                    nan = jnp.asarray(jnp.nan, jnp.float32)
                    lr = state.opt_state.hyperparams["learning_rate"]
                    row = jnp.concatenate(
                        [
                            jnp.stack(
                                [nan, nan, nan, lr.astype(jnp.float32),
                                 sched.stopped.astype(jnp.float32)]
                            ),
                            jnp.full((num_tasks,), jnp.nan, jnp.float32),
                        ]
                    )
                    return (state, best_state, sched), row

                return jax.lax.cond(
                    jnp.logical_or(sched.stopped, jnp.logical_not(act)),
                    skip,
                    run,
                    (state, best_state, sched),
                )

            (state, best_state, sched), series = jax.lax.scan(
                epoch_body, (state, best_state, sched), (perms, rngs, active)
            )
            return state, best_state, sched, series

        def multi_train_step(state, batches, rngs):
            """K optimizer steps in ONE XLA program (``lax.scan`` over a
            stacked batch). Amortizes dispatch latency: at QM9 scale a single
            step's device time is well under the host's per-dispatch cost, so
            the eager-style loop is launch-bound (measured ~2.3 ms/step wall
            vs ~0.6 ms device on v5e). Metrics come back stacked ``[K, ...]``
            so epoch accumulation stays exact."""

            def body(s, inp):
                b, r = inp
                return train_step(s, b, r)

            return jax.lax.scan(body, state, (batches, rngs))

        def predict_scan(params, batch_stats, data):
            """Full-set prediction in one program: stacked per-microbatch
            (loss, tasks, num_graphs, outputs) — callers do ONE readback."""

            def body(_, idx):
                m = eval_step(params, batch_stats, _microbatch(data, idx))
                return _, (
                    m["loss"], m["tasks"], m["num_graphs"], m["outputs"]
                )

            nb = jax.tree_util.tree_leaves(data)[0].shape[0]
            return jax.lax.scan(body, None, jnp.arange(nb))[1]

        self._train_step = jax.jit(train_step, donate_argnums=(0,))
        self._train_multi = jax.jit(multi_train_step, donate_argnums=(0,))
        self._epoch_scan = jax.jit(epoch_scan, donate_argnums=(0,))
        self._eval_epoch = jax.jit(eval_epoch)
        self._predict_scan = jax.jit(predict_scan)
        # donate state + sched; best_state is NOT donated (its initial value
        # may alias state's buffers)
        self._fit_scan = jax.jit(fit_scan, donate_argnums=(0, 2))
        self._eval_step = jax.jit(eval_step)

    # ---- device-resident dataset --------------------------------------
    def stage_batches(self, batches) -> GraphBatch:
        """Stack same-shape collated batches and park them in HBM once.

        Returns a device-resident epoch usable with
        :meth:`train_epoch_staged`. Use when the (padded) training set fits
        device memory — it removes host->device transfers from the training
        loop entirely, which otherwise bound small-graph workloads."""
        from hydragnn_tpu.graph.batch import stack_batches

        return self.put_batch_stacked(stack_batches(list(batches)))

    def train_epoch_staged(self, state, staged, rng, shuffle=True):
        """One epoch over an HBM-staged dataset in a single dispatch.

        Shuffling permutes microbatch ORDER each epoch (sample->batch
        assignment is fixed at staging time — the streaming ``train_epoch``
        path reshuffles samples fully; restage periodically if you want
        that here). Returns the same (state, rng, loss, tasks) contract as
        :meth:`train_epoch`."""
        nb = jax.tree_util.tree_leaves(staged)[0].shape[0]
        cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
        n_use = min(nb, int(cap)) if cap is not None else nb
        rng, prng = jax.random.split(rng)
        if shuffle:
            perm = jax.random.permutation(prng, nb)[:n_use]
        else:
            perm = jnp.arange(n_use)
        subs = jax.random.split(rng, n_use + 1)
        rng = subs[0]
        tr.start("train")
        state, metrics = self._epoch_scan(state, staged, perm, subs[1:])
        g = np.asarray(metrics["num_graphs"], np.float64)
        tot = float(np.asarray(metrics["loss"], np.float64) @ g)
        tasks = (np.asarray(metrics["tasks"], np.float64) * g[:, None]).sum(0)
        tr.stop("train")
        n = max(float(g.sum()), 1.0)
        return state, rng, tot / n, tasks / n

    def evaluate_staged(self, state, staged):
        """Whole eval set in one dispatch over an HBM-staged stack — the
        staged counterpart of :meth:`evaluate` (same averaged metrics)."""
        loss, tasks = self._eval_epoch(state.params, state.batch_stats, staged)
        return float(np.asarray(loss)), np.asarray(tasks, np.float64)

    def fit_staged(
        self,
        state,
        staged_train,
        num_epoch: int,
        rng,
        staged_val=None,
        staged_test=None,
        shuffle: bool = True,
        sched: Optional[SchedState] = None,
        best_state: Optional[TrainState] = None,
        pad_to: Optional[int] = None,
    ):
        """Run ``num_epoch`` training epochs as ONE device dispatch.

        Everything the reference's epoch driver does per epoch —
        ReduceLROnPlateau on the val loss, EarlyStopping, best-val state
        tracking (the ``Checkpoint`` analog), val+test evaluation — runs on
        device inside a single ``lax.scan`` over epochs; the metric series
        comes back as one packed array, i.e. ONE host readback per call.
        Call it in chunks (e.g. 10 epochs at a time) when host-side
        per-epoch actions are needed (TensorBoard, SLURM wall-clock guard):
        ``sched``/``best_state`` carry across calls. ``pad_to`` pads the
        scan length so a shorter final chunk reuses the compiled program
        (padded epochs are inert and trimmed from the returned series).

        Returns ``(state, best_state, sched, rng, series)`` where ``rng`` is
        the advanced key and ``series`` is a dict of numpy arrays over
        epochs: ``train_loss``, ``val_loss``, ``test_loss``, ``lr``,
        ``stopped``, ``train_tasks [E, T]`` — NaN rows mark epochs skipped
        after early stop fired.
        """
        nb = jax.tree_util.tree_leaves(staged_train)[0].shape[0]
        cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
        n_use = min(nb, int(cap)) if cap is not None else nb
        n_sched = max(num_epoch, pad_to or 0)
        rng, prng = jax.random.split(rng)
        if shuffle:
            perms = jax.vmap(
                lambda k: jax.random.permutation(k, nb)[:n_use]
            )(jax.random.split(prng, n_sched))
        else:
            perms = jnp.tile(jnp.arange(n_use), (n_sched, 1))
        subs = jax.random.split(rng, n_sched * n_use + 1)
        rng = subs[0]
        erngs = subs[1:].reshape(n_sched, n_use, -1)
        active = jnp.arange(n_sched) < num_epoch
        if sched is None:
            sched = SchedState.init()
            if self.mesh is not None:
                sched = jax.tree_util.tree_map(jnp.asarray, sched)
        if best_state is None:
            # explicit copy: ``state`` is donated, the snapshot must not
            # alias its buffers. One jitted dispatch — eager per-leaf copies
            # would cost ~a hundred dispatches on high-latency backends.
            best_state = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t)
            )(state)
        tr.start("train")
        state, best_state, sched, series = self._fit_scan(
            state, best_state, sched, staged_train, staged_val,
            staged_test, perms, erngs, active,
        )
        series = np.asarray(series)[:num_epoch]  # the single readback
        tr.stop("train")
        out = {
            "train_loss": series[:, 0],
            "val_loss": series[:, 1],
            "test_loss": series[:, 2],
            "lr": series[:, 3],
            "stopped": series[:, 4] > 0.5,
            "train_tasks": series[:, 5:],
        }
        return state, best_state, sched, rng, out

    # ---- epoch loops ---------------------------------------------------
    @staticmethod
    def _acc_add(acc, metrics, multi):
        """Collect per-batch epoch metrics WITHOUT a host readback: each
        batch appends one packed [loss_sum, graph_count, task_sums...]
        device vector to ``acc`` — per-batch ``float(...)`` fetches cost a
        full round trip each on TPU backends AND serialize the dispatch
        pipeline. :meth:`_acc_read` stacks the parts, does the epoch's ONE
        readback, and sums in float64 on the host (exact, unlike a
        sequential on-device f32 running sum).

        Multi-host: eager jnp ops on jit outputs spanning non-addressable
        devices are disallowed — fall back to the (permitted) per-batch
        host fetch of the replicated scalars, as before this optimization.
        """
        g32 = metrics["num_graphs"]
        if jax.process_count() > 1:
            g = np.asarray(g32, np.float64)
            t = np.asarray(metrics["tasks"], np.float64)
            loss = np.asarray(metrics["loss"], np.float64)
            if multi:
                part = np.concatenate([[loss @ g], [g.sum()], t.T @ g])
            else:
                part = np.concatenate([[loss * g], [g], t * g])
        else:
            g32 = g32.astype(jnp.float32)
            t = metrics["tasks"].astype(jnp.float32)
            if multi:  # stacked [K] / [K, T] from a scan
                part = jnp.concatenate(
                    [(metrics["loss"] @ g32)[None], g32.sum()[None], t.T @ g32]
                )
            else:
                part = jnp.concatenate(
                    [(metrics["loss"] * g32)[None], g32[None], t * g32]
                )
        acc = [] if acc is None else acc
        acc.append(part)
        return acc

    @staticmethod
    def _acc_read(acc):
        """(avg_loss, per-task avg): one readback, float64 host summation."""
        if not acc:
            return 0.0, np.zeros(0)
        if isinstance(acc[0], np.ndarray):
            a = np.stack(acc).astype(np.float64).sum(axis=0)
        else:
            a = (
                np.asarray(jnp.stack(acc), np.float64).sum(axis=0)
            )  # the epoch's single readback
        n = max(a[1], 1.0)
        return a[0] / n, a[2:] / n

    def train_epoch(self, state, loader, rng):
        acc = None
        nbatch = _nbatch(loader)
        K = max(1, self.steps_per_dispatch)
        pending = []
        tr.start("train")

        def _flush(state, rng, acc, group):
            if len(group) > 1:
                from hydragnn_tpu.graph.batch import stack_batches

                tr.start("dataload")
                stacked = self.put_batch_stacked(stack_batches(group))
                tr.stop("dataload")
                subs = jax.random.split(rng, len(group) + 1)
                rng = subs[0]
                tr.start("train_step")
                state, metrics = self._train_multi(state, stacked, subs[1:])
                tr.stop("train_step")
                return state, rng, self._acc_add(acc, metrics, multi=True)
            tr.start("dataload")
            batch = self.put_batch(group[0])
            tr.stop("dataload")
            rng, sub = jax.random.split(rng)
            tr.start("train_step")
            state, metrics = self._train_step(state, batch, sub)
            tr.stop("train_step")
            return state, rng, self._acc_add(acc, metrics, multi=False)

        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            if K == 1:
                state, rng, acc = _flush(state, rng, acc, [batch])
                continue
            pending.append(batch)
            if len(pending) == K:
                state, rng, acc = _flush(state, rng, acc, pending)
                pending = []
        # trailing partial group: single-step path (a short stack would be a
        # fresh scan-length compile)
        for batch in pending:
            state, rng, acc = _flush(state, rng, acc, [batch])
        loss, tasks = self._acc_read(acc)  # the epoch's one readback
        tr.stop("train")
        return state, rng, loss, tasks

    def evaluate(self, state, loader, desc="validate"):
        acc = None
        nbatch = _nbatch(loader)
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            batch = self.put_batch(batch)
            metrics = self._eval_step(state.params, state.batch_stats, batch)
            acc = self._acc_add(acc, metrics, multi=False)
        return self._acc_read(acc)

    def predict(self, state, loader):
        """Full test pass with sample collection — the reference's ``test()``
        with return_samples (``train_validate_test.py:588-698``). Returns
        (avg loss, per-task avg, true_values, predicted_values) with per-head
        flattened [num_values, 1] arrays."""
        num_heads = self.model.num_heads
        head_types = self.model.output_type
        tot = 0.0
        tasks = None
        n = 0.0
        true_values = [[] for _ in range(num_heads)]
        predicted_values = [[] for _ in range(num_heads)]
        nbatch = _nbatch(loader)

        # device-resident fast path (single-process): run the whole test
        # set as ONE scan and do ONE readback — per-batch output fetches
        # cost a full host round trip each on tunneled backends. Own knob
        # (default: follows the training-set flag) because the TEST set +
        # stacked outputs have their own HBM footprint; non-uniform batch
        # shapes or an over-budget stage fall back to streaming.
        device_resident = _env_flag(
            "HYDRAGNN_PREDICT_DEVICE_RESIDENT",
            self.training_config,
            "predict_device_resident",
            default=_env_flag(
                "HYDRAGNN_DEVICE_RESIDENT",
                self.training_config,
                "device_resident_dataset",
            ),
        )
        if device_resident and (self.mesh is None or jax.process_count() == 1):
            host_batches = []
            for ibatch, batch in enumerate(loader):
                if ibatch >= nbatch:
                    break
                host_batches.append(batch)
            try:
                # only the two documented failure modes trigger the
                # fallback: ragged shapes (stack raises ValueError) and the
                # host-side budget estimate (MemoryError)
                stacked = self._stack_for_predict(host_batches)
            except (ValueError, MemoryError):
                loader = host_batches
            else:
                try:
                    return self._predict_device_resident(
                        state, host_batches, stacked
                    )
                except Exception as e:
                    # memory exhaustion (host or device) falls back to
                    # streaming; anything else is a genuine bug
                    if _is_oom(e):
                        loader = host_batches
                    else:
                        raise
                finally:
                    # don't hold the second full host copy of the test set
                    # through a (memory-pressured) streaming fallback
                    del stacked

        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            dev_batch = self.put_batch(batch)
            metrics = self._eval_step(
                state.params, state.batch_stats, dev_batch
            )
            g = float(metrics["num_graphs"])
            tot += float(metrics["loss"]) * g
            t = np.asarray(metrics["tasks"]) * g
            tasks = t if tasks is None else tasks + t
            n += g
            outputs = metrics["outputs"]
            if self.mesh is not None and jax.process_count() > 1:
                # global data-sharded arrays span non-addressable devices;
                # bring back THIS process's shard — rows then line up with
                # the local host batch masks (per-rank collection, like the
                # reference's per-rank test() loop)
                from jax.experimental import multihost_utils
                from jax.sharding import PartitionSpec as P

                outputs = multihost_utils.global_array_to_host_local_array(
                    outputs, self.mesh, jax.tree_util.tree_map(
                        lambda _: P("data"), outputs
                    )
                )
            outputs = jax.device_get(outputs)
            self._collect_head_values(
                batch, outputs, true_values, predicted_values
            )
        return self._predict_finish(tot, tasks, n, true_values, predicted_values)

    # allow roughly half a v5e HBM for (staged test set + stacked outputs);
    # beyond that the streaming path is the safe default. Best-effort only:
    # it cannot see HBM already held by staged training data / params — the
    # caller additionally catches the device's own RESOURCE_EXHAUSTED.
    _PREDICT_STAGE_BUDGET_BYTES = 8 * 1024**3

    def _collect_head_values(
        self, batch, outputs, true_values, predicted_values
    ):
        """Append one batch's masked per-head (true, pred) rows — shared by
        the streaming and device-resident predict paths."""
        graph_mask = np.asarray(batch.graph_mask)
        node_mask = np.asarray(batch.node_mask)
        for ihead in range(self.model.num_heads):
            mask = (
                graph_mask
                if self.model.output_type[ihead] == "graph"
                else node_mask
            )
            true = np.asarray(batch.targets[ihead])[mask]
            # NLL mode appends a log-variance channel — collected values
            # are the mean prediction only
            pred = np.asarray(outputs[ihead])[mask][..., : true.shape[-1]]
            pred = pred.reshape(-1, 1)
            true = true.reshape(-1, 1)
            predicted_values[ihead].append(pred)
            true_values[ihead].append(true)

    def _stack_for_predict(self, host_batches):
        """Stack + host-side budget estimate for the staged predict path.
        Raises ValueError (ragged shapes) or MemoryError (over budget)."""
        from hydragnn_tpu.graph.batch import stack_batches

        stacked = stack_batches(host_batches)  # ValueError if ragged
        stage_bytes = sum(
            a.nbytes
            for a in jax.tree_util.tree_leaves(stacked)
            if hasattr(a, "nbytes")
        )
        nb = len(host_batches)
        out_rows = {
            "graph": host_batches[0].graph_mask.shape[0],
            "node": host_batches[0].node_mask.shape[0],
        }
        out_bytes = sum(
            nb * out_rows[t] * d * 4
            for t, d in zip(self.model.output_type, self.model.output_dim)
        )
        if stage_bytes + out_bytes > self._PREDICT_STAGE_BUDGET_BYTES:
            raise MemoryError(
                f"staged predict would need {stage_bytes + out_bytes} bytes"
            )
        return stacked

    def _predict_device_resident(self, state, host_batches, stacked):
        """One-scan, one-readback predict over a staged test set."""
        num_heads = self.model.num_heads
        staged = self.put_batch_stacked(stacked)
        loss_b, tasks_b, g_b, outputs_b = jax.device_get(
            self._predict_scan(state.params, state.batch_stats, staged)
        )
        g_arr = np.asarray(g_b, np.float64)
        tot = float(np.asarray(loss_b, np.float64) @ g_arr)
        tasks = (np.asarray(tasks_b, np.float64) * g_arr[:, None]).sum(0)
        n = float(g_arr.sum())
        true_values = [[] for _ in range(num_heads)]
        predicted_values = [[] for _ in range(num_heads)]
        for ib, batch in enumerate(host_batches):
            self._collect_head_values(
                batch,
                [outputs_b[ihead][ib] for ihead in range(num_heads)],
                true_values,
                predicted_values,
            )
        return self._predict_finish(tot, tasks, n, true_values, predicted_values)

    def _predict_finish(self, tot, tasks, n, true_values, predicted_values):
        """Shared tail of both predict paths: concat, optional test-data
        dump, averaged metrics."""
        n = max(n, 1.0)
        true_values = [np.concatenate(v, axis=0) for v in true_values]
        predicted_values = [np.concatenate(v, axis=0) for v in predicted_values]
        dump = os.getenv("HYDRAGNN_DUMP_TESTDATA")
        if dump:
            # per-rank test-prediction dump (train_validate_test.py:602);
            # an explicit path gets the rank embedded so multi-host ranks
            # cannot clobber each other
            rank = jax.process_index()
            if dump == "1":
                path = f"testdata_rank{rank}.npz"
            elif jax.process_count() > 1:
                root, ext = os.path.splitext(dump)
                path = f"{root}_rank{rank}{ext or '.npz'}"
            else:
                path = dump
            np.savez(
                path,
                **{f"true_{i}": v for i, v in enumerate(true_values)},
                **{f"pred_{i}": v for i, v in enumerate(predicted_values)},
            )
        return (
            tot / n,
            (tasks / n if tasks is not None else np.zeros(0)),
            true_values,
            predicted_values,
        )


def train_validate_test(
    trainer: Trainer,
    state: TrainState,
    train_loader,
    val_loader,
    test_loader,
    config_nn: dict,
    log_name: str,
    verbosity: int = 0,
    writer=None,
    create_plots: bool = False,
    plot_init_solution: bool = False,
):
    """Epoch driver (``train_validate_test.py:54-250``)."""
    training = config_nn["Training"]
    num_epoch = training["num_epoch"]
    early = EarlyStopping(training.get("patience", 5)) if training.get(
        "EarlyStopping", False
    ) else None
    ckpt = (
        BestCheckpoint(log_name, warmup=training.get("checkpoint_warmup", 10))
        if training.get("Checkpoint", False)
        else None
    )
    scheduler = ReduceLROnPlateau(lr=get_learning_rate(state.opt_state))
    rng = jax.random.PRNGKey(1337)

    visualizer = None
    if create_plots:
        from hydragnn_tpu.postprocess.visualizer import Visualizer

        node_feature = []
        nodes_num_list = []
        for d in test_loader.dataset:
            node_feature.extend(np.asarray(d.x).tolist())
            nodes_num_list.append(d.num_nodes)
        visualizer = Visualizer(
            log_name,
            node_feature=node_feature,
            num_heads=trainer.model.num_heads,
            head_dims=list(trainer.model.output_dim),
            num_nodes_list=nodes_num_list,
        )
        visualizer.num_nodes_plot()
        if plot_init_solution:
            _, _, true_values, predicted_values = trainer.predict(
                state, test_loader
            )
            visualizer.create_scatter_plots(
                true_values,
                predicted_values,
                output_names=config_nn["Variables_of_interest"].get(
                    "output_names"
                ),
                iepoch=-1,
            )

    total_loss_train = np.zeros(num_epoch)
    total_loss_val = np.zeros(num_epoch)
    total_loss_test = np.zeros(num_epoch)
    skip_valtest = int(os.getenv("HYDRAGNN_VALTEST", "1")) == 0

    # device-resident mode: stage the (collated) training set in HBM once;
    # every epoch is then a single scan dispatch with no H2D traffic
    staged = None
    if _env_flag("HYDRAGNN_DEVICE_RESIDENT", training, "device_resident_dataset"):
        staged = trainer.stage_batches(list(train_loader))

    # whole-training dispatch: fit_chunk_epochs > 0 runs training in chunks
    # of N epochs, each chunk ONE XLA program (on-device plateau LR, early
    # stop, best-state tracking); host work between chunks only — logging,
    # TensorBoard, checkpoint, SLURM wall-clock guard
    fit_chunk = int(
        os.getenv(
            "HYDRAGNN_FIT_CHUNK", str(training.get("fit_chunk_epochs", 0))
        )
    )
    def _log_epoch(ep, train_loss, val_loss, test_loss, train_tasks):
        total_loss_train[ep] = train_loss
        total_loss_val[ep] = val_loss
        total_loss_test[ep] = test_loss
        print_distributed(
            verbosity,
            f"Epoch: {ep:04d}, Train Loss: {train_loss:.8f}, "
            f"Val Loss: {val_loss:.8f}, Test Loss: {test_loss:.8f}",
        )
        if writer is not None:
            writer.add_scalar("train error", train_loss, ep)
            writer.add_scalar("validate error", val_loss, ep)
            writer.add_scalar("test error", test_loss, ep)
            for itask, tl in enumerate(np.atleast_1d(train_tasks)):
                writer.add_scalar(f"train error of task {itask}", float(tl), ep)

    ran_fit = staged is not None and fit_chunk > 0
    if ran_fit:
        staged_val = (
            None if skip_valtest else trainer.stage_batches(list(val_loader))
        )
        staged_test = (
            None if skip_valtest else trainer.stage_batches(list(test_loader))
        )
        from hydragnn_tpu.parallel.distributed import check_remaining

        sched = None
        best_state = None
        best_saved = np.inf
        epoch0 = 0
        # full sample->batch reshuffle at chunk boundaries (the staged scan
        # only permutes batch ORDER within a chunk; this restores the
        # reference DistributedSampler's per-epoch sample shuffling at
        # chunk granularity, at the price of re-staging H2D per chunk)
        restage = _env_flag(
            "HYDRAGNN_RESTAGE_PER_CHUNK", training, "restage_per_chunk"
        )
        while epoch0 < num_epoch:
            n = min(fit_chunk, num_epoch - epoch0)
            if restage and epoch0 > 0:
                train_loader.set_epoch(epoch0)
                # release the old stack FIRST — holding it through the
                # re-stage would double the training set's HBM footprint
                staged = None
                staged = trainer.stage_batches(list(train_loader))
            t0 = time.time()
            # pad_to keeps every chunk at the same scan length — the short
            # final chunk must not recompile the whole-training program
            state, best_state, sched, rng, series = trainer.fit_staged(
                state,
                staged,
                n,
                rng,
                staged_val=staged_val,
                staged_test=staged_test,
                sched=sched,
                best_state=best_state,
                pad_to=fit_chunk,
            )
            chunk_time = time.time() - t0
            for i in range(n):
                if np.isnan(series["train_loss"][i]):
                    continue
                _log_epoch(
                    epoch0 + i,
                    series["train_loss"][i],
                    series["val_loss"][i],
                    series["test_loss"][i],
                    series["train_tasks"][i],
                )
            # persist the best state after every chunk that improved it —
            # a preempted job resumes from the last improvement, like the
            # reference's per-epoch BestCheckpoint (utils/model.py:207-248)
            if ckpt is not None:
                bv = float(np.asarray(sched.best_val))
                if np.isfinite(bv) and bv < best_saved:
                    save_model(best_state, log_name, ckpt.path)
                    best_saved = bv
            epoch0 += n
            if bool(np.asarray(sched.stopped)):
                ep_stop = epoch0 - n + int(np.argmax(series["stopped"]))
                print_distributed(
                    verbosity, f"Early stopping at epoch {ep_stop}"
                )
                break
            # the next unit of work is an indivisible fit_chunk-epoch
            # dispatch — reserve a whole chunk's wall time, not one epoch's
            if not check_remaining(chunk_time):
                print_distributed(
                    verbosity, "Stopping: not enough job wall-clock time left"
                )
                break

    epoch_time = 0.0
    staged_evals = None
    for epoch in range(num_epoch if not ran_fit else 0):
        t0 = time.time()
        train_loader.set_epoch(epoch)
        if staged is not None:
            state, rng, train_loss, train_tasks = trainer.train_epoch_staged(
                state, staged, rng
            )
        else:
            state, rng, train_loss, train_tasks = trainer.train_epoch(
                state, train_loader, rng
            )
        if skip_valtest:
            val_loss, val_tasks = train_loss, train_tasks
            test_loss, test_tasks = train_loss, train_tasks
        elif staged is not None:
            # device-resident epoch driver: evals run staged too (one
            # dispatch + one readback per split, no per-batch H2D). Any
            # staging/dispatch memory failure downgrades PERMANENTLY to the
            # streaming evaluate — the eval sets have their own footprint
            # on top of the staged training set.
            if staged_evals is None:
                try:
                    vb, tb = list(val_loader), list(test_loader)
                    if not vb or not tb:
                        raise ValueError("empty eval loader")
                    staged_evals = (
                        trainer.stage_batches(vb),
                        trainer.stage_batches(tb),
                    )
                except Exception as e:
                    if isinstance(e, ValueError) or _is_oom(e):
                        staged_evals = False
                    else:
                        raise
            if staged_evals:
                try:
                    val_loss, val_tasks = trainer.evaluate_staged(
                        state, staged_evals[0]
                    )
                    test_loss, test_tasks = trainer.evaluate_staged(
                        state, staged_evals[1]
                    )
                except Exception as e:
                    if _is_oom(e):
                        staged_evals = False
                    else:
                        raise
            if not staged_evals:
                val_loss, val_tasks = trainer.evaluate(state, val_loader)
                test_loss, test_tasks = trainer.evaluate(state, test_loader)
        else:
            val_loss, val_tasks = trainer.evaluate(state, val_loader)
            test_loss, test_tasks = trainer.evaluate(state, test_loader)

        new_lr = scheduler.step(val_loss)
        if abs(new_lr - get_learning_rate(state.opt_state)) > 1e-12:
            state = state.replace(
                opt_state=set_learning_rate(state.opt_state, new_lr)
            )

        _log_epoch(epoch, train_loss, val_loss, test_loss, train_tasks)

        if visualizer is not None and visualizer.plot_hist_solution:
            _, _, tv, pv = trainer.predict(state, test_loader)
            visualizer.plot_history(
                total_loss_train[: epoch + 1],
                total_loss_val[: epoch + 1],
                total_loss_test[: epoch + 1],
            )

        if ckpt is not None:
            ckpt(state, epoch, val_loss, save_model)
        if early is not None and early(val_loss):
            print_distributed(verbosity, f"Early stopping at epoch {epoch}")
            break

        epoch_time = time.time() - t0
        from hydragnn_tpu.parallel.distributed import check_remaining

        if not check_remaining(epoch_time):
            print_distributed(
                verbosity, "Stopping: not enough job wall-clock time left"
            )
            break

    if visualizer is not None:
        _, _, true_values, predicted_values = trainer.predict(state, test_loader)
        visualizer.plot_history(
            total_loss_train,
            total_loss_val,
            total_loss_test,
        )
        visualizer.create_plot_global(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
        visualizer.create_scatter_plots(
            true_values,
            predicted_values,
            output_names=config_nn["Variables_of_interest"].get("output_names"),
        )
    return state
