"""The ``Trainer``: state management, device placement, and epoch loops.

TPU-first redesign of ``hydragnn/train/train_validate_test.py``: instead of
an imperative hot loop (zero_grad / forward / backward / step as separate
CUDA launches, ``:437-540``), ONE XLA program per training step — forward,
masked multi-task loss, backward, optimizer update and BatchNorm-stat
update fused by the compiler. Data parallelism comes from sharding the
batch over the mesh's ``data`` axis; gradient all-reduce is inserted by
XLA over ICI (no NCCL, no DDP hooks).

Round-3 split (verdict item 10): the traced programs live in
``steps.py`` (:func:`~hydragnn_tpu.train.steps.build_steps`), the wire
format in ``transfer.py``, the predict paths in ``predict.py``
(:class:`~hydragnn_tpu.train.predict.PredictMixin`), the epoch driver in
``epoch_driver.py``, and shared state containers in ``common.py``. This
module re-exports the public names so existing imports keep working.
"""

import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.obs import runtime as obs
from hydragnn_tpu.models.create import init_model_params
from hydragnn_tpu.train.common import (  # noqa: F401  (re-exported API)
    SchedState,
    TrainState,
    _env_flag,
    _is_oom,
    _nbatch,
)
from hydragnn_tpu.train.epoch_driver import (  # noqa: F401  (re-exported)
    train_validate_test,
)
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.predict import PredictMixin
from hydragnn_tpu.train.steps import build_steps
from hydragnn_tpu.train.transfer import (  # noqa: F401  (re-exported API)
    _decompact_traced,
    _offset_local_shard,
)
from hydragnn_tpu.utils import tracer as tr

# cached at module scope: a fresh ``jax.jit(lambda ...)`` built at the call
# site re-traces on EVERY invocation (the jit cache keys on function object
# identity) — one deep-copy program serves every fit_staged best-state seed
_copy_tree = jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))


class Trainer(PredictMixin):
    def __init__(
        self,
        model,
        training_config: dict,
        mesh=None,
        verbosity: int = 0,
        freeze_conv: bool = False,
    ):
        # every Trainer front-door (driver, examples, benches) gets the
        # persistent XLA cache; idempotent, and on the tunneled backend it
        # is worth ~25 s of sub-second recompiles per process startup
        from hydragnn_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        self.model = model
        self.training_config = training_config
        self.mesh = mesh
        self.verbosity = verbosity
        self.freeze_conv = freeze_conv
        self.tx = None
        self._steps = None
        self._batch_sharding = None
        self._stacked_sharding = None
        # rule-engine state placement (parallel/rules.py), computed by
        # place_state and declared as the step programs' in/out shardings
        self._state_shardings = None
        self._sharding_summary = None
        # one dispatch runs this many optimizer steps via lax.scan (1 = the
        # plain per-batch path); settable in config or HYDRAGNN_STEPS_PER_DISPATCH
        from hydragnn_tpu.utils.envparse import env_int

        self.steps_per_dispatch = env_int(
            "HYDRAGNN_STEPS_PER_DISPATCH",
            int(training_config.get("steps_per_dispatch", 1)),
        )
        # streaming double-buffering: keep this many batches' H2D transfers
        # in flight AHEAD of the step consuming them, issued from a
        # background thread (the role of the reference's DDStore
        # double-buffered loader, train_validate_test.py:459-536). Costs
        # `depth` extra batches of HBM. Default OFF: measured A/B on the
        # tunneled dev chip (benchmarks/streaming_bench.py, BASELINE.md)
        # shows the extra in-flight RPCs CONTEND with dispatch there
        # (0.64x); jax's async dispatch already overlaps transfer and
        # compute when the host link is not the bottleneck. Enable on
        # production TPU-VM hosts via config or HYDRAGNN_DEVICE_PREFETCH.
        self.device_prefetch = env_int(
            "HYDRAGNN_DEVICE_PREFETCH",
            int(training_config.get("device_prefetch", 0)),
        )
        # divergence guard (train/guard.py): skip non-finite steps, restore
        # last-good with halved LR after N consecutive bad ones. Opt-in —
        # it costs a snapshot + a scalar fetch per step.
        from hydragnn_tpu.train.guard import DivergenceGuard, guard_enabled

        self.guard = (
            DivergenceGuard(training_config)
            if guard_enabled(training_config)
            else None
        )
        # process-global optimizer-step counter: drives the fault-injection
        # hooks (kill_at_step / nan_at_step, utils/faults.py)
        self._host_step = 0

    # compiled-program accessors: tests and the partitioned trainer reach
    # these by their historical names
    @property
    def _train_step(self):
        return self._steps.train_step

    @property
    def _train_multi(self):
        return self._steps.train_multi

    @property
    def _epoch_scan(self):
        return self._steps.epoch_scan

    @property
    def _eval_epoch(self):
        return self._steps.eval_epoch

    @property
    def _predict_scan(self):
        return self._steps.predict_scan

    @_predict_scan.setter
    def _predict_scan(self, fn):  # tests monkeypatch this hook
        self._steps.predict_scan = fn

    @property
    def _fit_scan(self):
        return self._steps.fit_scan

    @property
    def _eval_step(self):
        return self._steps.eval_step

    @property
    def _eval_multi(self):
        return self._steps.eval_multi

    # ---- state ---------------------------------------------------------
    def init_state(self, example_batch: GraphBatch, seed: int = 0) -> TrainState:
        if self.mesh is None or jax.process_count() == 1:
            init_batch = self.put_batch(example_batch)
        else:
            # multi-host: init on a process-local copy — parameters depend
            # only on shapes and the seed, so every process derives identical
            # values (flax init cannot trace non-addressable global shards)
            init_batch = jax.tree_util.tree_map(jnp.asarray, example_batch)
        # aggregation autotune warmup (ops/autotune.py, opt-in via
        # HYDRAGNN_AUTOTUNE / Training.autotune_aggregation): measure the
        # example bucket's candidates BEFORE anything traces, so the
        # models' trace-time choice reads a warm cache
        from hydragnn_tpu.ops.autotune import maybe_autotune

        maybe_autotune(self.model, example_batch, self.training_config)
        variables = init_model_params(self.model, init_batch, seed=seed)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self.tx = select_optimizer(
            self.training_config, params=params, freeze_conv=self.freeze_conv
        )
        opt_state = self.tx.init(params)
        state = TrainState(
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )
        state = self.place_state(state)
        self._build_steps()
        return state

    def place_state(self, state: TrainState) -> TrainState:
        """Build the state DIRECTLY at the step programs' input shardings
        — used at init AND after checkpoint restore (a host-restored
        state fed straight in costs a duplicate sharding-signature
        compile; on the 2-D mesh it would hard-error against the
        explicit ``in_shardings``).

        Placement is the rule engine's (``parallel/rules.py``): matmul
        weights column-split over ``model``, biases/norms replicated,
        ZeRO's ``data``-axis overlay on optimizer moments (stage >= 1)
        and parameters (stage 3) — every leaf lands at its target
        sharding in one hop, no host-side replicate-then-reshard (which
        would transiently hold the full state on every device). The
        multi-process path assembles each leaf's global array from the
        identical host-local values (seeded init / restored checkpoint)."""
        if self.mesh is None:
            return jax.tree_util.tree_map(jnp.asarray, state)
        from hydragnn_tpu.parallel import rules

        self._state_shardings = rules.state_shardings(
            state,
            self.mesh,
            zero_stage=self._zero_stage(),
            rules=rules.resolve_rules(self.training_config),
        )
        self._sharding_summary = rules.summarize_shardings(
            state, self._state_shardings
        )
        return rules.put_tree(state, self._state_shardings)

    def sharding_summary(self):
        """Rule-engine placement report of the last ``place_state`` (the
        ``param_sharding`` event payload); None before placement."""
        return self._sharding_summary

    def _zero_stage(self) -> int:
        """Resolved ZeRO stage: ``Training.Optimizer.zero_stage`` (0-3,
        DeepSpeed's scale — ``run_training.py:134-151``); absent, the
        reference's ``use_zero_redundancy`` bool maps to stage 1. Stages
        1 and 2 are one implementation (gradient partitioning is XLA's
        scheduling decision, not a user knob); stage 3 also shards the
        parameters."""
        opt = self.training_config.get("Optimizer", {})
        stage = opt.get("zero_stage")
        if stage is None:
            return 1 if opt.get("use_zero_redundancy") else 0
        return int(stage)

    def _zero_enabled(self) -> bool:
        """ZeRO sharding active? — the reference's ZeroRedundancyOptimizer
        / DeepSpeed-ZeRO switch (``utils/optimizer.py:142-151``). A
        sharding decision, not a different optimizer — XLA inserts the
        all-gathers."""
        return self._zero_stage() >= 1

    def _compact_for_transfer(
        self, batch: GraphBatch, allow_pos_placeholder: bool = True
    ):
        """Shrink the host->device wire format (streaming is H2D-bound;
        undone INSIDE the jitted step by ``_decompact_traced``):

        - index arrays (senders/receivers/node_graph) travel as int16 when
          the node/graph counts fit, and are cast back to int32 on device —
          the jitted step still sees int32, so nothing else changes;
        - ``pos`` is replaced by a ``[..., 1, 3]`` placeholder when the
          model never reads positions (no distance/coordinate convs, no
          equivariance); the step synthesizes a device-side fill. Disabled
          under a mesh (``allow_pos_placeholder=False``): a 1-row axis
          cannot shard over the data axis.

        Applies to single-process transfers (plain and mesh-sharded); the
        multi-host assembly path ships uncompacted. ``compact_transfer`` /
        ``HYDRAGNN_COMPACT_TRANSFER`` (default on) disables it entirely.
        """
        if not _env_flag(
            "HYDRAGNN_COMPACT_TRANSFER", self.training_config,
            "compact_transfer", default=True,
        ):
            return batch
        # shape[-2] of x is the node count for both plain [N, F] and
        # stacked [K, N, F] layouts; n_node's last axis is the graph count
        if batch.x.shape[-2] < 2**15 and batch.n_node.shape[-1] < 2**15:
            batch = batch.replace(
                senders=np.asarray(batch.senders, np.int16),
                receivers=np.asarray(batch.receivers, np.int16),
                node_graph=np.asarray(batch.node_graph, np.int16),
            )
        needs_pos = getattr(self.model, "conv_needs_pos", True) or getattr(
            self.model, "equivariance", False
        )
        if not needs_pos and allow_pos_placeholder:
            placeholder = np.zeros(batch.pos.shape[:-2] + (1, 3), np.float32)
            batch = batch.replace(pos=placeholder)
        return batch

    def put_batch(self, batch: GraphBatch) -> GraphBatch:
        """Host batch -> device(s). Under a mesh, every leading axis (nodes /
        edges / graphs / triplets) is sharded over the ``data`` axis — the
        layout pads each to a multiple of the axis size.

        Multi-host (``jax.process_count() > 1``): each process passes ITS
        loader's local shard (the DistributedSampler split) and the global
        sharded batch is assembled with ``make_array_from_process_local_data``
        — the reference's per-rank DataLoader semantics
        (``preprocess/load_data.py:237-245``) with XLA owning the transport.
        """
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from hydragnn_tpu.parallel.mesh import DATA_AXIS

            if self._batch_sharding is None:
                self._batch_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
            if jax.process_count() > 1:
                batch = _offset_local_shard(batch, jax.process_index())
                return jax.tree_util.tree_map(
                    lambda a: jax.make_array_from_process_local_data(
                        self._batch_sharding, np.asarray(a)
                    ),
                    batch,
                )
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(jnp.asarray(a), self._batch_sharding),
                self._compact_for_transfer(batch, allow_pos_placeholder=False),
            )
        return jax.tree_util.tree_map(
            jnp.asarray, self._compact_for_transfer(batch)
        )

    def put_batch_stacked(self, stacked: GraphBatch) -> GraphBatch:
        """Like :meth:`put_batch` for a ``stack_batches`` result: the scan
        axis stays unsharded, each microbatch's leading axis shards over
        ``data``."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from hydragnn_tpu.parallel.mesh import DATA_AXIS

            if self._stacked_sharding is None:
                self._stacked_sharding = NamedSharding(
                    self.mesh, P(None, DATA_AXIS)
                )
            if jax.process_count() > 1:
                stacked = _offset_local_shard(stacked, jax.process_index())
                return jax.tree_util.tree_map(
                    lambda a: jax.make_array_from_process_local_data(
                        self._stacked_sharding, np.asarray(a)
                    ),
                    stacked,
                )
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(jnp.asarray(a), self._stacked_sharding),
                self._compact_for_transfer(
                    stacked, allow_pos_placeholder=False
                ),
            )
        return jax.tree_util.tree_map(
            jnp.asarray, self._compact_for_transfer(stacked)
        )

    # ---- compiled steps ------------------------------------------------
    def _build_steps(self):
        self._steps = build_steps(
            self.model,
            self.tx,
            self.training_config,
            mesh=self.mesh,
            state_shardings=self._state_shardings,
        )

    # ---- device-resident dataset --------------------------------------
    def stage_batches(self, batches) -> GraphBatch:
        """Stack same-shape collated batches and park them in HBM once.

        Returns a device-resident epoch usable with
        :meth:`train_epoch_staged`. Use when the (padded) training set fits
        device memory — it removes host->device transfers from the training
        loop entirely, which otherwise bound small-graph workloads."""
        from hydragnn_tpu.graph.batch import stack_batches

        batches = list(batches)
        obs.emit("staged", num_batches=len(batches))
        return self.put_batch_stacked(stack_batches(batches))

    def train_epoch_staged(self, state, staged, rng, shuffle=True):
        """One epoch over an HBM-staged dataset in a single dispatch.

        Shuffling permutes microbatch ORDER each epoch (sample->batch
        assignment is fixed at staging time — the streaming ``train_epoch``
        path reshuffles samples fully; restage periodically if you want
        that here). Returns the same (state, rng, loss, tasks) contract as
        :meth:`train_epoch`."""
        nb = jax.tree_util.tree_leaves(staged)[0].shape[0]
        cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
        n_use = min(nb, int(cap)) if cap is not None else nb
        rng, prng = jax.random.split(rng)
        if shuffle:
            perm = jax.random.permutation(prng, nb)[:n_use]
        else:
            perm = jnp.arange(n_use)
        subs = jax.random.split(rng, n_use + 1)
        rng = subs[0]
        tr.start("train")
        state, metrics = self._epoch_scan(state, staged, perm, subs[1:])
        g = np.asarray(metrics["num_graphs"], np.float64)
        tot = float(np.asarray(metrics["loss"], np.float64) @ g)
        tasks = (np.asarray(metrics["tasks"], np.float64) * g[:, None]).sum(0)
        tr.stop("train")
        # the staged epoch is ONE dispatch with no per-step hook: trace
        # capture (/profile, HYDRAGNN_PROFILE_AT_STEP) ticks per epoch
        obs.dispatch_boundary()
        n = max(float(g.sum()), 1.0)
        return state, rng, tot / n, tasks / n

    def evaluate_staged(self, state, staged):
        """Whole eval set in one dispatch over an HBM-staged stack — the
        staged counterpart of :meth:`evaluate` (same averaged metrics)."""
        loss, tasks = self._eval_epoch(state.params, state.batch_stats, staged)
        return float(np.asarray(loss)), np.asarray(tasks, np.float64)

    def fit_staged(
        self,
        state,
        staged_train,
        num_epoch: int,
        rng,
        staged_val=None,
        staged_test=None,
        shuffle: bool = True,
        sched: Optional[SchedState] = None,
        best_state: Optional[TrainState] = None,
        pad_to: Optional[int] = None,
    ):
        """Run ``num_epoch`` training epochs as ONE device dispatch.

        Everything the reference's epoch driver does per epoch —
        ReduceLROnPlateau on the val loss, EarlyStopping, best-val state
        tracking (the ``Checkpoint`` analog), val+test evaluation — runs on
        device inside a single ``lax.scan`` over epochs; the metric series
        comes back as one packed array, i.e. ONE host readback per call.
        Call it in chunks (e.g. 10 epochs at a time) when host-side
        per-epoch actions are needed (TensorBoard, SLURM wall-clock guard):
        ``sched``/``best_state`` carry across calls. ``pad_to`` pads the
        scan length so a shorter final chunk reuses the compiled program
        (padded epochs are inert and trimmed from the returned series).

        Returns ``(state, best_state, sched, rng, series)`` where ``rng`` is
        the advanced key and ``series`` is a dict of numpy arrays over
        epochs: ``train_loss``, ``val_loss``, ``test_loss``, ``lr``,
        ``stopped``, ``train_tasks [E, T]`` — NaN rows mark epochs skipped
        after early stop fired.
        """
        nb = jax.tree_util.tree_leaves(staged_train)[0].shape[0]
        cap = os.getenv("HYDRAGNN_MAX_NUM_BATCH")
        n_use = min(nb, int(cap)) if cap is not None else nb
        n_sched = max(num_epoch, pad_to or 0)
        rng, prng = jax.random.split(rng)
        if shuffle:
            perms = jax.vmap(
                lambda k: jax.random.permutation(k, nb)[:n_use]
            )(jax.random.split(prng, n_sched))
        else:
            perms = jnp.tile(jnp.arange(n_use), (n_sched, 1))
        subs = jax.random.split(rng, n_sched * n_use + 1)
        rng = subs[0]
        erngs = subs[1:].reshape(n_sched, n_use, -1)
        active = jnp.arange(n_sched) < num_epoch
        if sched is None:
            sched = SchedState.init()
            if self.mesh is not None:
                sched = jax.tree_util.tree_map(jnp.asarray, sched)
        if best_state is None:
            # explicit copy: ``state`` is donated, the snapshot must not
            # alias its buffers. One jitted dispatch — eager per-leaf copies
            # would cost ~a hundred dispatches on high-latency backends.
            best_state = _copy_tree(state)
        tr.start("train")
        state, best_state, sched, series = self._fit_scan(
            state, best_state, sched, staged_train, staged_val,
            staged_test, perms, erngs, active,
        )
        series = np.asarray(series)[:num_epoch]  # the single readback
        tr.stop("train")
        out = {
            "train_loss": series[:, 0],
            "val_loss": series[:, 1],
            "test_loss": series[:, 2],
            "lr": series[:, 3],
            "stopped": series[:, 4] > 0.5,
            "train_tasks": series[:, 5:],
        }
        return state, best_state, sched, rng, out

    # ---- epoch loops ---------------------------------------------------
    @staticmethod
    def _acc_add(acc, metrics, multi):
        """Collect per-batch epoch metrics WITHOUT a host readback: each
        batch appends one packed [loss_sum, graph_count, task_sums...]
        device vector to ``acc`` — per-batch ``float(...)`` fetches cost a
        full round trip each on TPU backends AND serialize the dispatch
        pipeline. :meth:`_acc_read` stacks the parts, does the epoch's ONE
        readback, and sums in float64 on the host (exact, unlike a
        sequential on-device f32 running sum).

        Multi-host: eager jnp ops on jit outputs spanning non-addressable
        devices are disallowed — fall back to the (permitted) per-batch
        host fetch of the replicated scalars, as before this optimization.
        """
        g32 = metrics["num_graphs"]
        if jax.process_count() > 1:
            g = np.asarray(g32, np.float64)
            t = np.asarray(metrics["tasks"], np.float64)
            loss = np.asarray(metrics["loss"], np.float64)
            if multi:
                part = np.concatenate([[loss @ g], [g.sum()], t.T @ g])
            else:
                part = np.concatenate([[loss * g], [g], t * g])
        else:
            g32 = g32.astype(jnp.float32)
            t = metrics["tasks"].astype(jnp.float32)
            if multi:  # stacked [K] / [K, T] from a scan
                part = jnp.concatenate(
                    [(metrics["loss"] @ g32)[None], g32.sum()[None], t.T @ g32]
                )
            else:
                part = jnp.concatenate(
                    [(metrics["loss"] * g32)[None], g32[None], t * g32]
                )
        acc = [] if acc is None else acc
        acc.append(part)
        return acc

    @staticmethod
    def _acc_read(acc):
        """(avg_loss, per-task avg): one readback, float64 host summation."""
        if not acc:
            return 0.0, np.zeros(0)
        if isinstance(acc[0], np.ndarray):
            a = np.stack(acc).astype(np.float64).sum(axis=0)
        else:
            # the epoch's single readback — EXPLICIT device_get, so the
            # transfer-guard harness (analysis/guards.py no_host_syncs)
            # can hard-error every implicit fetch in the epoch loop while
            # this one sanctioned transfer passes
            a = np.asarray(jax.device_get(jnp.stack(acc)), np.float64).sum(
                axis=0
            )
        n = max(a[1], 1.0)
        return a[0] / n, a[2:] / n

    def _prefetch_put(self, loader, nbatch, depth, put=None,
                      ledger_waits=True):
        """Yield device-resident batches with up to ``depth`` transfers in
        flight ahead of the consumer. The transfers are issued from a
        background thread (shared :func:`prefetch_iter` machinery): both
        halves of a put's cost — the host-side compaction/assembly (numpy,
        releases the GIL) and the H2D copy (async RPC on the tunneled
        link) — overlap the steps already dispatched on earlier batches.
        ``depth <= 0`` degrades to the strict transfer/step alternation."""
        put = put or self.put_batch
        # goodput ledger (obs/ledger.py): the wall the consumer spends
        # waiting on the data plane is the data_stall category — resolved
        # once per epoch like the trainer's step hook. Callers whose
        # source loader reports its OWN stalls (StreamLoader via
        # stream_epoch_stats) pass ledger_waits=False so the same starved
        # seconds are not attributed twice.
        _telemetry = obs.active() if ledger_waits else None
        _ledger = _telemetry.ledger if _telemetry is not None else None

        def limited():
            for ibatch, batch in enumerate(loader):
                if ibatch >= nbatch:
                    break
                yield batch

        if depth <= 0:
            for batch in limited():
                tr.start("dataload")
                t0 = time.perf_counter() if _ledger is not None else 0.0
                dev = put(batch)
                if _ledger is not None:
                    _ledger.data_wait(time.perf_counter() - t0)
                tr.stop("dataload")
                yield dev
            return
        from hydragnn_tpu.data.loaders import prefetch_iter

        it = prefetch_iter(
            limited(), depth, fn=put, name="hydragnn-device-prefetch"
        )
        while True:
            tr.start("dataload")  # time spent WAITING on the transfer stage
            t0 = time.perf_counter() if _ledger is not None else 0.0
            try:
                try:
                    item = next(it)
                except StopIteration:
                    return
            finally:
                # a worker-side error re-raised by next(it) must not leave
                # the dataload timer running for the rest of the process
                if _ledger is not None:
                    _ledger.data_wait(time.perf_counter() - t0)
                tr.stop("dataload")
            yield item

    @staticmethod
    def _group_plan(loader, nbatch, K):
        """Host-side dispatch plan: yield ``K``-long shape-uniform groups
        (the multi-step scan path) and single batches (everything else).
        Only FULL K-groups take the scan — a partial group would compile a
        fresh scan program per novel length (bucketed layouts hit this at
        every segment boundary) — so partial groups stream through the
        single-step program."""

        def _shape_key(b):
            # ALL leaf shapes (incl. extras: triplet tables, neighbor
            # lists) — two buckets can share node/edge/graph pads while
            # their t_pad or k widths differ, and those must not stack
            return tuple(
                tuple(a.shape) for a in jax.tree_util.tree_leaves(b)
            )

        pending = []
        for ibatch, batch in enumerate(loader):
            if ibatch >= nbatch:
                break
            if K == 1:
                yield [batch]
                continue
            # bucketed layouts interleave batch shapes; a stack group must
            # be shape-uniform, so a shape change flushes the open group
            if pending and _shape_key(batch) != _shape_key(pending[0]):
                for b in pending:
                    yield [b]
                pending = []
            pending.append(batch)
            if len(pending) == K:
                yield pending
                pending = []
        for b in pending:  # trailing partial group: single-step path
            yield [b]

    def _put_group(self, group):
        """Transfer stage: a group becomes (device_payload, count). Runs on
        the prefetch thread when ``device_prefetch > 0`` — so stacked
        multi-step transfers double-buffer exactly like single batches."""
        if len(group) > 1:
            from hydragnn_tpu.graph.batch import stack_batches

            return self.put_batch_stacked(stack_batches(group)), len(group)
        return self.put_batch(group[0]), 1

    def train_epoch(self, state, loader, rng):
        from hydragnn_tpu.train import elastic
        from hydragnn_tpu.utils import faults

        acc = None
        nbatch = _nbatch(loader)
        guard = self.guard
        # the guard must isolate ONE step to skip it; stacked multi-step
        # dispatches apply K updates atomically, so guarded runs stream
        K = 1 if guard is not None else max(1, self.steps_per_dispatch)
        if guard is not None and guard.last_good is None:
            guard.commit(state)
        tr.start("train")
        # resolved once per epoch: the per-step telemetry hooks must cost
        # one global read when observability is off
        _telemetry = obs.active()
        plan = self._group_plan(loader, nbatch, K)
        for dev, count in self._prefetch_put(
            plan, float("inf"), self.device_prefetch, put=self._put_group,
            ledger_waits=not getattr(loader, "reports_stream_stats", False),
        ):
            if count > 1:
                subs = jax.random.split(rng, count + 1)
                rng = subs[0]
                tr.start("train_step")
                t0 = time.perf_counter() if _telemetry is not None else 0.0
                # straggler injection INSIDE the timed window (after t0):
                # the delay must reach on_step -> flight recorder, or the
                # stall detection the fault exists to exercise never sees
                # it. Every step id the K-group covers gets its check,
                # same as the kill loop below.
                for s in range(self._host_step, self._host_step + count):
                    faults.slow_step(s)
                state, metrics = self._train_multi(state, dev, subs[1:])
                if _telemetry is not None:
                    # the full per-step hook: metrics + flight recorder
                    # (stall alerts) + on-demand trace-capture ticks
                    _telemetry.on_step(time.perf_counter() - t0, count)
                tr.stop("train_step")
                acc = self._acc_add(acc, metrics, multi=True)
                first = self._host_step
                self._host_step += count
                elastic.note_step(self._host_step)
                for s in range(first, self._host_step):
                    faults.kill_at_step(s)
                    faults.lose_host_at_step(s)
            else:
                if faults.nan_at_step(self._host_step):
                    dev = dev.replace(x=dev.x * jnp.nan)
                prev = None if guard is None else guard.snapshot(state)
                rng, sub = jax.random.split(rng)
                tr.start("train_step")
                t0 = time.perf_counter() if _telemetry is not None else 0.0
                # inside the timed window — see the multi-step branch
                faults.slow_step(self._host_step)
                state, metrics = self._train_step(state, dev, sub)
                if _telemetry is not None:
                    _telemetry.on_step(time.perf_counter() - t0)
                tr.stop("train_step")
                # the guard's documented cost: ONE scalar fetch per step to
                # learn whether the update was finite — opt-in, and there is
                # no async way to branch host control flow on a device value
                if guard is not None and not bool(
                    np.asarray(metrics["finite"])  # jaxlint: disable=host-sync-in-hot-loop
                ):
                    # poisoned update: discard it (or restore last-good
                    # with halved LR after a streak) and keep the batch's
                    # metrics out of the epoch average
                    state = guard.on_bad_step(prev)
                else:
                    if guard is not None:
                        guard.bad_streak = 0
                    acc = self._acc_add(acc, metrics, multi=False)
                faults.kill_at_step(self._host_step)
                faults.lose_host_at_step(self._host_step)
                self._host_step += 1
                elastic.note_step(self._host_step)
        loss, tasks = self._acc_read(acc)  # the epoch's one readback
        tr.stop("train")
        return state, rng, loss, tasks

    def evaluate(self, state, loader, desc="validate"):
        """Streaming eval with the SAME multi-step dispatch as training:
        ``steps_per_dispatch`` same-shape batches stack into one scan
        program (at-scale QM9, per-batch eval dispatches cost as much
        wall as the whole stacked train epoch)."""
        acc = None
        nbatch = _nbatch(loader)
        K = max(1, self.steps_per_dispatch)
        plan = self._group_plan(loader, nbatch, K)
        for dev, count in self._prefetch_put(
            plan, float("inf"), self.device_prefetch, put=self._put_group,
            ledger_waits=not getattr(loader, "reports_stream_stats", False),
        ):
            if count > 1:
                metrics = self._eval_multi(
                    state.params, state.batch_stats, dev
                )
                acc = self._acc_add(acc, metrics, multi=True)
            else:
                metrics = self._eval_step(
                    state.params, state.batch_stats, dev
                )
                acc = self._acc_add(acc, metrics, multi=False)
        return self._acc_read(acc)
