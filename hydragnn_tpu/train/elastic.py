"""Elastic self-healing multi-host training: survive preemption and re-mesh.

The resilience story so far (``docs/resilience.md``) assumes a FIXED world:
a preempted run resumes only when an operator relaunches it at the same
size. This module removes the operator: each host runs an
:class:`ElasticAgent` that supervises its training worker process, hosts
exchange liveness through a shared coordination directory (the natural
primitive on the HPC filesystems the reference targets — no extra control
plane), and on host loss the survivors tear down, re-run the
``jax.distributed`` bootstrap at the new world size, and continue from the
rolling checkpoint.

Mechanics, one failure end to end:

1. every worker writes a **heartbeat lease** file
   (``<dir>/workers/host-<k>.json``) from a background thread; the payload
   carries rank/step/epoch/guard counters (fed by the cheap
   :func:`note_step`/:func:`note_epoch` hooks in the training loop);
2. every worker runs a **peer watchdog** thread: a peer whose lease is
   stale past ``HYDRAGNN_ELASTIC_LEASE_S`` (or already tombstoned) is
   declared lost. The watchdog lives OFF the training thread on purpose —
   it fires even while the trainer is wedged inside a collective that
   hangs because the peer died (the collective-timeout role; XLA's own
   timeouts are minutes, the lease is seconds);
3. the detecting watchdog writes a **tombstone**
   (``<dir>/dead/host-<k>.json``), emits a ``host_lost`` event, drains any
   pending async checkpoint writes (the shutdown barrier — see
   ``checkpoint.AsyncCheckpointWriter``), and hard-exits the worker with
   :data:`EXIT_RESHAPE`;
4. each surviving **agent** sees its worker exit, reads the coordination
   dir, and the lowest surviving host (the leader) publishes the next
   **generation** file: new member list, new coordinator address, the
   detection timestamp. A ``jax.distributed`` world cannot change size
   in-process (the PJRT backend is immutable once initialized), so the
   agent respawns the worker — the fresh process bootstraps at the new
   world size, per-process batch shards rebalance automatically (the
   loaders shard by ``process_count``/``process_index``) and per-rank
   PRNG folds derive from the new rank layout;
5. the respawned worker resumes from the rolling checkpoint and, on its
   first completed optimizer step, emits a ``world_resize`` event whose
   ``recovery_s`` spans tombstone-detection to first-step — the whole
   re-mesh (teardown + bootstrap + restore + recompile) measured as one
   number, mirrored to the ``world_size`` / ``last_recovery_seconds``
   gauges.

A host that was *declared* dead but is merely slow (partitioned, hung
device) finds its own tombstone and exits with :data:`EXIT_EVICTED`
instead of split-braining the run.

Env knobs (set by the agent for its worker; the ``HYDRAGNN_ELASTIC_DIR``
presence is what turns the worker-side runtime on):

- ``HYDRAGNN_ELASTIC_DIR``           shared coordination directory
- ``HYDRAGNN_ELASTIC_HOST``          this host's stable id (int)
- ``HYDRAGNN_ELASTIC_GEN``           current world generation
- ``HYDRAGNN_ELASTIC_MEMBERS``       csv of member host ids, rank order
- ``HYDRAGNN_ELASTIC_HEARTBEAT_S``   heartbeat interval (default 1.0)
- ``HYDRAGNN_ELASTIC_LEASE_S``       lease timeout (default 6.0)
- ``HYDRAGNN_ELASTIC_DETECT_TS``     loss-detection ts (gen > 0)
- ``HYDRAGNN_ELASTIC_PREV_WORLD``    world size before the resize

``HYDRAGNN_HEARTBEAT_FILE`` is the single-file lightweight mode: no
agent, no watchdog — just the progress heartbeat, which the HPO launcher
uses as its hang/divergence early-kill signal (``hpo/launcher.py``).

CLI (one agent per host)::

    python -m hydragnn_tpu.train.elastic --dir /shared/run1 --host 0 \\
        --hosts 4 --base-port 12360 -- python -m hydragnn_tpu.run_training cfg.json
"""

import glob
import json
import os
import re
import subprocess
import time
from typing import Dict, List, Optional

from hydragnn_tpu.coord import (  # noqa: F401  (re-exported API — the
    # lease/heartbeat/tombstone/watchdog core was extracted to
    # hydragnn_tpu.coord so the serving fleet (serve/fleet.py) shares one
    # implementation; this module keeps the historical names alive)
    DEFAULT_HEARTBEAT_S,
    DEFAULT_LEASE_S,
    Heartbeat,
    dead_members,
    heartbeat_age,
    read_tombstone,
    write_tombstone,
)
from hydragnn_tpu.coord import PeerWatchdog as _CoordPeerWatchdog
from hydragnn_tpu.coord import hb_path as _hb_path  # noqa: F401
from hydragnn_tpu.coord import read_json as _read_json  # noqa: F401
from hydragnn_tpu.coord import tomb_path as _tomb_path  # noqa: F401
from hydragnn_tpu.coord import write_json as _write_json  # noqa: F401
from hydragnn_tpu.obs import runtime as obs

# worker exit codes the agent keys on (distinct from faults.KILL_EXIT_CODE
# = 113, the injected-preemption code)
EXIT_RESHAPE = 117  # a peer was lost; respawn me at the new world size
EXIT_EVICTED = 115  # I was declared dead by the others; do not respawn
EXIT_GEN_TIMEOUT = 116  # no next-generation file appeared in time

_GEN_RE = re.compile(r"gen-(\d+)\.json$")


# ---- progress hooks (no-op cheap when no heartbeat is live) ---------------

# written by the training loop, read by the heartbeat thread. Plain dict
# stores of ints/floats (GIL-atomic); the heartbeat tolerates a torn
# multi-field view — it is a liveness signal, not a transaction.
_progress = {"step": 0, "epoch": 0, "guard_restores": 0, "progress_ts": 0.0}
_beating = False  # one global read gates every hook (the faults.py pattern)
_runtime: Optional["ElasticRuntime"] = None
# compact per-host step-time digest riding the heartbeat payload: the
# leader's /metrics scrape (and the offline fleet rollup) reads every
# host's p50/p99 out of the lease files, so a straggler is visible from
# any host without shipping event streams around. A LatencyHistogram is
# a few hundred bytes; torn heartbeat reads of it are as acceptable as
# they are for _progress.
_step_hist = None
# the worker's device-mesh shape [d, m] (parallel/mesh.py announces it):
# rides the heartbeat payload and the world_resize event, so a 2-D
# world's re-mesh is observable as a MESH change, not just a world count
_mesh_shape: Optional[List[int]] = None


def note_mesh_shape(shape):
    """The run resolved its device mesh (``[d, m]`` or None) — recorded
    for heartbeats and the next ``world_resize`` emission."""
    global _mesh_shape
    _mesh_shape = None if shape is None else [int(v) for v in shape]


def note_step(step: Optional[int] = None):
    """The trainer completed one optimizer step (called per step from the
    epoch loop; one global read and return when nothing heartbeats)."""
    if not _beating:
        return
    if step is not None:
        _progress["step"] = int(step)
    _progress["progress_ts"] = time.time()
    rt = _runtime
    if rt is not None and rt._pending_resize:
        rt.on_first_step()


def note_epoch(epoch: int):
    if not _beating:
        return
    _progress["epoch"] = int(epoch)
    _progress["progress_ts"] = time.time()


def note_step_time(seconds: float, count: int = 1, compiled: bool = False):
    """One timed step dispatch (fed by ``RunTelemetry.on_step``) — feeds
    the heartbeat's step-time digest. Compile-containing dispatches are
    excluded, mirroring the flight recorder: a freshly respawned host's
    first (compiling) step must not read as straggling."""
    if not _beating or compiled:
        return
    global _step_hist
    h = _step_hist
    if h is None:
        from hydragnn_tpu.obs.metrics import LatencyHistogram

        h = _step_hist = LatencyHistogram()
    per_step = float(seconds) / max(int(count), 1)
    for _ in range(max(int(count), 1)):
        h.observe(per_step)


def step_digest() -> Optional[Dict]:
    """{count, sum, p50, p99} of this host's recorded step times (None
    before the first timed step) — the heartbeat payload's digest."""
    h = _step_hist
    return None if h is None or h.total == 0 else h.state()


def note_guard_restore():
    """The divergence guard restored last-good state — the HPO launcher
    reads this counter out of the heartbeat as its early-kill signal."""
    if not _beating:
        return
    _progress["guard_restores"] = _progress["guard_restores"] + 1


# ---- coordination-directory primitives (generation files stay here —
# the agent's leader-elected re-mesh is elastic-specific; everything else
# lives in hydragnn_tpu.coord and is re-exported above) ---------------------


def _gen_path(coord_dir: str, gen: int) -> str:
    return os.path.join(coord_dir, "gens", f"gen-{int(gen):06d}.json")


def latest_gen(coord_dir: str):
    """(gen, payload) of the newest readable generation file, or (None,
    None) on a fresh directory."""
    best, payload = None, None
    for p in glob.glob(os.path.join(coord_dir, "gens", "gen-*.json")):
        m = _GEN_RE.search(p)
        if not m:
            continue
        g = int(m.group(1))
        if best is None or g > best:
            data = _read_json(p)
            if data is not None:
                best, payload = g, data
    return best, payload


# ---- heartbeat + watchdog threads (core in hydragnn_tpu.coord) ------------


class PeerWatchdog(_CoordPeerWatchdog):
    """The elastic-training watchdog: :class:`hydragnn_tpu.coord.
    PeerWatchdog` with the training teeth installed as defaults.

    Runs off the training thread so a collective hung on a dead peer
    still gets detected and broken (the default ``on_loss`` hard-exits
    with :data:`EXIT_RESHAPE` after writing tombstones and draining
    pending async checkpoint writes). Also notices this host's OWN
    tombstone — a partitioned straggler must evict itself rather than
    rejoin a world that already re-formed without it."""

    def _default_on_loss(self, dead: Dict[int, float]):
        for h, ts in sorted(dead.items()):
            write_tombstone(
                self.coord_dir, h, reason="lease_expired", by=self.host
            )
            age = heartbeat_age(self.coord_dir, "worker", h)
            obs.emit(
                "host_lost",
                host=int(h),
                stale_s=None if age is None else round(float(age), 3),
                by=self.host,
            )
        # the preemption-path drain barrier: pending async checkpoint
        # writes land before the process dies, so the re-formed world
        # resumes from the newest completed save, not a lost queue entry
        from hydragnn_tpu.train import checkpoint as ck

        ck.drain_async(timeout=30.0)
        os._exit(EXIT_RESHAPE)

    def _default_on_evicted(self):
        os._exit(EXIT_EVICTED)


# ---- worker-side runtime ---------------------------------------------------


class ElasticRuntime:
    """Everything the TRAINING process contributes to elasticity: its own
    heartbeat lease, the peer watchdog, and the ``world_resize`` recovery
    event on the first step after a re-mesh."""

    def __init__(
        self,
        coord_dir: str,
        host: int,
        gen: int,
        members: List[int],
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        lease_s: float = DEFAULT_LEASE_S,
        detect_ts: Optional[float] = None,
        prev_world: Optional[int] = None,
        lost_hosts: Optional[List[int]] = None,
    ):
        self.coord_dir = coord_dir
        self.host = int(host)
        self.gen = int(gen)
        self.members = [int(m) for m in members]
        self.rank = self.members.index(self.host)
        self.world = len(self.members)
        self._detect_ts = detect_ts
        self._prev_world = prev_world
        self._lost_hosts = list(lost_hosts or [])
        self._done = False
        self._pending_resize = bool(
            self.gen > 0 and detect_ts is not None and prev_world
        )
        self.heartbeat = Heartbeat(
            _hb_path(coord_dir, "worker", self.host),
            self._payload,
            heartbeat_s,
        )
        self.watchdog = (
            PeerWatchdog(
                coord_dir, self.host, self.members, lease_s,
                interval_s=min(heartbeat_s, lease_s / 3.0),
                gen=self.gen,
            )
            if self.world > 1
            else None
        )

    def _payload(self) -> Dict:
        p = dict(_progress)
        p.update(host=self.host, rank=self.rank, gen=self.gen,
                 world=self.world, done=self._done)
        if _mesh_shape is not None:
            p["mesh"] = _mesh_shape
        digest = step_digest()
        if digest is not None:
            p["step_digest"] = digest
        return p

    def start(self) -> "ElasticRuntime":
        global _beating, _runtime
        _beating = True
        _runtime = self
        self.heartbeat.start()
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def on_first_step(self):
        """First completed optimizer step of a post-resize generation:
        the recovery is over — detection -> teardown -> re-bootstrap ->
        restore -> recompile -> first step, measured as one number."""
        if not self._pending_resize:
            return
        self._pending_resize = False
        recovery = max(time.time() - float(self._detect_ts), 0.0)
        # the new generation's rank 0 records WHO was lost: when the lost
        # host was the PREVIOUS rank 0, the detecting survivors had no
        # active telemetry (obs is rank-0-only) and their host_lost
        # emits were dropped — this resize-side record is the one that
        # always lands (duplicates with the detection-side record when
        # old rank 0 survived are legal: two observers of one loss)
        for h in self._lost_hosts:
            tomb = read_tombstone(self.coord_dir, h)
            obs.emit(
                "host_lost",
                host=int(h),
                by=self.host,
                source="resize",
                reason=None if tomb is None else tomb.get("reason"),
            )
        obs.world_resized(
            old_world=int(self._prev_world),
            new_world=self.world,
            gen=self.gen,
            recovery_s=round(recovery, 3),
            **({} if _mesh_shape is None else {"mesh_shape": _mesh_shape}),
        )

    def stop(self):
        global _beating, _runtime
        # the final lease write carries done=True: peers whose watchdogs
        # outlive us (rank 0's post-training tail) must read "finished",
        # never "lost" — only an UNMARKED stale lease means death
        self._done = True
        self.heartbeat._write()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.heartbeat.stop()
        if _runtime is self:
            _runtime = None
            _beating = False


class FileHeartbeatRuntime:
    """``HYDRAGNN_HEARTBEAT_FILE`` mode: just the progress lease, written
    to one caller-chosen path — the HPO launcher's per-trial liveness +
    divergence signal."""

    def __init__(self, path: str, heartbeat_s: float = DEFAULT_HEARTBEAT_S):
        def _payload():
            p = dict(_progress)
            digest = step_digest()
            if digest is not None:
                p["step_digest"] = digest
            return p

        self.heartbeat = Heartbeat(path, _payload, heartbeat_s)

    def start(self) -> "FileHeartbeatRuntime":
        global _beating
        _beating = True
        self.heartbeat.start()
        return self

    def stop(self):
        global _beating
        self.heartbeat.stop()
        _beating = False


def maybe_elastic():
    """Driver hook: build + start the runtime the environment asks for
    (None when neither elastic nor file-heartbeat mode is configured).
    Call right after ``setup_distributed`` so the lease exists before the
    long data-load/compile phases — peers must not mistake a compiling
    host for a dead one."""
    coord_dir = os.getenv("HYDRAGNN_ELASTIC_DIR")
    if coord_dir:
        members = [
            int(m)
            for m in os.getenv("HYDRAGNN_ELASTIC_MEMBERS", "0").split(",")
            if m.strip() != ""
        ]
        detect = os.getenv("HYDRAGNN_ELASTIC_DETECT_TS")
        prev = os.getenv("HYDRAGNN_ELASTIC_PREV_WORLD")
        lost = [
            int(m)
            for m in os.getenv("HYDRAGNN_ELASTIC_LOST", "").split(",")
            if m.strip() != ""
        ]
        return ElasticRuntime(
            coord_dir,
            host=int(os.getenv("HYDRAGNN_ELASTIC_HOST", "0")),
            gen=int(os.getenv("HYDRAGNN_ELASTIC_GEN", "0")),
            members=members,
            lost_hosts=lost,
            heartbeat_s=float(
                os.getenv("HYDRAGNN_ELASTIC_HEARTBEAT_S",
                          str(DEFAULT_HEARTBEAT_S))
            ),
            lease_s=float(
                os.getenv("HYDRAGNN_ELASTIC_LEASE_S", str(DEFAULT_LEASE_S))
            ),
            detect_ts=float(detect) if detect else None,
            prev_world=int(prev) if prev else None,
        ).start()
    hb_file = os.getenv("HYDRAGNN_HEARTBEAT_FILE")
    if hb_file:
        return FileHeartbeatRuntime(
            hb_file,
            heartbeat_s=float(
                os.getenv("HYDRAGNN_ELASTIC_HEARTBEAT_S",
                          str(DEFAULT_HEARTBEAT_S))
            ),
        ).start()
    return None


# ---- per-host agent --------------------------------------------------------


class ElasticAgent:
    """One per host: spawns/respawns the training worker across world
    generations. The membership/coordinator decisions are driven entirely
    by the shared directory, so agents need no channel to each other."""

    def __init__(
        self,
        worker_cmd: List[str],
        coord_dir: str,
        host: int,
        n_hosts: Optional[int] = None,
        base_port: int = 12360,
        addr: str = "127.0.0.1",
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        lease_s: float = DEFAULT_LEASE_S,
        env: Optional[Dict[str, str]] = None,
        gen_timeout_s: float = 120.0,
        poll_s: float = 0.25,
    ):
        self.worker_cmd = list(worker_cmd)
        self.coord_dir = coord_dir
        self.host = int(host)
        self.n_hosts = n_hosts
        self.base_port = int(base_port)
        self.addr = addr
        self.heartbeat_s = float(heartbeat_s)
        self.lease_s = float(lease_s)
        self.extra_env = dict(env or {})
        self.gen_timeout_s = float(gen_timeout_s)
        self.poll_s = float(poll_s)

    # -- generation bookkeeping ---------------------------------------------
    def _bootstrap_gen(self):
        """Gen 0: the initial leader (host 0 of the declared size) writes
        it; everyone else waits for the file."""
        gen, info = latest_gen(self.coord_dir)
        if gen is not None:
            return gen, info
        if self.n_hosts is None:
            raise ValueError(
                "fresh coordination dir and no --hosts given: the first "
                "agent needs the initial world size"
            )
        members = list(range(int(self.n_hosts)))
        if self.host == members[0]:
            info = {
                "gen": 0,
                "members": members,
                "coordinator": f"{self.addr}:{self.base_port}",
                "detect_ts": None,
                "prev_members": None,
                "created_ts": time.time(),
            }
            _write_json(_gen_path(self.coord_dir, 0), info)
            return 0, info
        return self._await_gen(0)

    def _await_gen(self, gen: int):
        deadline = time.time() + self.gen_timeout_s
        while time.time() < deadline:
            info = _read_json(_gen_path(self.coord_dir, gen))
            if info is not None:
                return gen, info
            # keep OUR lease fresh while the leader decides — a surviving
            # agent mid-re-mesh must not be mistaken for a second loss
            self._agent_heartbeat(gen - 1)
            time.sleep(self.poll_s)
        return None, None

    def _publish_next_gen(self, gen: int, members: List[int],
                          dead: Dict[int, float]):
        """Publish generation ``gen+1`` with SINGLE-WINNER semantics.

        Two survivors can transiently disagree on who died (shared-FS
        metadata lag makes a live peer's lease look stale) and both
        self-elect: the publish must not be last-rename-wins with each
        proceeding on its OWN view — that is the split-brain this module
        promises away. ``os.link`` onto the final name is atomic AND
        exclusive (unlike ``os.replace``): exactly one candidate file
        becomes the generation, and EVERY publisher then re-reads the
        file to adopt whatever actually won. A loser whose winning view
        excludes it simply evicts in ``run()``."""
        survivors = [m for m in members if m not in dead]
        info = {
            "gen": gen + 1,
            "members": survivors,
            "coordinator": f"{self.addr}:{self.base_port + gen + 1}",
            "detect_ts": min(dead.values()),
            "prev_members": members,
            "created_ts": time.time(),
        }
        path = _gen_path(self.coord_dir, gen + 1)
        tmp = f"{path}.cand.{self.host}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(info, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)
        except FileExistsError:
            pass  # another leader won the race — its file governs
        except OSError:
            # filesystems without hard links: fall back to the (atomic,
            # last-wins) rename; the re-read below still converges all
            # agents onto one file's contents
            os.replace(tmp, path)
            tmp = None
        if tmp is not None:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return self._await_gen(gen + 1)

    def _agent_heartbeat(self, gen: int):
        _write_json(
            _hb_path(self.coord_dir, "agent", self.host),
            {"host": self.host, "gen": int(gen), "ts": time.time(),
             "pid": os.getpid(), "addr": self.addr},
        )

    # -- worker environment --------------------------------------------------
    def _worker_env(self, gen: int, info: Dict) -> Dict[str, str]:
        members = [int(m) for m in info["members"]]
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(
            HYDRAGNN_ELASTIC_DIR=self.coord_dir,
            HYDRAGNN_ELASTIC_HOST=str(self.host),
            HYDRAGNN_ELASTIC_GEN=str(gen),
            HYDRAGNN_ELASTIC_MEMBERS=",".join(str(m) for m in members),
            HYDRAGNN_ELASTIC_HEARTBEAT_S=str(self.heartbeat_s),
            HYDRAGNN_ELASTIC_LEASE_S=str(self.lease_s),
            HYDRAGNN_TPU_COORDINATOR=str(info["coordinator"]),
            HYDRAGNN_TPU_NUM_PROCESSES=str(len(members)),
            HYDRAGNN_TPU_PROCESS_ID=str(members.index(self.host)),
        )
        if info.get("detect_ts"):
            env["HYDRAGNN_ELASTIC_DETECT_TS"] = str(info["detect_ts"])
        if info.get("prev_members"):
            prev = [int(m) for m in info["prev_members"]]
            env["HYDRAGNN_ELASTIC_PREV_WORLD"] = str(len(prev))
            env["HYDRAGNN_ELASTIC_LOST"] = ",".join(
                str(m) for m in prev if m not in members
            )
        else:
            env.pop("HYDRAGNN_ELASTIC_DETECT_TS", None)
            env.pop("HYDRAGNN_ELASTIC_PREV_WORLD", None)
            env.pop("HYDRAGNN_ELASTIC_LOST", None)
        return env

    # -- main loop -----------------------------------------------------------
    def run(self) -> int:
        for sub in ("agents", "workers", "dead", "gens"):
            os.makedirs(os.path.join(self.coord_dir, sub), exist_ok=True)
        gen, info = self._bootstrap_gen()
        if gen is None:
            return EXIT_GEN_TIMEOUT
        while True:
            members = [int(m) for m in info["members"]]
            if self.host not in members:
                return EXIT_EVICTED
            rc = self._supervise_one(gen, info)
            if rc == 0:
                return 0
            from hydragnn_tpu.utils.faults import KILL_EXIT_CODE

            if rc == KILL_EXIT_CODE:
                # THIS host was preempted (injected or real): tombstone
                # ourselves so the survivors' leader re-meshes without
                # waiting out the lease, then die like the host did
                write_tombstone(
                    self.coord_dir, self.host, reason="preempted",
                    by=self.host,
                )
                return rc
            if rc == EXIT_EVICTED:
                return rc
            # EXIT_RESHAPE — or any crash that coincides with a peer
            # loss (a dead peer can also surface as a collective error
            # before the watchdog fires): re-mesh iff someone is dead
            # tombstones (fast path: written by the detecting watchdog or
            # the dying host's own agent) or an expired AGENT lease (the
            # whole-host-gone path) both count as dead
            dead = dead_members(
                self.coord_dir, [m for m in members if m != self.host],
                self.lease_s, kind="agent",
            )
            if not dead:
                return rc  # a genuine worker failure, not elasticity
            survivors = [m for m in members if m not in dead]
            if not survivors or self.host not in survivors:
                return EXIT_EVICTED
            if self.host == survivors[0]:
                gen, info = self._publish_next_gen(gen, members, dead)
            else:
                gen, info = self._await_gen(gen + 1)
            if gen is None:
                return EXIT_GEN_TIMEOUT

    def _supervise_one(self, gen: int, info: Dict) -> int:
        """Run one worker process to completion, heartbeating the AGENT
        lease (host liveness — it must outlive worker restarts) and
        watching for our own tombstone while it runs."""
        proc = subprocess.Popen(
            self.worker_cmd, env=self._worker_env(gen, info)
        )
        try:
            last_beat = 0.0
            while True:
                # the poll runs fast (worker exits and tombstones must be
                # noticed promptly) but the lease WRITE rate-limits to
                # heartbeat_s — at fleet scale an every-tick atomic
                # write+rename is sustained metadata traffic on exactly
                # the shared filesystem the lease is tuned around
                if time.time() - last_beat >= self.heartbeat_s:
                    self._agent_heartbeat(gen)
                    last_beat = time.time()
                rc = proc.poll()
                if rc is not None:
                    return rc
                if read_tombstone(self.coord_dir, self.host) is not None:
                    # the world decided we are dead (partition/straggler):
                    # kill the worker, do not split-brain
                    proc.kill()
                    proc.wait(timeout=30)
                    return EXIT_EVICTED
                time.sleep(min(self.heartbeat_s, 0.5))
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.train.elastic",
        description="Per-host elastic training agent (see module docs).",
    )
    parser.add_argument("--dir", required=True, help="shared coordination dir")
    parser.add_argument("--host", type=int, required=True)
    parser.add_argument("--hosts", type=int, default=None,
                        help="initial world size (first launch only)")
    parser.add_argument("--base-port", type=int, default=12360)
    parser.add_argument("--addr", default="127.0.0.1")
    parser.add_argument("--heartbeat", type=float, default=DEFAULT_HEARTBEAT_S)
    parser.add_argument("--lease", type=float, default=DEFAULT_LEASE_S)
    parser.add_argument("worker", nargs=argparse.REMAINDER,
                        help="-- worker command")
    args = parser.parse_args(argv)
    cmd = args.worker
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("missing worker command after --")
    agent = ElasticAgent(
        cmd, args.dir, args.host, n_hosts=args.hosts,
        base_port=args.base_port, addr=args.addr,
        heartbeat_s=args.heartbeat, lease_s=args.lease,
    )
    return agent.run()


if __name__ == "__main__":
    raise SystemExit(main())
