"""GraphSAGE stack.

Parity with reference ``hydragnn/models/SAGEStack.py:22-43`` (PyG SAGEConv
defaults): out = lin_l(mean_{j->i} x_j) + lin_r(x_i), lin_r without bias.
"""

import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear, gather_segment_mean


class SAGEConv(nn.Module):
    in_dim: int
    out_dim: int

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        extras = batch.extras or {}
        if "nbr_idx" in extras:  # dense scatter-free path (ops/dense_agg.py)
            from hydragnn_tpu.ops.dense_agg import dense_sum, gather_neighbors

            nmask = extras["nbr_mask"]
            x_j = gather_neighbors(
                x, extras["nbr_idx"], extras["rev_idx"], extras["rev_mask"]
            )
            deg = nmask.sum(axis=1).astype(x.dtype)
            aggr = dense_sum(x_j, nmask) / jnp.maximum(deg, 1.0)[:, None]
        else:
            # mean over real incoming edges only (sum / real degree),
            # through the shared helper: XLA segment path or the fused
            # Pallas kernel (autotuner/env decision)
            aggr = gather_segment_mean(
                x, batch.senders, batch.receivers, x.shape[0],
                batch.edge_mask, model_key="SAGE",
            )
        out = TorchLinear(self.out_dim, name="lin_l")(aggr) + TorchLinear(
            self.out_dim, use_bias=False, name="lin_r"
        )(x)
        return out, pos


class SAGEStack(HydraBase):
    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(SAGEConv)(in_dim=in_dim, out_dim=out_dim, name=name)
