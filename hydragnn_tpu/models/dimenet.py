"""DimeNet++ stack — directional message passing.

Parity with reference ``hydragnn/models/DIMEStack.py:32-201``: per conv layer a
Linear embedding + HydraEmbeddingBlock (no atomic-number embedding,
``:185-201``) + InteractionPPBlock + OutputPPBlock, with Bessel radial and
spherical (Legendre x Bessel) angular bases and an envelope cutoff; Identity
feature layers (no encoder BatchNorm, ``:71-77``).

TPU design: the reference builds triplets per batch with torch_sparse
SparseTensor (``DIMEStack.py:158-182``) — dynamic shapes. Here triplet index
arrays (k->j->i) are precomputed on the HOST at collation time and padded to a
static per-batch budget (``hydragnn_tpu/data`` fills ``batch.extras``);
distances, angles, rbf and sbf are computed inside the jitted step from those
static index arrays, so the whole conv remains one XLA program.

Basis functions: instead of sympy-lambdified code (PyG), the spherical basis
is computed numerically — spherical Bessel j_l via upward recurrence and
Legendre P_l(cos t) via recurrence — with the same zeros-based frequency
scaling; behavior matches PyG's implementation for the l,n ranges used.
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from hydragnn_tpu.graph import segment_sum
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear

# zeros of spherical Bessel functions j_l, l = 0..6, first 6 zeros each —
# j_0 zeros are n*pi; higher-l zeros computed offline with scipy.optimize
# (values match PyG's sympy-derived `bessel_basis` frequencies).
_BESSEL_ZEROS = np.array(
    [
        [3.141593, 6.283185, 9.424778, 12.566371, 15.707963, 18.849556],
        [4.493409, 7.725252, 10.904122, 14.066194, 17.220755, 20.371303],
        [5.763459, 9.095011, 12.322941, 15.514603, 18.689036, 21.853874],
        [6.987932, 10.417119, 13.698023, 16.923621, 20.121806, 23.304247],
        [8.182561, 11.704907, 15.039665, 18.301256, 21.525418, 24.727566],
        [9.355812, 12.966530, 16.354710, 19.653152, 22.904551, 26.127750],
        [10.512835, 14.207392, 17.647975, 20.983463, 24.262768, 27.507868],
    ]
)


def _safe_sqrt(x):
    """sqrt with a finite gradient at 0 (double-where idiom): coincident
    or padded positions make the squared distance EXACTLY 0, and
    sqrt'(0) = inf would NaN the backward pass through every such slot
    even where the forward value is masked away."""
    positive = x > 0.0
    return jnp.where(positive, jnp.sqrt(jnp.where(positive, x, 1.0)), 0.0)


def _spherical_jn(l_max: int, x):
    """j_0..j_{l_max} via upward recurrence; x > 0 assumed (clamped)."""
    x = jnp.maximum(x, 1e-8)
    j = [jnp.sin(x) / x]
    if l_max >= 1:
        j.append(jnp.sin(x) / (x * x) - jnp.cos(x) / x)
    for l in range(2, l_max + 1):
        j.append((2 * l - 1) / x * j[l - 1] - j[l - 2])
    return j


def _legendre(l_max: int, x):
    """P_0..P_{l_max}(x) by recurrence."""
    p = [jnp.ones_like(x)]
    if l_max >= 1:
        p.append(x)
    for l in range(2, l_max + 1):
        p.append(((2 * l - 1) * x * p[l - 1] - (l - 1) * p[l - 2]) / l)
    return p


class Envelope:
    """Smooth cutoff envelope u(x) = 1/x + a x^(p-1) + b x^p + c x^(p+1)."""

    def __init__(self, exponent: int):
        p = exponent + 1
        self.p = p
        self.a = -(p + 1) * (p + 2) / 2.0
        self.b = p * (p + 2.0)
        self.c = -p * (p + 1) / 2.0

    def __call__(self, x):
        p, a, b, c = self.p, self.a, self.b, self.c
        xp = jnp.power(jnp.maximum(x, 1e-8), p - 1)
        val = 1.0 / jnp.maximum(x, 1e-8) + a * xp + b * xp * x + c * xp * x * x
        return jnp.where(x < 1.0, val, 0.0)


class BesselBasisLayer(nn.Module):
    num_radial: int
    cutoff: float
    envelope_exponent: int = 5

    @nn.compact
    def __call__(self, dist):
        freq = self.param(
            "freq",
            lambda key, shape: jnp.arange(1, shape[0] + 1, dtype=jnp.float32)
            * math.pi,
            (self.num_radial,),
        )
        d = (dist / self.cutoff)[:, None]
        env = Envelope(self.envelope_exponent)(d)
        return env * jnp.sin(freq * d)


def _radial_sbf(dist, num_spherical, num_radial, cutoff, envelope_exponent):
    """``env(d) * j_l(z_ln * d)`` -> [..., S, R] — the radial half of the
    spherical basis. ONE implementation shared by the T-axis
    (:func:`spherical_basis`) and bmm (:func:`_dimenet_geometry_dense`)
    paths so their numerics cannot diverge."""
    d = jnp.clip(dist / cutoff, 1e-6, 1.0)
    env = Envelope(envelope_exponent)(d)
    zeros = jnp.asarray(
        _BESSEL_ZEROS[:num_spherical, :num_radial], dtype=jnp.float32
    )
    jl = _spherical_jn(num_spherical - 1, d[..., None, None] * zeros)
    rad = jnp.stack(
        [jl[l][..., l, :] for l in range(num_spherical)], axis=-2
    )  # [..., S, R]
    return env[..., None, None] * rad


def spherical_basis(
    num_spherical,
    num_radial,
    cutoff,
    envelope_exponent,
    dist,
    angle,
    idx_kj,
    dist_t=None,
):
    """sbf[t, l*num_radial+n] = env(d_kj) j_l(z_ln d_kj) P-norm_l(angle_t).

    Mirrors PyG's SphericalBasisLayer: radial part evaluated on the k->j
    edge distance gathered per triplet, angular part on the triplet angle.
    The normalization constants fold into the learned linear layers
    downstream. Parameter-free, so it is a plain function — which lets
    ``DIMEStack._prepare_batch`` hoist it out of the per-layer convs.

    ``dist_t``: optional per-TRIPLET k->j distances. The default path
    evaluates the radial basis per edge and gathers at ``idx_kj``; in
    graph-partition mode the (k->j) edge may live on another shard, so the
    caller passes the triplet distances computed from halo-extended
    positions and the gather disappears (identical numerics)."""
    rbf = _radial_sbf(
        dist if dist_t is None else dist_t,
        num_spherical,
        num_radial,
        cutoff,
        envelope_exponent,
    )  # [E or T, S, R]
    cbf = jnp.stack(
        _legendre(num_spherical - 1, jnp.cos(angle)), axis=1
    )  # [T, S]
    if dist_t is None:
        rbf = rbf[idx_kj]  # [T, S, R]
    out = rbf * cbf[:, :, None]
    return out.reshape(out.shape[0], num_spherical * num_radial)


def _dimenet_geometry_dense(
    batch, pos, num_spherical, num_radial, cutoff, envelope_exponent
):
    """(dist, rad, cbf) for the bmm-triplet path — no triplet axis.

    The T~deg*E triplet dimension is the reference design's scaling axis
    (``DIMEStack.py:158-182`` materializes per-triplet tensors); on TPU it
    is pure HBM pain: [T, D] gathers walk rows at ~1/10 of matmul-feed
    bandwidth and the segment-sum back to edges is a scatter. This path
    regroups every triplet (k->j->i) under its CENTRAL node j: the in-edge
    slots (k->j, width Ki) and out-edge slots (j->i, width Ko) of j
    enumerate all its triplets as a Ko x Ki grid, so the per-layer
    aggregation becomes a batched matmul over the fused (in-slot x
    spherical-component) axis — MXU work on [N, *] tensors (see
    ``DimeNetConv``). Geometry here is parameter-free and hoisted once per
    forward:

      ``dist [E]``          edge lengths (the learned per-layer rbf input)
      ``rad  [N, Ki, S, R]`` radial sbf part per in-edge slot
      ``cbf  [N, Ko, Ki, S]`` Legendre angular part per (out, in) slot
                             pair, with ALL validity masking folded in
                             (out/in slot masks + the k != i backtrack
                             exclusion), so downstream contractions need
                             no masks of their own.
    """
    ex = batch.extras
    i, j = batch.receivers, batch.senders
    nbr_edge, nbr_mask = ex["nbr_edge"], ex["nbr_mask"]
    # the out-slot grouping is the reverse-list grouping: rev_mask IS the
    # out-slot validity mask
    out_edge, out_mask = ex["out_edge"], ex["rev_mask"]

    dist = _safe_sqrt(((pos[i] - pos[j]) ** 2).sum(-1))
    dist = jnp.where(batch.edge_mask, dist, cutoff)  # keep env finite

    # radial part on the in-edge slots (shared _radial_sbf arithmetic)
    d_g = jnp.where(nbr_mask, dist[nbr_edge], cutoff)
    rad = _radial_sbf(
        d_g, num_spherical, num_radial, cutoff, envelope_exponent
    )  # [N, Ki, S, R]

    # angular part on the (out-slot, in-slot) grid: angle at vertex i
    # between (j - i) and (k - i), matching _dimenet_geometry exactly
    k_id = ex["nbr_idx"]  # [N, Ki] sender of each in-edge (k)
    i_id = jnp.where(out_mask, batch.receivers[out_edge], 0)  # [N, Ko]
    pos_i = pos[i_id]  # [N, Ko, 3]
    pos_k = pos[k_id]  # [N, Ki, 3]
    pos_ji = pos[:, None, :] - pos_i  # [N, Ko, 3]
    pos_ki = pos_k[:, None, :, :] - pos_i[:, :, None, :]  # [N, Ko, Ki, 3]
    a = (pos_ji[:, :, None, :] * pos_ki).sum(-1)
    b2 = (jnp.cross(pos_ji[:, :, None, :], pos_ki) ** 2).sum(-1)
    # Legendre needs cos(angle) only: cos(atan2(b, a)) == a / hypot(a, b)
    # exactly, so the atan2+cos transcendental pair on the [N, Ko, Ki]
    # grid becomes one rsqrt (the geometry is HALF the forward; see
    # BASELINE.md round 4). eps guards the degenerate a=b=0 pairs
    # (masked anyway, but NaN would poison the mask multiply).
    cos_t = a * jax.lax.rsqrt(jnp.maximum(a * a + b2, 1e-24))
    cbf = jnp.stack(
        _legendre(num_spherical - 1, cos_t), axis=-1
    )  # [N, Ko, Ki, S]
    valid = (
        out_mask[:, :, None]
        & nbr_mask[:, None, :]
        & (k_id[:, None, :] != i_id[:, :, None])
    )
    cbf = jnp.where(valid[..., None], cbf, 0.0)
    return dist, rad, cbf


def _dimenet_geometry(
    batch, pos, num_spherical, num_radial, cutoff, envelope_exponent,
    partition_axis,
):
    """(dist, sbf) for one batch — every interaction block consumes the
    same values, so the stack computes them once per forward.
    ``pos`` is explicit because partition mode evaluates on the per-layer
    halo-EXTENDED node table, not ``batch.pos``."""
    ex = batch.extras
    i, j = batch.receivers, batch.senders
    idx_i, idx_j, idx_k = ex["trip_i"], ex["trip_j"], ex["trip_k"]
    trip_mask = ex["trip_mask"]

    dist = _safe_sqrt(((pos[i] - pos[j]) ** 2).sum(-1))
    dist = jnp.where(batch.edge_mask, dist, cutoff)  # keep env finite

    pos_i = pos[idx_i]
    pos_ji = pos[idx_j] - pos_i
    pos_ki = pos[idx_k] - pos_i
    a = (pos_ji * pos_ki).sum(-1)
    b = jnp.linalg.norm(jnp.cross(pos_ji, pos_ki), axis=-1)
    angle = jnp.arctan2(b, a)

    dist_t = None
    if partition_axis is not None:
        # per-triplet k->j distance from halo-extended positions (the
        # (k->j) edge row itself may live on another shard)
        dist_t = _safe_sqrt(((pos[idx_k] - pos[idx_j]) ** 2).sum(-1))
        dist_t = jnp.where(trip_mask, dist_t, cutoff)
    sbf = spherical_basis(
        num_spherical,
        num_radial,
        cutoff,
        envelope_exponent,
        dist,
        angle,
        ex["trip_kj"],
        dist_t=dist_t,
    )
    sbf = jnp.where(trip_mask[:, None], sbf, 0.0)
    return dist, sbf


def _bmm_triplet_aggregate(
    x_down, rad, cbf, lin_sbf1, lin_sbf2, batch, num_spherical, num_radial
):
    """Triplet aggregation as per-central-node batched matmul (no T axis).

    Computes, for every edge j->i, ``sum_k sbf_b[(k,j,i)] * x_down[k->j]``
    — the InteractionPPBlock's directional message sum — by contracting
    over the fused (in-slot, spherical-component) axis at each central
    node j:

      ``out[j, ko, d] = sum_{ki, s} cbf[j, ko, ki, s]
                          * (rad[j, ki, s, :] @ Wf[s, :, d]) * xg[j, ki, d]``

    where ``Wf`` is the composed sbf projection. One MXU batched matmul
    replaces the reference path's [T, D] gather + multiply + segment-sum
    (T ~ deg * E rows); the gathers that remain move [N, K, D] blocks of
    full rows through single-owner permutations (scatter-free VJPs).
    Masking (slot validity + backtrack) is pre-folded into ``cbf`` by
    ``_dimenet_geometry_dense``."""
    from hydragnn_tpu.ops.dense_agg import (
        gather_rows_to_slots,
        slots_to_rows,
    )

    ex = batch.extras
    dt = x_down.dtype
    sr = num_spherical * num_radial
    # the two sbf projections are bias-free linears applied back-to-back:
    # their composition is one [S*R, int_emb] matrix, obtained by feeding
    # the identity through the SAME modules (param names/shapes stay
    # checkpoint-compatible with the segment path)
    wf = lin_sbf2(lin_sbf1(jnp.eye(sr, dtype=dt)))
    wf = wf.reshape(num_spherical, num_radial, -1)
    radw = jnp.einsum("nksr,srd->nksd", rad.astype(dt), wf)  # [N,Ki,S,D]
    xg = gather_rows_to_slots(
        x_down, ex["nbr_edge"], ex["nbr_mask"], ex["edge_slot"],
        batch.edge_mask,
    )  # [N, Ki, D]
    m = radw * xg[:, :, None, :]  # [N, Ki, S, D]
    n, ki, s, d = m.shape
    ko = cbf.shape[1]
    out = jax.lax.dot_general(
        cbf.astype(dt).reshape(n, ko, ki * s),
        m.reshape(n, ki * s, d),
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).astype(dt)  # [N, Ko, D]
    return slots_to_rows(
        out, ex["out_slot"], batch.edge_mask, ex["out_edge"], ex["rev_mask"]
    )


class ResidualLayer(nn.Module):
    dim: int

    @nn.compact
    def __call__(self, x):
        h = jax.nn.silu(TorchLinear(self.dim, name="lin1")(x))
        h = jax.nn.silu(TorchLinear(self.dim, name="lin2")(h))
        return x + h


class DimeNetConv(nn.Module):
    """One reference "conv": lin -> embedding -> interaction -> output block
    (``DIMEStack.py:79-116``)."""

    in_dim: int
    out_dim: int
    hidden_dim: int
    int_emb_size: int
    basis_emb_size: int
    out_emb_size: int
    num_radial: int
    num_spherical: int
    num_before_skip: int
    num_after_skip: int
    cutoff: float
    envelope_exponent: int
    # graph-partition mode: the triplet aggregation gathers the STATES of
    # (k->j) edges, which live on j's shard — an edge-level halo exchange
    # (the 2-hop part of the halo; node positions of k ride the ordinary
    # node halo, which the partitioner widens to 2 hops for triplets).
    partition_axis: str = None

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        act = jax.nn.silu
        ex = batch.extras
        bmm_mode = (
            ex is not None
            and ("dn2_rad" in ex or "out_edge" in ex)
            and self.partition_axis is None
        )
        if ex is None or not (bmm_mode or "trip_i" in ex):
            raise ValueError(
                "DimeNet needs triplet index tables or dense neighbor "
                "lists in batch.extras; build batches with "
                "need_triplets=True (create_dataloaders / partition_graph)"
            )
        i, j = batch.receivers, batch.senders
        n = x.shape[0]
        num_edges = i.shape[0]

        if bmm_mode:
            if "dn2_rad" in ex:
                # hoisted by DIMEStack._prepare_batch (parameter-free,
                # shared by every interaction block)
                dist, rad, cbf = ex["dn2_dist"], ex["dn2_rad"], ex["dn2_cbf"]
            else:  # direct conv invocation without the stack's hoist
                dist, rad, cbf = _dimenet_geometry_dense(
                    batch, pos, self.num_spherical, self.num_radial,
                    self.cutoff, self.envelope_exponent,
                )
        elif "dn_dist" in ex:
            # hoisted by DIMEStack._prepare_batch: dist/angle/sbf are
            # parameter-free functions of the batch, identical for every
            # interaction block — computed ONCE per forward instead of
            # num_conv_layers times (the spherical Bessel/Legendre chains
            # are the transcendental-heavy part of the step)
            dist, sbf = ex["dn_dist"], ex["dn_sbf"]
        else:
            dist, sbf = _dimenet_geometry(
                batch,
                pos,
                self.num_spherical,
                self.num_radial,
                self.cutoff,
                self.envelope_exponent,
                self.partition_axis,
            )

        rbf = BesselBasisLayer(
            self.num_radial, self.cutoff, self.envelope_exponent, name="rbf"
        )(dist)

        # lin + embedding block (edge-level states)
        h = TorchLinear(self.hidden_dim, name="lin")(x)
        r = act(TorchLinear(self.hidden_dim, name="emb_lin_rbf")(rbf))
        e = act(
            TorchLinear(self.hidden_dim, name="emb_lin")(
                jnp.concatenate([h[i], h[j], r], axis=-1)
            )
        )

        # InteractionPPBlock
        rbf_b = TorchLinear(self.basis_emb_size, use_bias=False, name="int_rbf1")(rbf)
        rbf_b = TorchLinear(self.hidden_dim, use_bias=False, name="int_rbf2")(rbf_b)
        lin_sbf1 = TorchLinear(
            self.basis_emb_size, use_bias=False, name="int_sbf1"
        )
        lin_sbf2 = TorchLinear(
            self.int_emb_size, use_bias=False, name="int_sbf2"
        )
        x_ji = act(TorchLinear(self.hidden_dim, name="int_lin_ji")(e))
        x_kj = act(TorchLinear(self.hidden_dim, name="int_lin_kj")(e))
        x_kj = x_kj * rbf_b
        x_kj = act(TorchLinear(self.int_emb_size, use_bias=False, name="int_down")(x_kj))
        if bmm_mode:
            x_kj = _bmm_triplet_aggregate(
                x_kj, rad, cbf, lin_sbf1, lin_sbf2, batch,
                self.num_spherical, self.num_radial,
            )
        else:
            idx_kj, idx_ji = ex["trip_kj"], ex["trip_ji"]
            trip_mask = ex["trip_mask"]
            sbf_b = lin_sbf2(lin_sbf1(sbf))
            if self.partition_axis is not None:
                from hydragnn_tpu.parallel.graph_partition import halo_extend

                # extend the edge-state table with fresh (k->j) states from
                # their owner shards; idx_kj already references this layout
                x_kj = halo_extend(
                    x_kj, ex["halo_send_edges"], self.partition_axis
                )
            x_kj = jnp.where(trip_mask[:, None], x_kj[idx_kj] * sbf_b, 0.0)
            x_kj = segment_sum(x_kj, idx_ji, num_edges)
        x_kj = act(TorchLinear(self.hidden_dim, use_bias=False, name="int_up")(x_kj))
        hh = x_ji + x_kj
        for bi in range(self.num_before_skip):
            hh = ResidualLayer(self.hidden_dim, name=f"before_skip_{bi}")(hh)
        hh = act(TorchLinear(self.hidden_dim, name="int_lin")(hh)) + e
        for ai in range(self.num_after_skip):
            hh = ResidualLayer(self.hidden_dim, name=f"after_skip_{ai}")(hh)

        # OutputPPBlock: edge states -> node states
        o = TorchLinear(self.hidden_dim, use_bias=False, name="out_lin_rbf")(rbf) * hh
        o = jnp.where(batch.edge_mask[:, None], o, 0.0)
        if "nbr_edge" in ex and self.partition_axis is None:
            # edges -> receivers through the neighbor-edge lists (each edge
            # has exactly one receiver: group_sum applies)
            from hydragnn_tpu.ops.dense_agg import group_sum

            o = group_sum(
                o, ex["nbr_edge"], ex["nbr_mask"], i, batch.edge_mask
            )
        else:
            o = segment_sum(o, i, n)
        o = TorchLinear(self.out_emb_size, use_bias=False, name="out_up")(o)
        o = act(TorchLinear(self.out_emb_size, name="out_0")(o))
        o = TorchLinear(self.out_dim, use_bias=False, name="out_final")(o)
        return o, pos


class DIMEStack(HydraBase):
    conv_needs_pos: bool = True
    basis_emb_size: int = 8
    envelope_exponent: int = 5
    int_emb_size: int = 64
    out_emb_size: int = 128
    num_after_skip: int = 2
    num_before_skip: int = 1
    num_radial: int = 6
    num_spherical: int = 7
    radius: float = 2.0
    conv_use_batchnorm: bool = False  # Identity feature layers (DIMEStack.py:73)

    def _prepare_batch(self, batch):
        """Hoist the parameter-free geometry that every interaction block
        consumes identically — one evaluation of the spherical Bessel /
        Legendre chains per forward instead of ``num_conv_layers`` (the
        reference recomputes per block, ``DIMEStack.py:79-116``; on TPU
        the transcendental chain is VPU time that scaled with depth for
        no reason). Dense-list batches get the bmm-path geometry
        (dist/rad/cbf on the per-node slot grids); triplet-table batches
        get dist/sbf on the T axis."""
        ex = batch.extras
        if (
            ex is None
            or "dn_dist" in ex
            or "dn2_rad" in ex
            or self.partition_axis is not None
            # partition mode: geometry must be evaluated on the PER-LAYER
            # halo-extended node table inside _apply_conv, not here
        ):
            return batch
        merged = dict(ex)
        if "out_edge" in ex:
            dist, rad, cbf = _dimenet_geometry_dense(
                batch,
                batch.pos,
                self.num_spherical,
                self.num_radial,
                self.radius,
                self.envelope_exponent,
            )
            merged.update(
                {"dn2_dist": dist, "dn2_rad": rad, "dn2_cbf": cbf}
            )
        elif "trip_i" in ex:
            dist, sbf = _dimenet_geometry(
                batch,
                batch.pos,
                self.num_spherical,
                self.num_radial,
                self.radius,
                self.envelope_exponent,
                self.partition_axis,
            )
            merged.update({"dn_dist": dist, "dn_sbf": sbf})
        else:
            return batch
        return batch.replace(extras=merged)

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        # hidden = out if in==1 else in (DIMEStack.py:80)
        hidden_dim = out_dim if in_dim == 1 else in_dim
        assert hidden_dim > 1, (
            "DimeNet requires more than one hidden dimension between "
            "input_dim and output_dim."
        )
        return self._conv_cls(DimeNetConv)(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            hidden_dim=hidden_dim,
            int_emb_size=self.int_emb_size,
            basis_emb_size=self.basis_emb_size,
            out_emb_size=self.out_emb_size,
            num_radial=self.num_radial,
            num_spherical=self.num_spherical,
            num_before_skip=self.num_before_skip,
            num_after_skip=self.num_after_skip,
            cutoff=self.radius,
            envelope_exponent=self.envelope_exponent,
            partition_axis=self.partition_axis,
        )


def compute_triplets(edge_index: np.ndarray, num_nodes: int):
    """Host-side triplet construction (k->j -> j->i), numpy.

    Same contract as the reference's SparseTensor version
    (``DIMEStack.py:158-182``): for every directed edge j->i and every edge
    k->j with k != i, emit (idx_i, idx_j, idx_k, idx_kj, idx_ji).
    """
    row, col = np.asarray(edge_index[0]), np.asarray(edge_index[1])  # j -> i
    num_edges = row.shape[0]
    if num_edges == 0:
        z = np.zeros(0, np.int32)
        return z, z, z, z, z
    # vectorized (k->j, j->i) join: group edges by receiver, then for every
    # edge (j->i) expand over the in-edges of its sender j — O(sort + T),
    # no Python loops (giant partitioned graphs hit this path host-side)
    order = np.argsort(col, kind="stable")  # in-edge ids per node, eid-ascending
    deg = np.bincount(col, minlength=num_nodes)
    starts = np.concatenate([[0], np.cumsum(deg)[:-1]])
    c1 = deg[row]  # kj candidates per (j->i) edge
    total = int(c1.sum())
    tji = np.repeat(np.arange(num_edges), c1)
    within = np.arange(total) - np.repeat(np.cumsum(c1) - c1, c1)
    tkj = order[starts[row[tji]] + within]
    ti = col[tji]
    tj = row[tji]
    tk = row[tkj]
    keep = tk != ti  # exclude backtracking triplets (k == i)
    return (
        ti[keep].astype(np.int32),
        tj[keep].astype(np.int32),
        tk[keep].astype(np.int32),
        tkj[keep].astype(np.int32),
        tji[keep].astype(np.int32),
    )
