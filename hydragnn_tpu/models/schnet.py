"""SchNet stack (SCF) — continuous-filter convolutions.

Parity with reference ``hydragnn/models/SCFStack.py:32-223``: GaussianSmearing
distance basis, CFConv with cosine cutoff, ShiftedSoftplus filter MLP,
Identity feature layers (NO BatchNorm in the encoder, ``SCFStack.py:51-68``),
optional E(3)-equivariant position updates gated OFF on the last conv layer
(``:59-66``).

TPU design note: the reference recomputes the radius interaction graph from
positions every layer (``RadiusInteractionGraph``). Under XLA we keep the edge
TOPOLOGY static (host-side radius graph with the same cutoff) and recompute
edge WEIGHTS from the current positions each layer — identical when positions
are fixed, and a faithful approximation under the tiny (gain=1e-3) equivariant
position updates.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_mean, segment_sum
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear, gather_weighted_segment_sum


def shifted_softplus(x):
    return jax.nn.softplus(x) - math.log(2.0)


def _safe_sqrt(x):
    """sqrt with a finite gradient at 0 (double-where): degenerate
    zero-distance pairs (padding edges, dense-layout fill slots) otherwise
    turn a zero cotangent into NaN once pos is parameter-dependent."""
    nonzero = x > 0
    safe = jnp.where(nonzero, x, 1.0)
    return jnp.where(nonzero, jnp.sqrt(safe), 0.0)


class GaussianSmearing(nn.Module):
    start: float
    stop: float
    num_gaussians: int

    @nn.compact
    def __call__(self, dist):
        offset = jnp.linspace(self.start, self.stop, self.num_gaussians)
        coeff = -0.5 / (offset[1] - offset[0]) ** 2
        # rank-agnostic: [E] -> [E, G] and dense [N, K] -> [N, K, G]
        d = dist[..., None] - offset
        # coeff < 0 and d*d >= 0, so the clamp is forward-identical (and
        # gradient-identical: at the d=0 tie the inner chain-rule factor
        # 2*coeff*d is already 0) — it bounds the exp for the numerics
        # gate against a future dist that escapes the cutoff clamp
        return jnp.exp(jnp.minimum(coeff * d * d, 0.0))


class CFConv(nn.Module):
    in_dim: int
    out_dim: int
    num_filters: int
    num_gaussians: int
    cutoff: float
    equivariant: bool
    use_edge_attr: bool
    # graph-partition mode: the coord update aggregates at SENDERS — partials
    # on halo rows are folded back to their owner shard (see egnn.py).
    partition_axis: str = None

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        send, recv = batch.senders, batch.receivers
        extras = batch.extras or {}
        dense = "nbr_idx" in extras
        if dense:
            # dense scatter-free frame (ops/dense_agg.py): every per-edge
            # quantity lives as [N, K, *]; pos gathers go through the
            # custom-VJP gather so the equivariant backward stays
            # scatter-free too
            from hydragnn_tpu.ops.dense_agg import gather_neighbors

            nbr, nmask = extras["nbr_idx"], extras["nbr_mask"]
            rev, rmask = extras["rev_idx"], extras["rev_mask"]
            pos_j = gather_neighbors(pos, nbr, rev, rmask)
            pos_i = jnp.broadcast_to(pos[:, None, :], pos_j.shape)
            if self.use_edge_attr:
                edge_weight = jnp.linalg.norm(
                    batch.edge_attr[extras["nbr_edge"]], axis=-1
                )
            else:
                diff = pos_j - pos_i
                edge_weight = _safe_sqrt((diff * diff).sum(-1))
            emask = nmask
        elif self.use_edge_attr:
            # reference: edge_weight = edge_attr.norm(dim=-1) on the
            # normalized lengths (SCFStack.py:123-131)
            edge_weight = jnp.linalg.norm(batch.edge_attr, axis=-1)
        else:
            diff = pos[send] - pos[recv]
            edge_weight = _safe_sqrt((diff * diff).sum(-1))
        edge_attr = GaussianSmearing(0.0, self.cutoff, self.num_gaussians)(
            edge_weight
        )

        # filter network: Linear, ShiftedSoftplus, Linear; cosine cutoff
        w = TorchLinear(self.num_filters, name="filter_0")(edge_attr)
        w = shifted_softplus(w)
        w = TorchLinear(self.num_filters, name="filter_1")(w)
        cos_cut = 0.5 * (jnp.cos(edge_weight * math.pi / self.cutoff) + 1.0)
        w = w * cos_cut[..., None]
        if dense:
            w = jnp.where(emask[..., None], w, 0.0)
        else:
            w = jnp.where(batch.edge_mask[:, None], w, 0.0)

        glorot = nn.initializers.xavier_uniform()
        lin1 = self.param("lin1", glorot, (self.in_dim, self.num_filters))
        h = x @ lin1

        if self.equivariant:
            # coord update (SCFStack.py:173-181): aggregate at senders
            if dense:
                diff = pos_j - pos_i
            else:
                diff = pos[send] - pos[recv]
            norm = _safe_sqrt((diff * diff).sum(-1, keepdims=True)) + 1.0
            coord_diff = diff / norm
            cw = TorchLinear(self.num_filters, name="coord_mlp_0")(w)
            cw = jax.nn.relu(cw)
            small = nn.initializers.variance_scaling(
                0.001 * 0.001 / 3.0, "fan_avg", "uniform"
            )
            cw = cw @ self.param("coord_mlp_1", small, (self.num_filters, 1))
            trans = jnp.clip(coord_diff * cw, -100.0, 100.0)
            if dense:
                # sender-side sum through the reverse lists (scatter-free);
                # per-sender count = real out-degree
                from hydragnn_tpu.ops.dense_agg import aggregate_to_senders

                trans = jnp.where(nmask[..., None], trans, 0.0)
                agg = aggregate_to_senders(trans, nbr, nmask, rev, rmask)
                cnt = rmask.sum(axis=1).astype(trans.dtype)
                if self.partition_axis is not None:
                    from hydragnn_tpu.parallel.graph_partition import (
                        halo_reduce,
                    )

                    both = halo_reduce(
                        jnp.concatenate([agg, cnt[:, None]], -1),
                        batch.extras["halo_send"],
                        self.partition_axis,
                    )
                    agg, cnt = both[:, :3], both[:, 3]
            else:
                trans = jnp.where(batch.edge_mask[:, None], trans, 0.0)
                # trans and the count share one segment pass + halo_reduce
                both = segment_sum(
                    jnp.concatenate(
                        [trans, batch.edge_mask.astype(trans.dtype)[:, None]],
                        -1,
                    ),
                    send,
                    n,
                )
                if self.partition_axis is not None:
                    from hydragnn_tpu.parallel.graph_partition import (
                        halo_reduce,
                    )

                    both = halo_reduce(
                        both, batch.extras["halo_send"], self.partition_axis
                    )
                agg, cnt = both[:, :3], both[:, 3]
            pos = pos + agg / jnp.maximum(cnt, 1.0)[:, None]

        if dense:
            from hydragnn_tpu.ops.dense_agg import dense_sum, gather_neighbors

            h_j = gather_neighbors(h, nbr, rev, rmask)
            aggr = dense_sum(h_j * w, nmask)
        else:
            # continuous-filter aggregation through the shared helper: XLA
            # gather-multiply-scatter or the fused Pallas kernel
            # (autotuner/env decision); w is already edge-masked above
            aggr = gather_weighted_segment_sum(
                h, w, send, recv, n, model_key="SchNet"
            )
        lin2 = self.param("lin2", glorot, (self.num_filters, self.out_dim))
        bias2 = self.param("bias2", nn.initializers.zeros, (self.out_dim,))
        out = aggr @ lin2 + bias2
        return out, pos


class SCFStack(HydraBase):
    conv_needs_pos: bool = True
    num_filters: int = 126
    num_gaussians: int = 50
    radius: float = 2.0
    conv_use_batchnorm: bool = False  # Identity feature layers (SCFStack.py:63)

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(CFConv)(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            num_filters=self.num_filters,
            num_gaussians=self.num_gaussians,
            cutoff=self.radius,
            equivariant=self.equivariance and not last_layer,
            use_edge_attr=self.use_edge_attr,
            partition_axis=self.partition_axis,
        )

    def _conv_layer_specs(self):
        # same dims as Base, but the equivariance gate needs last_layer info
        specs = []
        for i in range(self.num_conv_layers):
            in_dim = self.input_dim if i == 0 else self.hidden_dim
            specs.append(
                (
                    in_dim,
                    self.hidden_dim,
                    self.hidden_dim,
                    {"last_layer": i == self.num_conv_layers - 1},
                )
            )
        return specs
