"""GAT stack — GATv2 attention.

Parity with reference ``hydragnn/models/GATStack.py:22-118`` (PyG GATv2Conv:
heads/negative_slope from the factory — 6 / 0.05, ``models/create.py:150-152``
— dropout on attention, add_self_loops=True, per-layer concat schedule:
hidden layers concat heads, final layer averages them,
``GATStack.py:36-47``).

TPU shape: self-loops are appended as a virtual edge block (static shapes);
attention softmax is a masked segment softmax over receivers.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_softmax_unnorm, segment_sum
from hydragnn_tpu.models.base import HydraBase


class GATv2Conv(nn.Module):
    in_dim: int
    out_dim: int
    heads: int
    negative_slope: float
    dropout: float
    concat: bool

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        h, c = self.heads, self.out_dim
        glorot = nn.initializers.xavier_uniform()
        w_l = self.param("w_l", glorot, (self.in_dim, h * c))
        b_l = self.param("b_l", nn.initializers.zeros, (h * c,))
        w_r = self.param("w_r", glorot, (self.in_dim, h * c))
        b_r = self.param("b_r", nn.initializers.zeros, (h * c,))
        att = self.param("att", glorot, (1, h, c))

        x_l = (x @ w_l + b_l).reshape(n, h, c)
        x_r = (x @ w_r + b_r).reshape(n, h, c)

        extras = batch.extras or {}
        if "nbr_idx" in extras:
            # dense scatter-free path: attention softmax is LOCAL over the
            # K neighbor slots + 1 self-loop slot — no segment ops at all.
            # The [N, K, H*C] gathered messages are the HBM cost center at
            # GAT's concat widths (H*C = 1536 at hidden 256): they are
            # materialized ONCE and every consumer reads them in place —
            # no [N, K+1, ...] concat copy (the self-loop slot is handled
            # as separate [N, H, C] terms), and the weighted-message sum
            # contracts the K axis with a dot instead of re-reading a
            # broadcast product.
            from hydragnn_tpu.ops.dense_agg import gather_neighbors

            nmask = extras["nbr_mask"]  # [N, K]
            xl_j = gather_neighbors(
                x_l.reshape(n, h * c),
                extras["nbr_idx"],
                extras["rev_idx"],
                extras["rev_mask"],
            ).reshape(n, -1, h, c)  # [N, K, H, C]
            k = xl_j.shape[1]
            alpha_n = (
                jax.nn.leaky_relu(xl_j + x_r[:, None], self.negative_slope)
                * att
            ).sum(axis=-1)  # [N, K, H]
            alpha_s = (
                jax.nn.leaky_relu(x_l + x_r, self.negative_slope) * att
            ).sum(axis=-1)  # [N, H] self-loop
            alpha_n = jnp.where(nmask[..., None], alpha_n, -1e9)
            alpha_s = jnp.where(batch.node_mask[:, None], alpha_s, -1e9)
            # fully-masked (padded) nodes: amax = -1e9 (finite by the
            # mask convention), exp(0)=1, then re-masked to 0 below
            amax = jnp.maximum(alpha_n.max(axis=1), alpha_s)[:, None]
            ex_n = jnp.where(
                nmask[..., None], jnp.exp(alpha_n - amax), 0.0
            )
            ex_s = jnp.where(
                batch.node_mask[:, None],
                jnp.exp(alpha_s - amax[:, 0]),
                0.0,
            )
            drop = nn.Dropout(rate=self.dropout, deterministic=not train)
            exd = drop(jnp.concatenate([ex_n, ex_s[:, None]], axis=1))
            # weighted message sum as a K-axis contraction (XLA chooses
            # the layout; reads xl_j once instead of a broadcast-product
            # rematerialization)
            num = jnp.einsum(
                "nkh,nkhc->nhc",
                exd[:, :k],
                xl_j,
                preferred_element_type=jnp.float32,
            ).astype(x_l.dtype)
            num = num + exd[:, k][..., None] * x_l
            den = ex_n.sum(axis=1) + ex_s  # [N, H]
            out = num / jnp.maximum(den[..., None], 1e-16)
        else:
            # real edges + one self-loop per node (add_self_loops=True)
            loop = jnp.arange(n, dtype=batch.senders.dtype)
            send = jnp.concatenate([batch.senders, loop])
            recv = jnp.concatenate([batch.receivers, loop])
            emask = jnp.concatenate([batch.edge_mask, batch.node_mask])

            g = x_l[send] + x_r[recv]
            g = jax.nn.leaky_relu(g, self.negative_slope)
            alpha = (g * att).sum(axis=-1)  # [E+N, H]
            # fused attention: softmax numerator (weighted messages) and
            # denominator share ONE scatter pass instead of
            # softmax-normalize + aggregate (3 scatter passes -> 2).
            # Attention dropout applies to the numerator only — identical
            # to dropping normalized alphas, since the 1/(1-p) scaling
            # commutes with the division.
            ex = segment_softmax_unnorm(alpha, recv, n, mask=emask)
            exd = nn.Dropout(rate=self.dropout, deterministic=not train)(ex)
            packed = jnp.concatenate(
                [x_l[send] * exd[..., None], ex[..., None]], axis=-1
            )  # [E+N, H, C+1]
            s = segment_sum(
                packed.reshape(packed.shape[0], h * (c + 1)), recv, n
            ).reshape(n, h, c + 1)
            out = s[..., :c] / jnp.maximum(s[..., -1:], 1e-16)  # [N, H, C]

        if self.concat:
            out = out.reshape(n, h * c)
            bias = self.param("bias", nn.initializers.zeros, (h * c,))
        else:
            out = out.mean(axis=1)
            bias = self.param("bias", nn.initializers.zeros, (c,))
        return out + bias, pos


class GATStack(HydraBase):
    heads: int = 6
    negative_slope: float = 0.05

    def _conv_layer_specs(self):
        # concat on all but the last conv layer (GATStack.py:36-47)
        specs = [
            (
                self.input_dim,
                self.hidden_dim,
                self.hidden_dim * self.heads,
                {"concat": True},
            )
        ]
        for _ in range(self.num_conv_layers - 2):
            specs.append(
                (
                    self.hidden_dim * self.heads,
                    self.hidden_dim,
                    self.hidden_dim * self.heads,
                    {"concat": True},
                )
            )
        specs.append(
            (
                self.hidden_dim * self.heads,
                self.hidden_dim,
                self.hidden_dim,
                {"concat": False},
            )
        )
        return specs

    def _node_conv_specs(self, node_cfg, head_dim):
        # concat on hidden node-head convs, average on the output conv
        # (GATStack.py:49-90)
        dims = node_cfg["dim_headlayers"]
        num = node_cfg["num_headlayers"]
        specs = [
            (
                self.hidden_dim,
                dims[0],
                dims[0] * self.heads,
                {"concat": True, "last_layer": False},
            )
        ]
        for il in range(num - 1):
            specs.append(
                (
                    dims[il] * self.heads,
                    dims[il + 1],
                    dims[il + 1] * self.heads,
                    {"concat": True, "last_layer": False},
                )
            )
        specs.append(
            (
                dims[-1] * self.heads,
                head_dim,
                head_dim,
                {"concat": False, "last_layer": True},
            )
        )
        return specs

    def get_conv(
        self,
        in_dim: int,
        out_dim: int,
        last_layer: bool = False,
        concat: bool = True,
        name=None,
        **kw,
    ):
        return self._conv_cls(GATv2Conv)(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            heads=self.heads,
            negative_slope=self.negative_slope,
            dropout=self.dropout,
            concat=concat,
        )
