from hydragnn_tpu.models.base import HydraBase, MLPNode
from hydragnn_tpu.models.create import (
    MODEL_TYPES,
    create_model_config,
    init_model_params,
    print_model,
)
from hydragnn_tpu.models.common import (
    MLP,
    MaskedBatchNorm,
    TorchLinear,
    get_activation,
    global_mean_pool,
    masked_error,
)
from hydragnn_tpu.models.pna import PNAStack
from hydragnn_tpu.models.gin import GINStack
from hydragnn_tpu.models.gat import GATStack
from hydragnn_tpu.models.mfc import MFCStack
from hydragnn_tpu.models.sage import SAGEStack
from hydragnn_tpu.models.cgcnn import CGCNNStack
from hydragnn_tpu.models.schnet import SCFStack
from hydragnn_tpu.models.egnn import EGCLStack
from hydragnn_tpu.models.dimenet import DIMEStack, compute_triplets
