"""MFC stack — Molecular Fingerprint Convolution.

Parity with reference ``hydragnn/models/MFCStack.py:22-51`` (PyG MFConv):
degree-indexed weight tables, out_i = W_l[d_i](sum_{j->i} x_j) + W_r[d_i](x_i)
with d_i clamped at ``max_degree`` (= config max_neighbours,
``models/create.py``), W_r without bias.

TPU shape: instead of PyG's Python loop over degree buckets with boolean
indexing (dynamic shapes), the weight tables are stacked parameter banks
``[K+1, in, out]`` applied through a one-hot degree expansion — ONE MXU
matmul over the fused (degree-class, feature) axis. The obvious
alternative (gather ``w[deg]`` then batched einsum) materializes a per-
node [in, out] weight matrix — [N, 256, 256] = 1.5 GB at hidden 256 —
and ran HBM-bound at 65 ms/step (round-3 BENCH_EXTRA); the one-hot form
spends K x the minimal FLOPs but they are dense matmul FLOPs, which is
the winning trade on the MXU (see BASELINE.md round 4).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_count, segment_sum
from hydragnn_tpu.models.base import HydraBase


class MFConv(nn.Module):
    in_dim: int
    out_dim: int
    max_degree: int
    # static dataset-wide max in-degree (config derivation); banks above
    # it can never be selected and are sliced out of the compute
    degree_bound: Optional[int] = None

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        k = self.max_degree + 1
        bound = 1.0 / jnp.sqrt(self.in_dim)

        def uniform(key, shape):
            return jax.random.uniform(key, shape, minval=-bound, maxval=bound)

        w_l = self.param("w_l", uniform, (k, self.in_dim, self.out_dim))
        b_l = self.param("b_l", uniform, (k, self.out_dim))
        w_r = self.param("w_r", uniform, (k, self.in_dim, self.out_dim))

        extras = batch.extras or {}
        if "nbr_idx" in extras:  # dense scatter-free path (ops/dense_agg.py)
            from hydragnn_tpu.ops.dense_agg import dense_sum, gather_neighbors

            nmask = extras["nbr_mask"]
            x_j = gather_neighbors(
                x, extras["nbr_idx"], extras["rev_idx"], extras["rev_mask"]
            )
            h = dense_sum(x_j, nmask)
            deg = nmask.sum(axis=1).astype(jnp.float32)
        else:
            msg = x[batch.senders]
            msg = jnp.where(batch.edge_mask[:, None], msg, 0.0)
            h = segment_sum(msg, batch.receivers, n)
            deg = segment_count(
                batch.receivers, n, weights=batch.edge_mask.astype(jnp.float32)
            )
        # static in-degree bounds — dense-list width and/or the dataset-wide
        # max from config derivation — let the compute slice dead banks off
        # the one-hot matmul (the parameter bank keeps its reference shape
        # [K+1, ...]). deg is clamped to the sliced range too, so
        # out-of-contract data (degree above the derived bound at predict
        # time) uses the top retained bank instead of silently zeroing.
        k_used = k
        if self.degree_bound is not None:
            k_used = min(k_used, self.degree_bound + 1)
        if "nbr_idx" in extras:
            k_used = min(k_used, int(extras["nbr_idx"].shape[1]) + 1)
        deg = jnp.clip(deg.astype(jnp.int32), 0, k_used - 1)
        # out_n = h_n @ w_l[deg_n] + x_n @ w_r[deg_n] + b_l[deg_n], with the
        # degree selection as a one-hot expansion: rows of the expanded
        # [N, 2*K*F] operand are zero outside the node's class block, so
        # one dense matmul applies every bank (zeros are exact — numerics
        # match the gathered-bank form)
        onehot = jax.nn.one_hot(deg, k_used, dtype=h.dtype)
        expanded = jnp.concatenate(
            [
                (onehot[:, :, None] * h[:, None, :]).reshape(n, -1),
                (onehot[:, :, None] * x[:, None, :]).reshape(n, -1),
            ],
            axis=1,
        )
        w = jnp.concatenate(
            [
                w_l[:k_used].reshape(k_used * self.in_dim, self.out_dim),
                w_r[:k_used].reshape(k_used * self.in_dim, self.out_dim),
            ],
            axis=0,
        )
        out = expanded @ w + b_l[deg]
        return out, pos


class MFCStack(HydraBase):
    max_degree: int = 10
    degree_bound: Optional[int] = None

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(MFConv)(
            in_dim=in_dim,
            out_dim=out_dim,
            max_degree=self.max_degree,
            degree_bound=self.degree_bound,
            name=name,
        )
