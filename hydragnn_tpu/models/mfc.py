"""MFC stack — Molecular Fingerprint Convolution.

Parity with reference ``hydragnn/models/MFCStack.py:22-51`` (PyG MFConv):
degree-indexed weight tables, out_i = W_l[d_i](sum_{j->i} x_j) + W_r[d_i](x_i)
with d_i clamped at ``max_degree`` (= config max_neighbours,
``models/create.py``), W_r without bias.

TPU shape: instead of PyG's Python loop over degree buckets with boolean
indexing (dynamic shapes), the weight tables are stacked parameter banks
``[K+1, in, out]`` gathered per node — a single batched einsum on the MXU.
"""

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_count, segment_sum
from hydragnn_tpu.models.base import HydraBase


class MFConv(nn.Module):
    in_dim: int
    out_dim: int
    max_degree: int

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        k = self.max_degree + 1
        bound = 1.0 / jnp.sqrt(self.in_dim)

        def uniform(key, shape):
            return jax.random.uniform(key, shape, minval=-bound, maxval=bound)

        w_l = self.param("w_l", uniform, (k, self.in_dim, self.out_dim))
        b_l = self.param("b_l", uniform, (k, self.out_dim))
        w_r = self.param("w_r", uniform, (k, self.in_dim, self.out_dim))

        extras = batch.extras or {}
        if "nbr_idx" in extras:  # dense scatter-free path (ops/dense_agg.py)
            from hydragnn_tpu.ops.dense_agg import dense_sum, gather_neighbors

            nmask = extras["nbr_mask"]
            x_j = gather_neighbors(
                x, extras["nbr_idx"], extras["rev_idx"], extras["rev_mask"]
            )
            h = dense_sum(x_j, nmask)
            deg = nmask.sum(axis=1).astype(jnp.float32)
        else:
            msg = x[batch.senders]
            msg = jnp.where(batch.edge_mask[:, None], msg, 0.0)
            h = segment_sum(msg, batch.receivers, n)
            deg = segment_count(
                batch.receivers, n, weights=batch.edge_mask.astype(jnp.float32)
            )
        deg = jnp.clip(deg.astype(jnp.int32), 0, self.max_degree)
        out = (
            jnp.einsum("nf,nfo->no", h, w_l[deg])
            + jnp.einsum("nf,nfo->no", x, w_r[deg])
            + b_l[deg]
        )
        return out, pos


class MFCStack(HydraBase):
    max_degree: int = 10

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(MFConv)(
            in_dim=in_dim, out_dim=out_dim, max_degree=self.max_degree, name=name
        )
