"""EGNN stack — E(n)-equivariant graph convolution.

Parity with reference ``hydragnn/models/EGCLStack.py:21-245`` (custom E_GCL):
edge MLP on [h_row, h_col, ||dx||^2, e_ij] (2x Linear+ReLU), node MLP on
[h, aggregated messages], tanh-bounded equivariant coordinate update with
xavier(gain=1e-3) final layer, message aggregation at the SENDER index
(``:194,210`` — `row` = edge_index[0]), Identity feature layers (no encoder
BatchNorm, ``:36-46``), coord update gated off on the last layer.

TPU-first deviation: the first edge-MLP Linear is algebraically split into
node-axis projections (see the fusion comment in :class:`E_GCL`) — same
parameters, same math, degree-fold less edge-axis MXU work and no
``[E, 2D+1+edge]`` concat intermediate in HBM.
"""

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_sum
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import SplitLinear, TorchLinear, fused_site


def _safe_sqrt(x):
    """sqrt with a finite gradient at 0. Degenerate zero-distance pairs
    (padding edges; dense-layout fill slots) sit exactly at radial=0, where
    sqrt's inf derivative turns a zero cotangent into NaN (0*inf) once pos
    is parameter-dependent (equivariant layers >= 2). Double-where keeps
    real-edge values and gradients bit-identical and kills the NaN."""
    nonzero = x > 0
    safe = jnp.where(nonzero, x, 1.0)
    return jnp.where(nonzero, jnp.sqrt(safe), 0.0)


class E_GCL(nn.Module):
    in_dim: int
    out_dim: int
    hidden_dim: int
    edge_attr_dim: int
    equivariant: bool
    # graph-partition mode: aggregations at the SENDER index land partly on
    # halo rows (edges are owned by the receiver's shard) and must be folded
    # back onto their owner via all_to_all (halo_reduce).
    partition_axis: str = None

    def _sender_sum(self, data, row, n, batch):
        out = segment_sum(data, row, n)
        if self.partition_axis is not None:
            from hydragnn_tpu.parallel.graph_partition import halo_reduce

            out = halo_reduce(out, batch.extras["halo_send"], self.partition_axis)
        return out

    def _sender_sum_dense(self, data, extras, batch):
        """Dense-frame sender aggregation: reverse-list sum
        (ops/dense_agg.py), plus the partition halo fold."""
        from hydragnn_tpu.ops.dense_agg import aggregate_to_senders

        out = aggregate_to_senders(
            data,
            extras["nbr_idx"],
            extras["nbr_mask"],
            extras["rev_idx"],
            extras["rev_mask"],
        )
        if self.partition_axis is not None:
            from hydragnn_tpu.parallel.graph_partition import halo_reduce

            out = halo_reduce(out, batch.extras["halo_send"], self.partition_axis)
        return out

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        row, col = batch.senders, batch.receivers
        extras = batch.extras or {}
        dense = "nbr_idx" in extras
        in_dim = x.shape[-1]

        # ---- algebraic edge-MLP fusion (round-4 verdict item 2) ----
        # The first edge-MLP Linear acts on concat([x_row, x_col, radial,
        # e_ij]), so by linearity
        #   L0 = x_row @ Wr + (x_col @ Wc + b) + radial * w_rad + e @ We
        # with the two D x H projections computed ONCE per NODE (deg-fold
        # less MXU work than the edge-axis matmul) and only cheap adds /
        # a rank-1 radial term left on the edge axis. The [E, 2D+1+edge]
        # concat intermediate disappears entirely. Parameters stay
        # TorchLinear-compatible (SplitLinear shares names/shapes/init),
        # same PNA move as models/pna.py:53-74.
        fan_in = 2 * in_dim + 1 + self.edge_attr_dim
        pre = SplitLinear(
            features=self.hidden_dim, fan_in=fan_in, name="edge_mlp_0"
        )
        y_snd = pre.piece(x, 0)  # sender-side contribution [N, H]
        y_rcv = pre.piece(x, in_dim) + pre.bias  # receiver side + bias
        w_rad = pre.kernel[2 * in_dim]  # [H] radial row

        # ---- fully fused edge phase (ops/fused_mp.py, autotuner/env
        # opt-in): radial + two-layer edge MLP (+ the equivariant coord
        # update) + the packed sender-side aggregation run as ONE Pallas
        # kernel — the [E, H] edge intermediate never exists in HBM.
        # Parameters are declared through SplitLinear under the SAME
        # names/shapes/init as the unfused TorchLinear path, so
        # checkpoints and seeded trajectories are unchanged.
        if (
            not dense
            and self.partition_axis is None
            and fused_site(
                "EGNN",
                n,
                row.shape[0],
                self.hidden_dim + 3,
                self.hidden_dim + (4 if self.equivariant else 1),
                table_dim_b=self.hidden_dim + 3,
            )
        ):
            from hydragnn_tpu.ops import fused_egnn_edge_phase

            lin1 = SplitLinear(
                features=self.hidden_dim, fan_in=self.hidden_dim,
                name="edge_mlp_1",
            )
            edge_params = [w_rad, lin1.kernel, lin1.bias]
            if self.equivariant:
                cm0 = SplitLinear(
                    features=self.hidden_dim, fan_in=self.hidden_dim,
                    name="coord_mlp_0",
                )
                small = nn.initializers.variance_scaling(
                    0.001 * 0.001 / 3.0, "fan_avg", "uniform"
                )
                cm1 = self.param(
                    "coord_mlp_1", small, (self.hidden_dim, 1)
                )
                edge_params += [cm0.kernel, cm0.bias, cm1]
            ze = (
                pre.piece(batch.edge_attr, 2 * in_dim + 1)
                if self.edge_attr_dim > 0
                else None
            )
            out = fused_egnn_edge_phase(
                y_snd, y_rcv, pos, edge_params, row, col, n,
                batch.edge_mask, ze=ze,
            )
            agg = out[:, : self.hidden_dim].astype(x.dtype)
            if self.equivariant:
                coord_agg = out[:, self.hidden_dim : self.hidden_dim + 3]
                cnt = out[:, -1]
                pos = pos + coord_agg / jnp.maximum(cnt, 1.0)[:, None]
            h = jnp.concatenate([x, agg], axis=-1)
            h = jax.nn.relu(TorchLinear(self.hidden_dim, name="node_mlp_0")(h))
            h = TorchLinear(self.out_dim, name="node_mlp_1")(h)
            return h, pos

        if dense:
            # dense scatter-free frame: per-edge values live as [N, K, *]
            # keyed by (receiver, slot); j = sender, i = receiver
            from hydragnn_tpu.ops.dense_agg import gather_neighbors

            nmask = extras["nbr_mask"]
            emask_nd = nmask[..., None]
            # ONE fused gather for projected-features+positions (halves the
            # gather / reverse-gather traffic — the dominant dense-mode cost)
            both_j = gather_neighbors(
                jnp.concatenate([y_snd, pos], axis=-1),
                extras["nbr_idx"],
                extras["rev_idx"],
                extras["rev_mask"],
            )
            y_j, pos_j = both_j[..., : self.hidden_dim], both_j[..., self.hidden_dim :]
            coord_diff = pos_j - pos[:, None, :]
            radial = (coord_diff * coord_diff).sum(-1, keepdims=True)
            norm = _safe_sqrt(radial) + 1.0  # norm_diff=True
            coord_diff = coord_diff / norm
            e = y_j + y_rcv[:, None, :] + radial * w_rad
            if self.edge_attr_dim > 0:
                # gather the NARROW raw edge_attr first, project after —
                # projecting first would gather [N, K, H] instead of
                # [N, K, edge_dim] and add a backward scatter
                e = e + pre.piece(
                    batch.edge_attr[extras["nbr_edge"]], 2 * in_dim + 1
                )
        else:
            emask_nd = batch.edge_mask[:, None]
            coord_diff = pos[row] - pos[col]
            radial = (coord_diff * coord_diff).sum(-1, keepdims=True)
            norm = _safe_sqrt(radial) + 1.0  # norm_diff=True
            coord_diff = coord_diff / norm
            e = y_snd[row] + y_rcv[col] + radial * w_rad
            if self.edge_attr_dim > 0:
                e = e + pre.piece(batch.edge_attr, 2 * in_dim + 1)
        e = jax.nn.relu(e)
        e = jax.nn.relu(TorchLinear(self.hidden_dim, name="edge_mlp_1")(e))
        e = jnp.where(emask_nd, e, 0.0)

        if self.equivariant:
            cw = jax.nn.relu(TorchLinear(self.hidden_dim, name="coord_mlp_0")(e))
            small = nn.initializers.variance_scaling(
                0.001 * 0.001 / 3.0, "fan_avg", "uniform"
            )
            cw = cw @ self.param("coord_mlp_1", small, (self.hidden_dim, 1))
            cw = jnp.tanh(cw)  # tanh=True bounds the update
            trans = jnp.clip(coord_diff * cw, -100.0, 100.0)
            trans = jnp.where(emask_nd, trans, 0.0)
            # the coord update (trans + count) and the node-model message
            # aggregation all land at the SAME sender index — ONE packed
            # pass (and one halo_reduce) instead of two
            packed = jnp.concatenate(
                [e, trans, emask_nd.astype(trans.dtype)], -1
            )
            both = (
                self._sender_sum_dense(packed, extras, batch)
                if dense
                else self._sender_sum(packed, row, n, batch)
            )
            agg = both[:, : self.hidden_dim]
            coord_agg = both[:, self.hidden_dim : self.hidden_dim + 3]
            cnt = both[:, -1]
            pos = pos + coord_agg / jnp.maximum(cnt, 1.0)[:, None]
        else:
            # node model: aggregate edge features at the sender index (row)
            agg = (
                self._sender_sum_dense(e, extras, batch)
                if dense
                else self._sender_sum(e, row, n, batch)
            )
        h = jnp.concatenate([x, agg], axis=-1)
        h = jax.nn.relu(TorchLinear(self.hidden_dim, name="node_mlp_0")(h))
        h = TorchLinear(self.out_dim, name="node_mlp_1")(h)
        return h, pos


class EGCLStack(HydraBase):
    conv_needs_pos: bool = True
    conv_use_batchnorm: bool = False  # Identity feature layers (EGCLStack.py:41)

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(E_GCL)(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            hidden_dim=self.hidden_dim,
            edge_attr_dim=self.edge_dim if self.edge_dim else 0,
            equivariant=self.equivariance and not last_layer,
            partition_axis=self.partition_axis,
        )

    def _conv_layer_specs(self):
        specs = []
        for i in range(self.num_conv_layers):
            in_dim = self.input_dim if i == 0 else self.hidden_dim
            specs.append(
                (
                    in_dim,
                    self.hidden_dim,
                    self.hidden_dim,
                    {"last_layer": i == self.num_conv_layers - 1},
                )
            )
        return specs
