"""EGNN stack — E(n)-equivariant graph convolution.

Parity with reference ``hydragnn/models/EGCLStack.py:21-245`` (custom E_GCL):
edge MLP on [h_row, h_col, ||dx||^2, e_ij] (2x Linear+ReLU), node MLP on
[h, aggregated messages], tanh-bounded equivariant coordinate update with
xavier(gain=1e-3) final layer, message aggregation at the SENDER index
(``:194,210`` — `row` = edge_index[0]), Identity feature layers (no encoder
BatchNorm, ``:36-46``), coord update gated off on the last layer.
"""

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_sum
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear


class E_GCL(nn.Module):
    in_dim: int
    out_dim: int
    hidden_dim: int
    edge_attr_dim: int
    equivariant: bool
    # graph-partition mode: aggregations at the SENDER index land partly on
    # halo rows (edges are owned by the receiver's shard) and must be folded
    # back onto their owner via all_to_all (halo_reduce).
    partition_axis: str = None

    def _sender_sum(self, data, row, n, batch):
        out = segment_sum(data, row, n)
        if self.partition_axis is not None:
            from hydragnn_tpu.parallel.graph_partition import halo_reduce

            out = halo_reduce(out, batch.extras["halo_send"], self.partition_axis)
        return out

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        row, col = batch.senders, batch.receivers

        coord_diff = pos[row] - pos[col]
        radial = (coord_diff * coord_diff).sum(-1, keepdims=True)
        norm = jnp.sqrt(radial) + 1.0  # norm_diff=True
        coord_diff = coord_diff / norm

        parts = [x[row], x[col], radial]
        if self.edge_attr_dim > 0:
            parts.append(batch.edge_attr)
        e = jnp.concatenate(parts, axis=-1)
        e = jax.nn.relu(TorchLinear(self.hidden_dim, name="edge_mlp_0")(e))
        e = jax.nn.relu(TorchLinear(self.hidden_dim, name="edge_mlp_1")(e))
        e = jnp.where(batch.edge_mask[:, None], e, 0.0)

        if self.equivariant:
            cw = jax.nn.relu(TorchLinear(self.hidden_dim, name="coord_mlp_0")(e))
            small = nn.initializers.variance_scaling(
                0.001 * 0.001 / 3.0, "fan_avg", "uniform"
            )
            cw = cw @ self.param("coord_mlp_1", small, (self.hidden_dim, 1))
            cw = jnp.tanh(cw)  # tanh=True bounds the update
            trans = jnp.clip(coord_diff * cw, -100.0, 100.0)
            trans = jnp.where(batch.edge_mask[:, None], trans, 0.0)
            # the coord update (trans + count) and the node-model message
            # aggregation all land at the SAME sender index — ONE packed
            # scatter (and one halo_reduce) instead of two
            both = self._sender_sum(
                jnp.concatenate(
                    [e, trans, batch.edge_mask.astype(trans.dtype)[:, None]],
                    -1,
                ),
                row,
                n,
                batch,
            )
            agg = both[:, : self.hidden_dim]
            coord_agg = both[:, self.hidden_dim : self.hidden_dim + 3]
            cnt = both[:, -1]
            pos = pos + coord_agg / jnp.maximum(cnt, 1.0)[:, None]
        else:
            # node model: aggregate edge features at the sender index (row)
            agg = self._sender_sum(e, row, n, batch)
        h = jnp.concatenate([x, agg], axis=-1)
        h = jax.nn.relu(TorchLinear(self.hidden_dim, name="node_mlp_0")(h))
        h = TorchLinear(self.out_dim, name="node_mlp_1")(h)
        return h, pos


class EGCLStack(HydraBase):
    conv_needs_pos: bool = True
    conv_use_batchnorm: bool = False  # Identity feature layers (EGCLStack.py:41)

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(E_GCL)(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            hidden_dim=self.hidden_dim,
            edge_attr_dim=self.edge_dim if self.edge_dim else 0,
            equivariant=self.equivariance and not last_layer,
            partition_axis=self.partition_axis,
        )

    def _conv_layer_specs(self):
        specs = []
        for i in range(self.num_conv_layers):
            in_dim = self.input_dim if i == 0 else self.hidden_dim
            specs.append(
                (
                    in_dim,
                    self.hidden_dim,
                    self.hidden_dim,
                    {"last_layer": i == self.num_conv_layers - 1},
                )
            )
        return specs
