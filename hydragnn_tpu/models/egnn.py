"""EGNN stack — E(n)-equivariant graph convolution.

Parity with reference ``hydragnn/models/EGCLStack.py:21-245`` (custom E_GCL):
edge MLP on [h_row, h_col, ||dx||^2, e_ij] (2x Linear+ReLU), node MLP on
[h, aggregated messages], tanh-bounded equivariant coordinate update with
xavier(gain=1e-3) final layer, message aggregation at the SENDER index
(``:194,210`` — `row` = edge_index[0]), Identity feature layers (no encoder
BatchNorm, ``:36-46``), coord update gated off on the last layer.
"""

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_sum
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear


def _safe_sqrt(x):
    """sqrt with a finite gradient at 0. Degenerate zero-distance pairs
    (padding edges; dense-layout fill slots) sit exactly at radial=0, where
    sqrt's inf derivative turns a zero cotangent into NaN (0*inf) once pos
    is parameter-dependent (equivariant layers >= 2). Double-where keeps
    real-edge values and gradients bit-identical and kills the NaN."""
    nonzero = x > 0
    safe = jnp.where(nonzero, x, 1.0)
    return jnp.where(nonzero, jnp.sqrt(safe), 0.0)


class E_GCL(nn.Module):
    in_dim: int
    out_dim: int
    hidden_dim: int
    edge_attr_dim: int
    equivariant: bool
    # graph-partition mode: aggregations at the SENDER index land partly on
    # halo rows (edges are owned by the receiver's shard) and must be folded
    # back onto their owner via all_to_all (halo_reduce).
    partition_axis: str = None

    def _sender_sum(self, data, row, n, batch):
        out = segment_sum(data, row, n)
        if self.partition_axis is not None:
            from hydragnn_tpu.parallel.graph_partition import halo_reduce

            out = halo_reduce(out, batch.extras["halo_send"], self.partition_axis)
        return out

    def _sender_sum_dense(self, data, extras, batch):
        """Dense-frame sender aggregation: reverse-list sum
        (ops/dense_agg.py), plus the partition halo fold."""
        from hydragnn_tpu.ops.dense_agg import aggregate_to_senders

        out = aggregate_to_senders(
            data,
            extras["nbr_idx"],
            extras["nbr_mask"],
            extras["rev_idx"],
            extras["rev_mask"],
        )
        if self.partition_axis is not None:
            from hydragnn_tpu.parallel.graph_partition import halo_reduce

            out = halo_reduce(out, batch.extras["halo_send"], self.partition_axis)
        return out

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        row, col = batch.senders, batch.receivers
        extras = batch.extras or {}
        dense = "nbr_idx" in extras
        if dense:
            # dense scatter-free frame: per-edge values live as [N, K, *]
            # keyed by (receiver, slot); j = sender, i = receiver
            from hydragnn_tpu.ops.dense_agg import gather_neighbors

            nmask = extras["nbr_mask"]
            emask_nd = nmask[..., None]
            # ONE fused gather for features+positions (halves the gather /
            # reverse-gather traffic — the dominant dense-mode cost here)
            both_j = gather_neighbors(
                jnp.concatenate([x, pos], axis=-1),
                extras["nbr_idx"],
                extras["rev_idx"],
                extras["rev_mask"],
            )
            x_j, pos_j = both_j[..., : x.shape[-1]], both_j[..., x.shape[-1] :]
            coord_diff = pos_j - pos[:, None, :]
            radial = (coord_diff * coord_diff).sum(-1, keepdims=True)
            norm = _safe_sqrt(radial) + 1.0  # norm_diff=True
            coord_diff = coord_diff / norm
            parts = [x_j, jnp.broadcast_to(x[:, None, :], x_j.shape), radial]
            if self.edge_attr_dim > 0:
                parts.append(batch.edge_attr[extras["nbr_edge"]])
        else:
            emask_nd = batch.edge_mask[:, None]
            coord_diff = pos[row] - pos[col]
            radial = (coord_diff * coord_diff).sum(-1, keepdims=True)
            norm = _safe_sqrt(radial) + 1.0  # norm_diff=True
            coord_diff = coord_diff / norm
            parts = [x[row], x[col], radial]
            if self.edge_attr_dim > 0:
                parts.append(batch.edge_attr)
        e = jnp.concatenate(parts, axis=-1)
        e = jax.nn.relu(TorchLinear(self.hidden_dim, name="edge_mlp_0")(e))
        e = jax.nn.relu(TorchLinear(self.hidden_dim, name="edge_mlp_1")(e))
        e = jnp.where(emask_nd, e, 0.0)

        if self.equivariant:
            cw = jax.nn.relu(TorchLinear(self.hidden_dim, name="coord_mlp_0")(e))
            small = nn.initializers.variance_scaling(
                0.001 * 0.001 / 3.0, "fan_avg", "uniform"
            )
            cw = cw @ self.param("coord_mlp_1", small, (self.hidden_dim, 1))
            cw = jnp.tanh(cw)  # tanh=True bounds the update
            trans = jnp.clip(coord_diff * cw, -100.0, 100.0)
            trans = jnp.where(emask_nd, trans, 0.0)
            # the coord update (trans + count) and the node-model message
            # aggregation all land at the SAME sender index — ONE packed
            # pass (and one halo_reduce) instead of two
            packed = jnp.concatenate(
                [e, trans, emask_nd.astype(trans.dtype)], -1
            )
            both = (
                self._sender_sum_dense(packed, extras, batch)
                if dense
                else self._sender_sum(packed, row, n, batch)
            )
            agg = both[:, : self.hidden_dim]
            coord_agg = both[:, self.hidden_dim : self.hidden_dim + 3]
            cnt = both[:, -1]
            pos = pos + coord_agg / jnp.maximum(cnt, 1.0)[:, None]
        else:
            # node model: aggregate edge features at the sender index (row)
            agg = (
                self._sender_sum_dense(e, extras, batch)
                if dense
                else self._sender_sum(e, row, n, batch)
            )
        h = jnp.concatenate([x, agg], axis=-1)
        h = jax.nn.relu(TorchLinear(self.hidden_dim, name="node_mlp_0")(h))
        h = TorchLinear(self.out_dim, name="node_mlp_1")(h)
        return h, pos


class EGCLStack(HydraBase):
    conv_needs_pos: bool = True
    conv_use_batchnorm: bool = False  # Identity feature layers (EGCLStack.py:41)

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(E_GCL)(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            hidden_dim=self.hidden_dim,
            edge_attr_dim=self.edge_dim if self.edge_dim else 0,
            equivariant=self.equivariance and not last_layer,
            partition_axis=self.partition_axis,
        )

    def _conv_layer_specs(self):
        specs = []
        for i in range(self.num_conv_layers):
            in_dim = self.input_dim if i == 0 else self.hidden_dim
            specs.append(
                (
                    in_dim,
                    self.hidden_dim,
                    self.hidden_dim,
                    {"last_layer": i == self.num_conv_layers - 1},
                )
            )
        return specs
