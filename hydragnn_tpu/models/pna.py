"""PNA stack — Principal Neighbourhood Aggregation.

Behavioral parity with the reference's PyG ``PNAConv`` usage
(``hydragnn/models/PNAStack.py:19-69``): aggregators [mean, min, max, std],
scalers [identity, amplification, attenuation, linear], degree statistics from
the dataset degree histogram, pre_layers=1, post_layers=1, towers=1,
divide_input=False, optional edge encoder.

TPU shape: messages are a gather + fused MLP over the edge axis; the four
aggregations are segment reductions over receivers; scalers are elementwise;
the post-MLP is one MXU matmul over the node axis. Padded edges carry zeroed
messages and the padded-degree clamp keeps the log-scalers finite.
"""

import math
from typing import Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_minmax_fused, segment_moments_fused
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import SplitLinear, TorchLinear, fused_site


def pna_degree_averages(deg_histogram) -> Tuple[float, float]:
    """(avg_log, avg_lin) degree statistics from a degree histogram, matching
    PyG's DegreeScalerAggregation init (histogram produced by the analog of
    ``preprocess/utils.py:177-234``)."""
    total = float(sum(deg_histogram))
    total = max(total, 1.0)
    avg_log = (
        sum(h * math.log(d + 1.0) for d, h in enumerate(deg_histogram)) / total
    )
    avg_lin = sum(h * float(d) for d, h in enumerate(deg_histogram)) / total
    return max(avg_log, 1e-12), max(avg_lin, 1e-12)


class PNAConv(nn.Module):
    in_dim: int
    out_dim: int
    avg_deg_log: float
    avg_deg_lin: float
    edge_dim: Optional[int] = None

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        n = x.shape[0]
        extras = batch.extras or {}
        dense = "nbr_idx" in extras
        use_edge = self.edge_dim is not None and self.edge_dim > 0

        # ---- algebraic message-MLP fusion (round-3 verdict item 1) ----
        # pre_layers=1 means the message MLP is ONE Linear, so
        #   m[r, k] = concat([x_i, x_j, e]) @ W + b
        #           = (x_i @ Wi + b) + (x_j @ Wj + e @ We)
        #           =        yi[r]   +        z[edge]
        # with yi/yj computed by NODE-axis matmuls (K-fold less MXU work
        # than the edge-axis matmul) and z = yj[sender] (+ encoded edge).
        # The aggregators then commute with the per-receiver constant yi:
        # mean/min/max shift by yi, std is shift-invariant — so ALL FOUR
        # statistics reduce to reductions of z, and the [E, 2-3D] concat
        # plus the edge-axis matmul disappear entirely. Parameters stay
        # TorchLinear-compatible (SplitLinear shares names/shapes/init).
        fan_in = 2 * self.in_dim + (self.in_dim if use_edge else 0)
        pre = SplitLinear(
            features=self.in_dim, fan_in=fan_in, name="pre_nn"
        )
        yi = pre.piece(x, 0) + pre.bias  # [N, D]
        yj = pre.piece(x, self.in_dim)  # [N, D]
        ze = None  # [E, D] encoded-edge contribution, shared by both paths
        if use_edge:
            e = TorchLinear(self.in_dim, name="edge_encoder")(batch.edge_attr)
            ze = pre.piece(e, 2 * self.in_dim)

        if dense:
            # scatter-free path: fixed-width neighbor lists, aggregations
            # as masked K-axis reductions, backward via the reverse list.
            # (A fused banded Pallas variant of this gather+stats pass was
            # built and measured in rounds 3-4 — it lost to XLA's own
            # fusion at every scale and was deleted; closing A/B in
            # BASELINE.md round 4.)
            from hydragnn_tpu.ops.dense_agg import (
                dense_minmax,
                dense_moments,
                gather_neighbors,
            )

            nbr_mask = extras["nbr_mask"]
            nbr_idx = extras["nbr_idx"]
            z = gather_neighbors(
                yj, nbr_idx, extras["rev_idx"], extras["rev_mask"]
            )  # [N, K, D]
            if ze is not None:
                z = z + ze[extras["nbr_edge"]]
            z = jnp.where(nbr_mask[..., None], z, 0.0)
            mean_z, std, deg, has = dense_moments(z, nbr_mask)
            mn_z, mx_z = dense_minmax(z, nbr_mask, has)
        elif fused_site(
            "PNA", n, batch.senders.shape[0], self.in_dim,
            2 * self.in_dim + 1,
        ):
            # fully fused statistics pass (ops/fused_mp.py, autotuner/env
            # opt-in): gather yj at senders, add the encoded edge, mask,
            # and reduce (sum, count, sum-of-squares) at receivers in ONE
            # kernel; the per-edge z comes back from the same pass so the
            # min/max scatter below needs no second gather
            from hydragnn_tpu.ops import fused_gather_moments

            s, cnt, sq, z = fused_gather_moments(
                yj, batch.senders, batch.receivers, n, batch.edge_mask,
                ze=ze,
            )
            # back to the caller's dtype (the kernel accumulates f32):
            # under bf16 mixed precision the downstream scalers/concat
            # must not silently promote the whole conv to f32 — cnt
            # included, or deg drags mean_z/std (and the concat tail)
            # back up to f32
            s, cnt, sq, z = (a.astype(yj.dtype) for a in (s, cnt, sq, z))
            has = cnt > 0
            deg = jnp.maximum(cnt, 1.0)
            mean_z = s / deg
            std = jnp.sqrt(
                jnp.maximum(sq / deg - mean_z * mean_z, 0.0) + 1e-5
            )
            mn_z, mx_z = segment_minmax_fused(z, batch.receivers, n, has=has)
        else:
            z = yj[batch.senders]  # [E, D]
            if ze is not None:
                z = z + ze
            z = jnp.where(batch.edge_mask[:, None], z, 0.0)

            from hydragnn_tpu.ops import (
                pallas_segments_enabled,
                segment_moments,
            )

            # mean/std/degree from ONE pass over z — pallas kernel or the
            # packed-scatter XLA fallback (padded edges target the padding
            # node / carry zero weight, so real-node stats are untouched)
            if pallas_segments_enabled(n, z.shape[1], n_outputs=2):
                s, cnt, sq = segment_moments(z, batch.receivers, n)
            else:
                s, cnt, sq = segment_moments_fused(
                    z, batch.receivers, n, weights=batch.edge_mask
                )
            has = cnt > 0
            deg = jnp.maximum(cnt, 1.0)
            mean_z = s / deg
            # PNA std numerics: sqrt(relu(E[z^2]-E[z]^2)+eps); identical
            # for m = yi + z because variance ignores the constant shift
            std = jnp.sqrt(
                jnp.maximum(sq / deg - mean_z * mean_z, 0.0) + 1e-5
            )
            # min+max from ONE packed scatter; reuses the non-empty mask
            mn_z, mx_z = segment_minmax_fused(z, batch.receivers, n, has=has)

        # shift the yi constant back in; empty receivers keep the segment
        # fill of 0 (reference scatter semantics)
        mean = jnp.where(has, yi + mean_z, 0.0)
        mn = jnp.where(has, yi + mn_z, 0.0)
        mx = jnp.where(has, yi + mx_z, 0.0)
        aggr = jnp.concatenate([mean, mn, mx, std], axis=-1)
        log_deg = jnp.log(deg + 1.0)
        scaled = jnp.concatenate(
            [
                aggr,  # identity
                aggr * (log_deg / self.avg_deg_log),  # amplification
                aggr * (self.avg_deg_log / log_deg),  # attenuation
                aggr * (deg / self.avg_deg_lin),  # linear
            ],
            axis=-1,
        )
        out = jnp.concatenate([x, scaled], axis=-1)
        # post_layers=1 -> single Linear, then the conv's final lin
        out = TorchLinear(self.out_dim, name="post_nn")(out)
        out = TorchLinear(self.out_dim, name="lin")(out)
        return out, pos


class PNAStack(HydraBase):
    """Reference factory hardcodes: 4 aggregators x 4 scalers + deg histogram
    (``models/PNAStack.py:28-51``, ``models/create.py:112-127``)."""

    deg: Tuple[int, ...] = ()

    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        avg_log, avg_lin = pna_degree_averages(self.deg)
        cls = self._conv_cls(PNAConv)
        return cls(
            name=name,
            in_dim=in_dim,
            out_dim=out_dim,
            avg_deg_log=avg_log,
            avg_deg_lin=avg_lin,
            edge_dim=self.edge_dim if self.use_edge_attr else None,
        )
