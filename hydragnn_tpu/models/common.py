"""Shared building blocks for all model stacks.

Numerics are kept behaviorally equivalent to the reference's torch modules
(``hydragnn/models/Base.py``, ``hydragnn/utils/model.py:30-57``) — same
activations, same BatchNorm statistics (masked to real nodes), torch-style
uniform init so tiny CI-scale models land in the same loss basin — while the
implementation is pure functional JAX that XLA can fuse end to end.
"""

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_sum

# torch.nn.Linear default init: U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for both
# weight and bias (kaiming_uniform(a=sqrt(5))). variance_scaling(1/3, fan_in,
# uniform) reproduces the weight bound exactly.
torch_weight_init = nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform")


def torch_bias_init(fan_in: int):
    """torch.nn.Linear's bias init: U(-1/sqrt(fan_in), 1/sqrt(fan_in)).
    One shared factory so TorchLinear and SplitLinear stay init-identical
    by construction (SplitLinear's checkpoint/seed parity depends on it)."""
    bound = 1.0 / jnp.sqrt(fan_in)
    return lambda key, shape: jax.random.uniform(
        key, shape, minval=-bound, maxval=bound
    )


class SplitLinear(nn.Module):
    """Parameter-compatible with ``TorchLinear(features)`` applied to a
    concatenated ``[..., fan_in]`` input, but exposing kernel SLICES so a
    caller can exploit linearity: ``concat([a, b]) @ W == a @ W[:da] +
    b @ W[da:]``. Same param names ("kernel"/"bias"), shapes and init as
    TorchLinear — checkpoints and seeded-init trajectories are unchanged;
    only the order of floating-point contractions differs."""

    features: int
    fan_in: int

    def setup(self):
        self.kernel = self.param(
            "kernel", torch_weight_init, (self.fan_in, self.features)
        )
        self.bias = self.param(
            "bias", torch_bias_init(self.fan_in), (self.features,)
        )

    def piece(self, x, start: int):
        """``x @ kernel[start : start + x.shape[-1]]`` — one concat
        segment's contribution (no bias; add :attr:`bias` once)."""
        return x @ self.kernel[start : start + x.shape[-1]]

    def __call__(self, x):
        return x @ self.kernel + self.bias


class TorchLinear(nn.Module):
    """Dense layer with torch.nn.Linear's default initialization."""

    features: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        kernel = self.param("kernel", torch_weight_init, (fan_in, self.features))
        y = x @ kernel
        if self.use_bias:
            bias = self.param(
                "bias", torch_bias_init(fan_in), (self.features,)
            )
            y = y + bias
        return y


def get_activation(name: str) -> Callable:
    """Activation selection (reference: ``utils/model.py:30-47``)."""
    table = {
        "relu": jax.nn.relu,
        "selu": jax.nn.selu,
        "prelu": lambda x: jnp.where(x >= 0, x, 0.25 * x),  # PReLU at init slope
        "elu": jax.nn.elu,
        "gelu": jax.nn.gelu,
        "tanh": jnp.tanh,
        "lrelu_01": lambda x: jax.nn.leaky_relu(x, 0.1),
        "lrelu_025": lambda x: jax.nn.leaky_relu(x, 0.25),
        "lrelu_05": lambda x: jax.nn.leaky_relu(x, 0.5),
        "sigmoid": jax.nn.sigmoid,
    }
    if name not in table:
        raise ValueError(f"Unknown activation function: {name}")
    return table[name]


def masked_error(pred, target, mask, kind: str = "mse", axis_name: Optional[str] = None):
    """Masked elementwise loss, mean over real rows x features.

    Matches ``loss_function_selection`` (``utils/model.py:49-57``) applied to
    unpadded tensors: padding rows contribute nothing to numerator or count.

    ``axis_name``: when the rows of ``pred`` are sharded over a mesh axis
    (graph-partition parallelism), numerator and count are ``psum``'d over it
    so the result is the exact global mean — same numerics as unsharded.
    """
    pred = pred.astype(jnp.float32)  # loss reductions always in f32
    target = target.astype(jnp.float32)
    m = mask.reshape(mask.shape + (1,) * (pred.ndim - 1)).astype(pred.dtype)
    # where (not multiply) so NaN/inf garbage in padded rows cannot leak in
    diff = jnp.where(m > 0, pred - target, 0.0)
    count = m.sum() * pred.shape[-1]
    if kind == "mse":
        numer = (diff * diff).sum()
    elif kind == "mae":
        numer = jnp.abs(diff).sum()
    elif kind == "rmse":
        numer = (diff * diff).sum()
    elif kind == "smooth_l1":
        a = jnp.abs(diff)
        val = jnp.where(a < 1.0, 0.5 * diff * diff, a - 0.5)
        numer = (val * m).sum()
    else:
        raise ValueError(f"Unknown loss function: {kind}")
    if axis_name is not None:
        numer = jax.lax.psum(numer, axis_name)
        count = jax.lax.psum(count, axis_name)
    count = jnp.maximum(count, 1.0)
    out = numer / count
    if kind == "rmse":
        # double-where: sqrt'(0) is inf, so a perfectly-fit batch (zero
        # masked error) would NaN the backward pass; forward-identical
        # (sqrt(0) = 0 either way)
        positive = out > 0.0
        safe = jnp.where(positive, out, 1.0)
        out = jnp.where(positive, jnp.sqrt(safe), 0.0)
    return out


def masked_gaussian_nll(
    mu, logvar, target, mask, axis_name: Optional[str] = None, eps: float = 1e-6
):
    """Masked Gaussian negative log-likelihood, mean over real rows.

    The Kendall/Gal/Cipolla multi-task uncertainty weighting the reference
    declares but never finished (``models/Base.py:335-354`` raises "not
    ready yet"; the factory cannot even reach it, ``create.py:71``): each
    head emits one extra channel interpreted as a per-sample log-variance
    ``s``; the loss ``0.5 * (exp(-s) * (mu - y)^2 + s)`` learns to
    down-weight tasks/samples it is uncertain about. Matches torch's
    ``GaussianNLLLoss(full=False)`` up to the 1/2 s-vs-log(var) convention.
    """
    mu = mu.astype(jnp.float32)
    target = target.astype(jnp.float32)
    logvar = logvar.astype(jnp.float32)
    m = mask.reshape(mask.shape + (1,) * (mu.ndim - 1)).astype(mu.dtype)
    diff = jnp.where(m > 0, mu - target, 0.0)
    # clamp the variance away from zero like torch's GaussianNLLLoss(eps)
    logvar = jnp.maximum(logvar, jnp.log(eps))
    val = 0.5 * (jnp.exp(-logvar) * diff * diff + logvar)
    numer = (jnp.where(m > 0, val, 0.0)).sum()
    count = m.sum() * mu.shape[-1]
    if axis_name is not None:
        numer = jax.lax.psum(numer, axis_name)
        count = jax.lax.psum(count, axis_name)
    return numer / jnp.maximum(count, 1.0)


class MaskedBatchNorm(nn.Module):
    """BatchNorm1d over real nodes only (padding excluded from statistics).

    Same statistics contract as torch's BatchNorm1d (eps=1e-5, momentum=0.1,
    biased var for normalization, unbiased var into the running estimate),
    used after every conv layer (reference ``models/Base.py:115-121,295-302``).
    Under a jitted data-parallel step the batch statistics are global across
    the mesh — i.e. SyncBatchNorm semantics (``utils/distributed.py:268-269``)
    by construction, deterministically.
    """

    features: int
    momentum: float = 0.1
    eps: float = 1e-5
    # set when node rows are sharded over a mesh axis (graph-partition
    # parallelism): statistics are psum'd so every shard normalizes with the
    # exact global mean/var — SyncBatchNorm semantics across partitions.
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask, use_running_average: bool):
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((self.features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((self.features,), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))

        in_dtype = x.dtype
        x = x.astype(jnp.float32)  # statistics always in f32 (bf16 sums drift)
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        elif self.axis_name is not None:
            # two-pass (centered) like the local branch: E[x^2]-E[x]^2 would
            # catastrophically cancel in float32 for large-mean features
            m = mask.astype(x.dtype)[:, None]
            count = m.sum()
            s = (x * m).sum(axis=0)
            count, s = jax.lax.psum((count, s), self.axis_name)
            count = jnp.maximum(count, 1.0)
            mean = s / count
            centered = (x - mean) * m
            var = (
                jax.lax.psum((centered * centered).sum(axis=0), self.axis_name)
                / count
            )
            if not self.is_initializing():
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_mean.value = (
                    1.0 - self.momentum
                ) * ra_mean.value + self.momentum * mean
                ra_var.value = (
                    1.0 - self.momentum
                ) * ra_var.value + self.momentum * unbiased
        else:
            m = mask.astype(x.dtype)[:, None]
            count = jnp.maximum(m.sum(), 1.0)
            mean = (x * m).sum(axis=0) / count
            centered = (x - mean) * m
            var = (centered * centered).sum(axis=0) / count
            if not self.is_initializing():
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_mean.value = (
                    1.0 - self.momentum
                ) * ra_mean.value + self.momentum * mean
                ra_var.value = (
                    1.0 - self.momentum
                ) * ra_var.value + self.momentum * unbiased
        y = (x - mean) * jax.lax.rsqrt(var + self.eps) * scale + bias
        return jnp.where(mask[:, None], y, 0.0).astype(in_dtype)


class MLP(nn.Module):
    """Sequence of TorchLinear layers with activation after each hidden layer.

    ``final_activation`` mirrors the reference's shared graph-head layers,
    which end in an activation (``models/Base.py:208-217``), vs per-head MLPs
    which end in a bare Linear (``:231-244``).
    """

    layer_dims: Sequence[int]
    activation: str = "relu"
    final_activation: bool = False
    final_bias_value: Optional[float] = None  # UQ initial_bias (Base.py:138-143)

    @nn.compact
    def __call__(self, x):
        act = get_activation(self.activation)
        n = len(self.layer_dims)
        for i, dim in enumerate(self.layer_dims):
            if i == n - 1 and self.final_bias_value is not None:
                fan_in = x.shape[-1]
                kernel = self.param(
                    f"final_kernel", torch_weight_init, (fan_in, dim)
                )
                bias = self.param(
                    "final_bias",
                    nn.initializers.constant(self.final_bias_value),
                    (dim,),
                )
                x = x @ kernel + bias
            else:
                x = TorchLinear(dim)(x)
            if i < n - 1 or self.final_activation:
                x = act(x)
        return x


def fused_site(model_key: str, num_nodes: int, num_edges: int,
               table_dim: int, out_dim: int, table_dim_b: int = 0) -> bool:
    """Trace-time check: should this aggregation site run the fused Pallas
    message-passing kernel (``ops/fused_mp.py``)? ONE funnel over the
    autotuner/env decision (``ops/autotune.py``) so every model stack opts
    in the same way — no per-model enablement forks."""
    from hydragnn_tpu.ops.autotune import use_fused

    return use_fused(
        model_key, num_nodes, num_edges, table_dim, out_dim,
        table_dim_b=table_dim_b,
    )


def gather_segment_sum(x, senders, receivers, num_segments, edge_mask,
                       model_key: str = "generic"):
    """``segment_sum(where(mask, x[senders], 0), receivers)`` — the
    sum-aggregation conv primitive (GIN et al) behind ONE helper: the
    fused gather->reduce Pallas kernel when the autotuner/env picks it,
    else the XLA gather + segment-sum path. Identical numerics either way
    (f32 accumulation; result in ``x.dtype``)."""
    e = senders.shape[0]
    if fused_site(model_key, x.shape[0], e, x.shape[-1], x.shape[-1]):
        from hydragnn_tpu.ops import fused_gather_sum

        return fused_gather_sum(
            x, senders, receivers, num_segments, edge_mask
        ).astype(x.dtype)
    msg = jnp.where(edge_mask[:, None], x[senders], 0.0)
    return segment_sum(msg, receivers, num_segments)


def gather_segment_mean(x, senders, receivers, num_segments, edge_mask,
                        model_key: str = "generic"):
    """Masked mean over real incoming edges (SAGE's aggregator): sum and
    real in-degree from one fused reduction, or the XLA two-scatter
    fallback. Returns ``[S, D]`` in ``x.dtype``."""
    e = senders.shape[0]
    if fused_site(model_key, x.shape[0], e, x.shape[-1], x.shape[-1] + 1):
        from hydragnn_tpu.ops import fused_gather_mean

        mean, _deg = fused_gather_mean(
            x, senders, receivers, num_segments, edge_mask
        )
        return mean.astype(x.dtype)
    from hydragnn_tpu.graph import segment_count

    msg = jnp.where(edge_mask[:, None], x[senders], 0.0)
    total = segment_sum(msg, receivers, num_segments)
    deg = segment_count(
        receivers, num_segments, weights=edge_mask.astype(jnp.float32)
    )
    return total / jnp.maximum(deg, 1.0)[:, None]


def gather_weighted_segment_sum(h, w, senders, receivers, num_segments,
                                model_key: str = "generic"):
    """``segment_sum(h[senders] * w, receivers)`` (SchNet's CFConv
    aggregation; ``w`` pre-masked ``[E, F]``) — fused kernel or the XLA
    gather-multiply-scatter, same numerics."""
    if fused_site(model_key, h.shape[0], senders.shape[0], h.shape[-1],
                  h.shape[-1]):
        from hydragnn_tpu.ops import fused_gather_weighted_sum

        return fused_gather_weighted_sum(
            h, w, senders, receivers, num_segments
        ).astype(h.dtype)
    return segment_sum(h[senders] * w, receivers, num_segments)


def global_mean_pool(x, node_graph, n_node, num_graphs: int):
    """Padding-aware per-graph mean of node features -> [G, F].

    Equivalent to PyG's ``global_mean_pool`` (``models/Base.py:306-309``); the
    padding graph's row is garbage-free because padded node rows are zero.
    """
    total = segment_sum(x, node_graph, num_graphs)
    denom = jnp.maximum(n_node.astype(x.dtype), 1.0)[:, None]
    return total / denom
