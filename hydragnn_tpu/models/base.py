"""HydraBase — the multi-headed GNN stack, TPU-native.

Behavioral contract from the reference ``hydragnn/models/Base.py:26-376``:
conv stack -> BatchNorm + activation per layer -> masked global mean pool ->
shared graph MLP + per-head MLPs (graph heads), node heads as shared-weight
MLP / per-node MLP bank / conv stacks -> weighted multi-task loss
(``loss_hpweighted``, ``Base.py:356-373``).

TPU-first differences:
  * one flax module, applied inside a single jitted train step;
  * all pooling/norm/loss are padding-aware (masks from ``GraphBatch``);
  * per-node MLPs (``mlp_per_node``) are a single gathered parameter bank
    (einsum over a [num_mlp, in, out] tensor) instead of a Python loop over
    ``num_nodes`` modules (``Base.py:379-439``) — one MXU matmul;
  * conv gradient checkpointing is ``nn.remat`` (``jax.checkpoint``) instead
    of ``torch.utils.checkpoint`` (``Base.py:296-301``).
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.common import (
    MLP,
    MaskedBatchNorm,
    TorchLinear,
    get_activation,
    global_mean_pool,
    masked_error,
    masked_gaussian_nll,
)


class MLPNode(nn.Module):
    """Node-level head: one shared MLP (``mlp``) or a per-node MLP bank
    (``mlp_per_node``) — reference ``Base.py:379-439``.

    The bank is stored as stacked parameters ``[num_mlp, fan_in, fan_out]``;
    each node gathers its own MLP by its position within the graph, so the
    whole head is a batched matmul instead of ``num_nodes`` separate modules.
    """

    input_dim: int
    output_dim: int
    num_mlp: int
    hidden_dims: Tuple[int, ...]
    activation: str = "relu"

    @nn.compact
    def __call__(self, x, node_index_in_graph):
        act = get_activation(self.activation)
        dims = [self.input_dim] + list(self.hidden_dims) + [self.output_dim]
        sel = jnp.clip(node_index_in_graph, 0, self.num_mlp - 1)
        h = x
        n_layers = len(dims) - 1
        for i in range(n_layers):
            fan_in, fan_out = dims[i], dims[i + 1]
            bound = 1.0 / jnp.sqrt(fan_in)
            kernel = self.param(
                f"kernel_{i}",
                lambda key, shape: jax.random.uniform(
                    key, shape, minval=-bound, maxval=bound
                ),
                (self.num_mlp, fan_in, fan_out),
            )
            bias = self.param(
                f"bias_{i}",
                lambda key, shape: jax.random.uniform(
                    key, shape, minval=-bound, maxval=bound
                ),
                (self.num_mlp, fan_out),
            )
            if self.num_mlp == 1:
                h = h @ kernel[0] + bias[0]
            else:
                h = jnp.einsum("nf,nfo->no", h, kernel[sel]) + bias[sel]
            if i < n_layers - 1:
                h = act(h)
        return h


class HydraBase(nn.Module):
    """Abstract multi-headed stack; subclasses provide ``get_conv``.

    ``get_conv(in_dim, out_dim, last_layer)`` must return a flax module with
    signature ``(x, pos, batch, train) -> (x, pos)`` (positions threaded for
    the E(3)-equivariant stacks, reference ``Base.py:289-302``).
    """

    input_dim: int = 1
    hidden_dim: int = 8
    output_dim: Tuple[int, ...] = ()
    output_type: Tuple[str, ...] = ()
    config_heads: Dict[str, Any] = None
    activation: str = "relu"
    loss_function_type: str = "mse"
    equivariance: bool = False
    loss_weights: Tuple[float, ...] = ()
    # Kendall-style uncertainty-weighted NLL multi-task loss
    # (``Architecture.ilossweights_nll``): every head emits one extra
    # log-variance channel; the loss learns per-sample task weighting. The
    # reference declares this mode but its implementation raises "not ready
    # yet" (``models/Base.py:335-354``) and the factory cannot reach it
    # (``create.py:71``) — here it is finished and config-reachable.
    loss_nll: bool = False
    num_conv_layers: int = 2
    num_nodes: Optional[int] = None
    edge_dim: Optional[int] = None
    conv_checkpointing: bool = False
    initial_bias: Optional[float] = None
    dropout: float = 0.25
    # Graph-partition parallelism (the long-context analog, SURVEY.md §5):
    # when set, the batch is ONE giant graph whose nodes/edges are sharded
    # over this mesh axis (see ``hydragnn_tpu/parallel/graph_partition``).
    # Convs see a halo-extended node table refreshed by all_to_all before
    # every layer; BatchNorm/pooling/loss psum over the axis so numerics
    # match the unpartitioned model exactly.
    partition_axis: Optional[str] = None

    # stacks whose convs read node positions (distances/angles/coordinate
    # updates) set this True; for the rest the partitioned halo exchange
    # skips the pos columns — pure ICI bandwidth savings
    conv_needs_pos: bool = False

    @property
    def use_edge_attr(self) -> bool:
        return self.edge_dim is not None and self.edge_dim > 0

    @property
    def num_heads(self) -> int:
        return len(self.output_dim)

    # ---- subclass hooks ------------------------------------------------
    def get_conv(
        self,
        in_dim: int,
        out_dim: int,
        last_layer: bool = False,
        name: Optional[str] = None,
        **kw,
    ):
        raise NotImplementedError

    def _conv_layer_specs(self):
        """(in_dim, out_dim, bn_dim, conv_kwargs) per encoder layer.

        Default matches ``Base._init_conv`` (``Base.py:115-121``); GAT
        overrides for attention-head concat (``GATStack.py:36-47``).
        """
        specs = []
        for i in range(self.num_conv_layers):
            in_dim = self.input_dim if i == 0 else self.hidden_dim
            specs.append((in_dim, self.hidden_dim, self.hidden_dim, {}))
        return specs

    def _node_conv_specs(self, node_cfg, head_dim):
        """Layer specs for a conv-type node head (``Base.py:145-203``)."""
        dims = node_cfg["dim_headlayers"]
        num = node_cfg["num_headlayers"]
        specs = []
        prev = self.hidden_dim
        for il in range(num):
            specs.append((prev, dims[il], dims[il], {"last_layer": False}))
            prev = dims[il]
        specs.append((prev, head_dim, head_dim, {"last_layer": True}))
        return specs

    def _node_index_in_graph(self, batch: GraphBatch):
        if batch.extras is not None and "node_index_in_graph" in batch.extras:
            # partitioned giant graph: global position precomputed host-side
            return batch.extras["node_index_in_graph"]
        starts = jnp.cumsum(batch.n_node) - batch.n_node
        return jnp.arange(batch.num_nodes, dtype=jnp.int32) - starts[batch.node_graph]

    def _conv_cls(self, cls):
        """Wrap a conv class in ``nn.remat`` when conv checkpointing is on
        (parity with ``torch.utils.checkpoint`` at ``Base.py:296-301``).
        Subclasses must construct their conv through this hook."""
        if self.conv_checkpointing:
            return nn.remat(cls, static_argnums=(4,), prevent_cse=False)
        return cls

    def _apply_conv(self, conv, x, pos, batch, train):
        if self.partition_axis is None:
            return conv(x, pos, batch, train)
        # Partitioned message passing: refresh the halo (remote-sender rows)
        # from their owner shards via all_to_all, run the conv on the
        # extended table, keep the local rows. The analog of exchanging KV
        # blocks in ring attention — features ride ICI, compute stays local.
        from hydragnn_tpu.parallel.graph_partition import halo_extend

        send_idx = batch.extras["halo_send"]
        nl = x.shape[0]
        if self.conv_needs_pos:
            # ONE all_to_all for features+positions (small collectives are
            # latency-bound on ICI; fuse, then split)
            both = halo_extend(
                jnp.concatenate([x, pos], axis=-1), send_idx, self.partition_axis
            )
            xe, pe = both[:, : x.shape[1]], both[:, x.shape[1] :]
        else:
            # convs of this stack never read pos: don't ship it. Pass None
            # so a future pos-reading conv that forgot conv_needs_pos=True
            # fails loudly at trace time instead of silently gathering
            # clamped out-of-range rows.
            xe = halo_extend(x, send_idx, self.partition_axis)
            pe = None
        # convs that build per-node virtual edges (GAT self-loops) consult
        # node_mask at the extended size; halo rows are masked off since
        # their aggregations happen on the owner shard.
        ext = xe.shape[0] - nl
        batch_ext = batch.replace(
            node_mask=jnp.concatenate(
                [batch.node_mask, jnp.zeros((ext,), dtype=batch.node_mask.dtype)]
            )
        )
        c, p = conv(xe, pe, batch_ext, train)
        c = c[:nl]
        if p is not None and p.shape[0] != nl:
            p = p[:nl]
        return c, p

    def _prepare_batch(self, batch: GraphBatch) -> GraphBatch:
        """Once-per-forward hook for values every conv layer would
        otherwise recompute identically (parameter-free functions of the
        batch — e.g. DimeNet's triplet angles and spherical basis, shared
        by all ``num_conv_layers`` interaction blocks). Default: no-op."""
        return batch

    @nn.compact
    def __call__(self, batch: GraphBatch, train: bool = False):
        act = get_activation(self.activation)
        heads_cfg = self.config_heads or {}
        batch = self._prepare_batch(batch)
        x = batch.x
        pos = batch.pos

        # ---- encoder: conv stack (Base.py:289-302) ----------------------
        # SchNet/EGNN use Identity feature layers instead of BatchNorm
        # (SCFStack.py:63, EGCLStack.py:41)
        use_bn = getattr(self, "conv_use_batchnorm", True)
        for i, (in_dim, out_dim, bn_dim, kw) in enumerate(self._conv_layer_specs()):
            conv = self.get_conv(in_dim, out_dim, name=f"encoder_conv_{i}", **kw)
            c, pos = self._apply_conv(conv, x, pos, batch, train)
            if use_bn:
                c = MaskedBatchNorm(
                    bn_dim, name=f"encoder_bn_{i}", axis_name=self.partition_axis
                )(c, batch.node_mask, not train)
            x = act(c)

        # ---- decoder: multihead (Base.py:205-283,304-327) ---------------
        x_graph = global_mean_pool(x, batch.node_graph, batch.n_node, batch.num_graphs)
        if self.partition_axis is not None:
            # nodes of the (single partitioned) graph live on every shard;
            # n_node[0] holds the GLOBAL real-node count, so the psum of the
            # local sums/count yields the exact global mean.
            x_graph = jax.lax.psum(x_graph, self.partition_axis)

        graph_shared = None
        if "graph" in heads_cfg:
            dim_shared = heads_cfg["graph"]["dim_sharedlayers"]
            n_shared = heads_cfg["graph"]["num_sharedlayers"]
            graph_shared = MLP(
                [dim_shared] * n_shared,
                activation=self.activation,
                final_activation=True,
                name="graph_shared",
            )

        outputs = []
        node_index = None
        # NLL mode: one extra log-variance channel per head (the reference
        # reserves the slot the same way, ``Base.py:241``)
        uq_extra = 1 if self.loss_nll else 0
        for ihead in range(self.num_heads):
            head_type = self.output_type[ihead]
            head_dim = self.output_dim[ihead] + uq_extra
            if head_type == "graph":
                num_head_hidden = heads_cfg["graph"]["num_headlayers"]
                dim_head_hidden = heads_cfg["graph"]["dim_headlayers"]
                layer_dims = list(dim_head_hidden[:num_head_hidden]) + [head_dim]
                head_mlp = MLP(
                    layer_dims,
                    activation=self.activation,
                    final_bias_value=self.initial_bias,
                    name=f"head_{ihead}_graph",
                )
                outputs.append(head_mlp(graph_shared(x_graph)))
            elif head_type == "node":
                node_cfg = heads_cfg["node"]
                node_type = node_cfg["type"]
                hidden_dims = tuple(node_cfg["dim_headlayers"])
                if node_type in ("mlp", "mlp_per_node"):
                    num_mlp = 1 if node_type == "mlp" else int(self.num_nodes)
                    if node_index is None:
                        node_index = self._node_index_in_graph(batch)
                    head = MLPNode(
                        input_dim=self.hidden_dim,
                        output_dim=head_dim,
                        num_mlp=num_mlp,
                        hidden_dims=hidden_dims,
                        activation=self.activation,
                        name=f"head_{ihead}_node",
                    )
                    out = head(x, node_index)
                    outputs.append(jnp.where(batch.node_mask[:, None], out, 0.0))
                elif node_type == "conv":
                    # shared hidden convs + per-head output conv, BatchNorm +
                    # activation after every conv incl. the output one
                    # (Base.py:318-323).
                    h = x
                    p = pos
                    for il, (in_dim, od, bn_dim, kw) in enumerate(
                        self._node_conv_specs(node_cfg, head_dim)
                    ):
                        conv = self.get_conv(
                            in_dim, od, name=f"head_{ihead}_conv_{il}", **kw
                        )
                        c, p = self._apply_conv(conv, h, p, batch, train)
                        c = MaskedBatchNorm(
                            bn_dim,
                            name=f"head_{ihead}_bn_{il}",
                            axis_name=self.partition_axis,
                        )(c, batch.node_mask, not train)
                        h = act(c)
                    outputs.append(h)
                else:
                    raise ValueError(
                        f"Unknown head NN structure for node features: {node_type};"
                        " supported: 'mlp', 'mlp_per_node', 'conv'"
                    )
            else:
                raise ValueError(f"Unknown head type: {head_type}")
        return tuple(outputs)

    # ---- loss (Base.py:329-373) -----------------------------------------
    def loss(self, outputs, batch: GraphBatch):
        """Weighted multi-task loss; returns (total, per-task list).

        ``loss_weights`` are already normalized by their abs-sum at model
        construction (``Base.py:89-90``).
        """
        tot = 0.0
        tasks = []
        for ihead in range(self.num_heads):
            pred = outputs[ihead]
            target = batch.targets[ihead]
            mask = (
                batch.graph_mask
                if self.output_type[ihead] == "graph"
                else batch.node_mask
            )
            if self.loss_nll:
                d = self.output_dim[ihead]
                tot = tot + masked_gaussian_nll(
                    pred[..., :d],
                    pred[..., d:],
                    target,
                    mask,
                    axis_name=self.partition_axis,
                )
                # per-task report stays plain MSE of the mean prediction
                # (the reference's tasks_mseloss, ``Base.py:352``)
                tasks.append(
                    masked_error(
                        pred[..., :d],
                        target,
                        mask,
                        "mse",
                        axis_name=self.partition_axis,
                    )
                )
                continue
            err = masked_error(
                pred,
                target,
                mask,
                self.loss_function_type,
                axis_name=self.partition_axis,
            )
            tasks.append(err)
            tot = tot + self.loss_weights[ihead] * err
        return tot, tasks
