"""GIN stack — Graph Isomorphism Network.

Parity with reference ``hydragnn/models/GINStack.py:21-47``: PyG GINConv with
an inner MLP [Linear(in,out), ReLU, Linear(out,out)], trainable eps
initialized at 100.0. Formula: out = MLP((1 + eps) * x_i + sum_{j->i} x_j).
"""

from flax import linen as nn

from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear, gather_segment_sum


class GINConv(nn.Module):
    in_dim: int
    out_dim: int
    eps_init: float = 100.0

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        eps = self.param("eps", nn.initializers.constant(self.eps_init), ())
        extras = batch.extras or {}
        if "nbr_idx" in extras:  # dense scatter-free path (ops/dense_agg.py)
            from hydragnn_tpu.ops.dense_agg import dense_sum, gather_neighbors

            x_j = gather_neighbors(
                x, extras["nbr_idx"], extras["rev_idx"], extras["rev_mask"]
            )
            aggr = dense_sum(x_j, extras["nbr_mask"])
        else:
            # gather+mask+reduce through the one shared helper: XLA
            # segment path or the fused Pallas kernel (autotuner/env)
            aggr = gather_segment_sum(
                x, batch.senders, batch.receivers, x.shape[0],
                batch.edge_mask, model_key="GIN",
            )
        h = (1.0 + eps) * x + aggr
        h = TorchLinear(self.out_dim, name="mlp_0")(h)
        h = nn.relu(h)  # GINStack hardcodes ReLU inside the conv MLP
        h = TorchLinear(self.out_dim, name="mlp_1")(h)
        return h, pos


class GINStack(HydraBase):
    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        return self._conv_cls(GINConv)(in_dim=in_dim, out_dim=out_dim, name=name)
