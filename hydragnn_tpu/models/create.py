"""Model factory — parity with ``hydragnn/models/create.py:31-312``.

``create_model_config(config["NeuralNetwork"]["Architecture"], ...)`` unpacks
the derived architecture section (after ``update_config``) and dispatches on
``model_type`` to one of the 9 stacks. Returns the flax module; parameters are
materialized separately (functional JAX) by ``init_model_params``.
"""

import functools
from typing import Optional

import jax
import numpy as np

from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.pna import PNAStack
from hydragnn_tpu.models.gin import GINStack
from hydragnn_tpu.models.gat import GATStack
from hydragnn_tpu.models.mfc import MFCStack
from hydragnn_tpu.models.sage import SAGEStack
from hydragnn_tpu.models.cgcnn import CGCNNStack
from hydragnn_tpu.models.schnet import SCFStack
from hydragnn_tpu.models.egnn import EGCLStack
from hydragnn_tpu.models.dimenet import DIMEStack

MODEL_TYPES = [
    "GIN",
    "PNA",
    "GAT",
    "MFC",
    "CGCNN",
    "SAGE",
    "SchNet",
    "DimeNet",
    "EGNN",
]


def _normalize_weights(task_weights, num_heads):
    if task_weights is None:
        task_weights = [1.0] * num_heads
    if len(task_weights) != num_heads:
        raise ValueError(
            f"Inconsistent number of loss weights and tasks: "
            f"{len(task_weights)} VS {num_heads}"
        )
    s = sum(abs(w) for w in task_weights)
    return tuple(w / s for w in task_weights)


def create_model_config(config: dict, verbosity: int = 0) -> HydraBase:
    """``config`` is the Architecture section, post-``update_config``."""
    model_type = config["model_type"]
    output_dim = tuple(config["output_dim"])
    output_type = tuple(config["output_type"])
    num_heads = len(output_dim)
    common = dict(
        input_dim=config["input_dim"],
        hidden_dim=config["hidden_dim"],
        output_dim=output_dim,
        output_type=output_type,
        config_heads=config["output_heads"],
        activation=config.get("activation_function", "relu"),
        loss_function_type=config.get("loss_function_type", "mse"),
        equivariance=config.get("equivariance", False),
        loss_weights=_normalize_weights(config.get("task_weights"), num_heads),
        num_conv_layers=config["num_conv_layers"],
        num_nodes=config.get("num_nodes"),
        conv_checkpointing=config.get("conv_checkpointing", False),
        initial_bias=config.get("initial_bias"),
        # uncertainty-weighted NLL multi-task loss — the mode the reference
        # declares but leaves unreachable/unfinished (Base.py:335-354,
        # create.py:71); heads grow one log-variance channel
        loss_nll=bool(config.get("ilossweights_nll", 0)),
        # graph-partition parallelism over one giant graph (config key
        # "partition_axis" names the mesh axis; see parallel/graph_partition)
        partition_axis=config.get("partition_axis"),
    )
    edge_dim = config.get("edge_dim")

    if model_type == "GIN":
        return GINStack(**common)
    if model_type == "PNA":
        assert config.get("pna_deg") is not None, "PNA requires degree input."
        return PNAStack(deg=tuple(config["pna_deg"]), edge_dim=edge_dim, **common)
    if model_type == "GAT":
        # reference hardcodes these (create.py:150-152)
        return GATStack(heads=6, negative_slope=0.05, **common)
    if model_type == "MFC":
        assert (
            config.get("max_neighbours") is not None
        ), "MFC requires max_neighbours input."
        return MFCStack(
            max_degree=config["max_neighbours"],
            degree_bound=config.get("mfc_degree_bound"),
            **common,
        )
    if model_type == "CGCNN":
        # constant width: hidden == input (CGCNNStack.py:30-40); conv node
        # heads unsupported (CGCNNStack.py:66-89)
        heads_cfg = config["output_heads"]
        if (
            "node" in heads_cfg
            and heads_cfg["node"].get("type") == "conv"
            and any(t == "node" for t in output_type)
        ):
            raise ValueError(
                '"conv" for node features decoder part in CGCNN is not ready yet.'
            )
        common["hidden_dim"] = common["input_dim"]
        return CGCNNStack(edge_dim=edge_dim if edge_dim is not None else 0, **common)
    if model_type == "SAGE":
        return SAGEStack(**common)
    if model_type == "SchNet":
        assert config.get("num_gaussians") is not None
        assert config.get("num_filters") is not None
        assert config.get("radius") is not None
        # NOTE: the reference passes (num_gaussians, num_filters) positionally
        # into SCFStack(num_filters, num_gaussians, ...) — effectively swapping
        # them (create.py:228-247 vs SCFStack.py:33-46). Replicated for parity.
        return SCFStack(
            num_filters=config["num_gaussians"],
            num_gaussians=config["num_filters"],
            radius=config["radius"],
            edge_dim=edge_dim,
            **common,
        )
    if model_type == "DimeNet":
        for key in (
            "basis_emb_size",
            "envelope_exponent",
            "int_emb_size",
            "out_emb_size",
            "num_after_skip",
            "num_before_skip",
            "num_radial",
            "num_spherical",
            "radius",
        ):
            assert config.get(key) is not None, f"DimeNet requires {key} input."
        return DIMEStack(
            basis_emb_size=config["basis_emb_size"],
            envelope_exponent=config["envelope_exponent"],
            int_emb_size=config["int_emb_size"],
            out_emb_size=config["out_emb_size"],
            num_after_skip=config["num_after_skip"],
            num_before_skip=config["num_before_skip"],
            num_radial=config["num_radial"],
            num_spherical=config["num_spherical"],
            radius=config["radius"],
            **common,
        )
    if model_type == "EGNN":
        return EGCLStack(edge_dim=edge_dim if edge_dim is not None else 0, **common)
    raise ValueError(f"Unknown model_type: {model_type}")


# ---------------------------------------------------------------------------
# param-precision policy (mixed bf16 across the model zoo)
# ---------------------------------------------------------------------------

# Minimum hidden width at which bf16 compute pays per stack: below it the
# step is op-latency/scatter-bound and bf16 buys nothing while costing
# precision (graph/segment.py upcasts scatters for exactly this reason);
# at MXU widths the measured wins are large (BENCH_EXTRA dense-bf16 rows,
# e.g. PNA h256 1.76x). DimeNet is deliberately absent: its spherical-
# basis recurrences are precision-sensitive and the measured bf16 delta
# was within noise — it stays f32 under "auto".
BF16_AUTO_MIN_HIDDEN = {
    "PNA": 128,
    "GAT": 128,
    "GIN": 128,
    "SAGE": 128,
    "MFC": 128,
    "CGCNN": 128,
    "SchNet": 128,
    "EGNN": 128,
}


def resolve_precision(model, training_config: dict) -> dict:
    """The ONE mixed-precision decision point (steps.py consumes it).

    Master params always stay f32 for the optimizer; this resolves whether
    the forward/backward COMPUTE runs in bf16. Order:

    1. ``HYDRAGNN_MIXED_PRECISION=0/1`` — operator override;
    2. explicit ``Training.mixed_precision: true/false``;
    3. ``Training.mixed_precision: "auto"`` — the per-model width policy
       above (bf16 iff the stack is in the table AND hidden_dim clears its
       threshold — tiny CI-scale configs stay f32 under "auto");
    4. absent — f32 (the conservative historical default).

    Returns ``{"mixed": bool, "source": "env|explicit|policy|default"}``.
    """
    import os

    from hydragnn_tpu.ops.autotune import model_key_for

    env = os.getenv("HYDRAGNN_MIXED_PRECISION")
    if env is not None and env.strip() != "":
        off = env.strip().lower() in ("0", "false", "no", "off")
        return {"mixed": not off, "source": "env"}
    flag = training_config.get("mixed_precision", False)
    if isinstance(flag, str) and flag.strip().lower() == "auto":
        key = model_key_for(model)
        th = BF16_AUTO_MIN_HIDDEN.get(key)
        mixed = th is not None and int(
            getattr(model, "hidden_dim", 0) or 0
        ) >= th
        return {"mixed": mixed, "source": "policy"}
    return {
        "mixed": bool(flag),
        "source": "explicit" if "mixed_precision" in training_config
        else "default",
    }


def init_model_params(model: HydraBase, example_batch, seed: int = 0):
    """Materialize parameters + batch stats (reference seeds torch with 0,
    ``create.py:107``).

    The init runs under ONE jit: eager flax init dispatches every traced
    primitive as its own XLA program, and on backends where each tiny
    compile costs ~0.5 s (the tunneled axon chip: 148 programs, 92 s of a
    112 s bench stage) none of them clear JAX's 1 s persistent-cache
    threshold — so the cost recurred every process. One program compiles
    once, persists, and PRNG values are bit-identical either way."""
    rngs = {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(1)}
    # one-shot by design: init runs ONCE per process/model, and jitting it
    # is the whole point (one fused program instead of 148 eager dispatches)
    variables = jax.jit(functools.partial(model.init, train=False))(  # jaxlint: disable=jit-in-loop
        rngs, example_batch
    )
    return variables


def print_model(model: HydraBase, variables, verbosity: int = 0):
    """Parameter summary — top-level module table + total trainable count
    (``hydragnn/utils/model.py:173-181``)."""
    from hydragnn_tpu.utils.print_utils import print_distributed

    params = variables.get("params", variables)
    per_module = {}
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        top = getattr(path[0], "key", str(path[0]))
        per_module[top] = per_module.get(top, 0) + int(np.prod(leaf.shape))
        total += int(np.prod(leaf.shape))
    print_distributed(verbosity, f"model: {type(model).__name__}")
    for name in sorted(per_module):
        print_distributed(verbosity, f"  {name}: {per_module[name]:,} params")
    print_distributed(verbosity, f"total trainable params: {total:,}")
    return total
