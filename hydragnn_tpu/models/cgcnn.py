"""CGCNN stack — Crystal Graph Convolutional Neural Network.

Parity with reference ``hydragnn/models/CGCNNStack.py:20-91`` (PyG CGConv,
aggr="add", batch_norm=False): z_ij = [x_i, x_j, e_ij];
out_i = x_i + sum_j sigmoid(W_f z + b_f) * softplus(W_s z + b_s).
Constant width: hidden_dim == input_dim (the factory passes input_dim as
hidden, ``CGCNNStack.py:30-40``), and conv-type node heads are forbidden
(``:66-89`` — enforced in our factory).
"""

import jax
import jax.numpy as jnp
from flax import linen as nn

from hydragnn_tpu.graph import segment_sum
from hydragnn_tpu.models.base import HydraBase
from hydragnn_tpu.models.common import TorchLinear


class CGConv(nn.Module):
    channels: int
    edge_dim: int = 0

    @nn.compact
    def __call__(self, x, pos, batch, train: bool = False):
        extras = batch.extras or {}
        dense = "nbr_idx" in extras
        if dense:  # dense scatter-free path (ops/dense_agg.py)
            from hydragnn_tpu.ops.dense_agg import dense_sum, gather_neighbors

            x_j = gather_neighbors(
                x, extras["nbr_idx"], extras["rev_idx"], extras["rev_mask"]
            )
            parts = [jnp.broadcast_to(x[:, None, :], x_j.shape), x_j]
            if self.edge_dim and self.edge_dim > 0:
                parts.append(batch.edge_attr[extras["nbr_edge"]])
        else:
            parts = [x[batch.receivers], x[batch.senders]]
            if self.edge_dim and self.edge_dim > 0:
                parts.append(batch.edge_attr)
        z = jnp.concatenate(parts, axis=-1)
        gate = jax.nn.sigmoid(TorchLinear(self.channels, name="lin_f")(z))
        core = jax.nn.softplus(TorchLinear(self.channels, name="lin_s")(z))
        msg = gate * core
        if dense:
            out = x + dense_sum(msg, extras["nbr_mask"])
        else:
            msg = jnp.where(batch.edge_mask[:, None], msg, 0.0)
            out = x + segment_sum(msg, batch.receivers, x.shape[0])
        return out, pos


class CGCNNStack(HydraBase):
    def get_conv(self, in_dim, out_dim, last_layer=False, name=None, **kw):
        # CGConv keeps dimensions: in_dim is both in and out.
        return self._conv_cls(CGConv)(
            channels=in_dim, edge_dim=self.edge_dim if self.edge_dim else 0,
            name=name,
        )
