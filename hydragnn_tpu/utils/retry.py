"""Retry-with-jittered-backoff for transient I/O.

Long runs on shared parallel filesystems (the reference's Summit/Frontier
GPFS, or NFS-mounted TPU-VM pods) see sporadic ``OSError``/``IOError``
from reads that succeed on the next attempt. :func:`retry_io` wraps one
read with bounded exponential backoff plus jitter (decorrelates the retry
stampede when every data-loader worker hits the same hiccup at once).

Knobs (env overrides argument defaults):
- ``HYDRAGNN_IO_RETRIES``       total attempts, default 3 (1 = no retry)
- ``HYDRAGNN_IO_RETRY_BASE_S``  first backoff delay seconds, default 0.05

Only ``OSError`` (and subclasses: ``FileNotFoundError`` excluded — a
missing file is not transient) is retried; everything else propagates
immediately.
"""

import os
import random
import time


def backoff_delay(attempt: int, base_delay: float) -> float:
    """Delay before retry ``attempt`` (0-based): exponential with
    uniform +0..50% jitter — THE repo backoff curve, shared by
    :func:`retry_io` and the serving fleet router
    (``serve/router.py``), so every retry storm in the system
    decorrelates the same way. Bounds: ``base * 2^attempt`` to
    ``1.5x`` that."""
    return base_delay * (2.0 ** attempt) * (1.0 + random.uniform(0.0, 0.5))


def retry_io(fn, *, what: str = "", attempts=None, base_delay=None):
    """Call ``fn()``; on transient ``OSError`` retry with exponential
    backoff + uniform jitter. Re-raises the last error once attempts are
    exhausted."""
    if attempts is None:
        attempts = int(os.getenv("HYDRAGNN_IO_RETRIES", "3"))
    if base_delay is None:
        base_delay = float(os.getenv("HYDRAGNN_IO_RETRY_BASE_S", "0.05"))
    attempts = max(int(attempts), 1)
    last = None
    for i in range(attempts):
        try:
            return fn()
        except FileNotFoundError:
            raise  # not transient: retrying a wrong path only adds latency
        except OSError as e:
            last = e
            if i == attempts - 1:
                break
            time.sleep(backoff_delay(i, base_delay))
    raise last
