"""Tracer facade — HPC-style nested region timers.

Parity with ``hydragnn/utils/tracer.py:18-171`` (GPTL/Score-P facade with a
registry, enable/disable, optional device sync for honest attribution, and a
``@profile`` decorator). Backends:

  * ``timer``  — pure-Python region timers with per-host summaries (GPTL
    analog; a C++ backend drops in behind the same interface, see
    ``native/``).
  * ``jax``    — forwards regions to ``jax.profiler.TraceAnnotation`` so they
    appear in TensorBoard/perfetto traces (Score-P analog).

``HYDRAGNN_TRACE_LEVEL=1`` inserts a device sync (``block_until_ready``
analog of the reference's cudasync+barrier, ``tracer.py:110-131``) at region
boundaries.
"""

import os
import time
from collections import defaultdict
from functools import wraps
from typing import Dict

_tracers: Dict[str, object] = {}
_enabled = True


class TimerTracer:
    def __init__(self):
        self.acc = defaultdict(float)
        self.count = defaultdict(int)
        self._start = {}

    def start(self, name):
        self._start[name] = time.perf_counter()

    def stop(self, name):
        if name in self._start:
            self.acc[name] += time.perf_counter() - self._start.pop(name)
            self.count[name] += 1

    def reset(self):
        self.acc.clear()
        self.count.clear()
        self._start.clear()

    def pr_file(self, filename):
        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        with open(filename, "w") as f:
            f.write(f"{'region':<30}{'calls':>10}{'total_s':>14}{'avg_ms':>12}\n")
            for name in sorted(self.acc):
                c = self.count[name]
                t = self.acc[name]
                f.write(
                    f"{name:<30}{c:>10}{t:>14.4f}{(t / max(c, 1)) * 1e3:>12.3f}\n"
                )


class JaxProfilerTracer:
    """Regions as jax.profiler trace annotations."""

    def __init__(self):
        self._spans = {}

    def start(self, name):
        import jax.profiler

        span = jax.profiler.TraceAnnotation(name)
        span.__enter__()
        self._spans.setdefault(name, []).append(span)

    def stop(self, name):
        spans = self._spans.get(name)
        if spans:
            spans.pop().__exit__(None, None, None)

    def reset(self):
        self._spans.clear()

    def pr_file(self, filename):
        pass


def initialize(trace_backends=("native",), verbosity: int = 0):
    for b in trace_backends:
        if b == "timer":
            _tracers["timer"] = TimerTracer()
        elif b == "jax":
            _tracers["jax"] = JaxProfilerTracer()
        elif b == "native":
            # C++ region timer (GPTL analog) with call-tree attribution and
            # chrome-trace export; falls back to the Python timer if the
            # toolchain is unavailable.
            try:
                from hydragnn_tpu.native.regiontimer import NativeRegionTimer

                _tracers["native"] = NativeRegionTimer()
            except Exception:
                _tracers["timer"] = TimerTracer()
    return list(_tracers)


def has(name):
    return name in _tracers


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def reset():
    for t in _tracers.values():
        t.reset()


_sync_fn = None


def _sync():
    """Block until in-flight device computation finishes (trace level 1's
    "honest attribution" contract). ``jax.effects_barrier()`` is NOT that —
    it only waits for ordered side effects and returns immediately with
    async compute still in flight; ``jax.device_put(...)`` doesn't help
    either, transfers bypass the execution stream. Dispatching a trivial
    jitted program and blocking on it does: executions are ordered per
    device, so its completion implies everything enqueued before it ran."""
    if os.getenv("HYDRAGNN_TRACE_LEVEL", "0") == "1":
        global _sync_fn
        try:
            import jax

            if _sync_fn is None:
                import jax.numpy as jnp

                _sync_fn = jax.jit(lambda: jnp.zeros(()))
            _sync_fn().block_until_ready()
        except Exception:
            pass


def start(name):
    if not _enabled or not _tracers:
        return
    _sync()
    for t in _tracers.values():
        t.start(name)


def stop(name):
    if not _enabled or not _tracers:
        return
    _sync()
    for t in _tracers.values():
        t.stop(name)


def profile(name):
    """Decorator marking a traced region (``tracer.py:149-164``)."""

    def deco(fn):
        @wraps(fn)
        def wrapper(*args, **kwargs):
            start(name)
            try:
                return fn(*args, **kwargs)
            finally:
                stop(name)

        return wrapper

    return deco


def totals() -> Dict[str, float]:
    """Accumulated seconds per region from ONE accumulating backend —
    preferring native over the Python timer (the jax backend only
    annotates device traces). Every registered backend times the same
    region boundaries, so summing across them would double-count; native
    regions additionally come back as call-tree paths
    ("train/train_step"). Feeds the telemetry layer's
    ``ScalarWriter.add_regions`` / ``tracer_totals`` run event."""
    for name in ("native", "timer"):
        t = _tracers.get(name)
        if t is None:
            continue
        if hasattr(t, "totals"):
            try:
                return {k: float(v) for k, v in t.totals().items()}
            except Exception:
                continue  # an old cached .so without the export
        acc = getattr(t, "acc", None)
        if acc:
            return {k: float(v) for k, v in acc.items()}
    return {}


def save(prefix: str = "./logs/trace"):
    """Per-host region dump (GPTL ``gp.pr_file`` analog). The native backend
    additionally writes a chrome://tracing JSON (`<prefix>.<rank>.trace.json`,
    loadable in perfetto)."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    for name, t in _tracers.items():
        # with several file-writing backends registered, each gets its own
        # file so one dump cannot clobber another
        tag = f".{name}" if len(_tracers) > 1 else ""
        t.pr_file(f"{prefix}{tag}.{rank}")
        if hasattr(t, "chrome_trace"):
            t.chrome_trace(f"{prefix}{tag}.{rank}.trace.json", pid=rank)
