"""Embedded periodic-table property data (mendeleev analog).

The reference pulls element properties from the ``mendeleev`` package at
runtime (``hydragnn/utils/atomicdescriptors.py:12-243``). That package is not
available here and descriptor generation is pure host-side preprocessing, so
the property table is embedded: standard physical constants for the elements
H–Xe plus common heavy elements used in atomistic ML datasets.

Per element: group (IUPAC 1-18), period, block (s/p/d/f), atomic weight,
covalent radius (pm), Pauling electronegativity, electron affinity (eV),
atomic volume (cm^3/mol), valence-electron count, first ionization energy
(eV). ``None`` marks properties that are undefined for an element (e.g.
Pauling electronegativity of light noble gases); consumers raise on ``None``
exactly like the reference does for mendeleev's ``None`` returns.
"""

from typing import Dict, Optional

# fmt: off
# symbol: (Z, group, period, block, weight, cov_radius_pm, en_pauling,
#          electron_affinity_eV, atomic_volume_cm3mol, n_valence, ion_energy_eV)
_ELEMENTS = {
    "H":  (1,  1,  1, "s", 1.008,   31,  2.20, 0.754, 14.1,  1, 13.598),
    "He": (2,  18, 1, "s", 4.003,   28,  None, None,  31.8,  2, 24.587),
    "Li": (3,  1,  2, "s", 6.940,   128, 0.98, 0.618, 13.1,  1, 5.392),
    "Be": (4,  2,  2, "s", 9.012,   96,  1.57, None,  5.0,   2, 9.323),
    "B":  (5,  13, 2, "p", 10.810,  84,  2.04, 0.277, 4.6,   3, 8.298),
    "C":  (6,  14, 2, "p", 12.011,  76,  2.55, 1.263, 5.3,   4, 11.260),
    "N":  (7,  15, 2, "p", 14.007,  71,  3.04, -0.07, 17.3,  5, 14.534),
    "O":  (8,  16, 2, "p", 15.999,  66,  3.44, 1.461, 14.0,  6, 13.618),
    "F":  (9,  17, 2, "p", 18.998,  57,  3.98, 3.401, 17.1,  7, 17.423),
    "Ne": (10, 18, 2, "p", 20.180,  58,  None, None,  16.8,  8, 21.565),
    "Na": (11, 1,  3, "s", 22.990,  166, 0.93, 0.548, 23.7,  1, 5.139),
    "Mg": (12, 2,  3, "s", 24.305,  141, 1.31, None,  14.0,  2, 7.646),
    "Al": (13, 13, 3, "p", 26.982,  121, 1.61, 0.441, 10.0,  3, 5.986),
    "Si": (14, 14, 3, "p", 28.085,  111, 1.90, 1.385, 12.1,  4, 8.152),
    "P":  (15, 15, 3, "p", 30.974,  107, 2.19, 0.746, 17.0,  5, 10.487),
    "S":  (16, 16, 3, "p", 32.060,  105, 2.58, 2.077, 15.5,  6, 10.360),
    "Cl": (17, 17, 3, "p", 35.450,  102, 3.16, 3.613, 18.7,  7, 12.968),
    "Ar": (18, 18, 3, "p", 39.948,  106, None, None,  24.2,  8, 15.760),
    "K":  (19, 1,  4, "s", 39.098,  203, 0.82, 0.501, 45.3,  1, 4.341),
    "Ca": (20, 2,  4, "s", 40.078,  176, 1.00, 0.025, 29.9,  2, 6.113),
    "Sc": (21, 3,  4, "d", 44.956,  170, 1.36, 0.188, 15.0,  3, 6.561),
    "Ti": (22, 4,  4, "d", 47.867,  160, 1.54, 0.079, 10.6,  4, 6.828),
    "V":  (23, 5,  4, "d", 50.942,  153, 1.63, 0.525, 8.35,  5, 6.746),
    "Cr": (24, 6,  4, "d", 51.996,  139, 1.66, 0.666, 7.23,  6, 6.767),
    "Mn": (25, 7,  4, "d", 54.938,  139, 1.55, None,  7.39,  7, 7.434),
    "Fe": (26, 8,  4, "d", 55.845,  132, 1.83, 0.151, 7.1,   8, 7.902),
    "Co": (27, 9,  4, "d", 58.933,  126, 1.88, 0.662, 6.7,   9, 7.881),
    "Ni": (28, 10, 4, "d", 58.693,  124, 1.91, 1.156, 6.6,  10, 7.640),
    "Cu": (29, 11, 4, "d", 63.546,  132, 1.90, 1.235, 7.1,  11, 7.726),
    "Zn": (30, 12, 4, "d", 65.380,  122, 1.65, None,  9.2,  12, 9.394),
    "Ga": (31, 13, 4, "p", 69.723,  122, 1.81, 0.301, 11.8,  3, 5.999),
    "Ge": (32, 14, 4, "p", 72.630,  120, 2.01, 1.233, 13.6,  4, 7.899),
    "As": (33, 15, 4, "p", 74.922,  119, 2.18, 0.804, 13.1,  5, 9.789),
    "Se": (34, 16, 4, "p", 78.971,  120, 2.55, 2.021, 16.5,  6, 9.752),
    "Br": (35, 17, 4, "p", 79.904,  120, 2.96, 3.364, 23.5,  7, 11.814),
    "Kr": (36, 18, 4, "p", 83.798,  116, 3.00, None,  32.2,  8, 13.999),
    "Rb": (37, 1,  5, "s", 85.468,  220, 0.82, 0.486, 55.9,  1, 4.177),
    "Sr": (38, 2,  5, "s", 87.620,  195, 0.95, 0.048, 33.7,  2, 5.695),
    "Y":  (39, 3,  5, "d", 88.906,  190, 1.22, 0.307, 19.8,  3, 6.217),
    "Zr": (40, 4,  5, "d", 91.224,  175, 1.33, 0.426, 14.1,  4, 6.634),
    "Nb": (41, 5,  5, "d", 92.906,  164, 1.60, 0.893, 10.8,  5, 6.759),
    "Mo": (42, 6,  5, "d", 95.950,  154, 2.16, 0.748, 9.4,   6, 7.092),
    "Tc": (43, 7,  5, "d", 98.000,  147, 1.90, 0.550, 8.5,   7, 7.280),
    "Ru": (44, 8,  5, "d", 101.070, 146, 2.20, 1.050, 8.3,   8, 7.360),
    "Rh": (45, 9,  5, "d", 102.906, 142, 2.28, 1.137, 8.3,   9, 7.459),
    "Pd": (46, 10, 5, "d", 106.420, 139, 2.20, 0.562, 8.9,  10, 8.337),
    "Ag": (47, 11, 5, "d", 107.868, 145, 1.93, 1.302, 10.3, 11, 7.576),
    "Cd": (48, 12, 5, "d", 112.414, 144, 1.69, None,  13.1, 12, 8.994),
    "In": (49, 13, 5, "p", 114.818, 142, 1.78, 0.300, 15.7,  3, 5.786),
    "Sn": (50, 14, 5, "p", 118.710, 139, 1.96, 1.112, 16.3,  4, 7.344),
    "Sb": (51, 15, 5, "p", 121.760, 139, 2.05, 1.047, 18.4,  5, 8.608),
    "Te": (52, 16, 5, "p", 127.600, 138, 2.10, 1.971, 20.5,  6, 9.010),
    "I":  (53, 17, 5, "p", 126.904, 139, 2.66, 3.059, 25.7,  7, 10.451),
    "Xe": (54, 18, 5, "p", 131.293, 140, 2.60, None,  42.9,  8, 12.130),
    "Cs": (55, 1,  6, "s", 132.905, 244, 0.79, 0.472, 70.0,  1, 3.894),
    "Ba": (56, 2,  6, "s", 137.327, 215, 0.89, 0.145, 39.0,  2, 5.212),
    "W":  (74, 6,  6, "d", 183.840, 162, 2.36, 0.816, 9.47,  6, 7.864),
    "Pt": (78, 10, 6, "d", 195.084, 136, 2.28, 2.128, 9.10, 10, 8.959),
    "Au": (79, 11, 6, "d", 196.967, 136, 2.54, 2.309, 10.2, 11, 9.226),
    "Hg": (80, 12, 6, "d", 200.592, 132, 2.00, None,  14.8, 12, 10.438),
    "Pb": (82, 14, 6, "p", 207.200, 146, 2.33, 0.356, 18.3,  4, 7.417),
    "Bi": (83, 15, 6, "p", 208.980, 148, 2.02, 0.942, 21.3,  5, 7.286),
}
# fmt: on

_FIELDS = (
    "atomic_number",
    "group_id",
    "period",
    "block",
    "atomic_weight",
    "covalent_radius",
    "en_pauling",
    "electron_affinity",
    "atomic_volume",
    "nvalence",
    "ionenergy",
)

_BY_NUMBER = {v[0]: k for k, v in _ELEMENTS.items()}


class Element:
    """Property record for one element (mendeleev ``element()`` analog)."""

    def __init__(self, symbol: str):
        if symbol not in _ELEMENTS:
            raise KeyError(f"element {symbol!r} not in embedded periodic table")
        self.symbol = symbol
        for name, value in zip(_FIELDS, _ELEMENTS[symbol]):
            setattr(self, name, value)

    def __repr__(self):
        return f"Element({self.symbol}, Z={self.atomic_number})"


def element(key) -> Element:
    """Look up by symbol or atomic number."""
    if isinstance(key, str):
        return Element(key)
    return Element(_BY_NUMBER[int(key)])


def get_all_elements():
    return [Element(s) for s in _ELEMENTS]


def atomic_number(symbol: str) -> int:
    return _ELEMENTS[symbol][0]


def symbol_of(z: int) -> str:
    return _BY_NUMBER[int(z)]


def standard_valences(symbol: str):
    """Allowed bonding valences for implicit-hydrogen filling (organic
    subset), lowest first — the rule rdkit applies for SMILES atoms outside
    brackets."""
    table: Dict[str, tuple] = {
        "B": (3,),
        "C": (4,),
        "N": (3, 5),
        "O": (2,),
        "P": (3, 5),
        "S": (2, 4, 6),
        "F": (1,),
        "Cl": (1,),
        "Br": (1,),
        "I": (1,),
        "H": (1,),
    }
    return table.get(symbol, ())
