"""Wall-clock timers with cross-host reduction.

Parity with ``hydragnn/utils/time_utils.py:22-138``: class-level aggregation
of named timers, min/max/avg across hosts printed at exit.
"""

import time
from typing import Dict

import numpy as np

_timers: Dict[str, "Timer"] = {}


class Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed = _timers[name].elapsed if name in _timers else 0.0
        self._start = None
        _timers[name] = self

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is not None:
            self.elapsed += time.perf_counter() - self._start
            self._start = None


def reset_timers():
    _timers.clear()


def print_timers(verbosity: int = 0):
    """Print min/max/avg over hosts for each named timer
    (``time_utils.py:97-138``)."""
    from hydragnn_tpu.parallel.distributed import (
        get_comm_size_and_rank,
        host_allreduce,
    )

    world, rank = get_comm_size_and_rank()
    if not _timers:
        return
    names = sorted(_timers)
    values = np.asarray([_timers[n].elapsed for n in names])
    tmin = host_allreduce(values, op="min")
    tmax = host_allreduce(values, op="max")
    tsum = host_allreduce(values, op="sum")
    if rank == 0:
        print(f"{'timer':<28}{'min_s':>12}{'max_s':>12}{'avg_s':>12}")
        for i, n in enumerate(names):
            print(
                f"{n:<28}{tmin[i]:>12.4f}{tmax[i]:>12.4f}"
                f"{tsum[i] / world:>12.4f}"
            )
