"""Config system — schema defaulting and data-derived fields.

Parity with ``hydragnn/utils/config_utils.py:24-318``: same JSON section
names (Verbosity / Dataset / NeuralNetwork{Architecture, Variables_of_interest,
Training} / Visualization) so reference configs translate mechanically;
``update_config`` derives input/output dims from the first sample, the PNA
degree histogram, edge_dim/equivariance validation, and min-max
denormalization tables.
"""

import json
import os
import pickle
from copy import deepcopy

import numpy as np


def update_config(config, train_loader, val_loader, test_loader):
    env = os.getenv("HYDRAGNN_USE_VARIABLE_GRAPH_SIZE")
    if env is None:
        graph_size_variable = check_if_graph_size_variable(
            train_loader, val_loader, test_loader
        )
    else:
        graph_size_variable = bool(int(env))

    ds = config.get("Dataset", {})
    if "graph_features" in ds or "node_features" in ds:
        # a Dataset section without declared feature dims (e.g. one that
        # only carries the `streaming` spec) has nothing to cross-check
        check_output_dim_consistent(train_loader.dataset[0], config)

    config["NeuralNetwork"] = update_config_NN_outputs(
        config["NeuralNetwork"], train_loader.dataset[0], graph_size_variable
    )
    config = normalize_output_config(config)

    config["NeuralNetwork"]["Architecture"]["input_dim"] = len(
        config["NeuralNetwork"]["Variables_of_interest"]["input_node_features"]
    )

    arch = config["NeuralNetwork"]["Architecture"]
    from hydragnn_tpu.parallel.distributed import host_allreduce
    if arch["model_type"] == "PNA":
        deg = gather_deg(train_loader.dataset)
        arch["pna_deg"] = deg.tolist()
        arch["max_neighbours"] = len(deg) - 1
    else:
        arch["pna_deg"] = None
    if "dense_aggregation" not in arch and not arch.get("partition_axis"):
        # record the AUTO aggregation-path decision so the saved config
        # and downstream consumers see the value THE RUN ACTUALLY USES —
        # needs_dense_neighbors resolves every tier (HYDRAGNN_AGG env
        # force > autotuner cache > measured-crossover static policy), so
        # a resume without the env var cannot silently flip the layout
        # mid-experiment; an explicit true/false in the input config
        # always wins, and partition mode keeps its own explicit opt-in
        # (per-shard lists change the memory equation)
        from hydragnn_tpu.data.loaders import needs_dense_neighbors

        arch["dense_aggregation"] = needs_dense_neighbors(arch)
    if arch["model_type"] == "MFC":
        # dataset-wide max in-degree: a STATIC bound that lets the conv
        # slice dead banks out of its one-hot degree matmul (the reference
        # allocates and applies all max_neighbours+1 banks regardless —
        # MFCStack.py:22-51; parameter shapes here stay identical, only
        # the compute shrinks). Derived ONLY from plain in-memory splits
        # (store-backed datasets — graph_sizes/epoch_begin markers — would
        # pay an O(dataset) edge walk at startup, or store-transport
        # traffic for DistDataset); everywhere else the bound is cleared
        # to None, never trusted from a loaded config: a stale bound from
        # a smaller dataset would silently clamp higher-degree nodes to
        # the wrong bank. The walk-or-not decision is reduced across
        # hosts first (min) so no host is stranded in max_in_degree's
        # allreduce if dataset wrappers differ.
        cheap = all(
            not hasattr(ld.dataset, "epoch_begin")
            and not hasattr(ld.dataset, "graph_sizes")
            for ld in (train_loader, val_loader, test_loader)
        )
        all_cheap = bool(host_allreduce(np.asarray([int(cheap)]), op="min")[0])
        arch["mfc_degree_bound"] = (
            max_in_degree(
                ld.dataset for ld in (train_loader, val_loader, test_loader)
            )
            if all_cheap
            else None
        )

    for key in (
        "radius",
        "num_gaussians",
        "num_filters",
        "envelope_exponent",
        "num_after_skip",
        "num_before_skip",
        "basis_emb_size",
        "int_emb_size",
        "out_emb_size",
        "num_radial",
        "num_spherical",
    ):
        arch.setdefault(key, None)

    config["NeuralNetwork"]["Architecture"] = update_config_edge_dim(arch)
    config["NeuralNetwork"]["Architecture"] = update_config_equivariance(
        config["NeuralNetwork"]["Architecture"]
    )

    arch = config["NeuralNetwork"]["Architecture"]
    arch.setdefault("freeze_conv_layers", False)
    arch.setdefault("initial_bias", None)
    arch.setdefault("activation_function", "relu")
    arch.setdefault("SyncBatchNorm", False)

    training = config["NeuralNetwork"]["Training"]
    training.setdefault("loss_function_type", "mse")
    training.setdefault("conv_checkpointing", False)
    if "Optimizer" not in training:
        training["Optimizer"] = {"type": "AdamW", "learning_rate": 1e-3}
    return config


def update_config_equivariance(arch):
    equivariant_models = ["EGNN", "SchNet"]
    if arch.get("equivariance"):
        assert (
            arch["model_type"] in equivariant_models
        ), "E(3) equivariance can only be ensured for EGNN and SchNet."
    elif "equivariance" not in arch:
        arch["equivariance"] = False
    return arch


def update_config_edge_dim(arch):
    arch["edge_dim"] = None
    edge_models = ["PNA", "CGCNN", "SchNet", "EGNN"]
    if arch.get("edge_features"):
        assert (
            arch["model_type"] in edge_models
        ), "Edge features can only be used with EGNN, SchNet, PNA and CGCNN."
        arch["edge_dim"] = len(arch["edge_features"])
    elif arch["model_type"] == "CGCNN":
        arch["edge_dim"] = 0
    return arch


def check_if_graph_size_variable(train_loader, val_loader, test_loader) -> bool:
    sizes = set()
    for loader in (train_loader, val_loader, test_loader):
        for d in loader.dataset:
            sizes.add(d.num_nodes)
            if len(sizes) > 1:
                break
        if len(sizes) > 1:
            break
    variable = len(sizes) > 1
    from hydragnn_tpu.parallel.distributed import host_allreduce

    return bool(host_allreduce(np.asarray([int(variable)]), op="max")[0] > 0)


def check_output_dim_consistent(data, config):
    output_type = config["NeuralNetwork"]["Variables_of_interest"]["type"]
    output_index = config["NeuralNetwork"]["Variables_of_interest"]["output_index"]
    for ihead, (t, idx) in enumerate(zip(output_type, output_index)):
        dim = data.targets[ihead].shape[-1] if data.targets[ihead].ndim > 1 else data.targets[ihead].shape[0]
        if t == "graph":
            assert dim == config["Dataset"]["graph_features"]["dim"][idx]
        elif t == "node":
            assert dim == config["Dataset"]["node_features"]["dim"][idx]


def update_config_NN_outputs(nn_config, data, graph_size_variable: bool):
    """Derive head output dims from the first sample's targets
    (``config_utils.py:156-192``)."""
    output_type = nn_config["Variables_of_interest"]["type"]
    dims_list = []
    for ihead, t in enumerate(output_type):
        if t == "graph":
            dims_list.append(int(data.targets[ihead].shape[0]))
        elif t == "node":
            if (
                graph_size_variable
                and nn_config["Architecture"]["output_heads"]["node"]["type"]
                == "mlp_per_node"
            ):
                raise ValueError(
                    '"mlp_per_node" is not allowed for variable graph size'
                )
            dims_list.append(int(data.targets[ihead].shape[-1]))
        else:
            raise ValueError("Unknown output type", t)
    nn_config["Architecture"]["output_dim"] = dims_list
    nn_config["Architecture"]["output_type"] = list(output_type)
    nn_config["Architecture"]["num_nodes"] = int(data.num_nodes)
    return nn_config


def normalize_output_config(config):
    var_config = config["NeuralNetwork"]["Variables_of_interest"]
    if var_config.get("denormalize_output"):
        if (
            var_config.get("minmax_node_feature") is not None
            and var_config.get("minmax_graph_feature") is not None
        ):
            dataset_path = None
        elif list(config["Dataset"]["path"].values())[0].endswith(".pkl"):
            dataset_path = list(config["Dataset"]["path"].values())[0]
        else:
            base = os.environ.get("SERIALIZED_DATA_PATH", os.getcwd())
            if "total" in config["Dataset"]["path"]:
                dataset_path = (
                    f"{base}/serialized_dataset/{config['Dataset']['name']}.pkl"
                )
            else:
                dataset_path = (
                    f"{base}/serialized_dataset/"
                    f"{config['Dataset']['name']}_train.pkl"
                )
        var_config = update_config_minmax(dataset_path, var_config)
    else:
        var_config["denormalize_output"] = False
    config["NeuralNetwork"]["Variables_of_interest"] = var_config
    return config


def update_config_minmax(dataset_path, var_config):
    """Load denormalization tables (``config_utils.py:219-243``)."""
    if (
        "minmax_node_feature" not in var_config
        and "minmax_graph_feature" not in var_config
    ):
        with open(dataset_path, "rb") as f:
            node_minmax = pickle.load(f)
            graph_minmax = pickle.load(f)
    else:
        node_minmax = np.asarray(var_config["minmax_node_feature"])
        graph_minmax = np.asarray(var_config["minmax_graph_feature"])
    var_config["x_minmax"] = [
        node_minmax[:, i].tolist() for i in var_config["input_node_features"]
    ]
    var_config["y_minmax"] = []
    for t, idx in zip(var_config["type"], var_config["output_index"]):
        if t == "graph":
            var_config["y_minmax"].append(graph_minmax[:, idx].tolist())
        elif t == "node":
            var_config["y_minmax"].append(node_minmax[:, idx].tolist())
        else:
            raise ValueError("Unknown output type", t)
    return var_config


def _in_degree_counts(d) -> np.ndarray:
    """Per-node in-degree of one sample (shared by the PNA histogram and
    the MFC bound so the two derivations cannot drift)."""
    return np.bincount(d.edge_index[1], minlength=d.num_nodes)


def max_in_degree(datasets) -> int:
    """Dataset-wide max in-degree (all splits), reduced across hosts."""
    from hydragnn_tpu.parallel.distributed import host_allreduce

    m = 0
    for ds in datasets:
        for d in ds:
            if d.num_edges:
                m = max(m, int(_in_degree_counts(d).max()))
    return int(host_allreduce(np.asarray([m]), op="max")[0])


def gather_deg(dataset) -> np.ndarray:
    """In-degree histogram over the dataset for PNA scalers
    (``preprocess/utils.py:177-234``), reduced across hosts."""
    from hydragnn_tpu.parallel.distributed import host_allreduce

    max_deg = 0
    for d in dataset:
        if d.num_edges:
            max_deg = max(max_deg, int(_in_degree_counts(d).max()))
    max_deg = int(host_allreduce(np.asarray([max_deg]), op="max")[0])
    deg = np.zeros(max_deg + 1, dtype=np.int64)
    for d in dataset:
        deg += np.bincount(_in_degree_counts(d), minlength=max_deg + 1)
    return host_allreduce(deg, op="sum")


def get_log_name_config(config):
    """Run naming (``config_utils.py:246-279``)."""
    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"]["Training"]
    name = config["Dataset"]["name"]
    cut = name.rfind("_") if name.rfind("_") > 0 else None
    return (
        f"{arch['model_type']}-r-{arch.get('radius')}"
        f"-ncl-{arch['num_conv_layers']}-hd-{arch['hidden_dim']}"
        f"-ne-{training['num_epoch']}"
        f"-lr-{training['Optimizer']['learning_rate']}"
        f"-bs-{training['batch_size']}"
        f"-data-{name[:cut]}"
        "-node_ft-"
        + "".join(
            str(x)
            for x in config["NeuralNetwork"]["Variables_of_interest"][
                "input_node_features"
            ]
        )
        + "-task_weights-"
        + "".join(f"{w}-" for w in arch["task_weights"])
    )


def save_config(config, log_name, path="./logs/"):
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    if rank == 0:
        fname = os.path.join(path, log_name, "config.json")
        os.makedirs(os.path.dirname(fname), exist_ok=True)
        with open(fname, "w") as f:
            json.dump(config, f, indent=4, default=str)


def merge_config(a: dict, b: dict) -> dict:
    """Deep merge b into a (``config_utils.py:310-318``)."""
    result = deepcopy(a)
    for k, v in b.items():
        if isinstance(result.get(k), dict) and isinstance(v, dict):
            result[k] = merge_config(result[k], v)
        else:
            result[k] = deepcopy(v)
    return result
