"""Fault-injection harness for resilience testing.

Every injection point is an env/config-driven hook that production code
calls unconditionally; with no ``HYDRAGNN_FAULT_*`` variable set each hook
is a cheap no-op, so the harness costs nothing outside tests. The points
(all consumed by ``tests/test_resilience.py``):

- ``HYDRAGNN_FAULT_KILL_AT_STEP=N`` — hard-kill the process (``os._exit``,
  no cleanup handlers, the closest userspace analog of a SLURM preemption
  SIGKILL) when the trainer reaches optimizer step ``N`` (0-based, counted
  per process).
- ``HYDRAGNN_FAULT_CORRUPT_CHECKPOINT=K`` — flip one payload byte of the
  ``K``-th checkpoint file written by this process (1-based; ``all``
  corrupts every write). Exercises the CRC detection + rolling-fallback
  path.
- ``HYDRAGNN_FAULT_FLAKY_READ=N`` — the first ``N`` dataset reads that
  pass through a flaky-read checkpoint raise ``OSError`` (then reads
  succeed). Exercises the retry-with-jittered-backoff wrappers.
- ``HYDRAGNN_FAULT_NAN_AT_STEP=SPEC`` — poison the training batch with
  NaNs at the optimizer steps named by ``SPEC`` (``"3"``, ``"3,5,9"`` or
  ``"4:9"`` half-open range). Exercises the divergence guard.
- ``HYDRAGNN_FAULT_LOSE_HOST_AT_STEP=RANK:N`` — hard-kill the process
  whose ``jax.process_index()`` is ``RANK`` at its optimizer step ``N``
  (bare ``N`` targets rank 0). The multi-host preemption injection:
  exactly one host of the world disappears mid-epoch, exercising the
  elastic lease/watchdog/re-mesh path (``train/elastic.py``).
- ``HYDRAGNN_FAULT_SLOW_STEP=SPEC@SECONDS`` — sleep ``SECONDS`` before
  dispatching each optimizer step covered by ``SPEC`` (same grammar as
  NAN_AT_STEP; ``SECONDS`` defaults to 0.25). The straggler injection:
  exercises the flight-recorder stall detection and the HPO launcher's
  heartbeat-staleness early kill without any host actually dying.

Serving-side knobs (consumed by ``serve/fleet.py`` replicas and
``tests/test_fleet.py`` — the serving twin of the host-loss injections):

- ``HYDRAGNN_FAULT_KILL_REPLICA_AT_REQUEST=REPLICA:K`` — hard-kill THIS
  process (``os._exit``, the SIGKILL-mid-request analog) when it is
  serving replica ``REPLICA`` (``HYDRAGNN_FLEET_REPLICA`` env) and its
  ``K``-th accepted request arrives (1-based; bare ``K`` targets replica
  0). Exercises lease-expiry detection + supervisor respawn + router
  retry with in-flight requests genuinely lost on the dead replica.
- ``HYDRAGNN_FAULT_SLOW_REPLICA=REPLICA:SPEC@SECONDS`` — sleep
  ``SECONDS`` before dispatching each request whose 0-based ordinal is
  covered by ``SPEC`` (NAN_AT_STEP grammar) on replica ``REPLICA``
  (bare ``SPEC@SECONDS`` targets replica 0; ``SECONDS`` defaults to
  0.25). The slow-replica injection: exercises deadline-aware routing
  and SLO-miss accounting without killing anything.
- ``HYDRAGNN_FAULT_CORRUPT_CANDIDATE=K`` — the ``K``-th candidate
  checkpoint a hot-swap promote loads in this process (1-based;
  ``all`` corrupts every one) is read through a byte-flipped COPY, so
  the strict v2 CRC check fails exactly as it would for real on-disk
  corruption (the shared original is untouched — other replicas must
  see the pristine file). Exercises the promote -> reject -> rollback
  path with the old version still serving.
- ``HYDRAGNN_FAULT_NAN_CANDIDATE=K`` — the ``K``-th request a CANARY
  replica serves answers with every head full of NaN (1-based; ``all``
  poisons every canary answer). The call site gates on the replica's
  canary role, so a globally-set knob can never poison live traffic —
  it exercises the canary controller's hard NaN veto.
- ``HYDRAGNN_FAULT_SLOW_CANDIDATE=SPEC@SECONDS`` — sleep ``SECONDS``
  before dispatching each canary request whose 0-based ordinal is
  covered by ``SPEC`` (NAN_AT_STEP grammar; ``SECONDS`` defaults to
  0.25). Canary-only for the same reason: exercises the per-bucket
  latency-regression gate without touching live SLOs.
- ``HYDRAGNN_FAULT_SHIFT_INPUTS=SPEC@SCALE`` — multiply the node
  features (and positions) of each decoded request graph whose 0-based
  ordinal is covered by ``SPEC`` by ``SCALE`` (default 3.0). The
  input-distribution-shift injection: exercises the drift detector's
  window scoring + alert hysteresis (``obs/drift.py``) without the
  load generator having to craft shifted traffic. Gated per replica
  via ``HYDRAGNN_FAULT_SHIFT_REPLICA`` (unset = every replica).

Counters are process-global and monotonic; :func:`reset` exists for tests
that exercise several scenarios in one process.
"""

import os
import threading
import time

_lock = threading.Lock()
_counters = {
    "ckpt_writes": 0,
    "flaky_reads": 0,
    "replica_requests": 0,
    "candidate_loads": 0,
}

KILL_EXIT_CODE = 113  # distinctive, checked by the kill-and-resume e2e test


def reset():
    """Zero the process-global injection counters (test helper)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0


def _parse_step_spec(spec: str):
    """``"3"`` / ``"3,5"`` / ``"4:9"`` -> membership predicate over ints."""
    spec = spec.strip()
    if not spec:
        return lambda step: False
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        lo = int(lo) if lo else 0
        hi = int(hi) if hi else None
        return lambda step: step >= lo and (hi is None or step < hi)
    members = {int(p) for p in spec.split(",") if p.strip()}
    return lambda step: step in members


def kill_at_step(step: int) -> None:
    """Preemption injection: hard-exit when ``step`` hits the configured
    value. ``os._exit`` skips atexit/finally on purpose — a preempted job
    gets no goodbye either; only already-fsynced checkpoints survive."""
    spec = os.getenv("HYDRAGNN_FAULT_KILL_AT_STEP")
    if spec is None:
        return
    if int(spec) == int(step):
        os._exit(KILL_EXIT_CODE)


def lose_host_at_step(step: int) -> None:
    """Multi-host preemption injection: hard-exit THIS process when it is
    the targeted rank and ``step`` hits the configured value. Spec is
    ``"RANK:N"`` (bare ``"N"`` = rank 0). Same no-cleanup ``os._exit``
    semantics as :func:`kill_at_step` — the host just vanishes."""
    spec = os.getenv("HYDRAGNN_FAULT_LOSE_HOST_AT_STEP")
    if spec is None:
        return
    rank_s, _, step_s = spec.rpartition(":")
    target_rank = int(rank_s) if rank_s else 0
    if int(step_s) != int(step):
        return
    import jax  # lazy: the no-op path must not initialize a backend

    try:
        rank = jax.process_index()
    except Exception:
        rank = 0
    if rank == target_rank:
        os._exit(KILL_EXIT_CODE)


def slow_step(step: int) -> None:
    """Straggler injection: sleep before dispatching a covered step.
    Spec is ``"SPEC@SECONDS"`` (``"12@0.3"``, ``"4:9@0.05"``); a bare
    ``"SPEC"`` sleeps the 0.25 s default. With
    ``HYDRAGNN_FAULT_SLOW_STEP_RANK=K`` only process rank K is slowed —
    the one-host straggler the goodput fleet rollup exists to flag
    (every rank otherwise sleeps, which is a fleet-wide slowdown, not a
    straggler)."""
    spec = os.getenv("HYDRAGNN_FAULT_SLOW_STEP")
    if spec is None:
        return
    rank_s = os.getenv("HYDRAGNN_FAULT_SLOW_STEP_RANK")
    if rank_s is not None and rank_s.strip() != "":
        import jax  # lazy: the no-op path must not initialize a backend

        try:
            rank = jax.process_index()
        except Exception:
            rank = 0
        if rank != int(rank_s):
            return
    member, _, secs = spec.partition("@")
    if _parse_step_spec(member)(int(step)):
        time.sleep(float(secs) if secs else 0.25)


def _this_replica() -> int:
    """The serving replica id of THIS process (0 when unset — matches
    the bare-spec default the step-side injections use for rank)."""
    try:
        return int(os.getenv("HYDRAGNN_FLEET_REPLICA", "0"))
    except ValueError:
        return 0


def kill_replica_at_request() -> None:
    """Replica-death injection: hard-exit when this replica's K-th
    accepted request arrives. Spec is ``"REPLICA:K"`` (bare ``"K"`` =
    replica 0, K 1-based). Called once per accepted request by the
    replica's request path; the counter advances ONLY when the knob is
    set and names this replica, so the fire point is exact regardless of
    traffic served before the knob applies. Same no-cleanup ``os._exit``
    as :func:`kill_at_step` — in-flight work dies with the process and
    only the router's retry resurrects it."""
    spec = os.getenv("HYDRAGNN_FAULT_KILL_REPLICA_AT_REQUEST")
    if spec is None:
        return
    replica_s, _, req_s = spec.rpartition(":")
    target = int(replica_s) if replica_s else 0
    if _this_replica() != target:
        return
    with _lock:
        _counters["replica_requests"] += 1
        ordinal = _counters["replica_requests"]
    if ordinal == int(req_s):
        os._exit(KILL_EXIT_CODE)


def slow_replica(request_ordinal: int) -> None:
    """Slow-replica injection: sleep before dispatching each covered
    request. Spec is ``"REPLICA:SPEC@SECONDS"`` (``"1:0:50@0.2"`` slows
    replica 1's first 50 requests by 0.2 s). A colon-free bare spec
    (``"7@0.5"``) targets replica 0; range/list specs containing ``:``
    need the explicit replica prefix. ``SECONDS`` defaults to 0.25."""
    spec = os.getenv("HYDRAGNN_FAULT_SLOW_REPLICA")
    if spec is None:
        return
    member, _, secs = spec.partition("@")
    replica_s, sep, step_spec = member.partition(":")
    if not sep:
        target, step_spec = 0, member
    else:
        target = int(replica_s)
    if _this_replica() != target:
        return
    if _parse_step_spec(step_spec)(int(request_ordinal)):
        time.sleep(float(secs) if secs else 0.25)


def shift_inputs(graph, request_ordinal: int):
    """Input-drift injection: scale a covered request graph's node
    features (and positions, when present) in place. Spec is
    ``"SPEC@SCALE"`` (``"200:@3.0"`` shifts every request from ordinal
    200 on by 3x); ``SCALE`` defaults to 3.0. The caller passes its own
    DECODED copy of the request — the client's payload is untouched.
    ``HYDRAGNN_FAULT_SHIFT_REPLICA=K`` restricts the shift to replica
    ``K`` (unset shifts every replica that sees a covered ordinal)."""
    spec = os.getenv("HYDRAGNN_FAULT_SHIFT_INPUTS")
    if spec is None:
        return graph
    replica_s = os.getenv("HYDRAGNN_FAULT_SHIFT_REPLICA")
    if replica_s is not None and replica_s.strip() != "":
        if _this_replica() != int(replica_s):
            return graph
    member, _, scale_s = spec.partition("@")
    if not _parse_step_spec(member)(int(request_ordinal)):
        return graph
    scale = float(scale_s) if scale_s else 3.0
    if getattr(graph, "x", None) is not None:
        graph.x = graph.x * scale
    if getattr(graph, "pos", None) is not None:
        graph.pos = graph.pos * scale
    return graph


def nan_candidate(request_ordinal: int) -> bool:
    """Bad-candidate injection: True when the canary replica's request
    at ``request_ordinal`` (1-based, the replica's own accepted-request
    counter) should answer all-NaN heads. Spec is the 1-based ordinal
    (``all`` = every request). The ONLY call site is the canary branch
    of ``ReplicaServer.handle_predict`` — live replicas never consult
    this knob, so setting it fleet-wide cannot corrupt live answers."""
    spec = os.getenv("HYDRAGNN_FAULT_NAN_CANDIDATE")
    if spec is None:
        return False
    if spec == "all":
        return True
    return int(spec) == int(request_ordinal)


def slow_candidate(request_ordinal: int) -> None:
    """Latency-regression injection: sleep before dispatching each
    canary request whose 0-based ordinal is covered. Spec is
    ``"SPEC@SECONDS"`` (``"0:50@0.2"`` slows the first 50 shadow
    requests by 0.2 s); ``SECONDS`` defaults to 0.25. Canary-only, same
    call-site gate as :func:`nan_candidate`."""
    spec = os.getenv("HYDRAGNN_FAULT_SLOW_CANDIDATE")
    if spec is None:
        return
    member, _, secs = spec.partition("@")
    if _parse_step_spec(member)(int(request_ordinal)):
        time.sleep(float(secs) if secs else 0.25)


def corrupt_candidate(path: str) -> str:
    """Candidate-corruption injection: when this process's selected
    hot-swap candidate load arrives, return a byte-flipped COPY of
    ``path`` for the loader to read (the original stays pristine — the
    other replicas' loads must succeed). Spec is the 1-based load
    ordinal (``all`` = every load); unset or unselected loads return
    ``path`` unchanged."""
    spec = os.getenv("HYDRAGNN_FAULT_CORRUPT_CANDIDATE")
    if spec is None:
        return path
    with _lock:
        _counters["candidate_loads"] += 1
        ordinal = _counters["candidate_loads"]
    if spec != "all" and int(spec) != ordinal:
        return path
    corrupt = f"{path}.injected-corrupt"
    with open(path, "rb") as src:
        blob = bytearray(src.read())
    if blob:
        blob[len(blob) // 2] ^= 0xFF
    with open(corrupt, "wb") as dst:
        dst.write(bytes(blob))
    return corrupt


def nan_at_step(step: int) -> bool:
    """True when the divergence-guard NaN injection covers ``step``."""
    spec = os.getenv("HYDRAGNN_FAULT_NAN_AT_STEP")
    if spec is None:
        return False
    return _parse_step_spec(spec)(int(step))


def corrupt_checkpoint(path: str) -> None:
    """Post-write corruption injection: called by ``save_model`` with the
    final checkpoint path after the atomic rename; flips one byte in the
    middle of the file when this write's ordinal is selected."""
    spec = os.getenv("HYDRAGNN_FAULT_CORRUPT_CHECKPOINT")
    if spec is None:
        return
    with _lock:
        _counters["ckpt_writes"] += 1
        ordinal = _counters["ckpt_writes"]
    if spec != "all" and int(spec) != ordinal:
        return
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))


def flaky_read(what: str = "") -> None:
    """Transient-I/O injection: raise ``OSError`` for the first ``N``
    reads that reach any flaky-read checkpoint, then behave."""
    spec = os.getenv("HYDRAGNN_FAULT_FLAKY_READ")
    if spec is None:
        return
    with _lock:
        if _counters["flaky_reads"] >= int(spec):
            return
        _counters["flaky_reads"] += 1
    raise OSError(f"injected transient read failure ({what or 'read'})")
