"""Verbosity-leveled, rank-aware logging.

Parity with ``hydragnn/utils/print_utils.py:19-111``: verbosity levels 0-4,
rank-0-only and per-rank variants, optional file logging under
``./logs/<name>/``.
"""

import logging
import os
import sys

VERBOSITY_LEVELS = (0, 1, 2, 3, 4)
_logger = None


def _rank():
    try:
        from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

        return get_comm_size_and_rank()[1]
    except Exception:
        return 0


def print_distributed(verbosity_level: int, *args):
    """Print on rank 0 when verbosity >= 2 (matches reference gating)."""
    if verbosity_level >= 2 and _rank() == 0:
        print(*args)


def print_master(*args, verbosity_level: int = 2):
    if _rank() == 0:
        print(*args)


def setup_log(log_name: str, path: str = "./logs/"):
    """Rank-tagged python logging to ./logs/<name>/run.log + console
    (``print_utils.py:63-96``)."""
    global _logger
    rank = _rank()
    log_dir = os.path.join(path, log_name)
    os.makedirs(log_dir, exist_ok=True)
    logger = logging.getLogger("hydragnn_tpu")
    logger.setLevel(logging.INFO)
    logger.handlers.clear()
    fmt = logging.Formatter(f"[rank {rank}] %(message)s")
    fh = logging.FileHandler(os.path.join(log_dir, "run.log"))
    fh.setFormatter(fmt)
    logger.addHandler(fh)
    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    _logger = logger
    return logger


def log(*args):
    msg = " ".join(str(a) for a in args)
    if _logger is not None:
        _logger.info(msg)


def log0(*args):
    if _rank() == 0:
        log(*args)


def iterate_tqdm(iterable, verbosity_level: int = 0, desc: str = ""):
    """tqdm wrapper gated on verbosity (``print_utils.py:55-59``); plain
    iteration if tqdm is unavailable."""
    if verbosity_level >= 2:
        try:
            from tqdm import tqdm

            return tqdm(iterable, desc=desc)
        except ImportError:
            pass
    return iterable
