"""Deprecated shim — the profiler moved to ``hydragnn_tpu.obs.introspect``.

``Profiler`` (the reference-parity wait/warmup/active step schedule over
``jax.profiler``) and ``record_function`` now live in the observability
layer next to the on-demand trace capture that superseded them
(``/profile?steps=N`` on the observability endpoint,
``HYDRAGNN_PROFILE_AT_STEP`` — see docs/observability.md). This module
re-exports them so the reference-parity import path keeps working; new
code should import from :mod:`hydragnn_tpu.obs.introspect`.
"""

import warnings

from hydragnn_tpu.obs.introspect import (  # noqa: F401  (re-exported API)
    Profiler,
    record_function,
)

# warn once per process, at first import — the module body runs once
warnings.warn(
    "hydragnn_tpu.utils.profile is deprecated: Profiler/record_function "
    "moved to hydragnn_tpu.obs.introspect (on-demand trace capture lives "
    "on the observability endpoint, /profile?steps=N)",
    DeprecationWarning,
    stacklevel=2,
)
