"""Profiler — step-scheduled device tracing for TensorBoard.

Parity with the reference's ``Profiler(torch.profiler.profile)``
(``hydragnn/utils/profile.py:9-70``): a wait/warmup/active step schedule, a
target-epoch gate, TensorBoard-consumable output, and a no-op object when
disabled so call sites stay unconditional. The backend is ``jax.profiler``
(XLA device traces, viewable in TensorBoard's profile plugin or perfetto)
instead of torch.profiler/kineto.

Usage (same call pattern as the reference train loop,
``train_validate_test.py:155-169``):

    prof = Profiler("./logs/run")
    prof.setup(config["Visualization"].get("Profile", {}))
    prof.set_current_epoch(epoch)
    with prof:
        for batch in loader:
            ...
            prof.step()
"""

import os
from typing import Optional


class Profiler:
    def __init__(
        self,
        trace_dir: str = "./logs/profile",
        wait: int = 5,
        warmup: int = 3,
        active: int = 3,
        target_epoch: Optional[int] = 1,
    ):
        self.trace_dir = trace_dir
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self.target_epoch = target_epoch
        self.enabled = False
        self._epoch = None
        self._step = 0
        self._tracing = False

    def setup(self, config: dict):
        """Config section ``{"Profile": {"enable": 1, "trace_dir": ...}}``
        (reference reads ``config["Profile"]``, ``profile.py:22-29``)."""
        if not config:
            return
        self.enabled = bool(config.get("enable", 0))
        self.trace_dir = config.get("trace_dir", self.trace_dir)
        self.wait = int(config.get("wait", self.wait))
        self.warmup = int(config.get("warmup", self.warmup))
        self.active = int(config.get("active", self.active))
        self.target_epoch = config.get("target_epoch", self.target_epoch)

    def set_current_epoch(self, epoch: int):
        self._epoch = epoch

    def _armed(self) -> bool:
        if not self.enabled:
            return False
        return self.target_epoch is None or self._epoch == self.target_epoch

    # -- context manager --------------------------------------------------
    def __enter__(self):
        self._step = 0
        return self

    def __exit__(self, *exc):
        self._stop_trace()
        return False

    def step(self):
        """Advance the schedule; starts/stops the device trace at the
        wait→warmup→active window boundaries."""
        if not self._armed():
            return
        self._step += 1
        # trace through warmup+active, discard-by-convention the warmup part
        if self._step == self.wait + 1:
            self._start_trace()
        elif self._step == self.wait + self.warmup + self.active + 1:
            self._stop_trace()

    def _start_trace(self):
        if self._tracing:
            return
        import jax.profiler

        os.makedirs(self.trace_dir, exist_ok=True)
        jax.profiler.start_trace(self.trace_dir)
        self._tracing = True

    def _stop_trace(self):
        if not self._tracing:
            return
        import jax.profiler

        jax.profiler.stop_trace()
        self._tracing = False


def record_function(name: str):
    """Annotation context (torch.profiler.record_function analog) — shows up
    inside the XLA trace timeline."""
    import jax.profiler

    return jax.profiler.TraceAnnotation(name)
