"""Periodic-table atomic descriptor embeddings.

Parity with ``hydragnn/utils/atomicdescriptors.py:12-243``: per-element
feature vectors built from element-type one-hot, group, period, covalent
radius, electron affinity, block one-hot, atomic volume, atomic number,
atomic weight, electronegativity, valence-electron count and first ionization
energy — real-valued properties min–max normalized over the chosen element
set, with an optional one-hot (binned) encoding of each property. Embeddings
are cached to a JSON file keyed by atomic number, exactly like the reference.

Implemented in numpy over the embedded periodic table
(:mod:`hydragnn_tpu.utils.periodic_table`) instead of mendeleev + torch: the
output feeds host-side preprocessing, never the XLA graph.
"""

import json
import os
from typing import List, Optional

import numpy as np

from hydragnn_tpu.utils import periodic_table as pt

_BLOCKS = ["s", "p", "d", "f"]


def _one_hot(indices: np.ndarray, num_classes: int) -> np.ndarray:
    out = np.zeros((indices.shape[0], num_classes), dtype=np.float32)
    out[np.arange(indices.shape[0]), indices.astype(int)] = 1.0
    return out


def _normalize(values: List[Optional[float]], prop_name: str) -> np.ndarray:
    none_elements = [i for i, v in enumerate(values) if v is None]
    if none_elements:
        raise ValueError(
            f"undefined property {prop_name!r} for element indices {none_elements}"
        )
    arr = np.asarray(values, dtype=np.float32)
    span = arr.max() - arr.min()
    return (arr - arr.min()) / (span if span > 0 else 1.0)


def _real_to_categorical(values: np.ndarray, num_classes: int = 10) -> np.ndarray:
    delta = (values.max() - values.min()) / num_classes
    if delta == 0:
        return np.zeros_like(values)
    return np.minimum((values - values.min()) / delta, num_classes - 1)


class atomicdescriptors:
    def __init__(
        self,
        embeddingfilename: str,
        overwritten: bool = True,
        element_types=("C", "H", "O", "N", "F", "S"),
        one_hot: bool = False,
    ):
        if os.path.exists(embeddingfilename) and not overwritten:
            with open(embeddingfilename, "r") as f:
                self.atom_embeddings = json.load(f)
            return

        if element_types is None:
            self.element_types = [e.symbol for e in pt.get_all_elements()]
        else:
            self.element_types = [
                e.symbol for e in pt.get_all_elements() if e.symbol in element_types
            ]
        self.one_hot = one_hot
        n = len(self.element_types)
        elems = [pt.element(s) for s in self.element_types]

        type_id = _one_hot(np.arange(n), n)
        group_id = np.asarray(
            [[e.group_id - 1] for e in elems], dtype=np.float32
        )
        period = np.asarray([[e.period - 1] for e in elems], dtype=np.float32)
        covalent_radius = _normalize(
            [e.covalent_radius for e in elems], "covalent_radius"
        ).reshape(n, 1)
        electron_affinity = _normalize(
            [e.electron_affinity for e in elems], "electron_affinity"
        ).reshape(n, 1)
        block = _one_hot(
            np.asarray([_BLOCKS.index(e.block) for e in elems]), len(_BLOCKS)
        )
        atomic_volume = _normalize(
            [e.atomic_volume for e in elems], "atomic_volume"
        ).reshape(n, 1)
        atomic_number = np.asarray(
            [[e.atomic_number] for e in elems], dtype=np.float32
        )
        atomic_weight = _normalize(
            [e.atomic_weight for e in elems], "atomic_weight"
        ).reshape(n, 1)
        electronegativity = _normalize(
            [e.en_pauling for e in elems], "en_pauling"
        ).reshape(n, 1)
        valenceelectrons = np.asarray(
            [[e.nvalence] for e in elems], dtype=np.float32
        )
        ionenergies = _normalize(
            [e.ionenergy for e in elems], "ionenergies"
        ).reshape(n, 1)

        if one_hot:
            def int_onehot(prop):
                flat = prop.reshape(-1)
                return _one_hot(flat, int(flat.max()) + 1)

            def real_onehot(prop, num_classes=10):
                cats = _real_to_categorical(prop.reshape(-1), num_classes)
                return _one_hot(cats, num_classes)

            group_id = int_onehot(group_id)
            period = int_onehot(period)
            atomic_number = int_onehot(atomic_number)
            valenceelectrons = int_onehot(valenceelectrons)
            covalent_radius = real_onehot(covalent_radius)
            electron_affinity = real_onehot(electron_affinity)
            atomic_volume = real_onehot(atomic_volume)
            atomic_weight = real_onehot(atomic_weight)
            electronegativity = real_onehot(electronegativity)
            ionenergies = real_onehot(ionenergies)

        self.atom_embeddings = {}
        columns = [
            type_id,
            group_id,
            period,
            covalent_radius,
            electron_affinity,
            block,
            atomic_volume,
            atomic_number,
            atomic_weight,
            electronegativity,
            valenceelectrons,
            ionenergies,
        ]
        for i, e in enumerate(elems):
            self.atom_embeddings[str(e.atomic_number)] = [
                float(v) for col in columns for v in np.atleast_2d(col)[i]
            ]
        with open(embeddingfilename, "w") as f:
            json.dump(self.atom_embeddings, f)

    def get_atom_features(self, atomtype) -> np.ndarray:
        if isinstance(atomtype, str):
            atomtype = pt.element(atomtype).atomic_number
        return np.asarray(self.atom_embeddings[str(atomtype)], dtype=np.float32)
