"""Device-completion fencing for honest timing.

``jax.block_until_ready`` does NOT actually block on some tunneled dev
backends (observed on the axon TPU plugin): it returns while the device
queue is still draining, so any wall-clock measurement fenced with it
records dispatch rate, not compute time. The only reliable completion
point is materializing a result byte on the host.

The reference faces the same problem on CUDA (async launches) and solves
it with ``torch.cuda.synchronize`` in its tracer
(``hydragnn/utils/tracer.py:110-131``, the ``cudasync`` option); ``fence``
is the TPU/JAX analog used by ``bench.py``, the examples, and the timers.
"""

import numpy as np


def fence(tree):
    """Block until every computation feeding ``tree`` has finished.

    Fetches one element of the first array leaf. Device queues execute in
    order, so fencing on the most recently dispatched output fences all
    work enqueued before it. Returns ``tree`` unchanged so it can wrap a
    call site: ``out = fence(step(...))``.
    """
    import jax

    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "ravel")
    ]
    if leaves:
        np.asarray(jax.device_get(leaves[0].ravel()[0:1]))
    return tree
