"""SMILES -> GraphData featurization.

Parity with ``hydragnn/utils/smiles_utils.py:35-121``: node features are
[one-hot atom type | atomic number, aromaticity, SP, SP2, SP3, #bonded-H],
edge features a 4-way one-hot over {single, double, triple, aromatic}, both
directions per bond, edges sorted by ``src*N+dst``; hydrogens are added as
explicit atoms (rdkit ``AddHs`` analog).

Backends: rdkit when importable; otherwise a built-in minimal SMILES parser
(organic subset, branches, ring closures incl. ``%nn``, bracket atoms with
explicit H/charge, aromatic lowercase atoms) so SMILES workloads (CSCE/OGB
band-gap) run in this image, which has no rdkit. The fallback approximates
rdkit on default bonds between aromatic atoms (aromatic only when the bond
lies on a cycle) and on hybridization flags (triple/cumulated -> SP,
double/aromatic -> SP2, else SP3 for heavy atoms).
"""

import math
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.utils.periodic_table import atomic_number, standard_valences

try:
    from rdkit import Chem  # noqa: F401

    _HAVE_RDKIT = True
except ImportError:
    _HAVE_RDKIT = False

# bond-type one-hot layout (reference ``smiles_utils.py:51``)
_BOND_TYPES = {"single": 0, "double": 1, "triple": 2, "aromatic": 3}

_ORGANIC = ["Cl", "Br", "B", "C", "N", "O", "P", "S", "F", "I"]
_AROMATIC = {"b": "B", "c": "C", "n": "N", "o": "O", "p": "P", "s": "S"}
_BRACKET_RE = re.compile(
    r"\[(?P<isotope>\d+)?(?P<symbol>[A-Z][a-z]?|[bcnops])"
    r"(?P<chiral>@{1,2})?(?P<hcount>H\d*)?(?P<charge>[+-]\d*|[+]+|[-]+)?"
    r"(?::\d+)?\]"
)


class _Atom:
    def __init__(self, symbol, aromatic, explicit_h=None):
        self.symbol = symbol
        self.aromatic = aromatic
        self.explicit_h = explicit_h  # None => implicit by valence


def _parse_smiles(smiles: str) -> Tuple[List[_Atom], List[Tuple[int, int, str]]]:
    """Minimal SMILES parser: atoms + bonds with order labels."""
    atoms: List[_Atom] = []
    bonds: List[Tuple[int, int, str]] = []
    stack: List[int] = []
    ring: Dict[int, Tuple[int, Optional[str]]] = {}
    prev: Optional[int] = None
    pending_bond: Optional[str] = None
    bond_symbols = {"-": "single", "=": "double", "#": "triple", ":": "aromatic",
                    "/": "single", "\\": "single"}

    def add_bond(a: int, b: int, symbol: Optional[str]):
        if symbol is not None:
            order = symbol
        elif atoms[a].aromatic and atoms[b].aromatic:
            order = "aromatic?"  # provisional: demoted later if not on a cycle
        else:
            order = "single"
        bonds.append((a, b, order))

    i = 0
    while i < len(smiles):
        ch = smiles[i]
        if ch in bond_symbols:
            pending_bond = bond_symbols[ch]
            i += 1
            continue
        if ch == "(":
            stack.append(prev)
            i += 1
            continue
        if ch == ")":
            prev = stack.pop()
            i += 1
            continue
        if ch == ".":
            prev = None
            pending_bond = None
            i += 1
            continue
        if ch.isdigit() or ch == "%":
            if ch == "%":
                num = int(smiles[i + 1 : i + 3])
                i += 3
            else:
                num = int(ch)
                i += 1
            if num in ring:
                other, sym = ring.pop(num)
                add_bond(other, prev, pending_bond or sym)
            else:
                ring[num] = (prev, pending_bond)
            pending_bond = None
            continue
        if ch == "[":
            m = _BRACKET_RE.match(smiles, i)
            if not m:
                raise ValueError(f"bad bracket atom in {smiles!r} at {i}")
            sym = m.group("symbol")
            aromatic = sym in _AROMATIC
            if aromatic:
                sym = _AROMATIC[sym]
            h = m.group("hcount")
            explicit_h = 0 if h is None else (1 if h == "H" else int(h[1:]))
            atoms.append(_Atom(sym, aromatic, explicit_h=explicit_h))
            idx = len(atoms) - 1
            if prev is not None:
                add_bond(prev, idx, pending_bond)
            pending_bond = None
            prev = idx
            i = m.end()
            continue
        matched = None
        for sym in _ORGANIC:
            if smiles.startswith(sym, i):
                matched = sym
                break
        if matched is not None:
            atoms.append(_Atom(matched, aromatic=False))
        elif ch in _AROMATIC:
            atoms.append(_Atom(_AROMATIC[ch], aromatic=True))
        else:
            raise ValueError(f"unsupported SMILES token {ch!r} in {smiles!r}")
        idx = len(atoms) - 1
        if prev is not None:
            add_bond(prev, idx, pending_bond)
        pending_bond = None
        prev = idx
        i += len(matched) if matched is not None else 1
    if ring:
        raise ValueError(f"unclosed ring bond(s) {sorted(ring)} in {smiles!r}")

    # demote provisional aromatic bonds that are not on any cycle (biphenyl
    # single bond between two aromatic atoms)
    def on_cycle(bi):
        a, b, _ = bonds[bi]
        adj: Dict[int, List[int]] = {}
        for j, (u, v, _o) in enumerate(bonds):
            if j == bi:
                continue
            adj.setdefault(u, []).append(v)
            adj.setdefault(v, []).append(u)
        seen, frontier = {a}, [a]
        while frontier:
            u = frontier.pop()
            for v in adj.get(u, ()):  # reachable without this bond?
                if v == b:
                    return True
                if v not in seen:
                    seen.add(v)
                    frontier.append(v)
        return False

    bonds = [
        (a, b, ("aromatic" if on_cycle(j) else "single") if o == "aromatic?" else o)
        for j, (a, b, o) in enumerate(bonds)
    ]
    return atoms, bonds


_ORDER_VALUE = {"single": 1.0, "double": 2.0, "triple": 3.0, "aromatic": 1.5}


def _mol_from_smiles_builtin(smiles: str):
    """(symbols, aromatic, sp, sp2, sp3, bonds) with hydrogens explicit."""
    atoms, bonds = _parse_smiles(smiles)
    n_heavy = len(atoms)
    order_sum = [0.0] * n_heavy
    for a, b, o in bonds:
        order_sum[a] += _ORDER_VALUE[o]
        order_sum[b] += _ORDER_VALUE[o]

    symbols = [a.symbol for a in atoms]
    aromatic = [a.aromatic for a in atoms]
    all_bonds = list(bonds)
    for idx, atom in enumerate(atoms):
        if atom.explicit_h is not None:
            nh = atom.explicit_h
        else:
            need = math.ceil(order_sum[idx] - 1e-6)
            nh = 0
            for v in standard_valences(atom.symbol):
                if v >= need:
                    nh = v - need
                    break
        for _ in range(nh):
            symbols.append("H")
            aromatic.append(False)
            all_bonds.append((idx, len(symbols) - 1, "single"))

    n = len(symbols)
    has_triple = [False] * n
    n_double = [0] * n
    for a, b, o in all_bonds:
        if o == "triple":
            has_triple[a] = has_triple[b] = True
        if o == "double":
            n_double[a] += 1
            n_double[b] += 1
    sp = [has_triple[i] or n_double[i] >= 2 for i in range(n)]
    sp2 = [
        not sp[i] and (n_double[i] == 1 or aromatic[i]) and symbols[i] != "H"
        for i in range(n)
    ]
    sp3 = [
        symbols[i] != "H" and not sp[i] and not sp2[i] for i in range(n)
    ]
    return symbols, aromatic, sp, sp2, sp3, all_bonds


def _mol_from_smiles_rdkit(smiles: str):
    from rdkit import Chem
    from rdkit.Chem.rdchem import BondType as BT
    from rdkit.Chem.rdchem import HybridizationType

    ps = Chem.SmilesParserParams()
    ps.removeHs = False
    mol = Chem.AddHs(Chem.MolFromSmiles(smiles, ps))
    bt_names = {BT.SINGLE: "single", BT.DOUBLE: "double",
                BT.TRIPLE: "triple", BT.AROMATIC: "aromatic"}
    symbols, aromatic, sp, sp2, sp3 = [], [], [], [], []
    for atom in mol.GetAtoms():
        symbols.append(atom.GetSymbol())
        aromatic.append(atom.GetIsAromatic())
        h = atom.GetHybridization()
        sp.append(h == HybridizationType.SP)
        sp2.append(h == HybridizationType.SP2)
        sp3.append(h == HybridizationType.SP3)
    bonds = [
        (b.GetBeginAtomIdx(), b.GetEndAtomIdx(), bt_names[b.GetBondType()])
        for b in mol.GetBonds()
    ]
    return symbols, aromatic, sp, sp2, sp3, bonds


def get_node_attribute_name(types: Dict[str, int]):
    """(names, dims) of the generated node features (``smiles_utils.py:18-32``)."""
    names = ["atom" + k for k in types] + [
        "atomicnumber",
        "IsAromatic",
        "HSP",
        "HSP2",
        "HSP3",
        "Hprop",
    ]
    return names, [1] * len(names)


def generate_graphdata_from_smilestr(
    smilestr: str,
    ytarget,
    types: Dict[str, int],
    var_config: Optional[dict] = None,
) -> GraphData:
    """Build a featurized molecular graph from a SMILES string.

    ``types`` maps atom symbol -> one-hot slot (must include ``"H"`` since
    hydrogens become explicit nodes).
    """
    if _HAVE_RDKIT:
        symbols, aromatic, sp, sp2, sp3, bonds = _mol_from_smiles_rdkit(smilestr)
    else:
        symbols, aromatic, sp, sp2, sp3, bonds = _mol_from_smiles_builtin(smilestr)

    n = len(symbols)
    z = np.asarray([atomic_number(s) for s in symbols], dtype=np.int64)
    row, col, etype = [], [], []
    for a, b, o in bonds:
        row += [a, b]
        col += [b, a]
        etype += 2 * [_BOND_TYPES[o]]
    edge_index = np.asarray([row, col], dtype=np.int64)
    etype = np.asarray(etype, dtype=np.int64)
    perm = np.argsort(edge_index[0] * n + edge_index[1], kind="stable")
    edge_index = edge_index[:, perm]
    edge_attr = np.zeros((etype.shape[0], len(_BOND_TYPES)), dtype=np.float32)
    edge_attr[np.arange(etype.shape[0]), etype[perm]] = 1.0

    num_hs = np.zeros(n, dtype=np.float32)
    np.add.at(num_hs, edge_index[1], (z == 1).astype(np.float32)[edge_index[0]])

    x1 = np.zeros((n, len(types)), dtype=np.float32)
    x1[np.arange(n), [types[s] for s in symbols]] = 1.0
    x2 = np.stack(
        [
            z.astype(np.float32),
            np.asarray(aromatic, dtype=np.float32),
            np.asarray(sp, dtype=np.float32),
            np.asarray(sp2, dtype=np.float32),
            np.asarray(sp3, dtype=np.float32),
            num_hs,
        ],
        axis=1,
    )
    x = np.concatenate([x1, x2], axis=1)

    data = GraphData(
        x=x,
        pos=np.zeros((n, 3), dtype=np.float32),
        y=np.asarray(ytarget, dtype=np.float32).reshape(-1),
        edge_index=edge_index,
        edge_attr=edge_attr,
    )
    if var_config is not None:
        from hydragnn_tpu.data.serialized import extract_targets

        extract_targets(
            var_config["type"],
            var_config["output_index"],
            var_config["graph_feature_dims"],
            var_config["input_node_feature_dims"],
            data,
        )
    return data
