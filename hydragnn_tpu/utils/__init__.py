from hydragnn_tpu.utils.config import (
    get_log_name_config,
    merge_config,
    save_config,
    update_config,
)
from hydragnn_tpu.utils.print_utils import (
    iterate_tqdm,
    log,
    log0,
    print_distributed,
    print_master,
    setup_log,
)
from hydragnn_tpu.utils.timers import Timer, print_timers, reset_timers
from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank, nsplit
