"""Validated ``HYDRAGNN_*`` environment-knob parsing.

Every numeric env knob routes through here so a typo'd value fails with
an error naming the VARIABLE and the offending text, not a bare
``ValueError: invalid literal for int()`` from deep inside a loader
thread (where the traceback points at the queue machinery, not at the
shell line that caused it).
"""

import os
from typing import Optional


def env_int(
    name: str, default: int, minimum: Optional[int] = 0
) -> int:
    """Integer env knob: unset/empty -> ``default``; non-integer or
    below-``minimum`` values raise a ``ValueError`` that names the
    variable. ``minimum=None`` skips the range check."""
    raw = os.getenv(name)
    if raw is None or raw.strip() == "":
        return int(default)
    try:
        value = int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}"
        ) from None
    if minimum is not None and value < minimum:
        raise ValueError(
            f"{name} must be >= {minimum}, got {value}"
        )
    return value
