"""Validated ``HYDRAGNN_*`` environment-knob parsing.

Every numeric env knob routes through here so a typo'd value fails with
an error naming the VARIABLE and the offending text, not a bare
``ValueError: invalid literal for int()`` from deep inside a loader
thread (where the traceback points at the queue machinery, not at the
shell line that caused it).
"""

import os
from typing import Optional, Tuple


def env_int(
    name: str, default: int, minimum: Optional[int] = 0
) -> int:
    """Integer env knob: unset/empty -> ``default``; non-integer or
    below-``minimum`` values raise a ``ValueError`` that names the
    variable. ``minimum=None`` skips the range check."""
    raw = os.getenv(name)
    if raw is None or raw.strip() == "":
        return int(default)
    try:
        value = int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}"
        ) from None
    if minimum is not None and value < minimum:
        raise ValueError(
            f"{name} must be >= {minimum}, got {value}"
        )
    return value


def env_float(
    name: str, default: float, minimum: Optional[float] = 0.0
) -> float:
    """Float env knob: unset/empty -> ``default``; non-numeric or
    below-``minimum`` values raise a ``ValueError`` that names the
    variable. ``minimum=None`` skips the range check."""
    raw = os.getenv(name)
    if raw is None or raw.strip() == "":
        return float(default)
    try:
        value = float(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {raw!r}"
        ) from None
    if value != value:  # NaN: comparisons below would silently pass
        raise ValueError(f"{name} must be a number, got {raw!r}")
    if minimum is not None and value < minimum:
        raise ValueError(
            f"{name} must be >= {minimum}, got {value}"
        )
    return value


def env_mesh(
    name: str = "HYDRAGNN_MESH",
) -> Optional[Tuple[Optional[int], int]]:
    """Mesh-shape env knob: ``"d,m"`` -> ``(d, m)``, a bare model width
    ``"m"`` -> ``(None, m)``, unset/empty -> None. Malformed values
    (``"4x2"``, three fields, non-integers, non-positive sizes) raise a
    ``ValueError`` naming the variable — not a bare ``int()`` traceback
    from inside ``resolve_mesh``."""
    raw = os.getenv(name)
    if raw is None or raw.strip() == "":
        return None
    parts = [p.strip() for p in raw.split(",")]
    try:
        if len(parts) == 1:
            pair: Tuple[Optional[int], int] = (None, int(parts[0]))
        elif len(parts) == 2:
            pair = (int(parts[0]), int(parts[1]))
        else:
            raise ValueError
        if any(v is not None and v < 1 for v in pair):
            raise ValueError
    except ValueError:
        raise ValueError(
            f'{name}={raw!r} is not "data,model" or a bare model width '
            '(expected e.g. "4,2" or "2", positive integers)'
        ) from None
    return pair
