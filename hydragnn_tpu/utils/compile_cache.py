"""Persistent XLA compilation cache.

First compilation of the fused training programs costs tens of seconds on
TPU (the whole-training ``fit_staged`` program most of all). JAX can
persist compiled executables across processes; enabling it makes every run
after the first start hot. No reference counterpart (torch eager has no
compile step).

``HYDRAGNN_COMPILE_CACHE`` controls it: unset/``1`` -> on (default dir
``~/.cache/hydragnn_tpu/xla``), ``0`` -> off, any other value -> used as
the cache directory.
"""

import os

_enabled = False


def enable_compile_cache():
    """Idempotent; call before the first jit compilation for best effect."""
    global _enabled
    if _enabled:
        return
    knob = os.getenv("HYDRAGNN_COMPILE_CACHE", "1")
    if knob == "0":
        return
    cache_dir = (
        knob
        if knob not in ("", "1")
        else os.path.join(
            os.path.expanduser("~"), ".cache", "hydragnn_tpu", "xla"
        )
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # persist SUB-second programs on accelerator backends: on the
        # tunneled axon chip every tiny compile costs ~0.6-0.9 s and a
        # training startup runs ~40 of them (put_batch layouts, metric
        # readbacks) — none clear the default 1.0 s floor, so ~25 s of
        # epoch-0 recompiles recurred per process (BASELINE.md round 5).
        # CPU keeps a small floor: millisecond compiles gain nothing and
        # the cache has no eviction, so persisting them is pure disk
        # growth. HYDRAGNN_COMPILE_CACHE_MIN_SECS overrides either way.
        # The platform is read from config/env ONLY — jax.default_backend()
        # would initialize the XLA backend here, and this runs before
        # jax.distributed.initialize() in the multi-host driver path.
        env_floor = os.getenv("HYDRAGNN_COMPILE_CACHE_MIN_SECS")
        if env_floor is not None:
            try:
                floor = float(env_floor)
            except ValueError:
                print(
                    "HYDRAGNN_COMPILE_CACHE_MIN_SECS="
                    f"{env_floor!r} is not a number; ignoring"
                )
                env_floor = None
        if env_floor is None:
            platforms = (
                jax.config.jax_platforms or os.getenv("JAX_PLATFORMS") or ""
            )
            floor = 0.1 if platforms.split(",")[0] == "cpu" else 0.0
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", floor
        )
        _enabled = True
    except Exception:
        # cache is an optimization only — never fail a run over it
        pass
