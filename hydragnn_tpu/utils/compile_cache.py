"""Persistent XLA compilation cache.

First compilation of the fused training programs costs tens of seconds on
TPU (the whole-training ``fit_staged`` program most of all). JAX can
persist compiled executables across processes; enabling it makes every run
after the first start hot. No reference counterpart (torch eager has no
compile step).

``HYDRAGNN_COMPILE_CACHE`` controls it: unset/``1`` -> on (default dir
``~/.cache/hydragnn_tpu/xla``), ``0`` -> off, any other value -> used as
the cache directory.
"""

import os

_enabled = False


def enable_compile_cache():
    """Idempotent; call before the first jit compilation for best effect."""
    global _enabled
    if _enabled:
        return
    knob = os.getenv("HYDRAGNN_COMPILE_CACHE", "1")
    if knob == "0":
        return
    cache_dir = (
        knob
        if knob not in ("", "1")
        else os.path.join(
            os.path.expanduser("~"), ".cache", "hydragnn_tpu", "xla"
        )
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:
        # cache is an optimization only — never fail a run over it
        pass
