"""run_training facade (reference: ``hydragnn/run_training.py:49-182``).

Accepts a config dict or a path to a JSON config file; orchestrates
distributed setup -> data loading/splitting -> config derivation -> model ->
optimizer -> train/validate/test -> checkpoint save.
"""

import json


def run_training(config, use_devices=None):
    # same contract as run_prediction: the argument was accepted and
    # silently ignored since the facade was ported — fail loudly instead
    if use_devices is not None:
        raise TypeError(
            "run_training(use_devices=...) is deprecated and was never "
            "honored; remove the argument and control device placement "
            "via JAX_PLATFORMS (or jax.distributed for multi-host runs)"
        )
    if isinstance(config, str):
        with open(config, "r") as f:
            config = json.load(f)
    from hydragnn_tpu.train.driver import run_training_impl

    return run_training_impl(config)
