"""run_training facade (reference: ``hydragnn/run_training.py:49-182``).

Accepts a config dict or a path to a JSON config file; orchestrates
distributed setup -> data loading/splitting -> config derivation -> model ->
optimizer -> train/validate/test -> checkpoint save.

Every driver run records unified telemetry (docs/observability.md): a
structured ``events.jsonl`` stream and per-epoch scalars under
``./logs/<run>/``, and — when ``telemetry_port`` /
``config["Telemetry"]["port"]`` / ``HYDRAGNN_OBS_PORT`` opts in — a live
``/metrics`` + ``/healthz`` endpoint for the duration of the run.
``HYDRAGNN_TELEMETRY=0`` disables the event stream, metrics, and endpoint
(the plain-file scalar backend stays on — every run keeps its loss
curves).
"""

import json


def run_training(config, use_devices=None, telemetry_port=None):
    # same contract as run_prediction: the argument was accepted and
    # silently ignored since the facade was ported — fail loudly instead
    if use_devices is not None:
        raise TypeError(
            "run_training(use_devices=...) is deprecated and was never "
            "honored; remove the argument and control device placement "
            "via JAX_PLATFORMS (or jax.distributed for multi-host runs)"
        )
    if isinstance(config, str):
        with open(config, "r") as f:
            config = json.load(f)
    if telemetry_port is not None:
        # programmatic opt-in to the live training endpoint (0 = ephemeral
        # port); equivalent to config["Telemetry"]["port"], and still
        # overridable by HYDRAGNN_OBS_PORT (env beats config, the framework
        # convention)
        config = dict(config)
        config["Telemetry"] = dict(config.get("Telemetry", {}) or {})
        config["Telemetry"]["port"] = int(telemetry_port)
    from hydragnn_tpu.train.driver import run_training_impl

    return run_training_impl(config)
