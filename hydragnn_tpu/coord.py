"""Shared-directory coordination: leases, tombstones, watchdogs.

The liveness protocol PR 8 built for elastic training
(``train/elastic.py``) generalized into the module BOTH supervision
planes consume — elastic multi-host training AND the self-healing
serving fleet (``serve/fleet.py``). The primitives are deliberately
boring: every member of a group writes an atomic JSON **heartbeat
lease** from a background thread; anyone can read everyone's lease age;
a member whose lease is stale past the timeout (or that has been
explicitly **tombstoned**) is dead; a background :class:`PeerWatchdog`
turns that read into a callback off the owner's main thread, so a
wedged main thread (a collective hung on a dead peer, a batcher stuck
in a dispatch) still gets its peers declared lost.

Nothing here knows about training or serving: the elastic agent layers
generation files and re-bootstrap on top, the serving fleet layers
respawn and hot-swap. ``train.elastic`` re-exports every name so
existing imports keep working.

File layout under one coordination directory (``kind`` picks the lease
family, ``prefix`` the member naming — elastic uses ``worker``/``agent``
leases named ``host-<k>``, the serving fleet ``replica`` leases named
``replica-<k>``)::

    <dir>/<kind>s/<prefix>-<k>.json    heartbeat leases
    <dir>/dead/<prefix>-<k>.json       tombstones (first write wins)
"""

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

DEFAULT_HEARTBEAT_S = 1.0
DEFAULT_LEASE_S = 6.0


# ---- atomic JSON files -----------------------------------------------------


def write_json(path: str, obj: Dict):
    """Atomic JSON write (tmp + rename): a reader never sees a torn file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def read_json(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # mid-rename/missing — the caller polls again


# ---- lease / tombstone paths ----------------------------------------------


def hb_path(coord_dir: str, kind: str, member: int,
            prefix: str = "host") -> str:
    return os.path.join(coord_dir, f"{kind}s", f"{prefix}-{int(member)}.json")


def tomb_path(coord_dir: str, member: int, prefix: str = "host") -> str:
    return os.path.join(coord_dir, "dead", f"{prefix}-{int(member)}.json")


def write_tombstone(coord_dir: str, member: int, reason: str, by: int,
                    prefix: str = "host", **extra):
    """Idempotent: the FIRST detection timestamp is the one recoveries are
    measured from, so an existing tombstone is never overwritten."""
    path = tomb_path(coord_dir, member, prefix=prefix)
    if os.path.exists(path):
        return
    rec = {"ts": time.time(), "reason": reason, "by": int(by)}
    rec[prefix] = int(member)
    rec.update(extra)
    write_json(path, rec)


def read_tombstone(coord_dir: str, member: int,
                   prefix: str = "host") -> Optional[Dict]:
    return read_json(tomb_path(coord_dir, member, prefix=prefix))


def clear_tombstone(coord_dir: str, member: int, prefix: str = "host"):
    """Remove a member's tombstone — the respawn path: a supervisor that
    healed the loss must lift the death sentence before the replacement
    starts, or the replacement reads itself as already-evicted."""
    try:
        os.remove(tomb_path(coord_dir, member, prefix=prefix))
    except OSError:
        pass


def heartbeat_age(coord_dir: str, kind: str, member: int,
                  now: Optional[float] = None,
                  prefix: str = "host") -> Optional[float]:
    """Seconds since ``member`` last heartbeat as ``kind``; None = never."""
    hb = read_json(hb_path(coord_dir, kind, member, prefix=prefix))
    if hb is None or "ts" not in hb:
        return None
    return (now if now is not None else time.time()) - float(hb["ts"])


def dead_members(
    coord_dir: str,
    members: List[int],
    lease_s: float,
    kind: str = "agent",
    now: Optional[float] = None,
    current_gen: Optional[int] = None,
    prefix: str = "host",
) -> Dict[int, float]:
    """``{member: detect_ts}`` for every member that is tombstoned or whose
    ``kind`` heartbeat lease expired. A member that never heartbeat at all
    is NOT dead — it may still be bootstrapping; the lease only starts
    ticking once a first heartbeat exists. With ``current_gen``, a lease
    from an EARLIER generation (or incarnation) is treated the same way:
    leases persist at one path across respawns, so a respawned member
    that has not yet written its first new-gen lease must read as
    bootstrapping, not as stale (its old lease is necessarily older than
    the downtime)."""
    now = time.time() if now is None else now
    dead: Dict[int, float] = {}
    for m in members:
        tomb = read_tombstone(coord_dir, m, prefix=prefix)
        if tomb is not None:
            dead[m] = float(tomb.get("ts", now))
            continue
        hb = read_json(hb_path(coord_dir, kind, m, prefix=prefix))
        if hb is None or "ts" not in hb:
            continue  # never heartbeat: still bootstrapping, not dead
        if (
            current_gen is not None
            and int(hb.get("gen", current_gen)) < current_gen
        ):
            continue  # pre-respawn lease: the new member is booting
        if hb.get("done"):
            # a CLEANLY finished member stops heartbeating forever — end
            # of run, not a death. Without this, a finished peer's stale
            # lease would read as a loss and kill survivors' tails.
            continue
        if now - float(hb["ts"]) > lease_s:
            dead[m] = now
    return dead


# ---- heartbeat + watchdog threads -----------------------------------------


class Heartbeat:
    """Background lease writer: one atomic JSON write per interval.

    The thread is daemon (a crashed owner must not hang interpreter
    exit) with an explicit lifecycle: :meth:`stop` joins it bounded."""

    def __init__(self, path: str, payload: Callable[[], Dict],
                 interval_s: float):
        self.path = path
        self._payload = payload
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="hydragnn-heartbeat", daemon=True
        )

    def start(self) -> "Heartbeat":
        self._write()  # the lease exists before start() returns
        self._thread.start()
        return self

    def _write(self):
        try:
            rec = dict(self._payload())
            rec["ts"] = time.time()
            rec["pid"] = os.getpid()
            write_json(self.path, rec)
        except OSError:
            pass  # a full/flaky shared FS must not kill the run

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self._write()

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=max(self.interval_s * 4, 5.0))
        # final flush: the file must end on the TRUE last progress (a run
        # whose tail beat the next tick would otherwise read one interval
        # stale forever — e.g. an HPO trial's final step count)
        self._write()


class PeerWatchdog:
    """Declares peers lost when their lease expires.

    Runs off the owner's main thread so a wedged main thread (a
    collective hung on a dead peer; a dispatch stuck on a wedged
    accelerator) still gets losses detected. ``on_loss`` receives
    ``{member: detect_ts}`` once and the watchdog returns; ``on_evicted``
    fires when THIS member finds its own tombstone — a partitioned
    straggler must evict itself rather than split-brain the group. The
    default callbacks do nothing but record; supervision planes
    (``train/elastic.py``, ``serve/fleet.py``) install the teeth."""

    def __init__(
        self,
        coord_dir: str,
        host: int,
        members: List[int],
        lease_s: float,
        interval_s: float,
        on_loss: Optional[Callable[[Dict[int, float]], None]] = None,
        on_evicted: Optional[Callable[[], None]] = None,
        gen: int = 0,
        kind: str = "worker",
        prefix: str = "host",
    ):
        self.coord_dir = coord_dir
        self.host = int(host)
        self.peers = [int(m) for m in members if int(m) != int(host)]
        self.lease_s = float(lease_s)
        self.interval_s = float(interval_s)
        self.gen = int(gen)
        self.kind = kind
        self.prefix = prefix
        self.last_loss: Optional[Dict[int, float]] = None
        self.evicted = False
        self._on_loss = on_loss or self._default_on_loss
        self._on_evicted = on_evicted or self._default_on_evicted
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="hydragnn-peer-watchdog", daemon=True
        )

    def start(self) -> "PeerWatchdog":
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if read_tombstone(
                self.coord_dir, self.host, prefix=self.prefix
            ) is not None:
                self.evicted = True
                self._on_evicted()
                return
            dead = dead_members(
                self.coord_dir, self.peers, self.lease_s, kind=self.kind,
                current_gen=self.gen, prefix=self.prefix,
            )
            if dead:
                self.last_loss = dead
                self._on_loss(dead)
                return

    def _default_on_loss(self, dead: Dict[int, float]):
        pass  # recorded in last_loss; the owner polls

    def _default_on_evicted(self):
        pass  # recorded in evicted; the owner polls

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=max(self.interval_s * 4, 5.0))
