"""``python -m hydragnn_tpu.analysis`` — the jaxlint CLI.

Exit status: 0 when every finding is baselined or suppressed, 1 when new
findings exist, 2 on usage/configuration errors. The CI gate runs::

    python -m hydragnn_tpu.analysis --format=github \
        --baseline .jaxlint-baseline.json --stats
"""

import argparse
import os
import sys

from hydragnn_tpu.analysis import baseline as baseline_mod
from hydragnn_tpu.analysis.core import (
    all_rules,
    all_suites,
    analyze_paths,
    rules_in_suite,
)
from hydragnn_tpu.analysis.report import (
    render_github,
    render_json,
    render_stats,
    render_text,
)

DEFAULT_PATHS = ("hydragnn_tpu", "examples", "benchmarks")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.analysis",
        description=(
            "jaxlint/threadlint/shardlint/numlint: JAX/TPU, "
            "concurrency, sharding and numerics static analysis "
            "(docs/static-analysis.md)"
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help=f"files/dirs to analyze (default: {' '.join(DEFAULT_PATHS)})",
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "github"),
        default="text",
        help="output format (github = Actions annotations)",
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        help="JSON baseline of pre-existing findings that do not fail "
        "the gate",
    )
    p.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="write the current findings as the new baseline and exit 0",
    )
    p.add_argument(
        "--stats",
        action="store_true",
        help="print per-rule counts (the ratchet numbers)",
    )
    p.add_argument(
        "--suite",
        metavar="SUITE",
        help="run only one rule suite: 'jax' (the jaxlint gate), "
        "'concurrency' (the threadlint gate), 'sharding' (the "
        "shardlint gate) or 'numerics' (the numlint gate); default: "
        "every suite",
    )
    p.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule names to run (default: all)",
    )
    p.add_argument(
        "--ignore",
        metavar="RULES",
        help="comma-separated rule names to skip",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    return p


# the gate each suite is known by in CI/docs — the --list-rules headers
SUITE_GATES = {
    "jax": "jaxlint",
    "concurrency": "threadlint",
    "sharding": "shardlint",
    "numerics": "numlint",
}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # an unknown --suite is a usage error EVERYWHERE, --list-rules
    # included (listing every rule for a suite that does not exist would
    # be a silently-wrong answer)
    if args.suite is not None and args.suite not in all_suites():
        print(
            f"jaxlint: unknown suite {args.suite!r} "
            f"(have: {', '.join(sorted(all_suites()))})",
            file=sys.stderr,
        )
        return 2

    if args.list_rules:
        # the per-suite catalog: four suites are too many to keep in
        # one flat list (or only in docs) — one block per suite, each
        # rule with its one-line doc
        for suite in sorted(all_suites()):
            if args.suite is not None and suite != args.suite:
                continue
            gate = SUITE_GATES.get(suite, suite)
            print(f"suite {suite} ({gate} gate, --suite={suite}):")
            for name, rule in sorted(all_rules().items()):
                if rule.suite != suite:
                    continue
                print(f"  {name}: {rule.description}")
        return 0

    paths = args.paths or [p for p in DEFAULT_PATHS if os.path.exists(p)]
    if not paths:
        print(
            "jaxlint: no paths given and none of the default paths "
            f"({', '.join(DEFAULT_PATHS)}) exist here",
            file=sys.stderr,
        )
        return 2

    select = (
        {r.strip() for r in args.select.split(",")} if args.select else None
    )
    ignore = (
        {r.strip() for r in args.ignore.split(",")} if args.ignore else None
    )
    known = set(all_rules())
    for given in (select or set()) | (ignore or set()):
        if given not in known:
            print(f"jaxlint: unknown rule {given!r}", file=sys.stderr)
            return 2
    if args.suite is not None:
        suite_rules = rules_in_suite(args.suite)
        select = suite_rules if select is None else (select & suite_rules)
    # contradictory flags must not masquerade as a clean run: a
    # --suite/--select/--ignore combination that leaves zero rules to
    # execute would report 0 findings and exit 0 — a green gate that
    # checked nothing
    effective = (select if select is not None else known) - (ignore or set())
    if not effective:
        print(
            "jaxlint: --suite/--select/--ignore leave no rule to run",
            file=sys.stderr,
        )
        return 2

    result = analyze_paths(paths, select=select, ignore=ignore)

    if args.write_baseline:
        baseline_mod.save_baseline(args.write_baseline, result.findings)
        print(
            f"jaxlint: wrote {len(result.findings)} finding(s) to "
            f"{args.write_baseline}"
        )
        return 0

    baselined = []
    new = result.findings
    if args.baseline:
        try:
            bl = baseline_mod.load_baseline(args.baseline)
        except FileNotFoundError:
            print(
                f"jaxlint: baseline {args.baseline} not found (treating "
                "as empty)",
                file=sys.stderr,
            )
            bl = baseline_mod.Counter()
        except ValueError as e:
            print(f"jaxlint: {e}", file=sys.stderr)
            return 2
        new, baselined, stale = baseline_mod.apply_baseline(
            result.findings, bl
        )
        if stale:
            print(
                f"jaxlint: {stale} baseline entr(ies) no longer match "
                "anything — prune them (the ratchet only tightens)",
                file=sys.stderr,
            )

    renderer = {
        "text": render_text,
        "json": render_json,
        "github": render_github,
    }[args.format]
    print(renderer(new, baselined, result))
    if args.stats:
        print(render_stats(new, baselined, result, rules=select))

    if result.parse_errors:
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
