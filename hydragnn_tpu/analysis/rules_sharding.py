"""Sharding-correctness rules (shardlint, ``--suite=sharding``).

PR 10 made the repo genuinely 2-D parallel: params column-split over
``model`` per the rule engine, batches over ``data``, eight jit programs
in ``train/steps.py`` declaring explicit in/out shardings. Nothing
*static* guarded that layer — a hardcoded axis string, a jit program
added without shardings, or a stray ``device_put`` all pass tier-1 on CPU
and surface only as an MFU regression on real hardware. These rules are
the lint half of shardlint; the compiled-HLO ratchet
(``analysis/hlo.py``) is the post-compile half.

The axis-name vocabulary is ``parallel/mesh.py``'s
``DATA_AXIS``/``MODEL_AXIS``/``GRAPH_AXIS`` (imported lazily with a
literal fallback, so the AST pass never depends on the analyzed package
importing cleanly).
"""

import ast
from typing import Iterable, List, Optional, Set

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    matches_any,
    register,
)


def _known_axes() -> frozenset:
    try:
        from hydragnn_tpu.parallel.mesh import KNOWN_AXES

        return frozenset(KNOWN_AXES)
    except Exception:
        return frozenset({"data", "model", "graph"})


_PARALLEL_PATTERNS = (
    "hydragnn_tpu/parallel/*",
    "parallel/*",
    "*/parallel/*",
)
# device-dispatching code that must declare its sharding contract
_CONTRACT_PATTERNS = (
    "hydragnn_tpu/train/*",
    "hydragnn_tpu/serve/*",
    "train/*",
    "serve/*",
    "*/train/*",
    "*/serve/*",
)

# calls whose string arguments ARE mesh-axis names
_SPEC_CALLEES = {"P", "PartitionSpec"}
_MESH_CALLEES = {"Mesh"}
_COLLECTIVE_TAILS = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "axis_index",
    "ppermute",
}


def _axis_call_kind(node: ast.Call) -> Optional[str]:
    """'spec' | 'mesh' | 'collective' when the call's string args name
    mesh axes; None otherwise."""
    callee = dotted_name(node.func)
    if not callee:
        return None
    tail = callee.rsplit(".", 1)[-1]
    if tail in _SPEC_CALLEES or callee.endswith(".PartitionSpec"):
        return "spec"
    if tail in _MESH_CALLEES and (
        callee == "Mesh" or callee.endswith(".Mesh")
    ):
        return "mesh"
    if tail in _COLLECTIVE_TAILS and (
        callee == tail or ".lax." in callee or callee.startswith("lax.")
    ):
        return "collective"
    return None


def _string_args(node: ast.Call):
    """Every string constant inside the call's argument expressions
    (walks nested tuples/lists, so ``P(None, ('data',))`` is covered)."""
    for arg in [*node.args, *[k.value for k in node.keywords]]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                yield sub


@register
class HardcodedMeshAxis(Rule):
    name = "hardcoded-mesh-axis"
    suite = "sharding"
    description = (
        "Mesh-axis string literal ('data'/'model'/'graph') in a "
        "PartitionSpec/Mesh/collective call outside parallel/ — route "
        "through parallel.mesh DATA_AXIS/MODEL_AXIS/GRAPH_AXIS so a "
        "renamed axis is a NameError, not a silent replication"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return not matches_any(module.rel_path, _PARALLEL_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        axes = _known_axes()
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _axis_call_kind(node)
            if kind is None:
                continue
            for const in _string_args(node):
                if const.value in axes:
                    findings.append(
                        module.finding(
                            self.name,
                            const,
                            f"axis name {const.value!r} hardcoded in a "
                            f"{kind} call — import the named constant "
                            "from hydragnn_tpu.parallel (DATA_AXIS/"
                            "MODEL_AXIS/GRAPH_AXIS); only parallel/ "
                            "spells the strings",
                        )
                    )
        return findings


@register
class UnknownSpecAxis(Rule):
    name = "unknown-spec-axis"
    suite = "sharding"
    description = (
        "PartitionSpec/collective axis literal that is not a 2-D mesh "
        "axis ('data'/'model'/'graph') — a typo'd axis name fails only "
        "at trace time on a mesh that HAS the axis, and silently "
        "replicates everywhere else"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        axes = _known_axes()
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if _axis_call_kind(node) not in ("spec", "collective"):
                continue
            for const in _string_args(node):
                if const.value not in axes:
                    findings.append(
                        module.finding(
                            self.name,
                            const,
                            f"axis name {const.value!r} is not one of "
                            f"the mesh axes {tuple(sorted(axes))} — "
                            "typo, or a new axis missing from "
                            "parallel.mesh.KNOWN_AXES",
                        )
                    )
        return findings


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_DISPATCH_SUBSTRINGS = (
    "train",
    "fit",
    "update",
    "eval",
    "predict",
    "infer",
    "apply",
    "scan",
    "epoch",
)
_DISPATCH_EXACT = {"step"}


def _wrapped_name(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    first = call.args[0]
    if isinstance(first, ast.Name):
        return first.id
    if isinstance(first, ast.Attribute):
        return first.attr
    return None


def _looks_dispatching(name: str) -> bool:
    low = name.lower()
    return any(s in low for s in _DISPATCH_SUBSTRINGS) or (
        low.lstrip("_") in _DISPATCH_EXACT
    )


def _decorator_jit_keywords(dec: ast.AST):
    """``(is_jit, keywords)`` for the decorator spellings: bare
    ``@jax.jit``, configured ``@jax.jit(...)``, and
    ``@partial(jax.jit, ...)``."""
    if dotted_name(dec) in _JIT_NAMES:
        return True, []
    if isinstance(dec, ast.Call):
        callee = dotted_name(dec.func)
        if callee in _JIT_NAMES:
            return True, dec.keywords
        if (
            callee in ("partial", "functools.partial")
            and dec.args
            and dotted_name(dec.args[0]) in _JIT_NAMES
        ):
            return True, dec.keywords
    return False, []


def _declares_contract(keywords) -> bool:
    kw_names = {kw.arg for kw in keywords}
    return bool(kw_names & {"in_shardings", "out_shardings"}) or (
        None in kw_names  # a **plan splat carries the contract
    )


@register
class JitMissingShardings(Rule):
    name = "jit-missing-shardings"
    suite = "sharding"
    description = (
        "Device-dispatching jax.jit in train//serve/ without explicit "
        "in_shardings/out_shardings — on the 2-D mesh the program "
        "inherits whatever placement its inputs carry; declare the "
        "contract (steps.py _sharding_plan) or use "
        "parallel.mesh.jit_replicated"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, _CONTRACT_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            # call form: jax.jit(fn, ...)
            if (
                isinstance(node, ast.Call)
                and dotted_name(node.func) in _JIT_NAMES
            ):
                fn_name = _wrapped_name(node)
                # lambdas/utility copies inherit deliberately
                if fn_name is not None and _looks_dispatching(fn_name):
                    if not _declares_contract(node.keywords):
                        findings.append(
                            module.finding(
                                self.name,
                                node,
                                f"jax.jit({fn_name}) dispatches to "
                                "devices but declares no in_shardings/"
                                "out_shardings — on a 2-D mesh its "
                                "placement is whatever the inputs "
                                "happened to carry; declare the "
                                "contract or route through "
                                "parallel.mesh.jit_replicated",
                            )
                        )
                continue
            # decorator forms: @jax.jit / @jax.jit(...) /
            # @partial(jax.jit, ...) on a dispatching-named def
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) or not _looks_dispatching(node.name):
                continue
            for dec in node.decorator_list:
                is_jit, keywords = _decorator_jit_keywords(dec)
                if is_jit and not _declares_contract(keywords):
                    findings.append(
                        module.finding(
                            self.name,
                            dec,
                            f"@jit on `{node.name}` dispatches to "
                            "devices but declares no in_shardings/"
                            "out_shardings — declare the contract or "
                            "route through parallel.mesh.jit_replicated",
                        )
                    )
        return findings


@register
class DevicePutWithoutSharding(Rule):
    name = "device-put-without-sharding"
    suite = "sharding"
    description = (
        "jax.device_put of a non-scalar without an explicit sharding — "
        "the array lands fully on the default device; pass a "
        "NamedSharding (or use rules.put_tree / Trainer.place_state)"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in (
                "jax.device_put",
                "device_put",
            ):
                continue
            if len(node.args) >= 2 or {
                kw.arg for kw in node.keywords
            } & {"device", "sharding", None}:
                continue
            if node.args and isinstance(node.args[0], ast.Constant):
                continue  # a literal scalar has no layout to get wrong
            findings.append(
                module.finding(
                    self.name,
                    node,
                    "device_put without a sharding places the full "
                    "array on ONE device — every sharded consumer then "
                    "pays a reshard; pass NamedSharding(mesh, spec) "
                    "(parallel/rules.put_tree for pytrees)",
                )
            )
        return findings


@register
class LegacyPmapUsage(Rule):
    name = "legacy-pmap-usage"
    suite = "sharding"
    description = (
        "jax.pmap — the pre-mesh SPMD API; it fights the 2-D mesh "
        "(separate device axes, no NamedSharding interop). Use jit with "
        "shardings on the ('data', 'model') mesh instead"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[int] = set()

        def flag(node, how: str):
            line = getattr(node, "lineno", 0)
            if line in seen:
                return
            seen.add(line)
            findings.append(
                module.finding(
                    self.name,
                    node,
                    f"jax.pmap {how} — replicated-params pmap cannot "
                    "compose with the mesh's NamedSharding placement; "
                    "express this as jax.jit with in/out shardings "
                    "(train/steps.py) or shard_map",
                )
            )

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and dotted_name(node.func) in (
                "jax.pmap",
                "pmap",
            ):
                flag(node, "call")
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                for dec in node.decorator_list:
                    if dotted_name(dec) in ("jax.pmap", "pmap") or (
                        isinstance(dec, ast.Call)
                        and dotted_name(dec.func) in ("jax.pmap", "pmap")
                    ):
                        flag(dec, "decorator")
        return findings


def _reshape_leading_dim(node: ast.Call) -> Optional[ast.AST]:
    """The expression for the FIRST target dim of a reshape call, or
    None when there is none (``x.reshape(dims)`` / ``jnp.reshape(x,
    shape)`` / splatted shapes)."""
    callee = dotted_name(node.func)
    if isinstance(node.func, ast.Attribute) and node.func.attr == "reshape":
        if callee in ("jnp.reshape", "jax.numpy.reshape", "np.reshape"):
            shape = node.args[1] if len(node.args) >= 2 else None
        else:
            shape = node.args[0] if node.args else None
    else:
        return None
    if isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
        return shape.elts[0]
    return shape


def _is_minus_one(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Constant) and node.value == -1:
        return True
    return (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.USub)
        and isinstance(node.operand, ast.Constant)
        and node.operand.value == 1
    )


@register
class ReshapeAcrossShardedDim(Rule):
    name = "reshape-across-sharded-dim"
    suite = "sharding"
    description = (
        "reshape(-1, ...) inside a function that pins shardings "
        "(with_sharding_constraint) — collapsing the leading dim merges "
        "the sharded axis into the rest and XLA inserts a full "
        "all-gather to honor it"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[tuple] = set()  # a nested fn is walked by its outer too
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            constrains = any(
                isinstance(sub, ast.Call)
                and dotted_name(sub.func).endswith(
                    "with_sharding_constraint"
                )
                for sub in ast.walk(fn)
            )
            if not constrains:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) and _is_minus_one(
                    _reshape_leading_dim(sub)
                ):
                    key = (sub.lineno, sub.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(
                        module.finding(
                            self.name,
                            sub,
                            "reshape with a leading -1 in a sharded "
                            "program body collapses the sharded leading "
                            "axis — XLA materializes a full all-gather; "
                            "keep the leading dim (reshape trailing "
                            "dims) or reshape shard-locally inside "
                            "shard_map",
                        )
                    )
        return findings
