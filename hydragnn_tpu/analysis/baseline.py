"""Baseline handling: the ratchet.

A committed ``.jaxlint-baseline.json`` lists findings that predate the
gate; CI fails only on findings NOT in the baseline, so the count can
only go down. Fingerprints are (path, rule, stripped source line) — no
line numbers, so edits elsewhere in a file don't rot the baseline.

Each baseline entry is matched at most ``count`` times; fixing one of two
identical lines still surfaces nothing until someone reintroduces a
third.
"""

import json
from collections import Counter
from typing import Iterable, List, Tuple

from hydragnn_tpu.analysis.core import Finding

BASELINE_VERSION = 1


def save_baseline(path: str, findings: Iterable[Finding]):
    counts = Counter(f.fingerprint for f in findings)
    payload = {
        "version": BASELINE_VERSION,
        "findings": [
            {"path": p, "rule": r, "snippet": s, "count": c}
            for (p, r, s), c in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> Counter:
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    version = payload.get("version")
    if version != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {version!r}; this analyzer "
            f"writes version {BASELINE_VERSION} — regenerate with "
            "--write-baseline"
        )
    counts: Counter = Counter()
    for entry in payload.get("findings", []):
        key = (entry["path"], entry["rule"], entry["snippet"])
        counts[key] += int(entry.get("count", 1))
    return counts


def apply_baseline(
    findings: List[Finding], baseline: Counter
) -> Tuple[List[Finding], List[Finding], int]:
    """Split into (new, baselined) and count stale baseline entries
    (entries that no longer match anything — candidates for deletion,
    reported so the baseline shrinks instead of fossilizing)."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        if remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = sum(c for c in remaining.values() if c > 0)
    return new, baselined, stale
