"""numlint's compiled-memory ratchet — the HBM twin of the HLO ratchet.

The numerics AST rules (``rules_numerics.py``) prove a kernel's
precision contract is *written*; nothing static can prove what a change
costs in device memory. A superblock fusion that materializes one extra
``[N, K, D]`` temp, a dense path that stops aliasing its donated input,
or an accidental f64 promotion all land as HBM growth that tier-1 on a
tiny CPU config never notices — until a real-shape run OOMs. So this
module fingerprints each canonical ``train/steps.py`` program's
``Compiled.memory_analysis()`` — peak / temp / output / argument bytes
(``obs/introspect.normalize_memory_analysis`` semantics, peak =
arg + out + temp + generated code − aliased) — into a committed
``.numlint-mem.json`` budget.

CI re-compiles the programs on the same forced-CPU canonical harness the
HLO ratchet uses (``analysis/hlo.compile_step_programs`` compiles ONCE
and hands the executables over) and fails with the program, the field
and the byte counts named when peak/temp/output bytes grow past
tolerance. ``--prove-injection`` doctors one program's fingerprint with
a synthetic HBM blow-up and asserts the gate catches it.

Tolerance resolves ``HYDRAGNN_NUMLINT_MEM_TOLERANCE`` through
``utils/envparse`` (a typo'd value names the variable, not a bare
``float()`` traceback).

CLI::

    python -m hydragnn_tpu.analysis.mem --check .numlint-mem.json
    python -m hydragnn_tpu.analysis.mem --write .numlint-mem.json
    python -m hydragnn_tpu.analysis.mem --check ... --prove-injection

Exit status: 0 clean, 1 budget violations (or a failed injection proof),
2 usage errors. Byte counts are backend-specific, so the budget records
the mesh AND is only comparable against the same canonical CPU harness
that wrote it — the point is the diff, not the absolute number.
"""

import argparse
import json
import os
import sys
from typing import Dict, List, Sequence, Tuple

from hydragnn_tpu.utils.envparse import env_float

BUDGET_VERSION = 1
DEFAULT_BUDGET = ".numlint-mem.json"
# the fields the gate fails on; the rest ride along informationally
GATED_FIELDS = ("peak_bytes", "temp_bytes", "output_bytes")


def default_tolerance() -> float:
    """Growth tolerance: ``HYDRAGNN_NUMLINT_MEM_TOLERANCE`` (validated,
    error names the variable) or 0.25 — generous enough for compiler
    noise across jaxlib point releases, tight enough that a doubled
    temp buffer cannot hide."""
    return env_float("HYDRAGNN_NUMLINT_MEM_TOLERANCE", 0.25)


def fingerprint_memory(compiled) -> Dict[str, int]:
    """One executable's budgetable memory fingerprint (ints, so the
    JSON diff reads as bytes)."""
    from hydragnn_tpu.obs.introspect import normalize_memory_analysis

    mem = normalize_memory_analysis(compiled.memory_analysis())
    if not mem:
        raise RuntimeError(
            "memory_analysis() reported nothing on this backend — the "
            "memory budget needs the canonical CPU harness"
        )
    fp = {k: int(v) for k, v in sorted(mem.items())}
    # XLA's donation/alias accounting is not stable across compiles of
    # the same program (alias_bytes can report 0 or the donated size),
    # and the normalized peak subtracts it — a ratchet gated on that
    # would flap. Gate on the alias-free upper bound instead; the raw
    # alias_bytes stays in the fingerprint informationally.
    fp["peak_bytes"] = (
        fp["argument_bytes"]
        + fp["output_bytes"]
        + fp["temp_bytes"]
        + fp["generated_code_bytes"]
    )
    return fp


def fingerprint_programs(compiled: Dict[str, object]) -> Dict[str, Dict]:
    return {name: fingerprint_memory(c) for name, c in compiled.items()}


# ---- the budget (the ratchet file) ----------------------------------------


def save_budget(
    path: str,
    programs: Dict[str, Dict],
    shape: Sequence[int],
    tolerance: float,
):
    payload = {
        "version": BUDGET_VERSION,
        "mesh": {"shape": [int(s) for s in shape]},
        "tolerance": tolerance,
        "programs": {k: programs[k] for k in sorted(programs)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def load_budget(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    version = payload.get("version")
    if version != BUDGET_VERSION:
        raise ValueError(
            f"memory budget {path} has version {version!r}; this "
            f"analyzer writes version {BUDGET_VERSION} — regenerate "
            "with --write"
        )
    return payload


def check_fingerprints(
    current: Dict[str, Dict],
    budget_programs: Dict[str, Dict],
    tolerance: float,
) -> Tuple[List[str], List[str]]:
    """``(violations, notes)`` of current memory fingerprints vs budget.

    Violations (gate-failing): a program absent from the budget, or a
    gated field (peak/temp/output bytes) grown past ``tolerance`` — a
    budgeted 0 tolerates nothing, so a program that today needs no temp
    buffer cannot silently start materializing one. Notes: fields
    shrunk past tolerance (tighten the budget) and stale budgeted
    programs — the ratchet only tightens."""
    violations: List[str] = []
    notes: List[str] = []
    for prog in sorted(current):
        fp = current[prog]
        b = budget_programs.get(prog)
        if b is None:
            violations.append(
                f"{prog}: program not in the memory budget — a new "
                "compiled step program must be budgeted deliberately "
                "(--write)"
            )
            continue
        for field in GATED_FIELDS:
            have = int(fp.get(field, 0))
            allowed = int(b.get(field, 0))
            if have > allowed * (1.0 + tolerance):
                violations.append(
                    f"{prog}: {field} grew {allowed} -> {have} bytes "
                    f"(> {tolerance:.0%} tolerance) — an HBM "
                    "regression the tiny-config tests cannot see"
                )
            elif allowed and have < allowed * (1.0 - tolerance):
                notes.append(
                    f"{prog}: {field} shrank {allowed} -> {have} bytes "
                    "— tighten the budget with --write"
                )
    for prog in sorted(set(budget_programs) - set(current)):
        notes.append(
            f"{prog}: budgeted but not compiled here — stale entry, "
            "prune with --write"
        )
    return violations, notes


# a synthetic HBM blow-up: one program's peak/temp inflated well past
# any tolerance — the signature of an accidentally materialized
# full-size temp (e.g. an unfused [N, K, D] intermediate)
INJECTED_TEMP_BYTES = 1 << 26  # 64 MiB


def prove_injection(
    current: Dict[str, Dict],
    budget_programs: Dict[str, Dict],
    tolerance: float,
) -> bool:
    """Inflate one program's temp/peak bytes and assert the budget
    check CATCHES it — run in CI so 'the gate would fire' is
    demonstrated, not assumed."""
    prog = sorted(current)[0]
    doctored = {k: dict(v) for k, v in current.items()}
    doctored[prog]["temp_bytes"] = (
        int(doctored[prog].get("temp_bytes", 0)) + INJECTED_TEMP_BYTES
    )
    doctored[prog]["peak_bytes"] = (
        int(doctored[prog].get("peak_bytes", 0)) + INJECTED_TEMP_BYTES
    )
    violations, _ = check_fingerprints(
        doctored, budget_programs, tolerance=tolerance
    )
    return any(
        prog in v and ("temp_bytes" in v or "peak_bytes" in v)
        for v in violations
    )


# ---- CLI ------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.analysis.mem",
        description=(
            "numlint compiled-memory ratchet: fingerprint the step "
            "programs' memory_analysis() against the committed budget "
            "(docs/static-analysis.md)"
        ),
    )
    p.add_argument(
        "--check",
        metavar="FILE",
        help=f"check fingerprints against a budget (e.g. {DEFAULT_BUDGET})",
    )
    p.add_argument(
        "--write",
        metavar="FILE",
        help="compile and write the current fingerprints as the budget",
    )
    p.add_argument(
        "--tolerance",
        type=float,
        default=None,
        help="byte-growth tolerance (default: the budget's, else "
        "HYDRAGNN_NUMLINT_MEM_TOLERANCE or 0.25)",
    )
    p.add_argument(
        "--mesh",
        default=None,
        help='harness mesh "d,m" (default: the HLO ratchet\'s 4,2 canon)',
    )
    p.add_argument(
        "--prove-injection",
        action="store_true",
        help="after checking, inflate one program's temp/peak bytes and "
        "assert the gate catches it (the CI reintroduction proof)",
    )
    args = p.parse_args(argv)

    from hydragnn_tpu.analysis import hlo as hlo_mod

    if not args.check and not args.write:
        print(
            "mem-ratchet: one of --check/--write is required",
            file=sys.stderr,
        )
        return 2
    mesh_arg = args.mesh or (
        f"{hlo_mod.DEFAULT_MESH[0]},{hlo_mod.DEFAULT_MESH[1]}"
    )
    try:
        d, m = (int(v) for v in mesh_arg.split(","))
    except ValueError:
        print(
            f'mem-ratchet: --mesh {mesh_arg!r} is not "d,m"',
            file=sys.stderr,
        )
        return 2

    # validate the budget BEFORE the multi-minute 8-program compile
    budget = None
    try:
        tolerance = (
            args.tolerance
            if args.tolerance is not None
            else default_tolerance()
        )
    except ValueError as e:
        print(f"mem-ratchet: {e}", file=sys.stderr)
        return 2
    if args.check and not args.write:
        try:
            budget = load_budget(args.check)
        except FileNotFoundError:
            print(
                f"mem-ratchet: budget {args.check} not found — derive "
                "it with --write",
                file=sys.stderr,
            )
            return 2
        except ValueError as e:
            print(f"mem-ratchet: {e}", file=sys.stderr)
            return 2
        if args.tolerance is None:
            tolerance = float(budget.get("tolerance", tolerance))
        bmesh = budget.get("mesh", {})
        if list(bmesh.get("shape", [])) != [d, m]:
            print(
                f"mem-ratchet: budget was derived on mesh "
                f"{bmesh.get('shape')} but this run uses [{d}, {m}] — "
                "fingerprints are not comparable (pass the matching "
                "--mesh)",
                file=sys.stderr,
            )
            return 2

    # the canonical environment (shared with the HLO ratchet): forced
    # CPU devices, no ambient HYDRAGNN_MESH leaking into the harness
    os.environ.pop("HYDRAGNN_MESH", None)
    hlo_mod._force_cpu_devices(max(d * m, 8))

    print(f"mem-ratchet: compiling 8 step programs on a {d}x{m} CPU mesh")
    _texts, _axes, shape, context = hlo_mod.compile_step_programs((d, m))
    try:
        current = fingerprint_programs(context["compiled"])
    except RuntimeError as e:
        print(f"mem-ratchet: {e}", file=sys.stderr)
        return 2

    if args.write:
        save_budget(args.write, current, shape, tolerance=tolerance)
        print(
            f"mem-ratchet: wrote {len(current)} program memory "
            f"fingerprint(s) to {args.write}"
        )
        return 0

    violations, notes = check_fingerprints(
        current, budget.get("programs", {}), tolerance=tolerance
    )
    for note in notes:
        print(f"note: {note}", file=sys.stderr)
    for v in violations:
        print(f"VIOLATION: {v}")
    ok = not violations
    print(
        f"mem-ratchet: {len(violations)} violation(s) across "
        f"{len(current)} program(s) (tolerance {tolerance:.0%})"
    )
    if ok and args.prove_injection:
        if prove_injection(
            current, budget.get("programs", {}), tolerance
        ):
            print(
                "mem-ratchet: injection proof OK — a synthetic HBM "
                "blow-up IS caught by this budget"
            )
        else:
            print(
                "mem-ratchet: injection proof FAILED — the gate did "
                "not catch a synthetic temp/peak-bytes inflation",
                file=sys.stderr,
            )
            return 1
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
