"""threadlint: the concurrency & shutdown-safety rule suite.

The always-on surface (micro-batched ``InferenceServer``, obs HTTP
listeners, prefetch workers, flight recorder, thread-pooled HPO launcher)
is exactly where deadlocks and leaked threads turn into 3 a.m. pages, and
exactly what an AST pass CAN reason about: lock nesting is syntactic
(``with self._lock:``), thread lifecycles are module-local (the repo's
idiom creates, starts, and joins threads in one class), and queue
boundedness is a constructor argument. Five rules, all reusing the
jaxlint engine (per-line suppressions, fingerprint baseline ratchet,
``--format=github`` annotations):

- **lock-order-inversion** — per-module/per-class lock-acquisition graph
  from nested ``with *_lock`` bodies; any cycle means two call paths can
  interleave into a deadlock.
- **blocking-under-lock** — device dispatch (``jax.device_get``,
  ``block_until_ready``), file/socket/process I/O, ``queue.get/put``,
  ``Event.wait`` and ``time.sleep`` inside a held-lock body: the lock's
  critical section inherits the full latency (and on the serving path,
  every submitter stalls behind it).
- **thread-leak** — a non-daemon ``threading.Thread`` started with no
  reachable ``join``, or an executor neither context-managed nor
  ``shutdown`` — interpreter exit hangs, or workers outlive the epoch
  holding batches on device.
- **unguarded-shared-state** — a class that owns a lock and mutates some
  attribute under it in one method, then mutates the same attribute
  lock-free in another: the lock documents the invariant, the bare write
  breaks it.
- **queue-misuse** — unbounded queues on serving/loader paths (a stalled
  consumer grows them without bound), and blocking ``.get()`` without a
  timeout inside stop/shutdown paths (shutdown wedges on an empty queue).

The static suite is paired with the runtime lock sanitizer
(:mod:`hydragnn_tpu.analysis.guards`: ``lock_sanitizer()`` /
``InstrumentedLock`` + the deadlock watchdog) for the orderings only
execution can see. Suppressions accept the ``# threadlint: disable=...``
tag as well as ``# jaxlint:``.
"""

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    matches_any,
    register,
    walk_no_nested_functions,
)

# a `with X:` context whose dotted name's last segment matches this is a
# lock acquisition (self._lock, _LOCK, _captured_lock, _pending_lock, ...)
_LOCK_NAME_RE = re.compile(r"(lock|mutex)s?$", re.IGNORECASE)

# receivers that read as queues for get/put classification
_QUEUE_RECV_RE = re.compile(r"(queue$|(^|\.)_?q$|_q$)", re.IGNORECASE)

# receivers that read as file/socket handles for read/write/flush
_FILE_RECV_RE = re.compile(
    r"(^|\.)_?(f|fh|fp|file\w*|out|sock\w*|conn\w*|wfile|rfile)$",
    re.IGNORECASE,
)


def _last_segment(name: str) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _is_lock_expr(node: ast.AST) -> Optional[str]:
    """Dotted name of a lock-like context expression, else None.
    ``with self._lock:`` and ``with lock.acquire_timeout(...)`` style
    helpers both resolve through their dotted names."""
    name = dotted_name(node)
    if not name and isinstance(node, ast.Call):
        name = dotted_name(node.func)
    if name and _LOCK_NAME_RE.search(_last_segment(name)):
        return name
    return None


def _with_lock_names(stmt: ast.With) -> List[str]:
    names = []
    for item in stmt.items:
        name = _is_lock_expr(item.context_expr)
        if name is not None:
            names.append(name)
    return names


def _receiver_name(call: ast.Call) -> str:
    """Dotted name of the receiver of an attribute call ('' otherwise)."""
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return ""


def _enclosing_scopes(module: ModuleInfo):
    """Yield (class_name_or_'', function_def) for every function, so
    rules can qualify ``self.X`` references per class."""
    def visit(node, class_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (class_name, child)
                yield from visit(child, class_name)
            else:
                yield from visit(child, class_name)

    yield from visit(module.tree, "")


def _qualify_lock(name: str, class_name: str) -> str:
    """Scope a lock's dotted name: ``self._lock`` inside class C becomes
    ``C.self._lock`` so two classes' ``self._lock`` stay distinct; bare
    module-level names pass through."""
    if name.startswith(("self.", "cls.")) and class_name:
        return f"{class_name}.{name}"
    return name


# ---- lock-order-inversion -------------------------------------------------


@register
class LockOrderInversion(Rule):
    name = "lock-order-inversion"
    suite = "concurrency"
    description = (
        "Two locks acquired in opposite orders on different paths "
        "(cycle in the module's nested-with lock graph) — two threads "
        "taking one edge each deadlock"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        # edges[(outer, inner)] = the With node that acquired `inner`
        edges: Dict[Tuple[str, str], ast.With] = {}
        for class_name, fn in _enclosing_scopes(module):
            self._collect(fn, class_name, [], edges)

        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)

        findings: List[Finding] = []
        reported: Set[Tuple[str, str]] = set()
        for (a, b), site in sorted(
            edges.items(), key=lambda kv: kv[1].lineno
        ):
            if (b, a) in reported:
                continue  # one report per cycle pair
            path = self._path(graph, b, a)
            if path is None:
                continue
            reported.add((a, b))
            chain = " -> ".join([a, b] + path[1:])
            findings.append(
                module.finding(
                    self.name,
                    site,
                    f"lock order cycle: `{a}` is held while acquiring "
                    f"`{b}` here, but another path acquires them in the "
                    f"reverse order ({chain}) — two threads taking one "
                    "path each deadlock; pick one global order",
                )
            )
        return findings

    def _collect(self, fn, class_name, held: List[str], edges):
        """DFS over a function body tracking the held-lock stack; does
        not descend into nested defs (they run on their own stacks)."""
        def visit(node, held):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                names = [
                    _qualify_lock(n, class_name)
                    for n in _with_lock_names(node)
                ]
                inner = list(held)
                for n in names:
                    for h in inner:
                        if h != n:
                            edges.setdefault((h, n), node)
                    inner.append(n)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, held)

    @staticmethod
    def _path(graph, src, dst) -> Optional[List[str]]:
        """Shortest edge path src -> dst, or None (BFS; graphs are tiny)."""
        if src == dst:
            return [src]
        frontier = [[src]]
        seen = {src}
        while frontier:
            path = frontier.pop(0)
            for nxt in sorted(graph.get(path[-1], ())):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(path + [nxt])
        return None


# ---- blocking-under-lock --------------------------------------------------

# dotted names that block outright, wherever they appear
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep()",
    "jax.device_get": "jax.device_get() (device sync)",
    "jax.device_put": "jax.device_put() (device transfer)",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
}

# terminal attribute names that block on any receiver
_BLOCKING_ANY_RECV = {
    "block_until_ready": "block_until_ready() (device sync)",
    "wait": ".wait()",
    "recv": "socket recv()",
    "recv_into": "socket recv_into()",
    "sendall": "socket sendall()",
    "accept": "socket accept()",
    "connect": "socket connect()",
}

# terminal names that block when the receiver reads as a file/socket
_BLOCKING_FILE_RECV = {"read", "readline", "readlines", "write", "flush",
                       "send"}

# terminal names that block when the receiver reads as a queue, unless
# the no-wait spelling / a non-blocking flag is used
_BLOCKING_QUEUE_RECV = {"get", "put"}


@register
class BlockingUnderLock(Rule):
    name = "blocking-under-lock"
    suite = "concurrency"
    description = (
        "Blocking call (device sync, file/socket I/O, queue get/put, "
        "Event.wait, sleep) inside a held-lock body — every other thread "
        "needing the lock inherits the full latency"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[int] = set()
        for class_name, fn in _enclosing_scopes(module):
            for node in walk_no_nested_functions(fn):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                lock_names = _with_lock_names(node)
                if not lock_names:
                    continue
                lock = _qualify_lock(lock_names[0], class_name)
                for child in self._body_nodes(node):
                    if id(child) in seen:
                        continue
                    what = self._classify(child)
                    if what:
                        seen.add(id(child))
                        findings.append(
                            module.finding(
                                self.name,
                                child,
                                f"{what} while holding `{lock}` — move "
                                "the blocking work outside the critical "
                                "section (snapshot under the lock, act "
                                "after releasing it)",
                            )
                        )
        return findings

    @staticmethod
    def _body_nodes(with_stmt):
        """Nodes inside the with body, not crossing nested defs and not
        descending into NESTED with-lock bodies (they report themselves,
        against their own — innermost — lock)."""
        stack = list(with_stmt.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)) and _with_lock_names(node):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _classify(node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name in _BLOCKING_DOTTED:
            return f"`{_BLOCKING_DOTTED[name]}`"
        if name == "open":
            return "`open()` (file I/O)"
        if not isinstance(node.func, ast.Attribute):
            return None
        terminal = node.func.attr
        recv = _receiver_name(node)
        if terminal in _BLOCKING_ANY_RECV:
            # threading.Event().wait() / sock.accept() / fut.wait() — but
            # never subprocess-style `self.wait` overloads on constants
            if isinstance(node.func.value, ast.Constant):
                return None
            return f"`{recv or '<expr>'}.{terminal}()`"
        if terminal in _BLOCKING_QUEUE_RECV and _QUEUE_RECV_RE.search(recv):
            for kw in node.keywords:
                if kw.arg == "block" and (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                ):
                    return None
            return f"blocking `{recv}.{terminal}()`"
        if terminal in _BLOCKING_FILE_RECV and _FILE_RECV_RE.search(recv):
            return f"`{recv}.{terminal}()` (file/socket I/O)"
        return None


# ---- thread-leak ----------------------------------------------------------

_THREAD_CTORS = {"threading.Thread", "Thread"}
_EXECUTOR_CTORS = {
    "ThreadPoolExecutor",
    "ProcessPoolExecutor",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "futures.ThreadPoolExecutor",
    "futures.ProcessPoolExecutor",
}


def _kwarg_const(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


@register
class ThreadLeak(Rule):
    name = "thread-leak"
    suite = "concurrency"
    description = (
        "Non-daemon Thread started with no reachable join, or an "
        "executor neither context-managed nor shutdown — interpreter "
        "exit hangs, or workers outlive their owner holding resources"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        joined = self._joined_names(module)
        shutdown = self._shutdown_names(module)
        with_ctx = self._context_managed(module)

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee in _THREAD_CTORS:
                if _kwarg_const(node, "daemon") is True:
                    continue
                target = self._binding_name(module, node)
                if target is not None and target in joined:
                    continue
                where = (
                    f"`{target}`" if target else "an unbound Thread"
                )
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        f"non-daemon Thread {where} is started but never "
                        "joined in this module — join it in the stop "
                        "path (bounded timeout), or mark it daemon=True "
                        "with an explicit lifecycle owner",
                    )
                )
            elif callee in _EXECUTOR_CTORS:
                if id(node) in with_ctx:
                    continue
                target = self._binding_name(module, node)
                if target is not None and target in shutdown:
                    continue
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        f"`{callee}` is neither used as a context "
                        "manager nor `.shutdown()` anywhere in this "
                        "module — worker threads outlive their owner",
                    )
                )
        return findings

    @staticmethod
    def _binding_name(module: ModuleInfo, call: ast.Call) -> Optional[str]:
        """'x' / 'self._x' when the call is the value of an assignment
        (searches the whole module — assignments are statements wrapping
        the call node)."""
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    name = dotted_name(t)
                    if name:
                        return _last_segment(name)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if node.value is call:
                    name = dotted_name(node.target)
                    if name:
                        return _last_segment(name)
        return None

    @staticmethod
    def _joined_names(module: ModuleInfo) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                recv = dotted_name(node.func.value)
                if recv:
                    out.add(_last_segment(recv))
        return out

    @staticmethod
    def _shutdown_names(module: ModuleInfo) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "shutdown"
            ):
                recv = dotted_name(node.func.value)
                if recv:
                    out.add(_last_segment(recv))
        return out

    @staticmethod
    def _context_managed(module: ModuleInfo) -> Set[int]:
        """ids of calls used directly as `with <call>(...)` contexts."""
        out: Set[int] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        out.add(id(item.context_expr))
        return out


# ---- unguarded-shared-state -----------------------------------------------

_LOCK_VALUE_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "Lock",
    "RLock",
}

# method calls on a self attribute that mutate the underlying container
_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "remove", "discard",
    "pop", "popitem", "clear", "update", "setdefault",
}


@register
class UnguardedSharedState(Rule):
    name = "unguarded-shared-state"
    suite = "concurrency"
    description = (
        "A class owns a lock and mutates an attribute under it in one "
        "method, but mutates the same attribute lock-free in another — "
        "the unguarded write races every guarded reader"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    def _check_class(self, module: ModuleInfo, cls: ast.ClassDef):
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return []
        # (attr, method, under_lock, site)
        mutations: List[Tuple[str, str, bool, ast.AST]] = []
        for method in cls.body:
            if not isinstance(
                method, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            self._collect_mutations(
                method, lock_attrs, mutations, under=False
            )
        guarded = {
            attr
            for attr, meth, under, _ in mutations
            if under and meth != "__init__"
        }
        out = []
        for attr, meth, under, site in mutations:
            if under or meth == "__init__" or attr not in guarded:
                continue
            if attr in lock_attrs:
                continue
            out.append(
                module.finding(
                    self.name,
                    site,
                    f"`self.{attr}` is mutated under the lock elsewhere "
                    f"in `{cls.name}` but written lock-free in "
                    f"`{meth}` — take the lock here too (or document "
                    "single-threaded ownership with a suppression)",
                )
            )
        return out

    @staticmethod
    def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in _LOCK_VALUE_CTORS
            ):
                continue
            for t in node.targets:
                name = dotted_name(t)
                if name.startswith("self."):
                    out.add(name.split(".", 1)[1])
        return out

    def _collect_mutations(self, fn, lock_attrs, mutations, under):
        """Walk a method body tracking whether a `with self.<lock>` is
        held; record every self-attribute mutation with that flag."""
        def self_attr_of_target(target) -> Optional[str]:
            # self.x = / self.x[k] = / self.x += ...
            node = target
            while isinstance(node, ast.Subscript):
                node = node.value
            name = dotted_name(node)
            if name.startswith("self.") and name.count(".") == 1:
                return name.split(".", 1)[1]
            return None

        def visit(node, under):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locks_here = {
                    n.split(".", 1)[1]
                    for n in _with_lock_names(node)
                    if n.startswith("self.")
                }
                inner = under or bool(locks_here & lock_attrs)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = self_attr_of_target(t)
                    if attr:
                        mutations.append((attr, fn.name, under, node))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                attr = self_attr_of_target(node.target)
                if attr:
                    mutations.append((attr, fn.name, under, node))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
            ):
                recv = dotted_name(node.func.value)
                if recv.startswith("self.") and recv.count(".") == 1:
                    mutations.append(
                        (recv.split(".", 1)[1], fn.name, under, node)
                    )
            for child in ast.iter_child_nodes(node):
                visit(child, under)

        for stmt in fn.body:
            visit(stmt, under)


# ---- queue-misuse ---------------------------------------------------------

_QUEUE_CTORS = {"queue.Queue", "Queue", "queue.LifoQueue", "LifoQueue",
                "queue.PriorityQueue", "PriorityQueue"}
_UNBOUNDED_OK_CTORS = {"queue.SimpleQueue", "SimpleQueue"}

# serving / loader / listener paths where an unbounded queue is a paging
# incident, not a style nit (extend when new always-on surfaces land)
QUEUE_HOT_PATTERNS = (
    "*/serve/*.py",
    "*/data/loaders.py",
    "*/obs/http.py",
    "*/obs/runtime.py",
    "*/hpo/launcher.py",
    "serve/*.py",
    "data/loaders.py",
    "obs/http.py",
    "obs/runtime.py",
    "hpo/launcher.py",
)

_STOP_PATH_RE = re.compile(
    r"^(stop|shutdown|close|drain|teardown|__exit__|__del__)\w*$"
)


@register
class QueueMisuse(Rule):
    name = "queue-misuse"
    suite = "concurrency"
    description = (
        "Unbounded queue on a serving/loader path (a stalled consumer "
        "grows it without bound), or a blocking queue get without a "
        "timeout inside a stop path (shutdown wedges on empty)"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, QUEUE_HOT_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee in _QUEUE_CTORS:
                if not self._bounded(node):
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            f"`{callee}()` without a maxsize on a "
                            "serving/loader path — a stalled consumer "
                            "grows it without bound; bound it and shed "
                            "or block at the submit edge",
                        )
                    )
            elif callee in _UNBOUNDED_OK_CTORS:
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        f"`{callee}()` is always unbounded — use "
                        "queue.Queue(maxsize=...) on serving/loader "
                        "paths",
                    )
                )

        for _, fn in _enclosing_scopes(module):
            if not _STOP_PATH_RE.match(fn.name):
                continue
            for node in walk_no_nested_functions(fn):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                if node.func.attr != "get":
                    continue
                recv = _receiver_name(node)
                if not _QUEUE_RECV_RE.search(recv):
                    continue
                if self._nonblocking_get(node):
                    continue
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        f"blocking `{recv}.get()` in stop path "
                        f"`{fn.name}` — an empty queue wedges shutdown; "
                        "use get_nowait() or a timeout",
                    )
                )
        return findings

    @staticmethod
    def _bounded(call: ast.Call) -> bool:
        size = None
        if call.args:
            size = call.args[0]
        for kw in call.keywords:
            if kw.arg == "maxsize":
                size = kw.value
        if size is None:
            return False
        if isinstance(size, ast.Constant) and size.value in (0, None):
            return False
        if (
            isinstance(size, ast.UnaryOp)
            and isinstance(size.op, ast.USub)
        ):
            return False  # negative maxsize is unbounded too
        return True

    @staticmethod
    def _nonblocking_get(call: ast.Call) -> bool:
        if call.args:  # q.get(False) / q.get(True, timeout)
            first = call.args[0]
            if isinstance(first, ast.Constant) and first.value is False:
                return True
            if len(call.args) > 1:
                return True  # positional timeout
        for kw in call.keywords:
            if kw.arg == "timeout":
                return True
            if kw.arg == "block" and (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is False
            ):
                return True
        return False
